"""Embedding lookup ops — plain and row-sharded.

The reference keeps embedding tables either wholly on the parameter server
(``1-ps-cpu/...py:166-168``; every lookup crosses the gRPC wire) or fully
replicated per GPU (Horovod). The TPU-native design row-shards the table
across the ``model`` mesh axis and turns each lookup into a *dense*
local-gather + mask + ``psum`` — one ICI collective, no host round-trips
(SURVEY.md Stage 3; the mask-and-psum keeps shapes static for XLA).

``sharded_lookup`` is written to run inside ``shard_map`` where ``table`` is
the local shard and ``ids`` are the (replicated-over-model) global indices.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


def lookup(table: jax.Array, ids: jax.Array, *,
           axis_name: Optional[str] = None,
           strategy: str = "masked_psum") -> jax.Array:
    """Gather rows of ``table`` at ``ids``.

    table: [V, ...] (or local shard [V/m, ...] inside shard_map)
    ids:   int32 [...]
    Returns [..., *table.shape[1:]] (f32), reassembled across ``axis_name``
    shards when given.

    ``strategy`` selects the collective pattern for the sharded case (see
    TUNING.md §"Sharded embedding lookup" for the measured/analytic
    comparison):

    * ``masked_psum`` (default): local masked gather + psum of the [B,F,K]
      activations — traffic ∝ batch, wins when B·F ≪ V (the CTR regime:
      activations ~1.3 MB vs a ~15 MB table at the reference shape).
    * ``allgather_table``: all_gather the shards into the full table, then
      plain gather — traffic ∝ V·K, wins only when B·F ≫ V (huge batches
      over small tables); backward reduce-scatters the table cotangent.
    """
    if axis_name is None:
        return jnp.take(table, ids, axis=0)
    if strategy == "allgather_table":
        return sharded_lookup_allgather(table, ids, axis_name)
    if strategy != "masked_psum":
        raise ValueError(f"unknown embedding lookup strategy {strategy!r}")
    return sharded_lookup(table, ids, axis_name)


def sharded_lookup(local_table: jax.Array, ids: jax.Array, axis_name: str) -> jax.Array:
    """Row-sharded gather: local masked take + psum over the shard axis.

    Each shard owns rows ``[idx*rows_local, (idx+1)*rows_local)``. Out-of-range
    ids contribute zeros; the psum reassembles the full gather. O(shards)
    redundant local gathers, but fully dense and XLA/ICI-friendly.
    """
    idx = jax.lax.axis_index(axis_name)
    rows_local = local_table.shape[0]
    local_ids = ids.astype(jnp.int32) - idx * rows_local
    in_range = (local_ids >= 0) & (local_ids < rows_local)
    safe = jnp.clip(local_ids, 0, rows_local - 1)
    emb = jnp.take(local_table, safe, axis=0)
    mask = in_range
    if local_table.ndim > 1:
        mask = jnp.expand_dims(in_range, tuple(range(ids.ndim, emb.ndim)))
    emb = jnp.where(mask, emb, jnp.zeros((), emb.dtype))
    return jax.lax.psum(emb, axis_name)


def sharded_lookup_allgather(local_table: jax.Array, ids: jax.Array,
                             axis_name: str) -> jax.Array:
    """Row-sharded gather via table reassembly: rebuild the full [V, ...]
    table on every shard, then a plain local gather.

    Implemented as scatter-into-zeros + psum rather than ``lax.all_gather``:
    the result is identical, XLA recognizes the pattern, and psum's output
    is *provably replicated* over the axis, which ``shard_map(check_vma)``
    requires downstream (all_gather output is conservatively marked
    axis-varying). Communication is O(V·K) per step independent of batch
    (vs masked+psum's O(B·F·K)); the table cotangent reduces back with the
    transposed collective. Only competitive when ids volume exceeds table
    volume — exposed for A/B (scripts/bench_embedding.py, TUNING.md) and
    for large-batch/small-table regimes via cfg.embedding_lookup."""
    m = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows_local = local_table.shape[0]
    full = jnp.zeros((rows_local * m, *local_table.shape[1:]),
                     local_table.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(
        full, local_table, idx * rows_local, axis=0)
    full = jax.lax.psum(full, axis_name)
    return jnp.take(full, ids.astype(jnp.int32), axis=0)


# Vocab rows are padded to a multiple of this REGARDLESS of the current
# mesh, so the table shape — and therefore every checkpoint — is identical
# across all power-of-two mesh_model layouts up to 64-way. Without a fixed
# multiple, a checkpoint trained row-sharded (padded to mesh_model) could
# not restore on a different mesh (eval single-chip, resume after resize).
_VOCAB_PAD_MULTIPLE = 64


def padded_vocab(feature_size: int, num_shards: int) -> int:
    """Round the vocabulary up so the table divides evenly across shards AND
    keeps a mesh-independent shape (see _VOCAB_PAD_MULTIPLE).

    Padding rows are zero-initialized and unreachable from real ids, so they
    stay exactly zero under training (zero data gradient; l2 gradient of a
    zero row is zero). Non-power-of-two shard counts (no TPU topology has
    them) fall back to lcm-style padding and are self-consistent only."""
    m = math.lcm(_VOCAB_PAD_MULTIPLE, max(num_shards, 1))
    if m != _VOCAB_PAD_MULTIPLE:
        _warn_mesh_dependent_padding(num_shards)
    return ((feature_size + m - 1) // m) * m


def _warn_mesh_dependent_padding(num_shards: int) -> None:
    """Once-per-process heads-up: shard counts that don't divide 64 make
    the padding mesh-dependent again, so checkpoints from this mesh won't
    restore on meshes with a different padding (surface it at save/train
    time, not as a confusing restore failure later)."""
    global _pad_warned
    if _pad_warned:
        return
    _pad_warned = True
    from ..utils import logging as ulog  # noqa: PLC0415 (avoid eager import)
    ulog.warning(
        f"mesh_model={num_shards} does not divide {_VOCAB_PAD_MULTIPLE}: "
        f"embedding padding becomes mesh-dependent and checkpoints from "
        f"this mesh are NOT portable to meshes with different padding")


_pad_warned = False


# ---------------------------------------------------------------------------
# Deterministic id hashing (multi-table bucketed embeddings)
# ---------------------------------------------------------------------------
# Stateless uint32 mixing (Knuth multiplicative + murmur3-style finalizer):
# determinism across processes, restarts and resume comes for free because
# the mapping is pure arithmetic — no dictionaries, no RNG, no host state.
# All math stays in uint32 (JAX_ENABLE_X64 off in tests and on TPU).

_KNUTH = jnp.uint32(2654435761)       # 2^32 / golden ratio
_MIX1 = jnp.uint32(0x85EBCA6B)        # murmur3 fmix32 constants
_MIX2 = jnp.uint32(0xC2B2AE35)
TABLE_ASSIGN_SALT = 0x9E3779B9        # distinct stream for table selection


def hash_mix(ids: jax.Array, salt: int) -> jax.Array:
    """Avalanche-mix ids (any int dtype) into uniform uint32, salted so each
    consumer (table assignment, each table's bucketing) draws an independent
    stream from the same id."""
    x = ids.astype(jnp.uint32) ^ jnp.uint32(salt)
    x = x * _KNUTH
    x = x ^ (x >> 16)
    x = x * _MIX1
    x = x ^ (x >> 13)
    x = x * _MIX2
    x = x ^ (x >> 16)
    return x


def hash_bucket(ids: jax.Array, num_buckets: int, salt: int) -> jax.Array:
    """Bucket index in [0, num_buckets) for each id — table ``salt`` gives
    every table an independent bucketing, so two ids colliding in one table
    almost surely separate in another."""
    return (hash_mix(ids, salt) % jnp.uint32(num_buckets)).astype(jnp.int32)


def hash_table_assign(ids: jax.Array, num_tables: int) -> jax.Array:
    """Table index in [0, num_tables) per id (embedding_assign="hash")."""
    return (hash_mix(ids, TABLE_ASSIGN_SALT)
            % jnp.uint32(num_tables)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Sparse-update plan: static-shape dedup of one batch's ids
# ---------------------------------------------------------------------------


class PlanEntry(NamedTuple):
    """Dedup of one batch's ids against ONE physical table.

    uids: int32 [U]    sorted unique row ids; U = ids.size (static). Slots
                       beyond the real uniques hold ``num_rows`` — OUT OF
                       BOUNDS by construction, so gathers read zero
                       (mode="fill") and scatters drop them: no sentinel
                       row and no dynamic shapes needed.
    inv:  int32 [...]  ids-shaped map position -> uid slot.
    mask: f32   [...]  1.0 where the position reads this table (hashed
                       multi-table assignment), else 0.0. None = all
                       positions (monolithic table).
    num_rows: int      static row count used as the OOB fill id.
    touched: bool [R]  (counting plans only) per-ROW touch marks over the
                       id space — enables the select-writeback in
                       ``scatter_rows``. None on ``make_plan`` plans.
    rank: int32 [R]    (counting plans only) row id -> uid slot for touched
                       rows (arbitrary elsewhere, masked by ``touched``).
    """
    uids: jax.Array
    inv: jax.Array
    mask: Optional[jax.Array]
    num_rows: int
    touched: Optional[jax.Array] = None
    rank: Optional[jax.Array] = None


def make_plan(ids: jax.Array, num_rows: int,
              mask: Optional[jax.Array] = None) -> PlanEntry:
    """Build a PlanEntry. ``ids`` must already be per-table row ids; masked
    positions must carry the OOB value ``num_rows`` (they then share the
    unique fill value and vanish in the drop-scatter)."""
    flat = ids.reshape(-1).astype(jnp.int32)
    uids, inv = jnp.unique(
        flat, size=flat.shape[0], fill_value=num_rows, return_inverse=True)
    return PlanEntry(uids=uids, inv=inv.reshape(ids.shape).astype(jnp.int32),
                     mask=mask, num_rows=num_rows)


def make_plan_counting(ids: jax.Array, num_rows: int,
                       mask: Optional[jax.Array] = None) -> PlanEntry:
    """``make_plan`` with bit-identical uids/inv, built by counting instead
    of sorting.

    ``jnp.unique(size=N)`` lowers to a sort-based program (~5x the cost of
    this formulation on XLA:CPU at the bench shape). A presence-mark pass
    over the [num_rows+1] id space recovers the same sorted dedup:

        mark[r]   = 1 iff r occurs in ids            (one scatter)
        csum      = inclusive prefix sum of mark
        rank[r]   = csum[r] - mark[r]                (# distinct values < r)
        inv       = rank[ids]                        (index in sorted uniques)
        uids[j]   = searchsorted(csum, j+1)          (j-th distinct value;
                    past the last unique this is num_rows+1 -> clamped to
                    the OOB fill id num_rows, same as unique's fill slots)

    Cost is O(ids + num_rows); only selected for tables small enough that
    the vocab-shaped prefix sum beats the sort (ops.pallas_embedding owns
    that choice). The touched/rank outputs additionally let
    ``scatter_rows`` write back via a select over the id space instead of
    a scatter — same result, one cheap vocab-shaped pass."""
    flat = ids.reshape(-1).astype(jnp.int32)
    n = flat.shape[0]
    mark = jnp.zeros((num_rows + 1,), jnp.int32).at[flat].set(1)
    csum = jnp.cumsum(mark)
    rank = csum - mark                       # exclusive rank per row id
    inv = jnp.take(rank, flat)
    uids = jnp.minimum(
        jnp.searchsorted(csum, jnp.arange(1, n + 1, dtype=csum.dtype),
                         side="left"),
        num_rows).astype(jnp.int32)
    return PlanEntry(uids=uids, inv=inv.reshape(ids.shape).astype(jnp.int32),
                     mask=mask, num_rows=num_rows,
                     touched=mark[:num_rows].astype(jnp.bool_),
                     rank=rank[:num_rows].astype(jnp.int32))


def valid_rows(entry: PlanEntry) -> jax.Array:
    """Bool [U]: which uid slots name a real (in-bounds) touched row."""
    return entry.uids < entry.num_rows


def gather_rows(table: jax.Array, entry: PlanEntry) -> jax.Array:
    """[U, ...] rows at ``entry.uids``. OOB fill slots read as ZERO
    (``mode="fill"`` — jnp.take's default fill is NaN, which would poison
    any masked-multiply downstream). Fill-slot values are never referenced
    by ``inv`` and their updates are dropped by the OOB scatter; zeros keep
    them inert in sums/l2 as well."""
    return jnp.take(table, entry.uids, axis=0, mode="fill", fill_value=0)


def lookup_rows(rows: jax.Array, entry: PlanEntry) -> jax.Array:
    """Positionwise view of gathered rows: rows[inv] (masked in hashed
    mode). Differentiating this gather w.r.t. ``rows`` IS the segment-sum:
    the transpose is a scatter-add of the per-position cotangents into [U]
    row slots — cost ∝ batch, never ∝ vocab."""
    out = jnp.take(rows, entry.inv, axis=0)
    if entry.mask is not None:
        mask = entry.mask.reshape(
            entry.mask.shape + (1,) * (out.ndim - entry.mask.ndim))
        out = out * mask
    return out


def scatter_rows(table: jax.Array, entry: PlanEntry,
                 new_rows: jax.Array) -> jax.Array:
    """Write back updated touched rows; the OOB fill slots are DROPPED by
    XLA's default scatter mode, so unique's padding can never alias a real
    row. Distinct in-bounds uids make the scatter duplicate-free and
    deterministic.

    Counting plans (touched/rank present) write back as a SELECT over the
    id space instead — ``where(touched, new_rows[rank], table)`` — which
    XLA:CPU executes as one fused vocab-shaped pass (~7x cheaper than its
    row scatter at the bench shape) and is element-for-element identical:
    rank[r] is exactly the uid slot of each touched row r, untouched rows
    keep their bits. A table shorter than the id space (the tiered hot
    cache gathers with slot ids < hot_rows < padded_vocab) truncates the
    marks — all touched ids are in-bounds for it by construction."""
    if entry.touched is None:
        return table.at[entry.uids].set(new_rows)
    keep = entry.touched[: table.shape[0]]
    sel = jnp.take(new_rows, entry.rank[: table.shape[0]], axis=0)
    keep = keep.reshape((-1,) + (1,) * (table.ndim - 1))
    return jnp.where(keep, sel.astype(table.dtype), table)


def set_rows_scalar(table: jax.Array, entry: PlanEntry,
                    value: jax.Array) -> jax.Array:
    """Set every touched row of a rank-1 per-row array (the lazy-Adam
    ``tau`` last-touch stamps) to ``value``. Same select-vs-scatter split
    as ``scatter_rows``."""
    if entry.touched is None:
        return table.at[entry.uids].set(value)
    keep = entry.touched[: table.shape[0]]
    return jnp.where(keep, jnp.asarray(value, table.dtype), table)


# ---------------------------------------------------------------------------
# Row-sharded all-to-all exchange (--embedding_shard rows)
# ---------------------------------------------------------------------------
# The sparse path's plan (PlanEntry.uids, sorted ascending with OOB fill)
# meets a row-sharded table here: each model peer takes an equal contiguous
# slice of the uid positions, buckets its slice by owner shard (sorted uids
# make owner runs contiguous — two searchsorted calls give the bucket
# bounds), ships static-shape padded request sets over ``lax.all_to_all``,
# the owners answer with a second all_to_all, and a zeros+psum reassembly
# replicates the gathered rows on every peer (psum output is provably
# replicated, which shard_map's check_vma needs downstream; all_gather's is
# not). Every element of the result has exactly ONE nonzero contributor in
# the psum, so the exchange is bit-identical to ``gather_rows`` on the
# unsharded table — no float reassociation anywhere.


class ExchangePlan(NamedTuple):
    """Static-shape routing for one table's row exchange, built per step
    from the (model-replicated) PlanEntry. All shapes are static: ``reqs``
    pads each owner bucket to the slice capacity C = ceil(U / D) with the
    OOB id ``num_rows``, which owners answer with zero rows and the
    reassembly never reads.

    reqs:     int32 [D, C]  row ids this peer requests from each owner.
    flat_idx: int32 [C]     position into the flattened [D*C] response
                            block for this peer's slice; D*C (OOB -> fill 0)
                            for pad slots.
    num_rows: int           global rows in the table (OOB fill id).
    rows_local: int         rows per shard (num_rows // num_shards).
    num_shards: int         model-axis size D.
    n_ids: int              U — uid slot count (static = batch ids.size).
    """
    reqs: jax.Array
    flat_idx: jax.Array
    num_rows: int
    rows_local: int
    num_shards: int
    n_ids: int


def build_exchange(entry: PlanEntry, num_shards: int,
                   axis_name: str) -> ExchangePlan:
    """Bucket this peer's uid slice by owner shard. Must run inside
    shard_map over ``axis_name``; the batch (hence the plan) is replicated
    over the model axis, so slicing by ``axis_index`` splits the request
    work D ways without any prior communication."""
    if entry.num_rows % num_shards:
        raise ValueError(
            f"table rows {entry.num_rows} not divisible by {num_shards} "
            f"shards")
    uids = entry.uids
    n = uids.shape[0]
    d = num_shards
    rows_local = entry.num_rows // d
    cap = -(-n // d)
    r = jax.lax.axis_index(axis_name)
    pad = jnp.full((d * cap - n,), entry.num_rows, uids.dtype)
    u_pad = jnp.concatenate([uids, pad])          # sorted: fill is the max
    sl = jax.lax.dynamic_slice_in_dim(u_pad, r * cap, cap)
    bounds = jnp.searchsorted(
        sl, jnp.arange(d + 1, dtype=sl.dtype) * rows_local,
        side="left").astype(jnp.int32)            # [D+1] owner-run bounds
    starts, ends = bounds[:-1], bounds[1:]
    idx = starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    valid = idx < ends[:, None]
    reqs = jnp.where(valid, jnp.take(sl, jnp.clip(idx, 0, cap - 1)),
                     entry.num_rows).astype(jnp.int32)
    owner = (sl // rows_local).astype(jnp.int32)  # fill ids land on D
    rank = jnp.arange(cap, dtype=jnp.int32) - jnp.take(
        starts, jnp.clip(owner, 0, d - 1))
    flat_idx = jnp.where(owner < d, owner * cap + rank, d * cap)
    return ExchangePlan(reqs=reqs, flat_idx=flat_idx,
                        num_rows=entry.num_rows, rows_local=rows_local,
                        num_shards=d, n_ids=n)


def exchange_rows(local_table: jax.Array, ex: ExchangePlan,
                  axis_name: str) -> jax.Array:
    """Gather ``ex``'s uid rows from a row-sharded table: all_to_all the
    request sets, owner-gather (OOB and other-shard ids read zero),
    all_to_all the responses back, reassemble + replicate via psum.
    Returns [U, ...rows] bit-identical to ``gather_rows`` on the full
    table. Runs inside shard_map over ``axis_name``."""
    d, cap = ex.num_shards, ex.reqs.shape[1]
    r = jax.lax.axis_index(axis_name)
    recv = jax.lax.all_to_all(ex.reqs, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)   # [D, C] asks
    local = recv - r * ex.rows_local
    ok = (local >= 0) & (local < ex.rows_local)
    safe = jnp.where(ok, local, ex.rows_local)
    resp = jnp.take(local_table, safe.reshape(-1), axis=0, mode="fill",
                    fill_value=0).reshape((d, cap) + local_table.shape[1:])
    got = jax.lax.all_to_all(resp, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)    # [D, C, ...]
    flat = got.reshape((d * cap,) + got.shape[2:])
    mine = jnp.take(flat, ex.flat_idx, axis=0, mode="fill", fill_value=0)
    full = jnp.zeros((d * cap,) + mine.shape[1:], mine.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(full, mine, r * cap, axis=0)
    full = jax.lax.psum(full, axis_name)
    return full[:ex.n_ids]


def owner_scatter_add(g_rows: jax.Array, entry: PlanEntry, num_shards: int,
                      axis_name: Optional[str]) -> tuple[jax.Array, jax.Array]:
    """Scatter per-uid cotangents into this shard's table space.

    Returns (grad [rows_local, ...], touched bool [rows_local]): the
    contribution of THIS replica's batch to the rows this shard owns.
    Ids owned elsewhere (and the plan's OOB fill slots) route to the
    ``rows_local`` sentinel and are dropped by XLA's default scatter mode —
    the sentinel is non-negative on purpose, negative indices would wrap.
    With ``axis_name=None`` (one shard) this degrades to the plain
    table-space segment scatter."""
    rows_local = entry.num_rows // num_shards
    off = 0
    if axis_name is not None:
        off = jax.lax.axis_index(axis_name) * rows_local
    local = entry.uids - off
    owned = (local >= 0) & (local < rows_local) & valid_rows(entry)
    safe = jnp.where(owned, local, rows_local)
    grad = jnp.zeros((rows_local,) + g_rows.shape[1:],
                     g_rows.dtype).at[safe].add(g_rows)
    touched = jnp.zeros((rows_local,), jnp.bool_).at[safe].set(True)
    return grad, touched


def exchange_payload_bytes(n_ids: int, row_elems: int, num_shards: int,
                           itemsize: int = 4) -> int:
    """Analytic per-device bytes for one table's forward exchange: the
    request all_to_all (D·C int32 ids), the response all_to_all (D·C rows),
    and the psum reassembly buffer (D·C rows; a ring all-reduce moves
    ~2(D-1)/D of it per device). C = ceil(n_ids / D). Zero when unsharded.
    TUNING §2.11 derives when this beats replicating the table."""
    if num_shards <= 1:
        return 0
    cap = -(-n_ids // num_shards)
    block = num_shards * cap
    return block * 4 + 2 * block * row_elems * itemsize


def pad_row_mask(num_rows_local: int, feature_size: int,
                 axis_name: Optional[str] = None) -> jax.Array:
    """Bool [num_rows_local]: True for real vocabulary rows, False for
    ``padded_vocab`` padding. Inside shard_map the table is a local shard;
    ``axis_name`` recovers the global row index."""
    row = jnp.arange(num_rows_local)
    if axis_name is not None:
        row = row + jax.lax.axis_index(axis_name) * num_rows_local
    return row < feature_size


def mask_pad_rows(x: jax.Array, feature_size: int,
                  axis_name: Optional[str] = None) -> jax.Array:
    """Zero the padded_vocab pad rows of a table-shaped array (used on
    dense embedding grads: pad rows are unreachable so their grads are
    already zero — this makes the exclusion a structural guarantee rather
    than an emergent property)."""
    if axis_name is None and x.shape[0] <= feature_size:
        return x
    keep = pad_row_mask(x.shape[0], feature_size, axis_name)
    keep = keep.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(keep, x, jnp.zeros((), x.dtype))
