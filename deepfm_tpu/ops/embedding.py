"""Embedding lookup ops — plain and row-sharded.

The reference keeps embedding tables either wholly on the parameter server
(``1-ps-cpu/...py:166-168``; every lookup crosses the gRPC wire) or fully
replicated per GPU (Horovod). The TPU-native design row-shards the table
across the ``model`` mesh axis and turns each lookup into a *dense*
local-gather + mask + ``psum`` — one ICI collective, no host round-trips
(SURVEY.md Stage 3; the mask-and-psum keeps shapes static for XLA).

``sharded_lookup`` is written to run inside ``shard_map`` where ``table`` is
the local shard and ``ids`` are the (replicated-over-model) global indices.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def lookup(table: jax.Array, ids: jax.Array, *,
           axis_name: Optional[str] = None,
           strategy: str = "masked_psum") -> jax.Array:
    """Gather rows of ``table`` at ``ids``.

    table: [V, ...] (or local shard [V/m, ...] inside shard_map)
    ids:   int32 [...]
    Returns [..., *table.shape[1:]] (f32), reassembled across ``axis_name``
    shards when given.

    ``strategy`` selects the collective pattern for the sharded case (see
    TUNING.md §"Sharded embedding lookup" for the measured/analytic
    comparison):

    * ``masked_psum`` (default): local masked gather + psum of the [B,F,K]
      activations — traffic ∝ batch, wins when B·F ≪ V (the CTR regime:
      activations ~1.3 MB vs a ~15 MB table at the reference shape).
    * ``allgather_table``: all_gather the shards into the full table, then
      plain gather — traffic ∝ V·K, wins only when B·F ≫ V (huge batches
      over small tables); backward reduce-scatters the table cotangent.
    """
    if axis_name is None:
        return jnp.take(table, ids, axis=0)
    if strategy == "allgather_table":
        return sharded_lookup_allgather(table, ids, axis_name)
    if strategy != "masked_psum":
        raise ValueError(f"unknown embedding lookup strategy {strategy!r}")
    return sharded_lookup(table, ids, axis_name)


def sharded_lookup(local_table: jax.Array, ids: jax.Array, axis_name: str) -> jax.Array:
    """Row-sharded gather: local masked take + psum over the shard axis.

    Each shard owns rows ``[idx*rows_local, (idx+1)*rows_local)``. Out-of-range
    ids contribute zeros; the psum reassembles the full gather. O(shards)
    redundant local gathers, but fully dense and XLA/ICI-friendly.
    """
    idx = jax.lax.axis_index(axis_name)
    rows_local = local_table.shape[0]
    local_ids = ids.astype(jnp.int32) - idx * rows_local
    in_range = (local_ids >= 0) & (local_ids < rows_local)
    safe = jnp.clip(local_ids, 0, rows_local - 1)
    emb = jnp.take(local_table, safe, axis=0)
    mask = in_range
    if local_table.ndim > 1:
        mask = jnp.expand_dims(in_range, tuple(range(ids.ndim, emb.ndim)))
    emb = jnp.where(mask, emb, jnp.zeros((), emb.dtype))
    return jax.lax.psum(emb, axis_name)


def sharded_lookup_allgather(local_table: jax.Array, ids: jax.Array,
                             axis_name: str) -> jax.Array:
    """Row-sharded gather via table reassembly: rebuild the full [V, ...]
    table on every shard, then a plain local gather.

    Implemented as scatter-into-zeros + psum rather than ``lax.all_gather``:
    the result is identical, XLA recognizes the pattern, and psum's output
    is *provably replicated* over the axis, which ``shard_map(check_vma)``
    requires downstream (all_gather output is conservatively marked
    axis-varying). Communication is O(V·K) per step independent of batch
    (vs masked+psum's O(B·F·K)); the table cotangent reduces back with the
    transposed collective. Only competitive when ids volume exceeds table
    volume — exposed for A/B (scripts/bench_embedding.py, TUNING.md) and
    for large-batch/small-table regimes via cfg.embedding_lookup."""
    m = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    rows_local = local_table.shape[0]
    full = jnp.zeros((rows_local * m, *local_table.shape[1:]),
                     local_table.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(
        full, local_table, idx * rows_local, axis=0)
    full = jax.lax.psum(full, axis_name)
    return jnp.take(full, ids.astype(jnp.int32), axis=0)


# Vocab rows are padded to a multiple of this REGARDLESS of the current
# mesh, so the table shape — and therefore every checkpoint — is identical
# across all power-of-two mesh_model layouts up to 64-way. Without a fixed
# multiple, a checkpoint trained row-sharded (padded to mesh_model) could
# not restore on a different mesh (eval single-chip, resume after resize).
_VOCAB_PAD_MULTIPLE = 64


def padded_vocab(feature_size: int, num_shards: int) -> int:
    """Round the vocabulary up so the table divides evenly across shards AND
    keeps a mesh-independent shape (see _VOCAB_PAD_MULTIPLE).

    Padding rows are zero-initialized and unreachable from real ids, so they
    stay exactly zero under training (zero data gradient; l2 gradient of a
    zero row is zero). Non-power-of-two shard counts (no TPU topology has
    them) fall back to lcm-style padding and are self-consistent only."""
    m = math.lcm(_VOCAB_PAD_MULTIPLE, max(num_shards, 1))
    if m != _VOCAB_PAD_MULTIPLE:
        _warn_mesh_dependent_padding(num_shards)
    return ((feature_size + m - 1) // m) * m


def _warn_mesh_dependent_padding(num_shards: int) -> None:
    """Once-per-process heads-up: shard counts that don't divide 64 make
    the padding mesh-dependent again, so checkpoints from this mesh won't
    restore on meshes with a different padding (surface it at save/train
    time, not as a confusing restore failure later)."""
    global _pad_warned
    if _pad_warned:
        return
    _pad_warned = True
    from ..utils import logging as ulog  # noqa: PLC0415 (avoid eager import)
    ulog.warning(
        f"mesh_model={num_shards} does not divide {_VOCAB_PAD_MULTIPLE}: "
        f"embedding padding becomes mesh-dependent and checkpoints from "
        f"this mesh are NOT portable to meshes with different padding")


_pad_warned = False
