"""Pallas TPU kernel: fused FM first-order + second-order interaction.

The reference computes the first-order term and the FM identity as separate
graph ops (``1-ps-cpu/...py:177-187``). Here both reductions run in one
VMEM pass over ``xv``: the kernel consumes the already-materialized
``xv = V[ids] * vals`` (which the DeepFM tower reuses as its input, so it
costs no extra HBM), produces ``y_w + y_v`` directly, and the hand-written
backward emits the compact ``dxv = (S - xv) * g`` form in a single pass —
avoiding the chain of separate square/reduce/broadcast kernels XLA schedules
for the naive formulation.

    y[b] = sum_f w[b,f]*vals[b,f]
         + 0.5 * sum_k [ (sum_f xv[b,f,k])^2 - sum_f xv[b,f,k]^2 ]

Exposed as ``fused_fm(w, vals, xv)`` with a custom VJP; gradients w.r.t. the
embedding ``v`` and ``vals``-through-``xv`` flow via JAX's product rule on
the caller side (xv is an ordinary traced value there). Both passes are
Pallas kernels gridded over batch tiles sized to VMEM. ``interpret=True``
runs the same kernels through the Pallas interpreter (used by the CPU test
suite to check numerics against the plain-jnp formulation in ``ops.fm``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu import fails on some non-TPU builds; interpret mode never needs it
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# VMEM budget for picking the batch-tile height. The backward kernel keeps
# ~4 [Bt, F, K] f32 buffers effectively live (inputs/outputs stream per grid
# step with double buffering); F pads to the 8-sublane, K to the 128-lane
# tile. 14MB of the ~16MB/core leaves headroom for scalars and control.
_VMEM_BUDGET = 14 * 1024 * 1024
_LIVE_BUFFERS = 4


def _pick_block_b(f: int, k: int) -> int:
    """Largest batch tile whose kernel fits VMEM; 0 if none does."""
    fpad = max(-(-f // 8) * 8, 8)
    kpad = max(-(-k // 128) * 128, 128)
    per_row = fpad * kpad * 4
    for bt in (128, 64, 32, 16, 8):
        if _LIVE_BUFFERS * bt * per_row <= _VMEM_BUDGET:
            return bt
    return 0


# Interpret mode has no VMEM constraint; used when _pick_block_b returns 0
# (callers should have gated the compiled path off via supported()).
_BLOCK_FALLBACK = 128


def supported(field_size: int = 39, embedding_size: int = 32) -> bool:
    """True when the compiled kernels can run at this (F, K) shape —
    requires a TPU backend and a batch tile that fits VMEM (larger shapes
    fall back to the XLA formulation rather than failing to compile)."""
    return (pltpu is not None and jax.default_backend() == "tpu"
            and _pick_block_b(field_size, embedding_size) > 0)


def _block_specs(bt: int, f: int, k: int, memory_space):
    kw = {} if memory_space is None else {"memory_space": memory_space}
    return [
        pl.BlockSpec((bt, f), lambda i: (i, 0), **kw),          # w
        pl.BlockSpec((bt, f), lambda i: (i, 0), **kw),          # vals
        pl.BlockSpec((bt, f, k), lambda i: (i, 0, 0), **kw),    # xv
    ]


def _fwd_kernel(w_ref, vals_ref, xv_ref, out_ref):
    # All intermediates stay >= 2-D (rank-1 vectors break Mosaic layout
    # inference on TPU). Inputs may be bf16 in HBM/VMEM; accumulate in f32
    # (cast after load — keeps HBM traffic and residuals at bf16 width).
    xv = xv_ref[:].astype(jnp.float32)                     # [Bt, F, K]
    s = jnp.sum(xv, axis=1)                                # [Bt, K]
    sum_sq = jnp.sum(s * s, axis=1, keepdims=True)         # [Bt, 1]
    sq_sum = jnp.sum(jnp.sum(xv * xv, axis=1), axis=1, keepdims=True)
    y_w = jnp.sum(w_ref[:].astype(jnp.float32)
                  * vals_ref[:].astype(jnp.float32), axis=1, keepdims=True)
    out_ref[:] = y_w + 0.5 * (sum_sq - sq_sum)


def _bwd_kernel(g_ref, w_ref, vals_ref, xv_ref, dw_ref, dvals_ref, dxv_ref):
    g = g_ref[:]                                           # [Bt, 1] f32
    xv = xv_ref[:].astype(jnp.float32)
    s = jnp.sum(xv, axis=1)                                # [Bt, K]
    dw_ref[:] = (vals_ref[:].astype(jnp.float32) * g).astype(dw_ref.dtype)
    dvals_ref[:] = (w_ref[:].astype(jnp.float32) * g).astype(dvals_ref.dtype)
    # d(y_v)/d(xv) * g
    dxv_ref[:] = ((s[:, None, :] - xv) * g[:, :, None]).astype(dxv_ref.dtype)


def _pad_b(x: jnp.ndarray, b_pad: int) -> jnp.ndarray:
    if b_pad == 0:
        return x
    pad = [(0, b_pad)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _run_fwd(w, vals, xv, interpret: bool) -> jnp.ndarray:
    b, f = w.shape
    k = xv.shape[-1]
    bt = _pick_block_b(f, k) or _BLOCK_FALLBACK
    b_pad = (-b) % bt
    w, vals, xv = _pad_b(w, b_pad), _pad_b(vals, b_pad), _pad_b(xv, b_pad)
    bp = b + b_pad
    ms = None if interpret else _VMEM
    kw = {} if ms is None else {"memory_space": ms}
    out = pl.pallas_call(
        _fwd_kernel,
        grid=(bp // bt,),
        in_specs=_block_specs(bt, f, k, ms),
        out_specs=pl.BlockSpec((bt, 1), lambda i: (i, 0), **kw),
        out_shape=jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        interpret=interpret,
    )(w, vals, xv)
    return out[:b, 0]


def _run_bwd(g, w, vals, xv, interpret: bool):
    b, f = w.shape
    k = xv.shape[-1]
    bt = _pick_block_b(f, k) or _BLOCK_FALLBACK
    b_pad = (-b) % bt
    g2 = _pad_b(g.reshape(b, 1), b_pad)
    w, vals, xv = _pad_b(w, b_pad), _pad_b(vals, b_pad), _pad_b(xv, b_pad)
    bp = b + b_pad
    ms = None if interpret else _VMEM
    kw = {} if ms is None else {"memory_space": ms}
    g_spec = pl.BlockSpec((bt, 1), lambda i: (i, 0), **kw)
    dw, dvals, dxv = pl.pallas_call(
        _bwd_kernel,
        grid=(bp // bt,),
        in_specs=[g_spec] + _block_specs(bt, f, k, ms),
        out_specs=[
            pl.BlockSpec((bt, f), lambda i: (i, 0), **kw),
            pl.BlockSpec((bt, f), lambda i: (i, 0), **kw),
            pl.BlockSpec((bt, f, k), lambda i: (i, 0, 0), **kw),
        ],
        out_shape=[
            # Cotangent dtypes mirror the primals (bf16 in -> bf16 grads),
            # written directly by the kernel — no f32 round trip in HBM.
            jax.ShapeDtypeStruct((bp, f), w.dtype),
            jax.ShapeDtypeStruct((bp, f), vals.dtype),
            jax.ShapeDtypeStruct((bp, f, k), xv.dtype),
        ],
        interpret=interpret,
    )(g2, w, vals, xv)
    return dw[:b], dvals[:b], dxv[:b]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_fm(w: jnp.ndarray, vals: jnp.ndarray, xv: jnp.ndarray,
             interpret: bool = False) -> jnp.ndarray:
    """Fused y_w + y_v.  w: [B,F], vals: [B,F], xv: [B,F,K] -> [B] (f32).

    Inputs may be bf16: the kernels cast to f32 AFTER the VMEM load, so
    residuals saved for the backward pass stay at bf16 width in HBM (the
    r1 version saved f32 copies — 2x the residual memory)."""
    return _run_fwd(w, vals, xv, interpret)


def _fused_fm_fwd(w, vals, xv, interpret):
    return _run_fwd(w, vals, xv, interpret), (w, vals, xv)


def _fused_fm_bwd(interpret, res, g):
    w, vals, xv = res
    dw, dvals, dxv = _run_bwd(g.astype(jnp.float32), w, vals, xv, interpret)
    return dw, dvals, dxv


fused_fm.defvjp(_fused_fm_fwd, _fused_fm_bwd)


def reference_fm(w: jnp.ndarray, vals: jnp.ndarray, xv: jnp.ndarray) -> jnp.ndarray:
    """Plain-jnp oracle for the fused kernel (same math as ``ops.fm``)."""
    y_w = jnp.sum(w.astype(jnp.float32) * vals.astype(jnp.float32), axis=1)
    xv = xv.astype(jnp.float32)
    s = jnp.sum(xv, axis=1)
    y_v = 0.5 * jnp.sum(s * s, axis=1) - 0.5 * jnp.sum(xv * xv, axis=(1, 2))
    return y_w + y_v
