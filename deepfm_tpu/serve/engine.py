"""TPU-native serving engine: queue → pipelined dynamic batcher → bucketed
predict.

The reference delegated serving to TF-Serving (``2-hvd-gpu/...py:429-431``
exports, a managed endpoint batches); this module is the in-repo engine that
closes the train→publish→serve loop. One device-owning process runs:

  * a **bounded request queue** — ``submit()`` admits up to
    ``queue_rows`` pending rows and then raises a typed
    :class:`ServerOverloaded` (backpressure a frontend can convert to a 429,
    never a hang);
  * a **priority lane** — requests of at most ``small_rows`` rows queue in
    a dedicated small lane with head-of-line bypass: every forming batch
    admits the small lane FIRST, so a cheap latency-sensitive request is
    never stranded behind a max-batch fill of large requests (0 disables
    the lane; per-lane p50/p99 land in :class:`ServingStats`);
  * a **pipelined dynamic batcher** — a batcher thread forms flushes
    (max-batch policy preempts a deadline anchored at the FIRST queued
    request across both lanes) and hands them to an executor thread over a
    bounded in-flight window (``inflight``, default 2): while flush k runs
    on the device, flush k+1 is already admitting and forming, so batch
    formation never serializes behind device execution (``inflight=1``
    restores the strict flush-then-refill pipeline depth);
  * **bucketed batch shapes** — each flush pads to the next bucket
    (``utils.export.padded_predict``), so at most ``len(buckets)`` predict
    programs ever compile no matter what sizes traffic brings;
  * a **response demux** — padding stripped, per-request futures resolved
    with per-request latency stamps (admission → resolution). The demux is
    shape-agnostic: a single-output model resolves each future with probs
    ``[n]`` (the historical wire shape, unchanged), a multitask artifact
    with a ``{task_name: probs[n]}`` dict — whatever structure the predict
    fn returns, rows are sliced per request.

Hot swap rides the existing :class:`~deepfm_tpu.utils.export.LatestWatcher`:
pass a watcher as ``predict_fn`` (or use :meth:`ServingEngine.serve_latest`)
and a newly published artifact is loaded off to the side and swapped in with
one assignment — the flush that is executing keeps the function reference it
already read, so in-flight batches finish on the old model and no request is
ever dropped or failed by a swap. A failed load keeps the current model
(``LatestWatcher.swap_failures`` counts it). Each flush is stamped with the
model VERSION that executed it (``LatestWatcher.current()``), so the
measured swap blackout is swap→first-flush-of-the-new-version — an
old-model flush completing after the swap (routine under pipelining) cannot
close the window early.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as trace_lib
from ..utils import faults as faults_lib
from .admission import VALUE_DEFAULT, AdmissionController
from .cache import ResultCache, request_fingerprint
from .stats import LANE_LARGE, LANE_SMALL, ServingStats


class ServerOverloaded(RuntimeError):
    """The bounded request queue is full (or the engine is shut down).

    The typed backpressure signal: callers retry with backoff or shed load;
    the engine never blocks a submitter and never silently drops a request.
    (A policy refusal of a low-value class under pressure is the distinct
    :class:`~deepfm_tpu.serve.admission.AdmissionShed`.)
    """


class ServeTimeout(TimeoutError):
    """A future did not resolve within the caller's budget.

    Typed so frontends can forward it over the wire distinctly from a
    predict failure: the request may STILL complete server-side (the engine
    never abandons an admitted request) — only this caller stopped waiting.
    """


class ServeFuture:
    """One request's pending result: resolved by the batcher's demux.

    Resolution is first-wins and idempotent: under request hedging two
    engine legs may race to resolve the caller-visible result, and a
    cancelled loser that was already mid-flush resolves harmlessly (the
    canceller ignores it). ``add_done_callback`` fires exactly once, after
    the winning resolution, outside the future's lock.
    """

    __slots__ = ("ids", "vals", "n", "lane", "value", "t_enqueue",
                 "latency_ms", "trace_id", "model_version", "arm",
                 "fingerprint", "cache_hit", "coalesced", "cache_bypass",
                 "_event", "_probs", "_error", "_lock", "_callbacks",
                 "_cancelled", "_followers")

    def __init__(self, ids: np.ndarray, vals: np.ndarray, t_enqueue: float,
                 lane: str = LANE_LARGE, trace_id: Optional[int] = None,
                 value: str = VALUE_DEFAULT):
        self.ids = ids
        self.vals = vals
        self.n = int(ids.shape[0])
        self.lane = lane
        self.value = value                  # admission value class
        self.t_enqueue = t_enqueue
        self.latency_ms: Optional[float] = None
        self.trace_id = trace_id            # correlation id (obs.trace)
        self.model_version: Optional[int] = None  # stamped by the flush
        self.arm: Optional[int] = None      # stamped by ExperimentRouter
        self.fingerprint: Optional[bytes] = None  # request content hash
        self.cache_hit = False              # resolved from the result cache
        self.coalesced = False              # joined an in-flight leader
        self.cache_bypass = False           # shadow lane: no cache, ever
        self._event = threading.Event()
        self._probs: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._callbacks: List[Callable[["ServeFuture"], None]] = []
        self._cancelled = False
        self._followers: List["ServeFuture"] = []  # coalesced joins

    def done(self) -> bool:
        return self._event.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def cancel(self) -> bool:
        """Best-effort: a cancelled future still waiting in the queue is
        dropped at batch formation or flush start (never executed); one
        already mid-predict resolves normally and the canceller ignores
        the result. Returns False if the future had already resolved.

        A coalesce LEADER with followers attached refuses cancellation
        outright (returns False without marking): other callers' responses
        fan out from this future's resolution, so a hedge race won
        elsewhere must not unresolve them."""
        with self._lock:
            if self._followers:
                return False
            self._cancelled = True
            return not self._event.is_set()

    def attach_follower(self, fut: "ServeFuture") -> bool:
        """Register ``fut`` as a coalesced follower of this in-flight
        leader; from now on :meth:`cancel` refuses (the leader carries
        other callers' responses). False if this future is already
        cancelled — the caller must submit normally instead."""
        with self._lock:
            if self._cancelled:
                return False
            self._followers.append(fut)
            return True

    def add_done_callback(self,
                          fn: Callable[["ServeFuture"], None]) -> None:
        """Run ``fn(self)`` once the future resolves (immediately if it
        already has). Callbacks run on the resolving thread, outside the
        future's lock — keep them cheap and non-blocking."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self) -> Optional[list]:
        """Under ``_lock``: claim the resolution; None if already done."""
        if self._event.is_set():
            return None
        cbs, self._callbacks = self._callbacks, []
        return cbs

    def set_result(self, probs: np.ndarray, latency_ms: float) -> None:
        with self._lock:
            cbs = self._resolve()
            if cbs is None:
                return
            self._probs = probs
            self.latency_ms = latency_ms
            self._event.set()
        for cb in cbs:
            cb(self)

    def set_error(self, exc: BaseException) -> None:
        with self._lock:
            cbs = self._resolve()
            if cbs is None:
                return
            self._error = exc
            self._event.set()
        for cb in cbs:
            cb(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the probs — ``[n]`` for single-output models,
        ``{task_name: [n]}`` for multitask artifacts; raises the predict
        error if the flush failed, typed :class:`ServeTimeout` if not
        resolved in ``timeout``."""
        if not self._event.wait(timeout):
            raise ServeTimeout(
                f"request of {self.n} rows unresolved after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._probs


class ServingEngine:
    """Bounded queue + pipelined batcher + bucketed jitted predict + demux.

    **Fast path** (both off by default — exact pre-existing behavior):
    ``cache_rows`` > 0 arms a version-keyed LRU result cache
    (:class:`~deepfm_tpu.serve.cache.ResultCache`): a submit whose
    ``(ids, vals)`` bytes match a response already flushed under the
    CURRENT model version resolves immediately, bit-identical to the
    cached flush; hot swaps invalidate for free because the key carries
    the version. ``coalesce=True`` additionally attaches concurrent
    byte-identical requests to one in-flight leader future — one device
    execution fans out to every joined caller (typed, first-wins, with
    the leader refusing cancellation while it carries followers).
    ``submit(..., bypass_cache=True)`` opts a single request out of BOTH
    (lookup, insert, and coalescing) — the shadow lane's honesty hook.
    """

    #: ExperimentRouter probes this to route ``bypass_cache`` safely.
    supports_cache_bypass = True

    def __init__(self, predict_fn: Callable[[np.ndarray, np.ndarray],
                                            np.ndarray], *,
                 max_batch: int = 256, max_delay_ms: float = 5.0,
                 queue_rows: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 inflight: int = 2, small_rows: int = 0,
                 cache_rows: int = 0, cache_ttl_s: float = 0.0,
                 coalesce: bool = False,
                 stats: Optional[ServingStats] = None,
                 admission: Optional[AdmissionController] = None,
                 admission_kw: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        from ..utils import export as export_lib  # lazy: jax-heavy
        self._export = export_lib
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if inflight < 1:
            raise ValueError(f"inflight must be >= 1, got {inflight}")
        if small_rows < 0 or small_rows > max_batch:
            raise ValueError(
                f"small_rows must be in 0..max_batch={max_batch}, "
                f"got {small_rows}")
        self._fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.queue_rows_requested = int(queue_rows)
        self.queue_rows = int(queue_rows) if queue_rows else 8 * self.max_batch
        if self.queue_rows < self.max_batch:
            raise ValueError(
                f"queue_rows ({self.queue_rows}) must hold at least one "
                f"max_batch ({self.max_batch})")
        self.inflight = int(inflight)
        self.small_rows = int(small_rows)
        bucket_src = (buckets if buckets is not None
                      else export_lib.serving_buckets(self.max_batch))
        self.buckets = tuple(sorted({int(b) for b in bucket_src}
                                    | {self.max_batch}))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        if cache_rows < 0:
            raise ValueError(f"cache_rows must be >= 0, got {cache_rows}")
        if cache_ttl_s < 0:
            raise ValueError(f"cache_ttl_s must be >= 0, got {cache_ttl_s}")
        self.cache: Optional[ResultCache] = (
            ResultCache(cache_rows, ttl_s=cache_ttl_s, clock=clock)
            if cache_rows > 0 else None)
        self.coalesce = bool(coalesce)
        self._fp_lock = threading.Lock()
        self._inflight_fp: dict = {}   # fingerprint -> leader ServeFuture
        self.stats = stats if stats is not None else ServingStats(clock)
        self.stats.set_policy(
            serve_queue_rows=self.queue_rows,
            serve_queue_rows_auto=(self.queue_rows_requested == 0),
            serve_inflight=self.inflight,
            serve_small_rows=self.small_rows,
            serve_cache_rows=int(cache_rows),
            serve_cache_ttl_s=float(cache_ttl_s),
            serve_coalesce=self.coalesce)
        self._clock = clock
        # SLO-aware admission gate (optional). ``admission_kw`` builds a
        # controller bound to THIS engine's queue/stats/clock — the form
        # replica constructors use, so each replica gets its own gate
        # (pressure is per-queue; sharing one would gate on stale state).
        if admission is None and admission_kw:
            admission = AdmissionController(
                queue_rows=self.queue_rows, stats=self.stats, clock=clock,
                **admission_kw)
        self._admission = admission
        if admission is not None:
            if admission.stats is None:
                admission.stats = self.stats
            self.stats.set_policy(
                serve_shed_watermark=admission.shed_watermark,
                serve_slo_ms=admission.slo_ms)
        self._cond = threading.Condition()
        self._queue: deque = deque()        # large lane (FIFO)
        self._small: deque = deque()        # priority lane (FIFO, pops first)
        self._queued_rows = 0
        self._closing = False
        # Pipeline handoff: formed batches wait here for the executor, at
        # most `inflight` formed-but-uncompleted at any instant.
        self._exec_cond = threading.Condition()
        self._exec_queue: deque = deque()
        self._exec_inflight = 0             # handed off, not yet completed
        self._exec_done = False             # batcher exited; drain and stop
        self._watcher = None        # owned LatestWatcher (serve_latest)
        self._batcher: Optional[threading.Thread] = None
        self._executor: Optional[threading.Thread] = None
        if start:
            self.start()

    def __repr__(self) -> str:
        qr = (f"{self.queue_rows} (resolved from 0)"
              if self.queue_rows_requested == 0 else str(self.queue_rows))
        return (f"ServingEngine(max_batch={self.max_batch}, "
                f"max_delay_ms={self.max_delay_s * 1000.0:g}, "
                f"queue_rows={qr}, inflight={self.inflight}, "
                f"small_rows={self.small_rows}, buckets={self.buckets})")

    # ------------------------------------------------------- construction
    @classmethod
    def from_config(cls, cfg: Any, predict_fn: Callable,
                    **kw: Any) -> "ServingEngine":
        """Engine with the ``--serve_*`` policy of ``cfg``."""
        kw.setdefault("max_batch", cfg.serve_max_batch)
        kw.setdefault("max_delay_ms", cfg.serve_max_delay_ms)
        kw.setdefault("queue_rows", cfg.serve_queue_rows)
        kw.setdefault("inflight", cfg.serve_inflight)
        kw.setdefault("small_rows", cfg.serve_small_rows)
        kw.setdefault("cache_rows", cfg.serve_cache_rows)
        kw.setdefault("cache_ttl_s", cfg.serve_cache_ttl_s)
        kw.setdefault("coalesce", cfg.serve_coalesce)
        if cfg.serve_slo_ms > 0 or cfg.serve_shed_watermark > 0:
            kw.setdefault("admission_kw", {
                "slo_ms": cfg.serve_slo_ms,
                "shed_watermark": cfg.serve_shed_watermark})
        bucket_list = cfg.serve_bucket_sizes
        if bucket_list:
            kw.setdefault("buckets", bucket_list)
        return cls(predict_fn, **kw)

    @classmethod
    def serve_latest(cls, publish_dir: str, *, poll_secs: float = 2.0,
                     watcher_kw: Optional[dict] = None,
                     **kw: Any) -> "ServingEngine":
        """Engine following ``<publish_dir>/LATEST`` with hot swap.

        The watcher is owned: closed with the engine, and every swap it
        performs is stamped into the engine's stats (the blackout series,
        versioned — the blackout closes at the first flush that EXECUTED
        the new version). The watcher's loader is bucketed with the
        ENGINE's own ladder, so the pre-swap warm-up
        (``LatestWatcher._warm_buckets``) compiles exactly the shapes the
        engine will flush — the near-zero-blackout contract the serving
        drill asserts. (The engine pads flushes to the same buckets, so
        the inner BucketedPredict passes through.)
        """
        from ..utils import export as export_lib  # lazy: jax-heavy
        stats = kw.pop("stats", None) or ServingStats(
            kw.get("clock", time.monotonic))
        max_batch = int(kw.get("max_batch", 256))
        bucket_src = (kw.pop("buckets", None)
                      or export_lib.serving_buckets(max_batch))
        resolved = tuple(sorted({int(b) for b in bucket_src} | {max_batch}))
        wkw = dict(watcher_kw or {})
        wkw.setdefault("loader", lambda path: export_lib.load_serving(
            path, buckets=resolved))
        wkw.setdefault("on_error",
                       lambda exc: stats.record_watcher_error())
        # The watcher's initial check_once fires on_swap from inside
        # watch_latest, before the name `watcher` binds — the box carries
        # the late binding (the initial load is always version 1).
        box: list = []

        def _on_swap(path: str) -> None:
            version = box[0].swap_count if box else 1
            # Version 1 is the initial LOAD, not a hot swap: nothing was
            # served before it, so there is no response stream to black
            # out. (Under staggered replica bring-up, counting it would
            # report the fleet's slowest initial load as a fake blackout
            # on the fastest replica.)
            if version > 1:
                stats.record_swap(version)

        watcher = export_lib.watch_latest(
            publish_dir, poll_secs=poll_secs, on_swap=_on_swap, **wkw)
        box.append(watcher)
        engine = cls(watcher, stats=stats, buckets=resolved, **kw)
        engine._watcher = watcher
        return engine

    @property
    def watcher(self):
        return self._watcher

    @property
    def admission(self) -> Optional[AdmissionController]:
        return self._admission

    # ------------------------------------------------------------- client
    def submit(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
               trace_id: Optional[int] = None,
               value: str = VALUE_DEFAULT,
               bypass_cache: bool = False) -> ServeFuture:
        """Enqueue one request ``(ids[n,F], vals[n,F])``; returns its
        future. Requests of at most ``small_rows`` rows enter the priority
        lane. ``trace_id`` (see ``obs.trace.new_trace_id``) rides the
        future and is stamped into the flush's trace span for
        request→model-version correlation. ``value`` is the admission
        value class (lowest shed first under pressure; ignored without an
        admission controller). ``bypass_cache`` opts this request out of
        the result cache AND in-flight coalescing entirely (no lookup, no
        insert, no join — the shadow lane's honesty contract). Raises
        :class:`~deepfm_tpu.serve.admission.AdmissionShed` when the gate
        refuses the class, :class:`ServerOverloaded` when the queue is
        full or the engine is shutting down, ValueError on malformed
        shapes."""
        ids = np.asarray(feat_ids)
        vals = np.asarray(feat_vals)
        if ids.ndim != 2 or vals.shape != ids.shape:
            raise ValueError(
                f"expected feat_ids/feat_vals of one [n, F] shape, got "
                f"{ids.shape} / {vals.shape}")
        n = int(ids.shape[0])
        if not 1 <= n <= self.max_batch:
            raise ValueError(
                f"request of {n} rows outside 1..max_batch={self.max_batch} "
                "(split oversized requests client-side)")
        small = 0 < n <= self.small_rows
        fut = ServeFuture(ids, vals, self._clock(),
                          lane=LANE_SMALL if small else LANE_LARGE,
                          trace_id=trace_id, value=value)
        fut.cache_bypass = bool(bypass_cache)
        fast = (self.cache is not None or self.coalesce) \
            and not fut.cache_bypass
        if fast:
            # Fingerprint once; rides the future to the flush demux (the
            # cache insert point) and keys the in-flight coalesce registry.
            fut.fingerprint = request_fingerprint(ids, vals)
            if self.cache is not None:
                version = self._cache_version()
                hit = self.cache.get(version, fut.fingerprint)
                if hit is not None:
                    # Bit-identical to the flush that stored it; resolved
                    # here, before admission — a hit consumes no queue
                    # rows and no device time.
                    fut.cache_hit = True
                    fut.model_version = version
                    lat = 1000.0 * (self._clock() - fut.t_enqueue)
                    self.stats.record_cache_hit()
                    trace_lib.instant("serve.cache", event="hit", rows=n,
                                      trace_id=trace_id)
                    fut.set_result(hit, latency_ms=lat)
                    self.stats.record_request_done(lat, lane=fut.lane)
                    return fut
                self.stats.record_cache_miss()
            if self.coalesce:
                with self._fp_lock:
                    leader = self._inflight_fp.get(fut.fingerprint)
                if leader is not None and leader is not fut \
                        and leader.attach_follower(fut):
                    fut.coalesced = True
                    self.stats.record_coalesced()
                    trace_lib.instant("serve.cache", event="coalesce",
                                      rows=n, trace_id=trace_id)
                    leader.add_done_callback(
                        lambda done, f=fut: self._fan_out(done, f))
                    return fut
        with self._cond:
            if self._closing:
                self.stats.record_overload()
                raise ServerOverloaded("serving engine is shut down")
            if self._admission is not None:
                # Value-aware gate BEFORE the queue-full wall: under
                # pressure low classes get a typed AdmissionShed while the
                # queue still has room for high-value work.
                self._admission.admit(value, self._queued_rows)
            if self._queued_rows + n > self.queue_rows:
                self.stats.record_overload()
                raise ServerOverloaded(
                    f"request queue full ({self._queued_rows} rows pending, "
                    f"limit {self.queue_rows}); retry with backoff")
            (self._small if small else self._queue).append(fut)
            self._queued_rows += n
            self._cond.notify_all()
        if fast and self.coalesce:
            # Become the in-flight leader for this fingerprint AFTER the
            # enqueue succeeded (a refused request must never be joined).
            # Two racing identical submits can both enqueue — benign: the
            # later registration wins and future joins attach to it.
            with self._fp_lock:
                self._inflight_fp[fut.fingerprint] = fut
            fut.add_done_callback(self._fp_release)
        return fut

    def _fan_out(self, leader: ServeFuture, follower: ServeFuture) -> None:
        """Resolve one coalesced follower from its leader's resolution
        (runs on the resolving thread). Copies, so followers never alias
        the leader's arrays; errors propagate typed."""
        now = self._clock()
        lat = 1000.0 * (now - follower.t_enqueue)
        follower.model_version = leader.model_version
        if leader._error is not None:
            self.stats.record_request_failed()
            follower.set_error(leader._error)
            return
        probs = leader._probs
        if isinstance(probs, dict):
            probs = {k: np.array(v, copy=True) for k, v in probs.items()}
        else:
            probs = np.array(probs, copy=True)
        follower.set_result(probs, latency_ms=lat)
        self.stats.record_request_done(lat, lane=follower.lane)

    def _fp_release(self, fut: ServeFuture) -> None:
        """Leader resolved: retire its coalesce-registry entry (unless a
        newer leader already took the fingerprint over)."""
        with self._fp_lock:
            if self._inflight_fp.get(fut.fingerprint) is fut:
                self._inflight_fp.pop(fut.fingerprint, None)

    def _cache_version(self):
        """The cache key's model-version component for a request admitted
        NOW: the installed artifact step when one is known, else the
        watcher swap ordinal, else None (a plain static predict fn — one
        version forever). Matches what :meth:`_flush` stamps at insert, so
        a hot swap strands old entries unreachable (invalidated for
        free)."""
        step = self._model_step()
        if step is not None:
            return step
        current = getattr(self._fn, "current", None)
        if callable(current):
            return current()[1]
        return None

    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                timeout: Optional[float] = None,
                trace_id: Optional[int] = None,
                value: str = VALUE_DEFAULT) -> np.ndarray:
        """Synchronous convenience: ``submit().result()``."""
        return self.submit(feat_ids, feat_vals, trace_id=trace_id,
                           value=value).result(timeout)

    # ------------------------------------------------------------ batcher
    def start(self) -> "ServingEngine":
        if self._batcher is None:
            self._batcher = threading.Thread(
                target=self._run_batcher, name="serving-batcher", daemon=True)
            self._executor = threading.Thread(
                target=self._run_executor, name="serving-executor",
                daemon=True)
            self._batcher.start()
            self._executor.start()
        return self

    def _run_batcher(self) -> None:
        """Form flushes and hand them to the executor over the bounded
        in-flight window; while flush k executes, flush k+1 forms here."""
        while True:
            with trace_lib.span("serve.batch") as sp:
                batch, rows = self._collect()
                sp.add(rows=rows, requests=len(batch))
            if not batch:
                with self._exec_cond:
                    self._exec_done = True
                    self._exec_cond.notify_all()
                return  # closed and drained
            with trace_lib.span("serve.handoff_wait"), self._exec_cond:
                while self._exec_inflight >= self.inflight:
                    self._exec_cond.wait()
                self._exec_queue.append((batch, rows))
                self._exec_inflight += 1
                self._exec_cond.notify_all()

    def _run_executor(self) -> None:
        while True:
            with self._exec_cond:
                while not self._exec_queue and not self._exec_done:
                    self._exec_cond.wait()
                if not self._exec_queue:
                    return  # batcher exited and the pipeline is drained
                batch, rows = self._exec_queue.popleft()
            try:
                self._flush(batch, rows)
            finally:
                with self._exec_cond:
                    self._exec_inflight -= 1
                    self._exec_cond.notify_all()

    def _head_enqueue_time(self) -> float:
        """Earliest enqueue time across both lane heads (caller holds
        ``_cond`` and at least one lane is non-empty)."""
        heads = [q[0].t_enqueue for q in (self._small, self._queue) if q]
        return min(heads)

    def _collect(self) -> tuple:
        """Block until a flush is due; pop and return it. Empty = exit.

        The small lane has head-of-line bypass: it fills the batch FIRST,
        so a priority request is never stranded behind a max-batch fill of
        larges — worst case it waits out the flush currently forming plus
        the in-flight window, never a whole queue of large rows.
        """
        with self._cond:
            while True:
                while not (self._queue or self._small) and not self._closing:
                    self._cond.wait()
                if not (self._queue or self._small):
                    return [], 0
                if not self._closing and self.max_delay_s > 0:
                    # Deadline anchored at the FIRST queued request (either
                    # lane): a single request waits at most max_delay_ms. A
                    # full max_batch of rows arriving earlier preempts it.
                    deadline = self._head_enqueue_time() + self.max_delay_s
                    while self._queued_rows < self.max_batch \
                            and not self._closing:
                        remaining = deadline - self._clock()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                batch: List[ServeFuture] = []
                rows = 0
                dropped = 0     # cancelled rows popped but never flushed
                while self._small \
                        and rows + self._small[0].n <= self.max_batch:
                    fut = self._small.popleft()
                    if fut.cancelled():
                        dropped += fut.n
                        continue
                    rows += fut.n
                    batch.append(fut)
                while self._queue \
                        and rows + self._queue[0].n <= self.max_batch:
                    fut = self._queue.popleft()
                    if fut.cancelled():
                        dropped += fut.n
                        continue
                    rows += fut.n
                    batch.append(fut)
                self._queued_rows -= rows + dropped
                if not batch:
                    # Everything popped was a cancelled hedge loser — this
                    # is NOT the drained-shutdown signal; re-wait.
                    continue
                if self._admission is not None:
                    # Queue-delay signal: enqueue -> batch formation, the
                    # part of the SLO the gate can still protect.
                    now = self._clock()
                    for fut in batch:
                        self._admission.observe_delay(
                            1000.0 * (now - fut.t_enqueue))
                return batch, rows

    def _snapshot_fn(self) -> Tuple[Callable, Optional[int]]:
        """The predict fn to execute plus the model version it represents
        (``LatestWatcher.current()``); a plain fn has no version."""
        fn = self._fn
        current = getattr(fn, "current", None)
        if callable(current):
            return current()
        return fn, None

    def _model_step(self) -> Optional[int]:
        """Artifact step of the CURRENTLY installed model (the basename of
        ``LatestWatcher.current_path``); None for plain predict fns or
        non-numeric paths. Read race-tolerantly — a concurrent swap can
        move the path between flushes, and the span stamp is advisory."""
        path = getattr(self._fn, "current_path", None)
        if not path:
            return None
        try:
            return int(os.path.basename(os.path.normpath(path)))
        except (TypeError, ValueError):
            return None

    def _flush(self, batch: List[ServeFuture], rows: int) -> None:
        # Last-chance drop BEFORE any device work: a future cancelled (or
        # somehow resolved) after batch formation but before this flush
        # began — the hedge-loser race window — is filtered here, so a won
        # race never double-computes. Rows are re-counted; an emptied
        # flush costs nothing.
        live = [f for f in batch if not (f.cancelled() or f.done())]
        if len(live) != len(batch):
            trace_lib.instant("serve.flush_dropped",
                              requests=len(batch) - len(live))
            batch = live
            rows = sum(f.n for f in batch)
        if not batch:
            return
        if len(batch) == 1:
            ids, vals = batch[0].ids, batch[0].vals
        else:
            ids = np.concatenate([f.ids for f in batch])
            vals = np.concatenate([f.vals for f in batch])
        bucket = self._export.next_bucket(rows, self.buckets)
        fn, version = self._snapshot_fn()
        step = self._model_step()
        for fut in batch:
            # Published artifact step when the watcher serves a versioned
            # dir (what impressions correlate against); swap ordinal
            # otherwise.
            fut.model_version = step if step is not None else version
        sp = trace_lib.span("serve.flush", rows=rows, bucket=bucket,
                            requests=len(batch))
        if version is not None:
            sp.add(model_version=version)
        if step is not None:
            sp.add(model_step=step)
        tids = [f.trace_id for f in batch if f.trace_id is not None]
        if tids:
            sp.add(trace_ids=tids[:64])  # bounded per-event payload
        with sp:
            # Chaos seam: an armed executor_slow fault (utils.faults) adds
            # injected latency per flush — how the drill drives the
            # degradation ladder without depending on host speed.
            slow_s = faults_lib.executor_slow_delay()
            if slow_s > 0:
                trace_lib.instant("serve.executor_slow", delay_s=slow_s)
                time.sleep(slow_s)
            try:
                out = self._export.padded_predict(fn, ids, vals, self.buckets)
            except Exception as exc:  # noqa: BLE001 — forwarded per-request
                for fut in batch:
                    self.stats.record_request_failed()
                    fut.set_error(exc)
                return
            now = self._clock()
            off = 0
            cache_key = step if step is not None else version
            if isinstance(out, dict):
                # Multitask artifact: named per-task probability columns,
                # each sliced per request — futures resolve with
                # {task: probs[n]}.
                named = {k: np.asarray(v) for k, v in out.items()}
                for fut in batch:
                    # Record the latency computed HERE, not fut.latency_ms:
                    # a future something else already resolved (a hedged
                    # loser mid-flush) keeps its first-wins stamp and this
                    # set_result is a no-op.
                    lat = 1000.0 * (now - fut.t_enqueue)
                    sliced = {k: v[off:off + fut.n]
                              for k, v in named.items()}
                    self._cache_insert(fut, cache_key, sliced)
                    fut.set_result(sliced, latency_ms=lat)
                    off += fut.n
                    self.stats.record_request_done(lat, lane=fut.lane)
            else:
                # Single-output: the historical wire shape [n], bit-unchanged.
                probs = np.asarray(out).reshape(-1)
                for fut in batch:
                    lat = 1000.0 * (now - fut.t_enqueue)
                    sliced = probs[off:off + fut.n]
                    self._cache_insert(fut, cache_key, sliced)
                    fut.set_result(sliced, latency_ms=lat)
                    off += fut.n
                    self.stats.record_request_done(lat, lane=fut.lane)
            self.stats.record_flush(rows, bucket,
                                    full=rows >= self.max_batch,
                                    version=version)

    def _cache_insert(self, fut: ServeFuture, cache_key, value) -> None:
        """Store one demuxed response under the version that EXECUTED it
        (insert-side half of the version-keyed contract). Bypass futures
        carry no fingerprint, so the shadow lane neither reads nor warms
        the cache."""
        if self.cache is not None and fut.fingerprint is not None:
            self.cache.put(cache_key, fut.fingerprint, value, fut.n)

    # ---------------------------------------------------------- lifecycle
    @property
    def pending_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop admitting, DRAIN the queue and the in-flight pipeline
        (every admitted request gets its response), join both threads,
        close an owned watcher."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._batcher is not None:
            self._batcher.join(timeout=timeout)
            self._batcher = None
        if self._executor is not None:
            self._executor.join(timeout=timeout)
            self._executor = None
        if self._watcher is not None:
            self._watcher.close()

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
