"""TPU-native serving engine: queue → dynamic batcher → bucketed predict.

The reference delegated serving to TF-Serving (``2-hvd-gpu/...py:429-431``
exports, a managed endpoint batches); this module is the in-repo engine that
closes the train→publish→serve loop. One device-owning process runs:

  * a **bounded request queue** — ``submit()`` admits up to
    ``queue_rows`` pending rows and then raises a typed
    :class:`ServerOverloaded` (backpressure a frontend can convert to a 429,
    never a hang);
  * a **dynamic batcher** — one flush thread waits for the first request,
    then collects until ``max_batch`` rows arrive (max-batch policy,
    preempts the deadline) or ``max_delay_ms`` elapses since the FIRST
    queued request (deadline policy — a lone request is never stranded);
  * **bucketed batch shapes** — each flush pads to the next bucket
    (``utils.export.padded_predict``), so at most ``len(buckets)`` predict
    programs ever compile no matter what sizes traffic brings;
  * a **response demux** — padding stripped, per-request futures resolved
    with per-request latency stamps (admission → resolution). The demux is
    shape-agnostic: a single-output model resolves each future with probs
    ``[n]`` (the historical wire shape, unchanged), a multitask artifact
    with a ``{task_name: probs[n]}`` dict — whatever structure the predict
    fn returns, rows are sliced per request.

Hot swap rides the existing :class:`~deepfm_tpu.utils.export.LatestWatcher`:
pass a watcher as ``predict_fn`` (or use :meth:`ServingEngine.serve_latest`)
and a newly published artifact is loaded off to the side and swapped in with
one assignment — the flush that is executing keeps the function reference it
already read, so in-flight batches finish on the old model and no request is
ever dropped or failed by a swap. A failed load keeps the current model
(``LatestWatcher.swap_failures`` counts it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .stats import ServingStats


class ServerOverloaded(RuntimeError):
    """The bounded request queue is full (or the engine is shut down).

    The typed backpressure signal: callers retry with backoff or shed load;
    the engine never blocks a submitter and never silently drops a request.
    """


class ServeFuture:
    """One request's pending result: resolved by the batcher's demux."""

    __slots__ = ("ids", "vals", "n", "t_enqueue", "latency_ms",
                 "_event", "_probs", "_error")

    def __init__(self, ids: np.ndarray, vals: np.ndarray, t_enqueue: float):
        self.ids = ids
        self.vals = vals
        self.n = int(ids.shape[0])
        self.t_enqueue = t_enqueue
        self.latency_ms: Optional[float] = None
        self._event = threading.Event()
        self._probs: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, probs: np.ndarray, latency_ms: float) -> None:
        self._probs = probs
        self.latency_ms = latency_ms
        self._event.set()

    def set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the probs — ``[n]`` for single-output models,
        ``{task_name: [n]}`` for multitask artifacts; raises the predict
        error if the flush failed, TimeoutError if not resolved in
        ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request of {self.n} rows unresolved after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._probs


class ServingEngine:
    """Bounded queue + dynamic batcher + bucketed jitted predict + demux."""

    def __init__(self, predict_fn: Callable[[np.ndarray, np.ndarray],
                                            np.ndarray], *,
                 max_batch: int = 256, max_delay_ms: float = 5.0,
                 queue_rows: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 stats: Optional[ServingStats] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        from ..utils import export as export_lib  # lazy: jax-heavy
        self._export = export_lib
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        self._fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.queue_rows = int(queue_rows) if queue_rows else 8 * self.max_batch
        if self.queue_rows < self.max_batch:
            raise ValueError(
                f"queue_rows ({self.queue_rows}) must hold at least one "
                f"max_batch ({self.max_batch})")
        bucket_src = (buckets if buckets is not None
                      else export_lib.serving_buckets(self.max_batch))
        self.buckets = tuple(sorted({int(b) for b in bucket_src}
                                    | {self.max_batch}))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        self.stats = stats if stats is not None else ServingStats(clock)
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._queued_rows = 0
        self._closing = False
        self._watcher = None        # owned LatestWatcher (serve_latest)
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------- construction
    @classmethod
    def from_config(cls, cfg: Any, predict_fn: Callable,
                    **kw: Any) -> "ServingEngine":
        """Engine with the ``--serve_*`` policy of ``cfg``."""
        kw.setdefault("max_batch", cfg.serve_max_batch)
        kw.setdefault("max_delay_ms", cfg.serve_max_delay_ms)
        kw.setdefault("queue_rows", cfg.serve_queue_rows)
        bucket_list = cfg.serve_bucket_sizes
        if bucket_list:
            kw.setdefault("buckets", bucket_list)
        return cls(predict_fn, **kw)

    @classmethod
    def serve_latest(cls, publish_dir: str, *, poll_secs: float = 2.0,
                     watcher_kw: Optional[dict] = None,
                     **kw: Any) -> "ServingEngine":
        """Engine following ``<publish_dir>/LATEST`` with hot swap.

        The watcher is owned: closed with the engine, and every swap it
        performs is stamped into the engine's stats (the blackout series).
        The watcher's loader is bucketed with the ENGINE's own ladder, so
        the pre-swap warm-up (``LatestWatcher._warm_buckets``) compiles
        exactly the shapes the engine will flush — the near-zero-blackout
        contract the serving drill asserts. (The engine pads flushes to
        the same buckets, so the inner BucketedPredict passes through.)
        """
        from ..utils import export as export_lib  # lazy: jax-heavy
        stats = kw.pop("stats", None) or ServingStats(
            kw.get("clock", time.monotonic))
        max_batch = int(kw.get("max_batch", 256))
        bucket_src = (kw.pop("buckets", None)
                      or export_lib.serving_buckets(max_batch))
        resolved = tuple(sorted({int(b) for b in bucket_src} | {max_batch}))
        wkw = dict(watcher_kw or {})
        wkw.setdefault("loader", lambda path: export_lib.load_serving(
            path, buckets=resolved))
        wkw.setdefault("on_error",
                       lambda exc: stats.record_watcher_error())
        watcher = export_lib.watch_latest(
            publish_dir, poll_secs=poll_secs,
            on_swap=lambda path: stats.record_swap(),
            **wkw)
        engine = cls(watcher, stats=stats, buckets=resolved, **kw)
        engine._watcher = watcher
        return engine

    @property
    def watcher(self):
        return self._watcher

    # ------------------------------------------------------------- client
    def submit(self, feat_ids: np.ndarray,
               feat_vals: np.ndarray) -> ServeFuture:
        """Enqueue one request ``(ids[n,F], vals[n,F])``; returns its
        future. Raises :class:`ServerOverloaded` when the queue is full or
        the engine is shutting down, ValueError on malformed shapes."""
        ids = np.asarray(feat_ids)
        vals = np.asarray(feat_vals)
        if ids.ndim != 2 or vals.shape != ids.shape:
            raise ValueError(
                f"expected feat_ids/feat_vals of one [n, F] shape, got "
                f"{ids.shape} / {vals.shape}")
        n = int(ids.shape[0])
        if not 1 <= n <= self.max_batch:
            raise ValueError(
                f"request of {n} rows outside 1..max_batch={self.max_batch} "
                "(split oversized requests client-side)")
        fut = ServeFuture(ids, vals, self._clock())
        with self._cond:
            if self._closing:
                self.stats.record_overload()
                raise ServerOverloaded("serving engine is shut down")
            if self._queued_rows + n > self.queue_rows:
                self.stats.record_overload()
                raise ServerOverloaded(
                    f"request queue full ({self._queued_rows} rows pending, "
                    f"limit {self.queue_rows}); retry with backoff")
            self._queue.append(fut)
            self._queued_rows += n
            self._cond.notify_all()
        return fut

    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: ``submit().result()``."""
        return self.submit(feat_ids, feat_vals).result(timeout)

    # ------------------------------------------------------------ batcher
    def start(self) -> "ServingEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="serving-batcher", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            batch, rows = self._collect()
            if not batch:
                return  # closed and drained
            self._flush(batch, rows)

    def _collect(self) -> tuple:
        """Block until a flush is due; pop and return it. Empty = exit."""
        with self._cond:
            while not self._queue and not self._closing:
                self._cond.wait()
            if not self._queue:
                return [], 0
            if not self._closing and self.max_delay_s > 0:
                # Deadline anchored at the FIRST queued request: a single
                # request waits at most max_delay_ms. A full max_batch of
                # rows arriving earlier preempts the deadline.
                deadline = self._queue[0].t_enqueue + self.max_delay_s
                while self._queued_rows < self.max_batch \
                        and not self._closing:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            batch: List[ServeFuture] = []
            rows = 0
            while self._queue and rows + self._queue[0].n <= self.max_batch:
                fut = self._queue.popleft()
                rows += fut.n
                batch.append(fut)
            self._queued_rows -= rows
            return batch, rows

    def _flush(self, batch: List[ServeFuture], rows: int) -> None:
        if len(batch) == 1:
            ids, vals = batch[0].ids, batch[0].vals
        else:
            ids = np.concatenate([f.ids for f in batch])
            vals = np.concatenate([f.vals for f in batch])
        bucket = self._export.next_bucket(rows, self.buckets)
        try:
            out = self._export.padded_predict(
                self._fn, ids, vals, self.buckets)
        except Exception as exc:  # noqa: BLE001 — forwarded per-request
            for fut in batch:
                self.stats.record_request_failed()
                fut.set_error(exc)
            return
        now = self._clock()
        off = 0
        if isinstance(out, dict):
            # Multitask artifact: named per-task probability columns, each
            # sliced per request — futures resolve with {task: probs[n]}.
            named = {k: np.asarray(v) for k, v in out.items()}
            for fut in batch:
                fut.set_result(
                    {k: v[off:off + fut.n] for k, v in named.items()},
                    latency_ms=1000.0 * (now - fut.t_enqueue))
                off += fut.n
                self.stats.record_request_done(fut.latency_ms)
        else:
            # Single-output: the historical wire shape [n], bit-unchanged.
            probs = np.asarray(out).reshape(-1)
            for fut in batch:
                fut.set_result(probs[off:off + fut.n],
                               latency_ms=1000.0 * (now - fut.t_enqueue))
                off += fut.n
                self.stats.record_request_done(fut.latency_ms)
        self.stats.record_flush(rows, bucket, full=rows >= self.max_batch)

    # ---------------------------------------------------------- lifecycle
    @property
    def pending_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop admitting, DRAIN the queue (every admitted request gets its
        response), join the batcher, close an owned watcher."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._watcher is not None:
            self._watcher.close()

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
