"""Serving-side observability: latency/QPS/occupancy/swap accounting.

The serving mirror of ``data.health.DataHealth`` / ``train.guard.TrainHealth``
— one thread-safe object every layer of the serving runtime stamps into, and
one ``summary()`` dict the drill and ``bench.py``'s ``serving`` series read.
All timestamps come from an injectable ``clock`` so tests are sleep-free.

What the fields mean (the contract ``SERVING_r0*.json`` reports):

  * ``serving_p50_ms`` / ``serving_p99_ms`` — per-request latency from
    ``submit()`` admission to future resolution (queue wait + batch wait +
    predict + demux; the number a client actually experiences).
  * ``serving_small_p50_ms`` / ``serving_small_p99_ms`` (and the ``large``
    pair) — the same latency split by priority lane. The small lane exists
    so a cheap request never queues behind a max-batch fill; its p99 staying
    at or under the global p99 is the lane's whole job (tier-1 smoke).
  * ``serving_qps`` — completed requests over the first→last completion
    window (steady-state, not including warm-up idle).
  * ``batch_occupancy_pct`` — real rows over padded bucket rows across all
    flushes: 100% means every flush exactly filled its bucket; low values
    mean the deadline fires before batches fill (see TUNING §2.10).
  * ``swap_blackout_ms`` — worst-case time from a hot model swap to the
    first completed flush that EXECUTED the new model version. Flushes are
    stamped with the model version that ran them, so a pre-swap flush
    completing after the swap (normal under pipelined batching) does not
    close the window early. Near-zero is the design goal: the new model
    loads and pre-warms off to the side, so a swap should never stall the
    response stream.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import metrics as metrics_lib

#: Lane names the engine stamps requests with. "small" is the priority lane
#: (row count <= --serve_small_rows); everything else is "large".
LANE_SMALL = "small"
LANE_LARGE = "large"


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingStats:
    """Thread-safe counters + latency reservoir for one serving engine."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.requests_completed = 0
        self.requests_failed = 0
        self.rows_completed = 0
        self.overloads = 0            # typed ServerOverloaded rejections
        self.flushes = 0
        self.padded_rows = 0          # sum of bucket sizes over flushes
        self.real_rows = 0            # sum of real rows over flushes
        self.max_batch_flushes = 0    # flushes that filled max_batch rows
        self.deadline_flushes = 0     # flushes fired by the delay deadline
        self.watcher_errors = 0       # LatestWatcher poll-loop exceptions
        # Overload-plane accounting (admission/hedging/degradation). The
        # reconciliation identity the flood harness asserts:
        #   offered == completed + failed + overloads + sheds.
        self.sheds = 0                # typed AdmissionShed rejections
        self.sheds_by_class: Dict[str, int] = {}
        self.admission_transitions = 0
        self.admission_level = 0      # last shed level the gate entered
        self.hedges_fired = 0         # hedge submitted to another replica
        self.hedges_won = 0           # hedge resolved before the primary
        self.hedges_cancelled = 0     # losing leg cancelled after a win
        # Fast-path accounting (serve/cache.py): a hit resolves at submit
        # without touching the batcher; a coalesced join attaches to an
        # in-flight leader and fans out from its flush. Both ALSO count in
        # requests_completed (they are answered requests); these counters
        # say how many were answered without device work of their own.
        self.cache_hits = 0
        self.cache_misses = 0         # cache armed, lookup missed
        self.coalesced = 0            # joins attached to an in-flight leader
        self.degraded_by_rung: Dict[str, int] = {}
        self.degrade_transitions = 0
        self.latencies_ms: List[float] = []
        self.lane_latencies_ms: Dict[str, List[float]] = {
            LANE_SMALL: [], LANE_LARGE: []}
        self.swap_blackouts_ms: List[float] = []
        # Resolved engine policy, stamped by the engine at construction so
        # the summary self-documents the configuration that produced it
        # (the implicit serve_queue_rows=0 -> 8*max_batch resolution made
        # the effective bound invisible before).
        self.policy: Dict[str, Any] = {}
        self._first_done: Optional[float] = None
        self._last_done: Optional[float] = None
        self._swap_at: Optional[float] = None
        self._swap_version: Optional[int] = None
        # Unified registry (obs.metrics): the existing summary() IS this
        # object's metric surface; registration is one weakref'd entry.
        metrics_lib.auto_register("serving", self)

    # ------------------------------------------------------------- stamps
    def set_policy(self, **kw: Any) -> None:
        """Record resolved engine policy (queue_rows, inflight, ...)."""
        with self._lock:
            self.policy.update(kw)

    def record_request_done(self, latency_ms: float,
                            lane: str = LANE_LARGE) -> None:
        with self._lock:
            self.requests_completed += 1
            self.latencies_ms.append(float(latency_ms))
            self.lane_latencies_ms.setdefault(lane, []).append(
                float(latency_ms))

    def record_request_failed(self) -> None:
        with self._lock:
            self.requests_failed += 1

    def record_overload(self) -> None:
        with self._lock:
            self.overloads += 1

    def record_shed(self, value_class: str) -> None:
        """Admission gate refused one request's value class (typed
        AdmissionShed — a policy refusal, not a full queue)."""
        with self._lock:
            self.sheds += 1
            self.sheds_by_class[value_class] = \
                self.sheds_by_class.get(value_class, 0) + 1

    def record_admission_transition(self, level: int) -> None:
        """The admission hysteresis ladder moved to ``level``."""
        with self._lock:
            self.admission_transitions += 1
            self.admission_level = int(level)

    def record_hedge_fired(self) -> None:
        with self._lock:
            self.hedges_fired += 1

    def record_hedge_won(self) -> None:
        with self._lock:
            self.hedges_won += 1

    def record_hedge_cancelled(self) -> None:
        with self._lock:
            self.hedges_cancelled += 1

    def record_cache_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def record_cache_miss(self) -> None:
        with self._lock:
            self.cache_misses += 1

    def record_coalesced(self) -> None:
        with self._lock:
            self.coalesced += 1

    def record_degraded(self, rung: str) -> None:
        """One request answered at a degraded cascade rung (reduced
        retrieve_k, or retrieval-only with the ranker skipped)."""
        with self._lock:
            self.degraded_by_rung[rung] = \
                self.degraded_by_rung.get(rung, 0) + 1

    def record_degrade_transition(self, rung: str) -> None:
        with self._lock:
            self.degrade_transitions += 1

    def record_flush(self, rows: int, bucket: int, *, full: bool = False,
                     version: Optional[int] = None) -> None:
        """One batch flushed through predict: ``rows`` real rows padded to
        ``bucket``. ``full`` = the max-batch policy fired (vs deadline).
        ``version`` = the model version (watcher swap_count) that EXECUTED
        this flush; under pipelined batching a pre-swap flush may complete
        after the swap, and only a flush of the new version may close the
        blackout window. None (no versioned predict fn) keeps the legacy
        swap→next-completed-flush measure."""
        now = self._clock()
        with self._lock:
            self.flushes += 1
            self.real_rows += int(rows)
            self.rows_completed += int(rows)
            self.padded_rows += int(bucket)
            if full:
                self.max_batch_flushes += 1
            else:
                self.deadline_flushes += 1
            if self._first_done is None:
                self._first_done = now
            if self._swap_at is not None and (
                    version is None or self._swap_version is None
                    or version >= self._swap_version):
                self.swap_blackouts_ms.append(
                    1000.0 * max(0.0, now - self._swap_at))
                self._swap_at = None
                self._swap_version = None
            self._last_done = now

    def record_watcher_error(self) -> None:
        """The LATEST poll loop hit an unexpected exception (and kept the
        current model). Alive-but-failing watchers must be visible."""
        with self._lock:
            self.watcher_errors += 1

    def record_swap(self, version: Optional[int] = None) -> None:
        """A hot model swap happened; the first flush that executed model
        ``version`` (or newer) closes the blackout window. Without a
        version, any next flush closes it (the pre-pipelining measure,
        which under-counts when an old-model flush lands post-swap)."""
        with self._lock:
            if self._swap_at is None:
                self._swap_at = self._clock()
                self._swap_version = version

    # ------------------------------------------------------------ summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            window = None
            if (self._first_done is not None and self._last_done is not None
                    and self._last_done > self._first_done):
                window = self._last_done - self._first_done
            # Zero completed requests is a VALID summary (a fleet that
            # served nothing — e.g. a challenger replica behind a 0% split
            # or a drained canary): 0 QPS, None percentiles, no raise. None
            # QPS is reserved for "requests exist but the window is
            # degenerate" (a single completion instant).
            if window:
                qps = self.requests_completed / window
            else:
                qps = 0.0 if self.requests_completed == 0 else None
            occupancy = (100.0 * self.real_rows / self.padded_rows
                         if self.padded_rows else None)
            small = self.lane_latencies_ms.get(LANE_SMALL, [])
            large = self.lane_latencies_ms.get(LANE_LARGE, [])
            out = {
                "serving_requests": self.requests_completed,
                "serving_failed": self.requests_failed,
                "serving_overloads": self.overloads,
                "serving_rows": self.rows_completed,
                "serving_p50_ms": _pct(self.latencies_ms, 50),
                "serving_p99_ms": _pct(self.latencies_ms, 99),
                "serving_small_requests": len(small),
                "serving_small_p50_ms": _pct(small, 50),
                "serving_small_p99_ms": _pct(small, 99),
                "serving_large_p50_ms": _pct(large, 50),
                "serving_large_p99_ms": _pct(large, 99),
                "serving_qps": round(qps, 1) if qps is not None else None,
                "batch_occupancy_pct": (round(occupancy, 2)
                                        if occupancy is not None else None),
                "serving_flushes": self.flushes,
                "serving_rows_per_flush": (
                    round(self.real_rows / self.flushes, 2)
                    if self.flushes else None),
                "serving_max_batch_flushes": self.max_batch_flushes,
                "serving_deadline_flushes": self.deadline_flushes,
                "serving_watcher_errors": self.watcher_errors,
                "serving_sheds": self.sheds,
                "serving_sheds_by_class": dict(self.sheds_by_class),
                "admission_level": self.admission_level,
                "admission_transitions": self.admission_transitions,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "hedges_cancelled": self.hedges_cancelled,
                "serving_cache_hits": self.cache_hits,
                "serving_cache_misses": self.cache_misses,
                "serving_cache_hit_rate": (
                    round(self.cache_hits
                          / (self.cache_hits + self.cache_misses), 4)
                    if (self.cache_hits + self.cache_misses) else None),
                "serving_coalesced": self.coalesced,
                "serving_degraded": sum(self.degraded_by_rung.values()),
                "serving_degraded_by_rung": dict(self.degraded_by_rung),
                "degrade_transitions": self.degrade_transitions,
                "swap_blackout_ms": (
                    round(max(self.swap_blackouts_ms), 3)
                    if self.swap_blackouts_ms else None),
            }
            out.update(self.policy)
            return out


def aggregate_summary(stats: Sequence[ServingStats]) -> Dict[str, Any]:
    """Fleet-level summary over N replicas' stats.

    Percentiles are computed over the CONCATENATED latency reservoirs (a
    true fleet percentile, not an average of per-replica percentiles); QPS
    uses the union completion window (earliest first-done → latest
    last-done), so overlapping replicas aggregate instead of double-count;
    blackout reports the worst replica (the fleet gate is per-replica, and
    staggered swaps mean the FLEET never sees them all at once — that claim
    lives with the swap coordinator, not here).
    """
    # Materialize first: a generator argument would be consumed by the
    # accumulation loop and then re-counted as replicas=0 below (and an
    # EMPTY fleet — or one that served nothing — must still summarize to
    # 0 QPS / None percentiles, never raise).
    stats = list(stats)
    lat: List[float] = []
    small: List[float] = []
    large: List[float] = []
    blackout: List[Optional[float]] = []
    watcher_errs: List[int] = []
    totals = {"serving_requests": 0, "serving_failed": 0,
              "serving_overloads": 0, "serving_rows": 0,
              "serving_flushes": 0, "serving_watcher_errors": 0,
              "serving_sheds": 0, "hedges_fired": 0, "hedges_won": 0,
              "hedges_cancelled": 0, "serving_cache_hits": 0,
              "serving_cache_misses": 0, "serving_coalesced": 0,
              "serving_degraded": 0,
              "degrade_transitions": 0, "admission_transitions": 0}
    sheds_by_class: Dict[str, int] = {}
    degraded_by_rung: Dict[str, int] = {}
    first_done: Optional[float] = None
    last_done: Optional[float] = None
    real_rows = padded_rows = 0
    for s in stats:
        with s._lock:
            lat.extend(s.latencies_ms)
            small.extend(s.lane_latencies_ms.get(LANE_SMALL, []))
            large.extend(s.lane_latencies_ms.get(LANE_LARGE, []))
            blackout.append(max(s.swap_blackouts_ms)
                            if s.swap_blackouts_ms else None)
            totals["serving_requests"] += s.requests_completed
            totals["serving_failed"] += s.requests_failed
            totals["serving_overloads"] += s.overloads
            totals["serving_rows"] += s.rows_completed
            totals["serving_flushes"] += s.flushes
            totals["serving_watcher_errors"] += s.watcher_errors
            totals["serving_sheds"] += s.sheds
            totals["hedges_fired"] += s.hedges_fired
            totals["hedges_won"] += s.hedges_won
            totals["hedges_cancelled"] += s.hedges_cancelled
            totals["serving_cache_hits"] += s.cache_hits
            totals["serving_cache_misses"] += s.cache_misses
            totals["serving_coalesced"] += s.coalesced
            totals["serving_degraded"] += sum(s.degraded_by_rung.values())
            totals["degrade_transitions"] += s.degrade_transitions
            totals["admission_transitions"] += s.admission_transitions
            for cls, count in s.sheds_by_class.items():
                sheds_by_class[cls] = sheds_by_class.get(cls, 0) + count
            for rung, count in s.degraded_by_rung.items():
                degraded_by_rung[rung] = degraded_by_rung.get(rung, 0) + count
            watcher_errs.append(s.watcher_errors)
            real_rows += s.real_rows
            padded_rows += s.padded_rows
            if s._first_done is not None:
                first_done = (s._first_done if first_done is None
                              else min(first_done, s._first_done))
            if s._last_done is not None:
                last_done = (s._last_done if last_done is None
                             else max(last_done, s._last_done))
    window = None
    if (first_done is not None and last_done is not None
            and last_done > first_done):
        window = last_done - first_done
    if window:
        qps = totals["serving_requests"] / window
    else:
        qps = 0.0 if totals["serving_requests"] == 0 else None
    known_blackouts = [b for b in blackout if b is not None]
    looked_up = (totals["serving_cache_hits"]
                 + totals["serving_cache_misses"])
    out = dict(totals)
    out.update({
        "replicas": len(stats),
        "serving_cache_hit_rate": (
            round(totals["serving_cache_hits"] / looked_up, 4)
            if looked_up else None),
        "serving_p50_ms": _pct(lat, 50),
        "serving_p99_ms": _pct(lat, 99),
        "serving_small_requests": len(small),
        "serving_small_p50_ms": _pct(small, 50),
        "serving_small_p99_ms": _pct(small, 99),
        "serving_large_p50_ms": _pct(large, 50),
        "serving_large_p99_ms": _pct(large, 99),
        "serving_qps": round(qps, 1) if qps is not None else None,
        "batch_occupancy_pct": (round(100.0 * real_rows / padded_rows, 2)
                                if padded_rows else None),
        "swap_blackout_ms": (round(max(known_blackouts), 3)
                             if known_blackouts else None),
        "serving_sheds_by_class": sheds_by_class,
        "serving_degraded_by_rung": degraded_by_rung,
        "swap_blackout_ms_per_replica": [
            round(b, 3) if b is not None else None for b in blackout],
        # Per-replica fault visibility: an alive-but-failing watcher on ONE
        # replica is invisible in the fleet total when the others are clean.
        "serving_watcher_errors_per_replica": watcher_errs,
    })
    return out
