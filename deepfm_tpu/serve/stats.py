"""Serving-side observability: latency/QPS/occupancy/swap accounting.

The serving mirror of ``data.health.DataHealth`` / ``train.guard.TrainHealth``
— one thread-safe object every layer of the serving runtime stamps into, and
one ``summary()`` dict the drill and ``bench.py``'s ``serving`` series read.
All timestamps come from an injectable ``clock`` so tests are sleep-free.

What the fields mean (the contract ``SERVING_r0*.json`` reports):

  * ``serving_p50_ms`` / ``serving_p99_ms`` — per-request latency from
    ``submit()`` admission to future resolution (queue wait + batch wait +
    predict + demux; the number a client actually experiences).
  * ``serving_qps`` — completed requests over the first→last completion
    window (steady-state, not including warm-up idle).
  * ``batch_occupancy_pct`` — real rows over padded bucket rows across all
    flushes: 100% means every flush exactly filled its bucket; low values
    mean the deadline fires before batches fill (see TUNING §2.10).
  * ``swap_blackout_ms`` — worst-case time from a hot model swap to the
    next completed flush. Near-zero is the design goal: the new model loads
    off to the side, so a swap should never stall the response stream.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingStats:
    """Thread-safe counters + latency reservoir for one serving engine."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.requests_completed = 0
        self.requests_failed = 0
        self.rows_completed = 0
        self.overloads = 0            # typed ServerOverloaded rejections
        self.flushes = 0
        self.padded_rows = 0          # sum of bucket sizes over flushes
        self.real_rows = 0            # sum of real rows over flushes
        self.max_batch_flushes = 0    # flushes that filled max_batch rows
        self.deadline_flushes = 0     # flushes fired by the delay deadline
        self.watcher_errors = 0       # LatestWatcher poll-loop exceptions
        self.latencies_ms: List[float] = []
        self.swap_blackouts_ms: List[float] = []
        self._first_done: Optional[float] = None
        self._last_done: Optional[float] = None
        self._swap_at: Optional[float] = None

    # ------------------------------------------------------------- stamps
    def record_request_done(self, latency_ms: float) -> None:
        with self._lock:
            self.requests_completed += 1
            self.latencies_ms.append(float(latency_ms))

    def record_request_failed(self) -> None:
        with self._lock:
            self.requests_failed += 1

    def record_overload(self) -> None:
        with self._lock:
            self.overloads += 1

    def record_flush(self, rows: int, bucket: int, *,
                     full: bool = False) -> None:
        """One batch flushed through predict: ``rows`` real rows padded to
        ``bucket``. ``full`` = the max-batch policy fired (vs deadline)."""
        now = self._clock()
        with self._lock:
            self.flushes += 1
            self.real_rows += int(rows)
            self.rows_completed += int(rows)
            self.padded_rows += int(bucket)
            if full:
                self.max_batch_flushes += 1
            else:
                self.deadline_flushes += 1
            if self._first_done is None:
                self._first_done = now
            if self._swap_at is not None:
                self.swap_blackouts_ms.append(
                    1000.0 * max(0.0, now - self._swap_at))
                self._swap_at = None
            self._last_done = now

    def record_watcher_error(self) -> None:
        """The LATEST poll loop hit an unexpected exception (and kept the
        current model). Alive-but-failing watchers must be visible."""
        with self._lock:
            self.watcher_errors += 1

    def record_swap(self) -> None:
        """A hot model swap happened; the next flush closes the blackout
        window (time the response stream went without a completion)."""
        with self._lock:
            if self._swap_at is None:
                self._swap_at = self._clock()

    # ------------------------------------------------------------ summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            window = None
            if (self._first_done is not None and self._last_done is not None
                    and self._last_done > self._first_done):
                window = self._last_done - self._first_done
            qps = (self.requests_completed / window if window else None)
            occupancy = (100.0 * self.real_rows / self.padded_rows
                         if self.padded_rows else None)
            return {
                "serving_requests": self.requests_completed,
                "serving_failed": self.requests_failed,
                "serving_overloads": self.overloads,
                "serving_rows": self.rows_completed,
                "serving_p50_ms": _pct(self.latencies_ms, 50),
                "serving_p99_ms": _pct(self.latencies_ms, 99),
                "serving_qps": round(qps, 1) if qps is not None else None,
                "batch_occupancy_pct": (round(occupancy, 2)
                                        if occupancy is not None else None),
                "serving_flushes": self.flushes,
                "serving_rows_per_flush": (
                    round(self.real_rows / self.flushes, 2)
                    if self.flushes else None),
                "serving_max_batch_flushes": self.max_batch_flushes,
                "serving_deadline_flushes": self.deadline_flushes,
                "serving_watcher_errors": self.watcher_errors,
                "swap_blackout_ms": (
                    round(max(self.swap_blackouts_ms), 3)
                    if self.swap_blackouts_ms else None),
            }
