"""Multi-process serving frontend: N client processes, one device owner.

A TPU chip belongs to one process; request traffic comes from many. This
module reuses the :mod:`deepfm_tpu.data.shm_ring` SPSC slab machinery (the
input service's transport) to let N client processes feed the one
device-owning server process without pickling a row:

  * per client, a **request ring** (client→server; ids/vals written straight
    into the slab) and a **response ring** (server→client; probs in the
    slab's label array, ``field_size=1`` so the segment stays small);
  * the server loop drains request rings round-robin into the
    :class:`~deepfm_tpu.serve.engine.ServingEngine` (copying rows out of the
    slab so the slot recycles immediately), and writes responses as the
    engine's futures resolve — demuxed by per-client ``req_id``, so clients
    may pipeline;
  * **backpressure end to end** — a full request ring blocks the client's
    ``acquire`` (bounded, timeout → typed error) and a full engine queue
    comes back as an ``("err", ..., "ServerOverloaded", ...)`` response;
  * **crash-safe shutdown** — clients announce ``bye``; the server retires
    them and exits when every client left and no response is owed. A client
    that dies WITHOUT a farewell is detected via the injectable
    ``client_alive`` probe when its response ring stops draining: its
    responses are dropped and it is retired (the input-worker death-policy
    analog).
  * **wedge detection** — a :class:`~deepfm_tpu.train.guard.StallWatchdog`
    beats on every served response (and while fully idle); a predict or a
    response write wedged past ``timeout_s`` aborts with the exit-43
    contract from ``utils/preempt.py``, so a supervisor restarts the server
    instead of letting it squat on the chip.

Clients import only numpy + the ring protocol (the engine's jax-heavy
imports are lazy), so a spawn-context client process stays light.
"""

from __future__ import annotations

import dataclasses
import multiprocessing as mp
import queue as _queue
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..data import shm_ring
from ..train.guard import StallWatchdog
from .admission import AdmissionShed
from .engine import ServeTimeout, ServerOverloaded, ServingEngine

_MP_CTX = "spawn"   # same rationale as data/workers.py: no JAX state leaks
_DEFAULT_CAPACITY = 4


@dataclasses.dataclass
class FrontendHandle:
    """Picklable attach token for one client (ring pair + geometry)."""

    client_id: int
    field_size: int
    max_rows: int
    request: shm_ring.RingHandle
    response: shm_ring.RingHandle


class ServingClient:
    """Client-side stub: ``predict()`` over the shared-memory ring pair.

    One client object per process/thread (the rings are SPSC). Requests may
    be pipelined (``submit`` then ``recv``); ``predict`` is the synchronous
    convenience. Not thread-safe — one submitter per handle, by design.
    """

    def __init__(self, handle: FrontendHandle):
        self._h = handle
        self._req = shm_ring.ShmRing.attach(handle.request)
        self._resp = shm_ring.ShmRing.attach(handle.response)
        self._next_id = 0
        self._pending: Dict[int, int] = {}   # req_id -> expected rows
        self._done: Dict[int, np.ndarray] = {}
        self._closed = False

    # ---------------------------------------------------------- pipelined
    def submit(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
               timeout: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        """Write one request into the ring; returns its ``req_id``.
        ``trace_id`` (``obs.trace.new_trace_id``) rides the wire tuple to
        the engine for request→model-version correlation. Raises
        :class:`ServerOverloaded` when the ring is full past ``timeout``
        (bounded backpressure, never silent drop)."""
        if self._closed:
            raise RuntimeError("client is closed")
        ids = np.asarray(feat_ids)
        vals = np.asarray(feat_vals)
        if ids.ndim != 2 or vals.shape != ids.shape \
                or ids.shape[1] != self._h.field_size:
            raise ValueError(
                f"expected [n, {self._h.field_size}] feat_ids/feat_vals, "
                f"got {ids.shape} / {vals.shape}")
        n = int(ids.shape[0])
        if not 1 <= n <= self._h.max_rows:
            raise ValueError(
                f"request of {n} rows outside 1..{self._h.max_rows}")
        slot = self._req.acquire(timeout=timeout)
        if slot is None:
            raise ServerOverloaded(
                f"request ring full ({self._req.capacity} slabs in flight); "
                "retry with backoff")
        _, slab_ids, slab_vals = self._req.arrays(slot, n)
        slab_ids[:] = ids
        slab_vals[:] = vals
        req_id = self._next_id
        self._next_id += 1
        self._pending[req_id] = n
        # 5th element is optional on the wire: old servers unpack 4 and a
        # None id is simply not sent, so mixed-version rings stay valid.
        if trace_id is None:
            self._req.send(("req", req_id, slot, n))
        else:
            self._req.send(("req", req_id, slot, n, int(trace_id)))
        return req_id

    def recv(self, req_id: int,
             timeout: Optional[float] = None) -> np.ndarray:
        """Block for the probs of ``req_id`` (out-of-order safe)."""
        if req_id in self._done:
            return self._done.pop(req_id)
        if req_id not in self._pending:
            raise KeyError(f"unknown req_id {req_id}")
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                msg = self._resp.pop(timeout=remaining)
            except _queue.Empty:
                raise TimeoutError(
                    f"no response for req_id {req_id} within {timeout}s"
                ) from None
            if msg[0] == "resp":
                _, rid, slot, n = msg
                probs, _, _ = self._resp.arrays(slot, n)
                out = probs.copy()
                self._resp.release(slot)
                self._pending.pop(rid, None)
                if rid == req_id:
                    return out
                self._done[rid] = out
            elif msg[0] == "err":
                _, rid, exc_type, detail = msg
                self._pending.pop(rid, None)
                err: Exception
                if exc_type == "ServerOverloaded":
                    err = ServerOverloaded(detail)
                elif exc_type == "AdmissionShed":
                    err = AdmissionShed(detail)
                elif exc_type == "ServeTimeout":
                    err = ServeTimeout(detail)
                elif exc_type == "ValueError":
                    err = ValueError(detail)
                else:
                    err = RuntimeError(f"{exc_type}: {detail}")
                if rid == req_id:
                    raise err
                # An error for a *different* pipelined request: surface it
                # on that request's recv by stashing the exception.
                self._done[rid] = err  # type: ignore[assignment]
            else:
                raise RuntimeError(
                    f"serving protocol violation: unexpected {msg[0]!r}")

    # ---------------------------------------------------------- one-shot
    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                timeout: Optional[float] = None,
                trace_id: Optional[int] = None) -> np.ndarray:
        out = self.recv(
            self.submit(feat_ids, feat_vals, timeout, trace_id=trace_id),
            timeout)
        if isinstance(out, Exception):
            raise out
        return out

    def close(self) -> None:
        """Announce the farewell; the server retires this client."""
        if self._closed:
            return
        self._closed = True
        try:
            self._req.send(("bye", self._h.client_id))
        except Exception:
            pass  # server gone: the alive-probe path cleans up
        self._req.close()
        self._resp.close()

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def client_main(handle: FrontendHandle, num_requests: int,
                max_rows: int, feature_size: int, seed: int) -> None:
    """Spawned-client entry point (module-level: spawn pickles by
    reference): fire ``num_requests`` random-size requests, assert finite
    correctly-shaped probs, exit 0. Any failure exits nonzero."""
    client = ServingClient(handle)
    rng = np.random.default_rng(seed)
    try:
        for _ in range(int(num_requests)):
            n = int(rng.integers(1, max_rows + 1))
            ids = rng.integers(0, feature_size,
                               (n, handle.field_size)).astype(np.int32)
            vals = rng.normal(size=(n, handle.field_size)).astype(np.float32)
            probs = client.predict(ids, vals, timeout=120.0)
            assert probs.shape == (n,) and np.all(np.isfinite(probs)), (
                f"bad response shape/values: {probs.shape}")
        client.close()
    except BaseException:
        import traceback
        traceback.print_exc()
        sys.exit(1)


class FrontendServer:
    """Device-owning side: ring pairs + the drain/respond loop."""

    def __init__(self, engine: ServingEngine, num_clients: int, *,
                 field_size: int, slab_records: Optional[int] = None,
                 capacity: int = _DEFAULT_CAPACITY, ctx: Any = None,
                 poll_secs: float = 0.005, timeout_s: float = 0.0,
                 request_timeout_s: float = 0.0,
                 abort: Optional[Callable[[str], None]] = None,
                 client_alive: Optional[Callable[[int], bool]] = None):
        if num_clients < 1:
            raise ValueError("num_clients must be >= 1")
        if request_timeout_s < 0:
            raise ValueError(
                f"request_timeout_s must be >= 0, got {request_timeout_s}")
        self._engine = engine
        # A replicated engine routes by client id (sticky affinity with
        # least-loaded spill); a single engine ignores the concept.
        self._affinity = bool(getattr(engine, "supports_affinity", False))
        self.num_clients = int(num_clients)
        self.field_size = int(field_size)
        self.max_rows = int(slab_records if slab_records is not None
                            else engine.max_batch)
        self._poll = float(poll_secs)
        self._timeout_s = float(timeout_s)
        # Per-request response budget (0 = wait forever, the legacy
        # behavior): a future pending past this is answered with a typed
        # ServeTimeout error instead of wedging the client — derived from
        # --serve_timeout_s by callers that pass a config.
        self._request_timeout_s = float(request_timeout_s)
        self._abort = abort
        self._client_alive = client_alive
        self.responses_sent = 0
        self.errors_sent = 0
        self.timeouts_sent = 0
        self.dropped_dead_client = 0
        ctx = ctx if ctx is not None else mp.get_context(_MP_CTX)
        req_spec = shm_ring.SlabSpec(self.max_rows, self.field_size)
        resp_spec = shm_ring.SlabSpec(self.max_rows, 1)
        self._req_rings: List[shm_ring.ShmRing] = []
        self._resp_rings: List[shm_ring.ShmRing] = []
        try:
            for _ in range(self.num_clients):
                self._req_rings.append(
                    shm_ring.ShmRing.create(req_spec, capacity, ctx))
                self._resp_rings.append(
                    shm_ring.ShmRing.create(resp_spec, capacity, ctx))
        except BaseException:
            self.close()
            raise
        self._alive = [True] * self.num_clients
        # (future, client_id, req_id) in submission order; completion may
        # resolve out of order but each client demuxes by req_id.
        self._inflight: deque = deque()
        self._stop_flag = False

    # ----------------------------------------------------------- plumbing
    def handle(self, client_id: int) -> FrontendHandle:
        return FrontendHandle(
            client_id=client_id, field_size=self.field_size,
            max_rows=self.max_rows,
            request=self._req_rings[client_id].handle,
            response=self._resp_rings[client_id].handle)

    def handles(self) -> List[FrontendHandle]:
        return [self.handle(c) for c in range(self.num_clients)]

    def stop(self) -> None:
        self._stop_flag = True

    def close(self) -> None:
        for ring in self._req_rings + self._resp_rings:
            ring.close()

    def __enter__(self) -> "FrontendServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- the loop
    def _pump_requests(self) -> bool:
        """Drain every live request ring without blocking; True if any."""
        progressed = False
        for cid in range(self.num_clients):
            if not self._alive[cid]:
                continue
            ring = self._req_rings[cid]
            while True:
                try:
                    msg = ring.pop(timeout=0)
                except _queue.Empty:
                    break
                progressed = True
                if msg[0] == "bye":
                    self._alive[cid] = False
                    break
                _, req_id, slot, n = msg[:4]
                trace_id = msg[4] if len(msg) > 4 else None
                # Copy out and recycle the slot immediately: the engine may
                # hold the rows well past this slab's next reuse.
                _, slab_ids, slab_vals = ring.arrays(slot, n)
                ids, vals = slab_ids.copy(), slab_vals.copy()
                ring.release(slot)
                try:
                    if self._affinity:
                        fut = self._engine.submit(ids, vals, affinity=cid,
                                                  trace_id=trace_id)
                    else:
                        fut = self._engine.submit(ids, vals,
                                                  trace_id=trace_id)
                except (ServerOverloaded, AdmissionShed, ValueError) as e:
                    self._send_error(cid, req_id, e)
                    continue
                self._inflight.append((fut, cid, req_id))
        return progressed

    def _send_error(self, cid: int, req_id: int, exc: Exception) -> None:
        self._resp_rings[cid].send(
            ("err", req_id, type(exc).__name__, str(exc)))
        self.errors_sent += 1

    def _client_gone(self, cid: int) -> bool:
        return (self._client_alive is not None
                and not self._client_alive(cid))

    def _respond(self) -> bool:
        """Ship every resolved future at the head of the line; True if any.

        Responses are sent head-first per submission order, but a resolved
        future behind an unresolved one does not wait (scan, not strict
        FIFO) — the engine resolves whole flushes at once, so scanning a
        bounded window is cheap.
        """
        progressed = False
        for _ in range(len(self._inflight)):
            fut, cid, req_id = self._inflight.popleft()
            if not fut.done():
                if self._request_timeout_s > 0 and (
                        time.monotonic() - fut.t_enqueue
                        > self._request_timeout_s):
                    # Budget blown: answer NOW with a typed timeout and
                    # cancel the engine leg (dropped at batch formation if
                    # still queued; a mid-flush resolution is ignored).
                    cancel = getattr(fut, "cancel", None)
                    if callable(cancel):
                        cancel()
                    self._send_error(cid, req_id, ServeTimeout(
                        f"request of {getattr(fut, 'n', '?')} rows exceeded "
                        f"the {self._request_timeout_s}s response budget"))
                    self.timeouts_sent += 1
                    progressed = True
                    continue
                self._inflight.append((fut, cid, req_id))
                continue
            if not self._alive[cid] and self._client_gone(cid):
                self.dropped_dead_client += 1
                progressed = True
                continue
            try:
                probs = fut.result(timeout=0)
            except Exception as e:  # noqa: BLE001 — forwarded to the client
                self._send_error(cid, req_id, e)
                progressed = True
                continue
            ring = self._resp_rings[cid]
            # A full response ring blocks here WITHOUT beating the watchdog:
            # a live-but-stuck reader wedging the loop is exactly what the
            # exit-43 contract exists to surface.
            slot = ring.acquire(timeout=self._poll)
            while slot is None and not self._stop_flag:
                if self._client_gone(cid):
                    # Died without a farewell: drop its responses, retire it
                    # so its ring never blocks the loop again.
                    self._alive[cid] = False
                    self.dropped_dead_client += 1
                    slot = -1
                    break
                slot = ring.acquire(timeout=self._poll)
            if slot is None:       # stop() while blocked: abandon the write
                return progressed
            if slot == -1:
                progressed = True
                continue
            n = len(probs)
            slab_probs, _, _ = ring.arrays(slot, n)
            slab_probs[:] = probs
            ring.send(("resp", req_id, slot, n))
            self.responses_sent += 1
            progressed = True
        return progressed

    def serve(self) -> None:
        """Run until every client said ``bye`` and nothing is owed (or
        :meth:`stop`). A stall past ``timeout_s`` with work pending aborts
        with the exit-43 contract (``StallWatchdog`` default abort)."""
        watchdog = None
        if self._timeout_s > 0:
            watchdog = StallWatchdog(
                self._timeout_s, name="serving-frontend",
                abort=self._abort).start()
        try:
            while not self._stop_flag:
                progressed = self._pump_requests()
                progressed |= self._respond()
                idle = not self._inflight
                if watchdog is not None and (progressed or idle):
                    watchdog.beat(self.responses_sent)
                if not any(self._alive) and not self._inflight:
                    return
                if not progressed:
                    time.sleep(self._poll)
        finally:
            if watchdog is not None:
                watchdog.stop()
