"""Serving fast path: version-keyed result cache + request fingerprints.

The flood harness shows a heavy Zipf head of repeat users, yet every
request — even an identical concurrent duplicate — pays the full predict
path. This module is the read-through layer the engine puts in front of
the batcher:

  * :func:`request_fingerprint` — a content hash of one request's
    ``(ids, vals)`` arrays (shape + dtype + bytes), the identity under
    which "the same request" is defined for both caching and in-flight
    coalescing. Pure bytes, no float tolerance: two requests either ARE
    byte-identical or they are different requests.
  * :class:`ResultCache` — a thread-safe LRU keyed by
    ``(model_version, fingerprint)`` with row-denominated capacity and an
    optional TTL. Keying on the version that EXECUTED the flush makes hot
    swaps invalidate for free: post-swap lookups use the new version and
    simply miss, and the stale entries age out of the LRU tail. Values are
    stored and returned as copies, so a hit is bit-identical to the flush
    that produced it and no caller can mutate a cached response.

Cache hit/miss/coalesce COUNTERS live in
:class:`~deepfm_tpu.serve.stats.ServingStats` (the engine's metric
surface); this module only counts its own internal evictions/expiries.
No jax import — same light-plane contract as ``stats.py``/``admission.py``.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


def request_fingerprint(feat_ids: np.ndarray,
                        feat_vals: np.ndarray) -> bytes:
    """Content identity of one request: shape + dtype + raw bytes of both
    arrays, blake2b-compressed. Deterministic across processes (no Python
    hash randomization) so a replayed drill fingerprints identically."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr(feat_ids.shape).encode())
    h.update(str(feat_ids.dtype).encode())
    h.update(np.ascontiguousarray(feat_ids).tobytes())
    h.update(str(feat_vals.dtype).encode())
    h.update(np.ascontiguousarray(feat_vals).tobytes())
    return h.digest()


def _copy_value(value: Any) -> Any:
    """Deep-enough copy of a demuxed response (``[n]`` array or the
    multitask ``{task: [n]}`` dict) — bit-identical, never aliased."""
    if isinstance(value, dict):
        return {k: np.array(v, copy=True) for k, v in value.items()}
    return np.array(value, copy=True)


class ResultCache:
    """LRU of ``(model_version, fingerprint) -> response`` in ROW units.

    ``rows`` bounds the total cached response rows (the same unit the
    request queue is bounded in); inserting past it evicts from the LRU
    tail. ``ttl_s`` > 0 expires entries on lookup (lazily — an expired
    entry costs nothing until it is next touched). All clock reads come
    from the injectable ``clock`` so TTL tests are sleep-free.
    """

    def __init__(self, rows: int, *, ttl_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        if rows < 1:
            raise ValueError(f"cache rows must be >= 1, got {rows}")
        if ttl_s < 0:
            raise ValueError(f"cache ttl_s must be >= 0, got {ttl_s}")
        self.capacity_rows = int(rows)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        # (version, fp) -> (value, rows, inserted_at); LRU order, most
        # recently used last.
        self._entries: "OrderedDict[Tuple[Any, bytes], Tuple[Any, int, float]]" = OrderedDict()
        self._rows = 0
        self.evictions = 0      # capacity evictions (LRU tail)
        self.expirations = 0    # TTL expiries seen at lookup/insert

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def rows(self) -> int:
        with self._lock:
            return self._rows

    def _expired(self, inserted_at: float, now: float) -> bool:
        return self.ttl_s > 0 and (now - inserted_at) > self.ttl_s

    def get(self, version: Any, fingerprint: bytes) -> Optional[Any]:
        """The cached response for this exact request under this exact
        model version, or None. A hit refreshes LRU recency and returns a
        COPY (bit-identical to the stored flush output)."""
        key = (version, fingerprint)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            value, n, at = entry
            if self._expired(at, self._clock()):
                del self._entries[key]
                self._rows -= n
                self.expirations += 1
                return None
            self._entries.move_to_end(key)
            return _copy_value(value)

    def put(self, version: Any, fingerprint: bytes, value: Any,
            rows: int) -> None:
        """Insert (a copy of) one response; evicts LRU entries until the
        row budget holds. An over-budget single response is simply not
        cached (never evict the whole cache for one giant request)."""
        n = int(rows)
        if n > self.capacity_rows:
            return
        key = (version, fingerprint)
        stored = _copy_value(value)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._rows -= old[1]
            while self._rows + n > self.capacity_rows and self._entries:
                _, (_, old_n, _) = self._entries.popitem(last=False)
                self._rows -= old_n
                self.evictions += 1
            self._entries[key] = (stored, n, self._clock())
            self._rows += n

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "cache_entries": len(self._entries),
                "cache_rows_used": self._rows,
                "cache_capacity_rows": self.capacity_rows,
                "cache_ttl_s": self.ttl_s,
                "cache_evictions": self.evictions,
                "cache_expirations": self.expirations,
            }
