"""TPU-native serving runtime: dynamic batching, bucketed shapes, hot swap.

Layering (heaviest import last — clients can use :mod:`.frontend` and
:mod:`.stats` without pulling jax):

  * :mod:`.stats` — thread-safe latency/QPS/occupancy/swap accounting.
  * :mod:`.engine` — bounded queue, dynamic batcher, bucketed predict,
    response demux, hot swap via ``utils.export.LatestWatcher`` (the jax
    import happens lazily at engine construction).
  * :mod:`.frontend` — N client processes → one device-owning server over
    ``data.shm_ring`` slab rings, with the exit-43 wedge contract.
"""

from .engine import ServeFuture, ServerOverloaded, ServingEngine
from .frontend import (FrontendHandle, FrontendServer, ServingClient,
                       client_main)
from .stats import ServingStats

__all__ = [
    "FrontendHandle",
    "FrontendServer",
    "ServeFuture",
    "ServerOverloaded",
    "ServingClient",
    "ServingEngine",
    "ServingStats",
    "client_main",
]
