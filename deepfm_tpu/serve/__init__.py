"""TPU-native serving runtime: dynamic batching, bucketed shapes, hot swap.

Layering (heaviest import last — clients can use :mod:`.frontend` and
:mod:`.stats` without pulling jax):

  * :mod:`.stats` — thread-safe latency/QPS/occupancy/swap accounting.
  * :mod:`.admission` — SLO-aware admission gate (value classes, hysteresis
    shed ladder, typed ``AdmissionShed``) and the cascade's degradation
    ladder; jax-free.
  * :mod:`.engine` — bounded queue, dynamic batcher, bucketed predict,
    response demux, hot swap via ``utils.export.LatestWatcher`` (the jax
    import happens lazily at engine construction).
  * :mod:`.replicas` — N engine replicas behind one submit surface: sticky
    client-affinity routing with least-loaded spill, staggered per-replica
    hot swap, fleet-aggregate stats.
  * :mod:`.frontend` — N client processes → one device-owning server over
    ``data.shm_ring`` slab rings, with the exit-43 wedge contract.
"""

from .admission import (VALUE_CLASSES, VALUE_DEFAULT, AdmissionController,
                        AdmissionShed, DegradationLadder, HysteresisLadder)
from .engine import ServeFuture, ServeTimeout, ServerOverloaded, ServingEngine
from .frontend import (FrontendHandle, FrontendServer, ServingClient,
                       client_main)
from .replicas import HedgedFuture, ReplicatedEngine
from .stats import ServingStats, aggregate_summary

__all__ = [
    "AdmissionController",
    "AdmissionShed",
    "DegradationLadder",
    "FrontendHandle",
    "FrontendServer",
    "HedgedFuture",
    "HysteresisLadder",
    "ReplicatedEngine",
    "ServeFuture",
    "ServeTimeout",
    "ServerOverloaded",
    "ServingClient",
    "ServingEngine",
    "ServingStats",
    "VALUE_CLASSES",
    "VALUE_DEFAULT",
    "aggregate_summary",
    "client_main",
]
