"""TPU-native serving runtime: dynamic batching, bucketed shapes, hot swap.

Layering (heaviest import last — clients can use :mod:`.frontend` and
:mod:`.stats` without pulling jax):

  * :mod:`.stats` — thread-safe latency/QPS/occupancy/swap accounting.
  * :mod:`.admission` — SLO-aware admission gate (value classes, hysteresis
    shed ladder, typed ``AdmissionShed``) and the cascade's degradation
    ladder; jax-free.
  * :mod:`.cache` — serving fast path: version-keyed LRU result cache and
    the request fingerprint that defines "the same request" for caching and
    in-flight coalescing; jax-free.
  * :mod:`.engine` — bounded queue, dynamic batcher, bucketed predict,
    response demux, hot swap via ``utils.export.LatestWatcher`` (the jax
    import happens lazily at engine construction).
  * :mod:`.experiment` — traffic-split router (A/B, shadow, canary) with
    pure hash-split arm assignment, shadow-lane isolation, and the canary
    kill-switch; jax-free (pairs with ``train.promote`` for gated
    deployment).
  * :mod:`.replicas` — N engine replicas behind one submit surface: sticky
    client-affinity routing with least-loaded spill, staggered per-replica
    hot swap, fleet-aggregate stats.
  * :mod:`.frontend` — N client processes → one device-owning server over
    ``data.shm_ring`` slab rings, with the exit-43 wedge contract.
"""

from .admission import (VALUE_CLASSES, VALUE_DEFAULT, AdmissionController,
                        AdmissionShed, DegradationLadder, HysteresisLadder)
from .cache import ResultCache, request_fingerprint
from .engine import ServeFuture, ServeTimeout, ServerOverloaded, ServingEngine
from .experiment import (ARM_CHALLENGER, ARM_CONTROL, ExperimentRouter,
                         assign_arm)
from .frontend import (FrontendHandle, FrontendServer, ServingClient,
                       client_main)
from .replicas import HedgedFuture, ReplicatedEngine
from .stats import ServingStats, aggregate_summary

__all__ = [
    "ARM_CHALLENGER",
    "ARM_CONTROL",
    "AdmissionController",
    "AdmissionShed",
    "DegradationLadder",
    "ExperimentRouter",
    "FrontendHandle",
    "FrontendServer",
    "HedgedFuture",
    "HysteresisLadder",
    "ReplicatedEngine",
    "ResultCache",
    "ServeFuture",
    "ServeTimeout",
    "ServerOverloaded",
    "ServingClient",
    "ServingEngine",
    "ServingStats",
    "VALUE_CLASSES",
    "VALUE_DEFAULT",
    "aggregate_summary",
    "assign_arm",
    "client_main",
    "request_fingerprint",
]
