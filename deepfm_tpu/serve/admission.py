"""SLO-aware admission control: shed lowest-value work first, with hysteresis.

The engine's only pressure answer used to be queue-full
:class:`~deepfm_tpu.serve.engine.ServerOverloaded` — a hard wall that hits
every caller equally, and only once the queue is ALREADY the full SLO-budget
deep. This module puts a value-aware gate in FRONT of that wall:

  * **value classes** — every request carries one of :data:`VALUE_CLASSES`
    (lowest value first). The priority small lane generalizes into this:
    lanes say *how* a request batches, classes say *whether* it is admitted
    under pressure.
  * **pressure** — the max of two normalized signals: queue depth over the
    shed watermark (``pending_rows / shed_watermark``), and the EWMA of the
    measured queue delay over the SLO-derived delay budget
    (``delay_ms / (slo_ms * slo_fraction)``). Either signal crossing 1.0
    means the engine is no longer meeting its SLO for work already queued —
    adding more low-value work only makes every response later.
  * **hysteresis ladder** — the shed level rises when pressure crosses an
    enter threshold (level L engages at ``1 + (L-1) * step``) and falls only
    when pressure drops below ``hysteresis *`` that threshold, so an
    oscillation around a watermark cannot flap the gate open/closed on every
    request. Level L sheds the L lowest value classes with a typed
    :class:`AdmissionShed` — distinct from ``ServerOverloaded`` so callers
    can tell "the server chose to refuse my class" from "the queue is
    physically full". The HIGHEST class is never admission-shed: it only
    ever hits the queue-full wall.

Exact-watermark tie rule: enter thresholds compare with ``>=``, so pressure
landing EXACTLY on the watermark already sheds the lowest class — at the
boundary the gate protects the SLO rather than the marginal request.

The same :class:`HysteresisLadder` drives the cascade's degraded-mode rungs
(:class:`DegradationLadder`): shrink ``retrieve_k`` first, then skip the
ranker — every transition counted and trace-stamped, never silent.

This module is jax-free (stats/trace only) so frontends can import it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import trace as trace_lib

#: Value classes, LOWEST value first. "bulk" is offline/backfill-grade
#: traffic (shed first), "normal" is the default interactive class,
#: "critical" is never admission-shed (queue-full still applies).
VALUE_CLASSES: Tuple[str, ...] = ("bulk", "normal", "critical")
VALUE_DEFAULT = "normal"


class AdmissionShed(RuntimeError):
    """The admission gate refused this request's VALUE CLASS under pressure.

    Distinct from :class:`~deepfm_tpu.serve.engine.ServerOverloaded` (queue
    physically full): a shed is a policy decision — higher-value classes are
    still being admitted, and the caller should degrade or drop rather than
    retry immediately.
    """


class HysteresisLadder:
    """A monotone level ladder over a scalar pressure signal, with
    hysteresis: level L engages when pressure >= ``enter_at + (L-1)*step``
    (``>=`` — the exact-watermark tie escalates) and releases only when
    pressure < ``hysteresis`` x that same threshold. Between the release
    and enter thresholds the level HOLDS — oscillating load cannot flap it.

    Not thread-safe by itself; callers serialize ``update`` (the admission
    controller and the cascade both update under their own locks).
    """

    def __init__(self, levels: int, *, enter_at: float = 1.0,
                 step: float = 0.5, hysteresis: float = 0.7,
                 on_transition: Optional[
                     Callable[[int, int, float], None]] = None):
        if levels < 1:
            raise ValueError(f"levels must be >= 1, got {levels}")
        if not 0.0 < hysteresis < 1.0:
            raise ValueError(
                f"hysteresis must be in (0, 1), got {hysteresis}")
        if step <= 0 or enter_at <= 0:
            raise ValueError(
                f"need positive enter_at/step, got {enter_at}/{step}")
        self.levels = int(levels)
        self._enter = [enter_at + (lv - 1) * step
                       for lv in range(1, self.levels)]
        self._hysteresis = float(hysteresis)
        self._on_transition = on_transition
        self.level = 0
        self.transitions = 0
        # Bounded recent-transition log: (from, to, pressure) — the drill
        # asserts the ladder engaged AND recovered from this.
        self.transition_log: List[Tuple[int, int, float]] = []

    def enter_threshold(self, level: int) -> float:
        """Pressure at which ``level`` engages (level >= 1)."""
        return self._enter[level - 1]

    def update(self, pressure: float) -> int:
        """Advance the ladder for one observation; returns the new level."""
        p = float(pressure)
        up = 0
        for lv in range(1, self.levels):
            if p >= self._enter[lv - 1]:
                up = lv
        if up > self.level:
            target = up
        else:
            down = 0
            for lv in range(1, self.levels):
                if p >= self._hysteresis * self._enter[lv - 1]:
                    down = lv
            target = min(self.level, max(down, up))
        if target != self.level:
            prev, self.level = self.level, target
            self.transitions += 1
            if len(self.transition_log) < 256:
                self.transition_log.append((prev, target, p))
            if self._on_transition is not None:
                self._on_transition(prev, target, p)
        return self.level


class AdmissionController:
    """The SLO-aware gate one engine consults before its queue-full check.

    ``admit(value, pending_rows)`` raises :class:`AdmissionShed` when the
    request's value class falls below the current shed level; otherwise it
    returns the level (0 = everything admitted). All counters land in the
    engine's :class:`~deepfm_tpu.serve.stats.ServingStats` so the summary
    reconciles: offered == completed + failed + overloads + sheds.

    Each engine owns ITS controller (pressure is per-queue); replicas never
    share one.
    """

    def __init__(self, *, slo_ms: float = 0.0, shed_watermark: int = 0,
                 queue_rows: int = 0,
                 classes: Sequence[str] = VALUE_CLASSES,
                 hysteresis: float = 0.7, step: float = 0.5,
                 slo_fraction: float = 0.5, delay_alpha: float = 0.2,
                 stats: Any = None,
                 clock: Callable[[], float] = time.monotonic):
        if slo_ms < 0:
            raise ValueError(f"slo_ms must be >= 0, got {slo_ms}")
        if shed_watermark < 0:
            raise ValueError(
                f"shed_watermark must be >= 0, got {shed_watermark}")
        if len(classes) < 2:
            raise ValueError(
                f"need >= 2 value classes to shed by value, got {classes!r}")
        self.slo_ms = float(slo_ms)
        # Watermark default: half the queue — shedding starts while the
        # queue can still absorb a burst of higher-value work.
        self.shed_watermark = int(shed_watermark) or max(1, queue_rows // 2)
        self.classes = tuple(classes)
        self._rank = {c: i for i, c in enumerate(self.classes)}
        self.slo_fraction = float(slo_fraction)
        self._alpha = float(delay_alpha)
        self.stats = stats
        self._clock = clock
        self._lock = threading.Lock()
        self._ewma_delay_ms: Optional[float] = None
        self._ewma_at: Optional[float] = None
        # Max level sheds all but the highest class.
        self._ladder = HysteresisLadder(
            len(self.classes), hysteresis=hysteresis, step=step,
            on_transition=self._on_transition)

    # ------------------------------------------------------------ signals
    def _on_transition(self, prev: int, new: int, pressure: float) -> None:
        trace_lib.instant("serve.admission_level", prev=prev, level=new,
                          pressure=round(pressure, 4))
        if self.stats is not None:
            self.stats.record_admission_transition(new)

    def rank(self, value: str) -> int:
        try:
            return self._rank[value]
        except KeyError:
            raise ValueError(
                f"unknown value class {value!r}; expected one of "
                f"{self.classes}") from None

    def observe_delay(self, delay_ms: float) -> None:
        """Feed one measured queue delay (enqueue → batch formation)."""
        with self._lock:
            if self._ewma_delay_ms is None:
                self._ewma_delay_ms = float(delay_ms)
            else:
                self._ewma_delay_ms += self._alpha * (
                    float(delay_ms) - self._ewma_delay_ms)
            self._ewma_at = self._clock()

    def pressure(self, pending_rows: int) -> float:
        """Max of the depth and delay signals, each normalized to 1.0 at
        its watermark.

        The delay EWMA is a TRAILING indicator: once the gate (or the
        cascade's retrieval-only rung) stops work from reaching the
        batcher, no new delays are observed and a peak reading would pin
        the pressure high forever. So the delay signal ages: it halves
        per ``slo_ms`` elapsed since the last observation — under live
        traffic the age is ~0 and nothing changes, while a drained queue
        releases the ladder within a few SLOs instead of wedging
        degraded."""
        depth = pending_rows / self.shed_watermark
        with self._lock:
            ewma, at = self._ewma_delay_ms, self._ewma_at
        if self.slo_ms > 0 and ewma is not None:
            half_life_s = self.slo_ms / 1000.0
            age_s = max(0.0, self._clock() - at)
            stale = ewma * (0.5 ** (age_s / half_life_s))
            return max(depth, stale / (self.slo_ms * self.slo_fraction))
        return depth

    # ------------------------------------------------------------- gating
    @property
    def level(self) -> int:
        with self._lock:
            return self._ladder.level

    @property
    def transitions(self) -> int:
        with self._lock:
            return self._ladder.transitions

    def admit(self, value: str, pending_rows: int) -> int:
        """Raise :class:`AdmissionShed` if ``value`` is below the current
        shed level; returns the level otherwise."""
        rank = self.rank(value)
        p = self.pressure(pending_rows)
        with self._lock:
            level = self._ladder.update(p)
        if rank < level:
            if self.stats is not None:
                self.stats.record_shed(value)
            raise AdmissionShed(
                f"admission shed: class {value!r} (rank {rank}) below shed "
                f"level {level} at pressure {p:.2f} "
                f"({pending_rows} rows pending, watermark "
                f"{self.shed_watermark}); degrade or retry later")
        return level

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "admission_level": self._ladder.level,
                "admission_transitions": self._ladder.transitions,
                "admission_watermark_rows": self.shed_watermark,
                "admission_slo_ms": self.slo_ms or None,
                "admission_ewma_delay_ms": (
                    round(self._ewma_delay_ms, 3)
                    if self._ewma_delay_ms is not None else None),
            }


#: Degradation rungs, healthy first: full cascade → shrunken retrieve_k →
#: ranker skipped (retrieval-order results).
DEGRADE_RUNGS: Tuple[str, ...] = ("full", "reduced_retrieve",
                                  "retrieval_only")


class DegradationLadder:
    """The cascade's graceful-degradation state machine over the same
    hysteresis ladder: rung 1 shrinks ``retrieve_k``, rung 2 answers from
    retrieval order without ranking. Every transition is an explicit,
    counted, trace-stamped event (``serve.degrade``) — a degraded answer is
    a product decision, never a silent quality drop."""

    def __init__(self, *, hysteresis: float = 0.7, step: float = 0.5,
                 stats: Any = None):
        self.stats = stats
        self._lock = threading.Lock()
        self._ladder = HysteresisLadder(
            len(DEGRADE_RUNGS), hysteresis=hysteresis, step=step,
            on_transition=self._on_transition)

    def _on_transition(self, prev: int, new: int, pressure: float) -> None:
        trace_lib.instant(
            "serve.degrade", prev=DEGRADE_RUNGS[prev],
            rung=DEGRADE_RUNGS[new], pressure=round(pressure, 4))
        if self.stats is not None:
            self.stats.record_degrade_transition(DEGRADE_RUNGS[new])

    @property
    def rung(self) -> int:
        with self._lock:
            return self._ladder.level

    @property
    def rung_name(self) -> str:
        return DEGRADE_RUNGS[self.rung]

    @property
    def transitions(self) -> int:
        with self._lock:
            return self._ladder.transitions

    @property
    def transition_log(self) -> List[Tuple[int, int, float]]:
        with self._lock:
            return list(self._ladder.transition_log)

    def update(self, pressure: float) -> int:
        with self._lock:
            return self._ladder.update(pressure)
