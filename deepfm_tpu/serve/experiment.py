"""Traffic-split experimentation: A/B, shadow, and canary arms over engines.

Production serving is never one model: every candidate earns live traffic
through a gated pipeline (shadow-validate, canary, promote — see
``train/promote.py`` for the controller that moves ``LATEST``). This module
is the request-path half: a router in front of two engines (control +
challenger — each a :class:`~deepfm_tpu.serve.engine.ServingEngine`,
:class:`~deepfm_tpu.serve.replicas.ReplicatedEngine`, or anything with the
same ``submit()`` surface) that assigns every request an **arm** and keeps
the challenger from ever hurting the primary lane.

Arm assignment is a pure function of ``(seed, request_id)`` — a seeded
integer hash threshold, no RNG state, no time — so a replayed request lands
on the identical arm and a drill's split is bit-reproducible (the same
property every audit fingerprint in this repo is built on). Granularity is
permille (0–1000) so a 0.5% canary is expressible.

The three modes:

  * **ab** — live split: a request's arm serves its response. Both arms are
    production; the split percentage is the experiment design.
  * **canary** — same mechanics as ``ab`` (a small live slice), plus the
    operational contract: :meth:`ExperimentRouter.kill` is the instant
    kill-switch that collapses ALL traffic back to control (one flag flip,
    no pointer move, counted and span-traced). The promotion controller
    pulls it on a guardrail breach.
  * **shadow** — every request is served by control; assigned-challenger
    requests are ALSO duplicated to the challenger on a side lane whose
    response is observed (logged, measured, NaN-checked) but never
    returned. Isolation is enforced structurally: the primary future is
    returned before the shadow submit happens, the shadow submit and its
    completion callback are wrapped wall-to-wall, and nothing on the shadow
    path can touch the primary future. A challenger that raises, sheds,
    returns NaN, or sleeps past its SLO surfaces ONLY as a typed counter
    (``shadow_submit_rejected`` / ``shadow_errors`` / ``shadow_nonfinite``
    / ``shadow_slo_misses``) — tested in ``tests/test_experiment.py``.

No jax import — the router is pure numpy + threading, same contract as the
rest of the light serving plane (``stats.py`` / ``admission.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..obs import metrics as metrics_lib
from ..obs import trace as trace_lib
from .admission import VALUE_DEFAULT

#: Arm ids as they ride the impression record (``loop.impressions.ARM_KEY``,
#: an optional int64 next to ``model_version``). Ints, not names, on the
#: wire; names only in summaries.
ARM_CONTROL = 0
ARM_CHALLENGER = 1
ARM_NAMES = {ARM_CONTROL: "control", ARM_CHALLENGER: "challenger"}

#: Router modes. "off" routes everything to control and duplicates nothing.
MODES = ("off", "shadow", "canary", "ab")

_MASK64 = (1 << 64) - 1


def _mix64(seed: int, request_id: int) -> int:
    """splitmix64-style avalanche of (seed, request_id) — stdlib-only and
    spec-pinned arithmetic, so the value (hence every arm decision built on
    it) is stable across platforms and numpy versions."""
    h = ((int(request_id) & _MASK64) * 0x9E3779B97F4A7C15
         + (int(seed) & _MASK64) * 0xBF58476D1CE4E5B9) & _MASK64
    h ^= h >> 30
    h = (h * 0xBF58476D1CE4E5B9) & _MASK64
    h ^= h >> 27
    h = (h * 0x94D049BB133111EB) & _MASK64
    h ^= h >> 31
    return h


def assign_arm(request_id: int, *, seed: int,
               challenger_permille: int) -> int:
    """Deterministic arm for one request: challenger iff the seeded hash of
    the request id lands under the permille threshold. Pure — replaying the
    same (seed, id, permille) reproduces the identical split bit-for-bit,
    and nearby ids decorrelate (a client's sequential ids don't stripe)."""
    permille = int(challenger_permille)
    if permille <= 0:
        return ARM_CONTROL
    if permille >= 1000:
        return ARM_CHALLENGER
    return (ARM_CHALLENGER
            if _mix64(seed, request_id) % 1000 < permille else ARM_CONTROL)


class ExperimentRouter:
    """Two-arm traffic splitter with shadow isolation and a kill-switch.

    ``control`` / ``challenger`` are engines (anything with the
    ``submit(feat_ids, feat_vals, trace_id=..., value=...)`` surface
    returning a future with ``result()`` / ``add_done_callback()``). The
    router does NOT own them — the caller closes its engines; ``close()``
    here only detaches the challenger so late shadow callbacks can't race a
    teardown.

    ``on_shadow_result(request_id, probs, latency_ms)`` is the logging hook
    for shadow responses (the drill writes them to the impression log under
    the challenger arm); it runs on the engine's executor callback thread
    and is itself guarded — a raising hook is a counted shadow error, never
    a primary-lane perturbation.
    """

    def __init__(self, control: Any, challenger: Optional[Any] = None, *,
                 mode: str = "off", seed: int = 0,
                 challenger_permille: int = 50,
                 shadow_slo_ms: float = 0.0,
                 on_shadow_result: Optional[
                     Callable[[int, np.ndarray, float], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if not 0 <= int(challenger_permille) <= 1000:
            raise ValueError(
                f"challenger_permille must be in [0, 1000], got "
                f"{challenger_permille}")
        if mode != "off" and challenger is None:
            raise ValueError(f"mode {mode!r} needs a challenger engine")
        self.control = control
        self.challenger = challenger
        self.mode = mode
        self.seed = int(seed)
        self.challenger_permille = int(challenger_permille)
        self.shadow_slo_ms = float(shadow_slo_ms)
        self._on_shadow_result = on_shadow_result
        self._clock = clock
        self._lock = threading.Lock()
        self._killed = False
        self.kill_reason: Optional[str] = None
        # Typed counters: the ONLY way shadow-lane trouble surfaces.
        self.requests_by_arm: Dict[int, int] = {ARM_CONTROL: 0,
                                                ARM_CHALLENGER: 0}
        self.shadow_submitted = 0
        self.shadow_completed = 0
        self.shadow_submit_rejected = 0   # challenger.submit itself refused
        self.shadow_errors = 0            # shadow future resolved with error
        self.shadow_nonfinite = 0         # shadow probs contained NaN/Inf
        self.shadow_slo_misses = 0        # shadow latency > shadow_slo_ms
        self.kills = 0
        self.shadow_latencies_ms: List[float] = []
        metrics_lib.auto_register("experiment", self)

    # ---------------------------------------------------------- assignment
    @property
    def killed(self) -> bool:
        return self._killed

    def assign(self, request_id: int) -> int:
        """The experiment-design arm for ``request_id`` (pure; ignores the
        kill-switch — :meth:`serving_arm` is what routing actually uses)."""
        return assign_arm(request_id, seed=self.seed,
                          challenger_permille=self.challenger_permille)

    def serving_arm(self, request_id: int) -> int:
        """The arm whose engine SERVES this request's response: always
        control when off / killed / shadowing; the assigned arm only for
        live-split modes (ab, canary)."""
        if (self.mode in ("ab", "canary") and not self._killed
                and self.challenger is not None):
            return self.assign(request_id)
        return ARM_CONTROL

    # ------------------------------------------------------------- routing
    def submit(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
               request_id: int, *, trace_id: Optional[int] = None,
               value: str = VALUE_DEFAULT,
               affinity: Optional[int] = None) -> Any:
        """Route one request. Returns the PRIMARY future (stamped with
        ``.arm``); any shadow duplication happens after the primary future
        already exists and cannot reach it. Primary-lane errors (overload,
        shed, validation) propagate exactly as the underlying engine raises
        them — the router adds no failure modes to the primary path."""
        arm = self.serving_arm(request_id)
        engine = self.challenger if arm == ARM_CHALLENGER else self.control
        fut = self._submit(engine, feat_ids, feat_vals, trace_id=trace_id,
                           value=value, affinity=affinity)
        try:
            fut.arm = arm
        except AttributeError:     # __slots__ futures without an arm slot
            pass
        with self._lock:
            self.requests_by_arm[arm] = self.requests_by_arm.get(arm, 0) + 1
        if (self.mode == "shadow" and not self._killed
                and self.challenger is not None
                and self.assign(request_id) == ARM_CHALLENGER):
            self._shadow(feat_ids, feat_vals, request_id, trace_id=trace_id,
                         value=value)
        return fut

    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                request_id: int, timeout: Optional[float] = None,
                **kw: Any) -> np.ndarray:
        return self.submit(feat_ids, feat_vals, request_id, **kw).result(
            timeout)

    @staticmethod
    def _submit(engine: Any, feat_ids: np.ndarray, feat_vals: np.ndarray,
                *, trace_id: Optional[int], value: str,
                affinity: Optional[int], bypass_cache: bool = False) -> Any:
        kw: Dict[str, Any] = {"trace_id": trace_id, "value": value}
        if affinity is not None and getattr(engine, "supports_affinity",
                                            False):
            kw["affinity"] = affinity
        if bypass_cache and getattr(engine, "supports_cache_bypass", False):
            kw["bypass_cache"] = True
        return engine.submit(feat_ids, feat_vals, **kw)

    # -------------------------------------------------------- shadow lane
    def _shadow(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                request_id: int, *, trace_id: Optional[int],
                value: str) -> None:
        """Fire-and-observe duplicate to the challenger. Guarded
        wall-to-wall: ANY exception (typed refusal, validation, a dead
        engine) becomes ``shadow_submit_rejected`` — never the caller's
        problem. Shadow submits BYPASS the challenger's result cache (when
        it advertises ``supports_cache_bypass``): the lane exists to
        measure the challenger's real predict path, and its duplicated
        traffic must neither read nor warm entries the live lane sees."""
        t0 = self._clock()
        try:
            sfut = self._submit(self.challenger, feat_ids, feat_vals,
                                trace_id=trace_id, value=value,
                                affinity=None, bypass_cache=True)
        except Exception:  # noqa: BLE001 — isolation IS the contract
            with self._lock:
                self.shadow_submit_rejected += 1
            return
        with self._lock:
            self.shadow_submitted += 1
        sfut.add_done_callback(
            lambda f: self._shadow_done(f, request_id, t0))

    def _shadow_done(self, fut: Any, request_id: int, t0: float) -> None:
        """Observe one shadow resolution on the challenger's executor
        thread. Fully guarded — a raising user hook or a malformed future
        counts as a shadow error and nothing else."""
        try:
            latency_ms = 1000.0 * (self._clock() - t0)
            if getattr(fut, "_error", None) is not None:
                with self._lock:
                    self.shadow_errors += 1
                return
            probs = fut._probs
            finite = bool(np.all(np.isfinite(probs)))
            with self._lock:
                self.shadow_completed += 1
                self.shadow_latencies_ms.append(latency_ms)
                if not finite:
                    self.shadow_nonfinite += 1
                if self.shadow_slo_ms > 0 and latency_ms > self.shadow_slo_ms:
                    self.shadow_slo_misses += 1
            if self._on_shadow_result is not None:
                self._on_shadow_result(request_id, probs, latency_ms)
        except Exception:  # noqa: BLE001 — shadow trouble never escapes
            with self._lock:
                self.shadow_errors += 1

    # --------------------------------------------------------- kill-switch
    def kill(self, reason: str = "") -> None:
        """Instant kill-switch: all subsequent traffic serves from control
        and shadow duplication stops. One flag under the lock — no pointer
        move, no engine teardown, effective on the very next request."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
            self.kill_reason = str(reason)
            self.kills += 1
        trace_lib.instant("experiment.kill", mode=self.mode,
                          reason=str(reason))

    def revive(self) -> None:
        """Re-open the experiment after a kill (a NEW candidate earned a
        fresh shot); counters keep accumulating — they are the audit."""
        with self._lock:
            self._killed = False
            self.kill_reason = None

    # ------------------------------------------------------------- summary
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            lat = list(self.shadow_latencies_ms)
            out = {
                "experiment_mode": self.mode,
                "experiment_killed": self._killed,
                "experiment_kills": self.kills,
                "experiment_kill_reason": self.kill_reason,
                "experiment_permille": self.challenger_permille,
                "arm_control_requests": self.requests_by_arm.get(
                    ARM_CONTROL, 0),
                "arm_challenger_requests": self.requests_by_arm.get(
                    ARM_CHALLENGER, 0),
                "shadow_submitted": self.shadow_submitted,
                "shadow_completed": self.shadow_completed,
                "shadow_submit_rejected": self.shadow_submit_rejected,
                "shadow_errors": self.shadow_errors,
                "shadow_nonfinite": self.shadow_nonfinite,
                "shadow_slo_misses": self.shadow_slo_misses,
            }
        out["shadow_p50_ms"] = (
            float(np.percentile(np.asarray(lat, np.float64), 50))
            if lat else None)
        out["shadow_p99_ms"] = (
            float(np.percentile(np.asarray(lat, np.float64), 99))
            if lat else None)
        return out

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Detach the challenger (late shadow callbacks still resolve into
        counters harmlessly). Engines belong to the caller."""
        with self._lock:
            self._killed = True
        self.challenger = None
