"""Replica scale-out: N serving engines behind one frontend.

One :class:`~deepfm_tpu.serve.engine.ServingEngine` owns one device (or one
host time-slice); serving "millions of users" means running several behind
the same shm_ring frontend. :class:`ReplicatedEngine` presents the ENGINE
interface the frontend already speaks (``submit`` / ``pending_rows`` /
``close``) over a fleet of replicas, adding exactly three things:

  * **sticky routing with least-loaded spill** — a request carrying an
    ``affinity`` key (the frontend passes its client id) lands on the same
    replica every time, so per-client traffic keeps its admission order and
    one client's burst warms one replica's batcher. When the sticky replica
    is overloaded (typed :class:`ServerOverloaded`), the request spills to
    the least-loaded other replica by pending rows — and only if EVERY
    replica refuses does the caller see the overload. A closed/dead replica
    is just a replica that refuses: requests re-route with the same typed
    error path, never a hang.
  * **per-replica model slots with STAGGERED hot swap** — each replica owns
    its own :class:`~deepfm_tpu.utils.export.LatestWatcher` (created with
    ``start=False``: no per-replica poll threads), and ONE coordinator
    thread walks the fleet sequentially calling ``check_once()``. A swap —
    including its off-to-the-side bucket prewarm — completes on replica k
    before replica k+1 even looks at LATEST, so the fleet never pays all
    its (already near-zero) blackouts at the same instant and old/new model
    versions briefly co-serve, exactly like a rolling production rollout.
  * **aggregate stats** — :func:`~deepfm_tpu.serve.stats.aggregate_summary`
    over the replicas' reservoirs: true fleet percentiles (concatenated
    latencies, not averaged percentiles), union-window QPS, and the
    worst-replica blackout plus the per-replica list the drill gates on.

Scaling honesty: on a time-sliced host (the 1-core CI box) replicas share
the same core, so aggregate QPS does NOT scale and this module makes no
claim that it does — the bench series labels those points, per BASELINE.md.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as trace_lib
from .admission import VALUE_DEFAULT, AdmissionShed
from .engine import ServeFuture, ServerOverloaded, ServeTimeout, \
    ServingEngine
from .stats import aggregate_summary


class HedgedFuture:
    """A caller-visible future over one or two engine legs: the primary
    submission plus (possibly) one hedge fired to another replica. First
    resolution wins — the loser is cancelled and counted, and the wrapper
    resolves exactly once (the engine futures are themselves first-wins, so
    a cancelled loser mid-flush resolving late is harmless).

    An errored leg does NOT resolve the wrapper while the other leg is
    still pending: a failed primary with a healthy hedge in flight waits
    for the hedge (and vice versa) — the caller only sees an error when no
    leg can succeed.
    """

    __slots__ = ("n", "lane", "value", "trace_id", "t_enqueue",
                 "latency_ms", "model_version", "home_idx", "_primary",
                 "_hedge", "_event", "_lock", "_winner", "_stats", "_clock")

    def __init__(self, primary: ServeFuture, *, home_idx: int, stats: Any,
                 clock: Callable[[], float]):
        self.n = primary.n
        self.lane = primary.lane
        self.value = primary.value
        self.trace_id = primary.trace_id
        self.t_enqueue = primary.t_enqueue
        self.latency_ms: Optional[float] = None
        self.model_version: Optional[int] = None
        self.home_idx = home_idx
        self._primary = primary
        self._hedge: Optional[ServeFuture] = None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._winner: Optional[ServeFuture] = None
        self._stats = stats
        self._clock = clock
        primary.add_done_callback(self._child_done)

    @property
    def hedged(self) -> bool:
        return self._hedge is not None

    def attach_hedge(self, fut: ServeFuture) -> bool:
        """Adopt a fired hedge leg; False (and cancel it) if the race is
        already over or a hedge is already attached."""
        with self._lock:
            if self._event.is_set() or self._hedge is not None:
                adopted = False
            else:
                self._hedge = fut
                self._stats.record_hedge_fired()
                adopted = True
        if not adopted:
            fut.cancel()
            return False
        # Register OUTSIDE the wrapper lock: a hedge leg can resolve the
        # instant it is submitted (result-cache hit, coalesced join onto a
        # finishing leader), in which case add_done_callback invokes
        # _child_done synchronously on THIS thread — which must be able to
        # take the wrapper lock. If the primary wins the narrow window
        # before this line, _child_done sees the attached hedge as the
        # loser and cancels it as usual.
        fut.add_done_callback(self._child_done)
        return True

    def _child_done(self, child: ServeFuture) -> None:
        won_by_hedge = False
        loser: Optional[ServeFuture] = None
        with self._lock:
            if self._event.is_set():
                return                      # race already decided
            other = self._hedge if child is self._primary else self._primary
            if child._error is not None and other is not None \
                    and not other.done():
                # This leg failed but the other may still answer: hold the
                # wrapper open; the other leg's callback decides.
                return
            self._winner = child
            self.latency_ms = 1000.0 * (self._clock() - self.t_enqueue)
            self.model_version = child.model_version
            won_by_hedge = child is self._hedge and child._error is None
            loser = other
            self._event.set()
        if loser is not None and not loser.done():
            loser.cancel()
            self._stats.record_hedge_cancelled()
        if won_by_hedge:
            self._stats.record_hedge_won()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def cache_hit(self) -> bool:
        """True when the leg that WON the race was a cache hit."""
        winner = self._winner
        return (winner or self._primary).cache_hit

    @property
    def coalesced(self) -> bool:
        """True when the winning leg joined an in-flight leader."""
        winner = self._winner
        return (winner or self._primary).coalesced

    def cancelled(self) -> bool:
        return self._primary.cancelled()

    def cancel(self) -> bool:
        self._primary.cancel()
        with self._lock:
            hedge = self._hedge
        if hedge is not None:
            hedge.cancel()
        return not self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise ServeTimeout(
                f"hedged request of {self.n} rows unresolved after "
                f"{timeout}s")
        winner = self._winner
        if winner._error is not None:
            raise winner._error
        return winner._probs


class ReplicatedEngine:
    """N :class:`ServingEngine` replicas behind one submit() surface."""

    #: The frontend checks this to pass its client id as the sticky key.
    supports_affinity = True
    #: The experiment router checks this to bypass the result cache on the
    #: shadow lane (every replica engine honours ``bypass_cache``).
    supports_cache_bypass = True

    def __init__(self, engines: Sequence[ServingEngine], *,
                 swap_poll_secs: float = 0.0, hedge_ms: float = 0.0,
                 hedge_poll_secs: float = 0.002,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        engines = list(engines)
        if not engines:
            raise ValueError("need at least one replica engine")
        if hedge_ms < 0:
            raise ValueError(f"hedge_ms must be >= 0, got {hedge_ms}")
        self._engines = engines
        self.max_batch = min(e.max_batch for e in engines)
        self.small_rows = max(e.small_rows for e in engines)
        self._swap_poll = float(swap_poll_secs)
        self._clock = clock
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Routing observability (tests + drill): how many requests each
        # replica admitted, and how many left their sticky replica.
        self.routed: List[int] = [0] * len(engines)
        self.spills = 0
        # Request hedging (0 disables; needs >= 2 replicas to have a
        # "somewhere else"). hedge_ms is the FLOOR of the hedge delay; the
        # effective delay tracks the fleet's recent p99 so hedges fire only
        # for genuine stragglers, not the median request.
        self.hedge_ms = float(hedge_ms)
        self._hedge_poll = float(hedge_poll_secs)
        self._hedge_enabled = self.hedge_ms > 0 and len(engines) > 1
        self._outstanding: List[HedgedFuture] = []
        self._recent_latencies: deque = deque(maxlen=512)
        self._coordinator: Optional[threading.Thread] = None
        self._hedger: Optional[threading.Thread] = None
        if start and self._swap_poll > 0 and any(
                e.watcher is not None for e in engines):
            self._coordinator = threading.Thread(
                target=self._run_coordinator, name="replica-swap-coordinator",
                daemon=True)
            self._coordinator.start()
        if start and self._hedge_enabled:
            self._hedger = threading.Thread(
                target=self._run_hedger, name="replica-hedge-monitor",
                daemon=True)
            self._hedger.start()

    # ------------------------------------------------------- construction
    @classmethod
    def serve_latest(cls, publish_dir: str, *, replicas: int = 2,
                     poll_secs: float = 2.0,
                     watcher_kw: Optional[dict] = None,
                     **kw: Any) -> "ReplicatedEngine":
        """``replicas`` engines, each following ``<publish_dir>/LATEST``
        through its OWN model slot, swaps staggered by the coordinator.

        Per-replica watchers are created with ``start=False`` — the
        coordinator thread here is the only poller, and its sequential
        walk IS the stagger. Engine kwargs (``max_batch``, ``inflight``,
        ``small_rows``, ``admission_kw``, ...) apply to every replica —
        ``admission_kw`` (not a shared ``admission`` instance) so each
        replica builds its OWN gate over its own queue. ``hedge_ms``
        enables request hedging across the fleet.
        """
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        hedge_ms = float(kw.pop("hedge_ms", 0.0))
        hedge_poll_secs = float(kw.pop("hedge_poll_secs", 0.002))
        wkw = dict(watcher_kw or {})
        wkw["start"] = False
        engines = [ServingEngine.serve_latest(
            publish_dir, poll_secs=poll_secs, watcher_kw=dict(wkw), **kw)
            for _ in range(replicas)]
        return cls(engines, swap_poll_secs=poll_secs, hedge_ms=hedge_ms,
                   hedge_poll_secs=hedge_poll_secs)

    # ------------------------------------------------------------ routing
    @property
    def engines(self) -> List[ServingEngine]:
        return list(self._engines)

    @property
    def replicas(self) -> int:
        return len(self._engines)

    @property
    def pending_rows(self) -> int:
        return sum(e.pending_rows for e in self._engines)

    def _next_attempt(self, affinity: Optional[int],
                      tried: List[int]) -> Optional[int]:
        """The next replica to try: the sticky home first (affinity mod N),
        then the least-loaded untried replica by pending rows — RE-READ at
        each attempt, not snapshotted once up front, so a burst of spills
        spreads across the fleet instead of piling onto whichever replica
        was least loaded at the instant the first spill was computed."""
        if affinity is not None:
            home = int(affinity) % len(self._engines)
            if home not in tried:
                return home
        remaining = [i for i in range(len(self._engines)) if i not in tried]
        if not remaining:
            return None
        return min(remaining,
                   key=lambda i: (self._engines[i].pending_rows, i))

    def submit(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
               affinity: Optional[int] = None,
               trace_id: Optional[int] = None,
               value: str = VALUE_DEFAULT,
               bypass_cache: bool = False) -> ServeFuture:
        """Route one request: sticky replica, spill on overload/shed, typed
        error only when EVERY replica refused (:class:`AdmissionShed` when
        every refusal was a shed — the fleet CHOSE to refuse this class —
        :class:`ServerOverloaded` otherwise). Malformed requests
        (ValueError) fail fast without re-routing — they would be rejected
        everywhere. With hedging enabled the returned future is a
        :class:`HedgedFuture` (same ``done()``/``result()`` surface)."""
        tried: List[int] = []
        home: Optional[int] = None
        last: Optional[Exception] = None
        all_sheds = True
        while True:
            idx = self._next_attempt(affinity, tried)
            if idx is None:
                break
            if home is None:
                home = idx
            tried.append(idx)
            try:
                fut = self._engines[idx].submit(feat_ids, feat_vals,
                                                trace_id=trace_id,
                                                value=value,
                                                bypass_cache=bypass_cache)
            except AdmissionShed as e:
                last = e
                continue
            except ServerOverloaded as e:
                last = e
                all_sheds = False
                continue
            with self._lock:
                self.routed[idx] += 1
                if affinity is not None and idx != home:
                    self.spills += 1
                    trace_lib.instant("serve.spill", replica=idx,
                                      home=home, trace_id=trace_id)
            if self._hedge_enabled:
                hedged = HedgedFuture(fut, home_idx=idx,
                                      stats=self._engines[idx].stats,
                                      clock=self._clock)
                with self._lock:
                    self._outstanding.append(hedged)
                return hedged
            return fut
        assert last is not None
        if all_sheds:
            raise AdmissionShed(
                f"all {len(self._engines)} replicas refused: {last}")
        raise ServerOverloaded(
            f"all {len(self._engines)} replicas refused: {last}")

    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                timeout: Optional[float] = None,
                affinity: Optional[int] = None,
                trace_id: Optional[int] = None,
                value: str = VALUE_DEFAULT) -> np.ndarray:
        return self.submit(feat_ids, feat_vals, affinity=affinity,
                           trace_id=trace_id, value=value).result(timeout)

    # ------------------------------------------------------------- hedging
    def hedge_delay_s(self) -> float:
        """Current hedge trigger: max(hedge_ms floor, fleet p99 of recent
        completions) — p99-tracked so hedges chase genuine stragglers."""
        floor = self.hedge_ms / 1000.0
        with self._lock:
            recent = list(self._recent_latencies)
        if len(recent) >= 20:
            return max(floor, float(np.percentile(recent, 99)) / 1000.0)
        return floor

    def hedge_pass(self, now: Optional[float] = None) -> int:
        """One monitor scan (public so tests drive it deterministically):
        prune resolved wrappers into the latency window, fire a hedge for
        every wrapper pending past the delay; returns hedges fired."""
        now = self._clock() if now is None else now
        delay = self.hedge_delay_s()
        fired = 0
        with self._lock:
            outstanding = list(self._outstanding)
        for hf in outstanding:
            if hf.done() or hf.cancelled():
                with self._lock:
                    try:
                        self._outstanding.remove(hf)
                    except ValueError:
                        pass
                    if hf.latency_ms is not None:
                        self._recent_latencies.append(hf.latency_ms)
                continue
            if hf.hedged or now - hf.t_enqueue < delay:
                continue
            others = [i for i in range(len(self._engines))
                      if i != hf.home_idx]
            # Least-loaded re-snapshot at fire time, same rule as spill.
            idx = min(others,
                      key=lambda i: (self._engines[i].pending_rows, i))
            try:
                fut = self._engines[idx].submit(
                    hf._primary.ids, hf._primary.vals,
                    trace_id=hf.trace_id, value=hf.value,
                    bypass_cache=hf._primary.cache_bypass)
            except (AdmissionShed, ServerOverloaded):
                continue    # fleet too hot to hedge; retry next pass
            if hf.attach_hedge(fut):
                fired += 1
                trace_lib.instant("serve.hedge", replica=idx,
                                  home=hf.home_idx, trace_id=hf.trace_id,
                                  delay_ms=round(delay * 1000.0, 3))
        return fired

    def _run_hedger(self) -> None:
        while not self._stop.wait(self._hedge_poll):
            self.hedge_pass()

    # ------------------------------------------------------ staggered swap
    def check_swaps_once(self) -> int:
        """One sequential stagger pass over the fleet; returns how many
        replicas swapped. Each ``check_once`` finishes (load + prewarm +
        swap) before the next replica's begins — at most one replica is
        ever mid-swap."""
        swapped = 0
        for eng in self._engines:
            watcher = eng.watcher
            if watcher is None:
                continue
            try:
                if watcher.check_once():
                    swapped += 1
            except Exception:  # noqa: BLE001 — poll faults never kill serving
                eng.stats.record_watcher_error()
        return swapped

    def _run_coordinator(self) -> None:
        while not self._stop.wait(self._swap_poll):
            self.check_swaps_once()

    # -------------------------------------------------------------- stats
    def summary(self) -> Dict[str, Any]:
        """Fleet aggregate (true fleet percentiles, union-window QPS,
        worst-replica + per-replica blackout/watcher-error lists), plus
        the per-replica bucket-prewarm counts from the owned watchers
        (None for a replica serving a plain fn without one)."""
        out = aggregate_summary([e.stats for e in self._engines])
        out["prewarmed_buckets_per_replica"] = [
            getattr(e.watcher, "prewarmed_buckets", None)
            for e in self._engines]
        return out

    def replica_summaries(self) -> List[Dict[str, Any]]:
        return [e.stats.summary() for e in self._engines]

    # ---------------------------------------------------------- lifecycle
    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the swap coordinator, then drain-close every replica —
        every admitted future across the fleet resolves."""
        self._stop.set()
        if self._coordinator is not None:
            self._coordinator.join(timeout=timeout)
            self._coordinator = None
        if self._hedger is not None:
            self._hedger.join(timeout=timeout)
            self._hedger = None
        for eng in self._engines:
            eng.close(timeout=timeout)

    def __enter__(self) -> "ReplicatedEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
