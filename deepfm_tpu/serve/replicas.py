"""Replica scale-out: N serving engines behind one frontend.

One :class:`~deepfm_tpu.serve.engine.ServingEngine` owns one device (or one
host time-slice); serving "millions of users" means running several behind
the same shm_ring frontend. :class:`ReplicatedEngine` presents the ENGINE
interface the frontend already speaks (``submit`` / ``pending_rows`` /
``close``) over a fleet of replicas, adding exactly three things:

  * **sticky routing with least-loaded spill** — a request carrying an
    ``affinity`` key (the frontend passes its client id) lands on the same
    replica every time, so per-client traffic keeps its admission order and
    one client's burst warms one replica's batcher. When the sticky replica
    is overloaded (typed :class:`ServerOverloaded`), the request spills to
    the least-loaded other replica by pending rows — and only if EVERY
    replica refuses does the caller see the overload. A closed/dead replica
    is just a replica that refuses: requests re-route with the same typed
    error path, never a hang.
  * **per-replica model slots with STAGGERED hot swap** — each replica owns
    its own :class:`~deepfm_tpu.utils.export.LatestWatcher` (created with
    ``start=False``: no per-replica poll threads), and ONE coordinator
    thread walks the fleet sequentially calling ``check_once()``. A swap —
    including its off-to-the-side bucket prewarm — completes on replica k
    before replica k+1 even looks at LATEST, so the fleet never pays all
    its (already near-zero) blackouts at the same instant and old/new model
    versions briefly co-serve, exactly like a rolling production rollout.
  * **aggregate stats** — :func:`~deepfm_tpu.serve.stats.aggregate_summary`
    over the replicas' reservoirs: true fleet percentiles (concatenated
    latencies, not averaged percentiles), union-window QPS, and the
    worst-replica blackout plus the per-replica list the drill gates on.

Scaling honesty: on a time-sliced host (the 1-core CI box) replicas share
the same core, so aggregate QPS does NOT scale and this module makes no
claim that it does — the bench series labels those points, per BASELINE.md.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import trace as trace_lib
from .engine import ServeFuture, ServerOverloaded, ServingEngine
from .stats import aggregate_summary


class ReplicatedEngine:
    """N :class:`ServingEngine` replicas behind one submit() surface."""

    #: The frontend checks this to pass its client id as the sticky key.
    supports_affinity = True

    def __init__(self, engines: Sequence[ServingEngine], *,
                 swap_poll_secs: float = 0.0, start: bool = True):
        engines = list(engines)
        if not engines:
            raise ValueError("need at least one replica engine")
        self._engines = engines
        self.max_batch = min(e.max_batch for e in engines)
        self.small_rows = max(e.small_rows for e in engines)
        self._swap_poll = float(swap_poll_secs)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Routing observability (tests + drill): how many requests each
        # replica admitted, and how many left their sticky replica.
        self.routed: List[int] = [0] * len(engines)
        self.spills = 0
        self._coordinator: Optional[threading.Thread] = None
        if start and self._swap_poll > 0 and any(
                e.watcher is not None for e in engines):
            self._coordinator = threading.Thread(
                target=self._run_coordinator, name="replica-swap-coordinator",
                daemon=True)
            self._coordinator.start()

    # ------------------------------------------------------- construction
    @classmethod
    def serve_latest(cls, publish_dir: str, *, replicas: int = 2,
                     poll_secs: float = 2.0,
                     watcher_kw: Optional[dict] = None,
                     **kw: Any) -> "ReplicatedEngine":
        """``replicas`` engines, each following ``<publish_dir>/LATEST``
        through its OWN model slot, swaps staggered by the coordinator.

        Per-replica watchers are created with ``start=False`` — the
        coordinator thread here is the only poller, and its sequential
        walk IS the stagger. Engine kwargs (``max_batch``, ``inflight``,
        ``small_rows``, ...) apply to every replica.
        """
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        wkw = dict(watcher_kw or {})
        wkw["start"] = False
        engines = [ServingEngine.serve_latest(
            publish_dir, poll_secs=poll_secs, watcher_kw=dict(wkw), **kw)
            for _ in range(replicas)]
        return cls(engines, swap_poll_secs=poll_secs)

    # ------------------------------------------------------------ routing
    @property
    def engines(self) -> List[ServingEngine]:
        return list(self._engines)

    @property
    def replicas(self) -> int:
        return len(self._engines)

    @property
    def pending_rows(self) -> int:
        return sum(e.pending_rows for e in self._engines)

    def _route_order(self, affinity: Optional[int]) -> List[int]:
        """Sticky replica first (affinity mod N), then the rest by load."""
        load = [(e.pending_rows, i) for i, e in enumerate(self._engines)]
        if affinity is None:
            # No sticky key: pure least-loaded (ties broken by index).
            return [i for _, i in sorted(load)]
        home = int(affinity) % len(self._engines)
        rest = sorted(pair for pair in load if pair[1] != home)
        return [home] + [i for _, i in rest]

    def submit(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
               affinity: Optional[int] = None,
               trace_id: Optional[int] = None) -> ServeFuture:
        """Route one request: sticky replica, spill on overload, typed
        :class:`ServerOverloaded` only when EVERY replica refused.
        Malformed requests (ValueError) fail fast without re-routing —
        they would be rejected everywhere."""
        order = self._route_order(affinity)
        last: Optional[ServerOverloaded] = None
        for pos, idx in enumerate(order):
            try:
                fut = self._engines[idx].submit(feat_ids, feat_vals,
                                                trace_id=trace_id)
            except ServerOverloaded as e:
                last = e
                continue
            with self._lock:
                self.routed[idx] += 1
                if affinity is not None and pos > 0:
                    self.spills += 1
                    trace_lib.instant("serve.spill", replica=idx,
                                      home=order[0], trace_id=trace_id)
            return fut
        assert last is not None
        raise ServerOverloaded(
            f"all {len(self._engines)} replicas refused: {last}")

    def predict(self, feat_ids: np.ndarray, feat_vals: np.ndarray,
                timeout: Optional[float] = None,
                affinity: Optional[int] = None,
                trace_id: Optional[int] = None) -> np.ndarray:
        return self.submit(feat_ids, feat_vals, affinity=affinity,
                           trace_id=trace_id).result(timeout)

    # ------------------------------------------------------ staggered swap
    def check_swaps_once(self) -> int:
        """One sequential stagger pass over the fleet; returns how many
        replicas swapped. Each ``check_once`` finishes (load + prewarm +
        swap) before the next replica's begins — at most one replica is
        ever mid-swap."""
        swapped = 0
        for eng in self._engines:
            watcher = eng.watcher
            if watcher is None:
                continue
            try:
                if watcher.check_once():
                    swapped += 1
            except Exception:  # noqa: BLE001 — poll faults never kill serving
                eng.stats.record_watcher_error()
        return swapped

    def _run_coordinator(self) -> None:
        while not self._stop.wait(self._swap_poll):
            self.check_swaps_once()

    # -------------------------------------------------------------- stats
    def summary(self) -> Dict[str, Any]:
        """Fleet aggregate (true fleet percentiles, union-window QPS,
        worst-replica + per-replica blackout/watcher-error lists), plus
        the per-replica bucket-prewarm counts from the owned watchers
        (None for a replica serving a plain fn without one)."""
        out = aggregate_summary([e.stats for e in self._engines])
        out["prewarmed_buckets_per_replica"] = [
            getattr(e.watcher, "prewarmed_buckets", None)
            for e in self._engines]
        return out

    def replica_summaries(self) -> List[Dict[str, Any]]:
        return [e.stats.summary() for e in self._engines]

    # ---------------------------------------------------------- lifecycle
    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the swap coordinator, then drain-close every replica —
        every admitted future across the fleet resolves."""
        self._stop.set()
        if self._coordinator is not None:
            self._coordinator.join(timeout=timeout)
            self._coordinator = None
        for eng in self._engines:
            eng.close(timeout=timeout)

    def __enter__(self) -> "ReplicatedEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
