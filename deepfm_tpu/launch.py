"""CLI launcher: ``python -m deepfm_tpu.launch --task_type train ...``

The L5/L3 entry point replacing the SageMaker notebook + ``tf.app.run()``
pair (reference ``1-ps-cpu/...py:469-471``). All reference hyperparameters
are accepted as ``--flag value`` argv (the SageMaker hyperparameter-dict
contract); SageMaker-style env defaults (``SM_CHANNELS`` etc.) are honored
by ``parse_args``. See ``examples/launch_tpu.md`` for slice-creation recipes.
"""

from __future__ import annotations

import json
import sys

from .config import parse_args
from .parallel import bootstrap
from .train import tasks
from .utils import logging as ulog
from .utils import preempt as preempt_lib


def main(argv=None) -> int:
    cfg = parse_args(argv)
    # Bootstrap before the first log line: rank-aware logging calls
    # jax.process_index(), which would initialize the XLA backend and break
    # a later jax.distributed.initialize() (it must run first).
    bootstrap.initialize(cfg)
    ulog.info("config: " + json.dumps(cfg.to_dict(), sort_keys=True))
    try:
        result = tasks.run(cfg)
    except preempt_lib.Preempted as p:
        # Graceful preemption: the checkpoint + resume sidecar are already
        # durable (the train task force-saved before raising). The distinct
        # exit code tells an orchestrator (scripts/supervise.py) "restart
        # me" as opposed to an ordinary crash.
        ulog.warning(f"exiting after preemption: {p}")
        print(json.dumps({"task": cfg.task_type, "preempted": True,
                          "step": p.step}))
        return preempt_lib.EXIT_PREEMPTED
    ulog.info(f"task {cfg.task_type} finished: {result}")
    print(json.dumps({"task": cfg.task_type, **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
