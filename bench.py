#!/usr/bin/env python
"""Benchmark harness: DeepFM training throughput on the reference config.

Measures steady-state examples/sec of the shipped training loop — K=8
optimizer steps per dispatch via ``Trainer.multi_step`` (one stacked
host->device transfer + one ``lax.scan`` program; forward + backward + Adam
update per step) — at the reference benchmark anchors (BASELINE.md):
feature_size=117581, field_size=39, embedding_size=32, deep_layers 128/64/32,
global batch 1024, Adam lr 5e-4 — on whatever accelerator JAX exposes (the
driver runs this on one real TPU chip). Host batches are pre-staged so the
number isolates transfer+device throughput; disk decode is benched separately
(~1.4M ex/s on this 1-core host, see BASELINE.md).

Also runs an 8-way-DP wiring check on a virtual 8-device CPU mesh (the
collective layout is identical to real multi-chip; the aggregate ratio it
reports is time-slicing overhead, NOT hardware scaling — real multi-chip
hardware is not available this round). Disable with --no-scaling.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec", "vs_baseline": N, ...}

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
comparison anchor is a documented nominal estimate of the reference Horovod
recipe: ~250k examples/sec aggregate on the 4xV100 p3.8xlarge (TF1 DeepFM at
batch 1024/GPU is input/update-bound, not FLOP-bound). Per-accelerator
baseline = 62.5k examples/sec; vs_baseline = measured_per_chip / 62.5k.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

K_STEPS = 8          # steps per dispatch (cfg.steps_per_loop default)
N_DISPATCH = 12      # dispatches per trial -> 96 steps/trial
N_TRIALS = 5


def _make_groups(cfg, n_groups: int):
    rng = np.random.default_rng(0)
    groups = []
    for _ in range(n_groups):
        group = []
        for _ in range(K_STEPS):
            group.append({
                "feat_ids": rng.integers(
                    0, cfg.feature_size,
                    (cfg.batch_size, cfg.field_size)).astype(np.int32),
                "feat_vals": rng.normal(
                    size=(cfg.batch_size, cfg.field_size)).astype(np.float32),
                "label": (rng.random(
                    (cfg.batch_size, 1)) < 0.25).astype(np.float32),
            })
        groups.append(group)
    return groups


def measure(cfg) -> dict:
    """Best-of-N-trials throughput of put_superbatch + multi_step(K)."""
    import jax

    from deepfm_tpu.train import Trainer

    n_dev = len(jax.devices())
    trainer = Trainer(cfg)
    state = trainer.init_state()
    groups = _make_groups(cfg, 4)

    step = trainer.multi_step
    for g in groups[:2]:  # warmup/compile
        state, m = step(state, trainer.put_superbatch(g))
    jax.block_until_ready(m["loss"])

    # Several trials, best wins: host/tunnel jitter dominates a single trial;
    # the fastest trial is the honest steady-state device+transfer throughput.
    dt = float("inf")
    for _ in range(N_TRIALS):
        t0 = time.perf_counter()
        for i in range(N_DISPATCH):
            state, m = step(state, trainer.put_superbatch(groups[i % 4]))
        jax.block_until_ready(m["loss"])
        dt = min(dt, time.perf_counter() - t0)

    # Device-only series: the same dispatch loop over PRE-STAGED device
    # superbatches — no bulk host->device data transfer inside the timed
    # window (VERDICT r3 #6). NOT fully tunnel-free: each dispatch is
    # still an RPC through the chip tunnel, so congested windows inflate
    # this series too (measured same-day swings 0.015 -> 3.0 ms/step with
    # identical code; all blocking modes agree, so it is launch latency,
    # not under-blocking). Best-of-N picks the clean window; host_series
    # is the fully tunnel-free canary.
    sb_dev = [trainer.put_superbatch(g) for g in groups]
    dt_dev = float("inf")
    for _ in range(N_TRIALS):
        t0 = time.perf_counter()
        for i in range(N_DISPATCH):
            state, m = step(state, sb_dev[i % 4])
        jax.block_until_ready(m["loss"])
        dt_dev = min(dt_dev, time.perf_counter() - t0)

    n_examples = N_DISPATCH * K_STEPS * cfg.batch_size
    total_eps = n_examples / dt
    return {
        "devices": n_dev,
        "total_eps": total_eps,
        "per_chip_eps": total_eps / max(n_dev, 1),
        "ms_per_step": 1000 * dt / (N_DISPATCH * K_STEPS),
        "device_only_ms_per_step": 1000 * dt_dev / (N_DISPATCH * K_STEPS),
        "loss": float(m["loss"]),
    }


def host_stage_series() -> dict:
    """Tunnel-free host-pipeline series (VERDICT r3 #6): ns/record of the
    TFRecord frame stage, the full decode-to-arrays stage, and the complete
    staged pipeline (decode pool + shuffle + batch assembly) on synthetic
    Criteo-shaped data. Runs entirely on the host CPU — stable across
    rounds regardless of TPU-tunnel weather, so deltas here are real
    regressions in the data path, not weather."""
    import glob as glob_mod
    import tempfile

    from deepfm_tpu.data import libsvm
    from deepfm_tpu.data.pipeline import CtrPipeline
    from deepfm_tpu.native import loader
    from deepfm_tpu.utils import profiling

    out = {}
    with tempfile.TemporaryDirectory() as d:
        libsvm.generate_synthetic_ctr(
            d, num_files=2, examples_per_file=20000,
            feature_size=117581, field_size=39, prefix="tr", seed=0)
        files = sorted(glob_mod.glob(os.path.join(d, "tr*.tfrecords")))
        bufs = [open(f, "rb").read() for f in files]
        n_records = 2 * 20000

        def best_of(fn, trials=3):
            best = float("inf")
            for _ in range(trials):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best

        if loader.available():
            dt = best_of(lambda: [loader.split_frames(b, verify_crc=False)
                                  for b in bufs])
            out["frame_ns_per_record"] = round(1e9 * dt / n_records, 1)
            dt = best_of(lambda: [loader.decode_file_bytes(
                b, 39, verify_crc=False) for b in bufs])
            out["decode_ns_per_record"] = round(1e9 * dt / n_records, 1)

        def make_pipe(**kw):
            return CtrPipeline(
                files, field_size=39, batch_size=1024, num_epochs=1,
                shuffle=True, shuffle_files=True, drop_remainder=True,
                seed=0, **kw)

        def staged_ns(trials=3, with_stages=False, **kw):
            """Best-of-N ns/record of the full staged pipeline. The
            pipeline is built OUTSIDE the timed region (construction is
            not staging cost) and the denominator is the record count the
            pipeline actually returned — drop_remainder eats the tail, so
            dividing by the on-disk count understated the per-record cost
            (advisor r5, both). With ``with_stages`` the BEST trial's
            per-stage breakdown rides along (read/frame/decode_assemble/
            emit + unattributed 'other'), so a total-ns regression is
            attributable to a stage, not just asserted."""
            best, n = float("inf"), 0
            breakdown = None
            for _ in range(trials):
                pipe = make_pipe(**kw)  # single-use: fresh per trial
                stats = profiling.HostStageStats() if with_stages else None
                pipe.stage_stats = stats
                t0 = time.perf_counter()
                n = sum(n_ex for _, _, n_ex
                        in pipe.iter_superbatches(K_STEPS))
                dt = time.perf_counter() - t0
                if dt < best:
                    best = dt
                    if stats is not None:
                        per = stats.ns_per_record(n)
                        per["other"] = round(
                            1e9 * dt / max(n, 1) - sum(per.values()), 1)
                        breakdown = per
            return round(1e9 * best / max(n, 1), 1), n, breakdown

        out["staged_pipeline_ns_per_record"], n_staged, stage_bd = staged_ns(
            with_stages=True)
        out["staged_records_returned"] = n_staged
        if stage_bd is not None:
            out["host_stage_breakdown_ns_per_record"] = stage_bd
        if "decode_ns_per_record" in out:
            # What the pool/shuffle/assembly machinery costs on top of the
            # raw decode — the part a decoded-epoch cache cannot remove.
            out["pool_overhead_ns_per_record"] = round(
                out["staged_pipeline_ns_per_record"]
                - out["decode_ns_per_record"], 1)

        # Decoded-epoch cache, warm: every trial pipeline hits the RAM
        # registry (built once, outside the timed region), so this is the
        # cached-epoch cost — pool + batch slicing over memres columns,
        # zero frame/decode.
        from deepfm_tpu.data import cache as cache_lib
        cache_lib.clear_ram_cache()
        make_pipe(decoded_cache="ram").decoded_epoch_columns()
        out["cached_epoch_ns_per_record"], _, _ = staged_ns(
            decoded_cache="ram")
        out["cached_over_staged_ratio"] = round(
            out["cached_epoch_ns_per_record"]
            / max(out["staged_pipeline_ns_per_record"], 1e-9), 3)

        if loader.available():
            # Forced fused-assembly fallback (per-chunk scatter decode):
            # quantifies what the one-C-call-per-drain path buys, and keeps
            # an always-on measurement of the kill-switch path.
            out["staged_fallback_ns_per_record"], _, _ = staged_ns(
                native_assembly=False)
            # Prefetch-thread-free: on a 1-core bench host the prefetch
            # thread is pure GIL contention with this consumer (it exists
            # to overlap DEVICE work, absent here), so this series is the
            # pipeline's own cost without measurement-rig interference.
            out["staged_noprefetch_ns_per_record"], _, _ = staged_ns(
                prefetch_batches=0)
            # Worker path: decode in 2 processes feeding shared-memory
            # slabs. On a multi-core host this should beat the in-process
            # series; on a 1-core host it mostly measures IPC overhead —
            # report both and let the reader compare against nproc.
            out["staged_workers2_ns_per_record"], _, _ = staged_ns(
                input_workers=2)
            out["host_cores"] = os.cpu_count()

            def stream_hash(**kw):
                import hashlib
                h = hashlib.blake2b(digest_size=12)
                for rows, m, n_ex in make_pipe(**kw).iter_superbatches(
                        K_STEPS):
                    for key in ("label", "feat_ids", "feat_vals"):
                        h.update(rows[key].tobytes())
                return h.hexdigest()

            # Same-seed parity: the worker path must emit the bit-identical
            # batch stream (same records, same shuffle, same grouping).
            out["worker_parity_bit_identical"] = (
                stream_hash() == stream_hash(input_workers=2))
            # ...as must the fused-assembly kill switch (per-chunk scatter).
            out["assembly_parity_bit_identical"] = (
                stream_hash() == stream_hash(native_assembly=False))
            # ...and so must a cached epoch (whole-epoch pool: emission is
            # one full permutation, independent of chunk arrival shape).
            out["cache_parity_bit_identical"] = (
                stream_hash() == stream_hash(decoded_cache="ram"))
    return out


def _model_flops_per_example(cfg) -> float:
    """Analytic training FLOPs per example at the bench shape.

    Dense-math inventory of one example: the DNN tower matmuls (2*m*n
    FLOPs each) over [F*k, *deep_layers, 1] plus the FM second-order
    interaction (~5*F*k: square-of-sum, sum-of-squares, combine on [F, k]).
    Embedding gathers and the first-order term are lookups/adds of
    negligible FLOP count. Training ~= 3x forward (backward re-runs each
    matmul twice: grad-wrt-input and grad-wrt-weights)."""
    layers = [int(x) for x in str(cfg.deep_layers).split(",") if x]
    dims = [cfg.field_size * cfg.embedding_size] + layers + [1]
    dnn = sum(2 * m * n for m, n in zip(dims[:-1], dims[1:]))
    fm = 5 * cfg.field_size * cfg.embedding_size
    return 3.0 * (dnn + fm)


# Peak-FLOPS tables and the MFU basis labels live in deepfm_tpu.utils.mfu
# so bench.py and bench_multiprocess.py stamp the same in-band basis
# (measured-device-peak | nominal-estimate | unavailable) on every MFU.


def _bench_cfg(batch_size: int = 1024, mesh_data: int = 0,
               mesh_model: int = 1, use_pallas: bool = True, **extra):
    from deepfm_tpu.config import Config
    return Config(
        feature_size=117581, field_size=39, embedding_size=32,
        deep_layers="128,64,32", dropout="0.5,0.5,0.5",
        batch_size=batch_size, learning_rate=5e-4, optimizer="Adam",
        l2_reg=1e-4, compute_dtype="bfloat16", mesh_data=mesh_data,
        mesh_model=mesh_model, log_steps=0, seed=0, steps_per_loop=K_STEPS,
        use_pallas=use_pallas, **extra)


def device_resident_series() -> dict:
    """End-to-end epoch throughput: staged host pipeline vs --device_dataset
    over the SAME files, cache, and trainer config on one chip. The staged
    number pays decode-or-cache + pool + host->device transfer per epoch;
    the device-resident number pays a one-time column upload, then each
    dispatch ships ONE int32 cursor. Warmup epoch first (compiles + builds
    the cache + uploads), then best-of-2 measured epochs per mode."""
    import glob as glob_mod
    import tempfile

    from deepfm_tpu.data import cache as cache_lib
    from deepfm_tpu.data import libsvm
    from deepfm_tpu.train import Trainer
    from deepfm_tpu.train import tasks as tasks_lib

    with tempfile.TemporaryDirectory() as d:
        libsvm.generate_synthetic_ctr(
            d, num_files=2, examples_per_file=8192,
            feature_size=117581, field_size=39, prefix="tr", seed=0)
        files = sorted(glob_mod.glob(os.path.join(d, "tr*.tfrecords")))
        cfg = _bench_cfg(mesh_data=1, decoded_cache="ram",
                         shuffle_buffer=1 << 20, drop_remainder=True)

        def run(device: bool) -> float:
            cache_lib.clear_ram_cache()
            trainer = Trainer(cfg)
            state = trainer.init_state()
            best, n = float("inf"), 0
            for e in range(3):  # epoch 0 = warmup (compile/cache/upload)
                pipe = tasks_lib.make_pipeline(
                    cfg, files, epochs=1, shuffle=True, epoch_offset=e)
                t0 = time.perf_counter()
                if device:
                    state, m = trainer.fit_device_resident(state, pipe)
                else:
                    state, m = trainer.fit(state, pipe)
                dt = time.perf_counter() - t0
                n = int(m["steps"]) * cfg.batch_size
                if e:
                    best = min(best, dt)
            return n / best

        staged = run(False)
        # Preflight: if this config is ineligible the honest answer is an
        # explicit reason, not a silently-staged "device" number.
        trainer = Trainer(cfg)
        cache_lib.clear_ram_cache()
        probe = tasks_lib.make_pipeline(cfg, files, epochs=1, shuffle=True)
        reason = trainer.device_dataset_ineligible(probe)
        if reason is not None:
            return {"staged_ex_per_s": round(staged, 1),
                    "device_resident_ineligible": reason}
        device = run(True)
        return {
            "staged_ex_per_s": round(staged, 1),
            "device_resident_ex_per_s": round(device, 1),
            "device_over_staged_speedup": round(device / max(staged, 1e-9),
                                                3),
        }


def online_publish_series() -> dict:
    """Hot-publishing interference: ex/s of the same pre-staged dispatch
    loop with the Publisher hook active vs absent (the <5% acceptance bar
    from docs/TUNING.md §2.9), plus publish latency p50/p99 and worst-case
    artifact staleness. The hook's synchronous cost is the device->host
    params snapshot; the artifact write itself runs on the async executor,
    so on a real TPU it overlaps device compute (on a 1-core CPU host the
    background export steals the only core and the overhead reads high)."""
    import shutil
    import tempfile

    import jax

    from deepfm_tpu.train import Trainer
    from deepfm_tpu.train.publish import Publisher

    cfg = _bench_cfg()
    trainer = Trainer(cfg)
    state = trainer.init_state()
    sb = [trainer.put_superbatch(g) for g in _make_groups(cfg, 4)]
    step = trainer.multi_step
    state, m = step(state, sb[0])  # compile
    jax.block_until_ready(m["loss"])

    def run(publisher):
        nonlocal state
        dt = float("inf")
        steps = 0
        for _ in range(N_TRIALS):
            t0 = time.perf_counter()
            for i in range(N_DISPATCH):
                state, m = step(state, sb[i % 4])
                steps += K_STEPS
                if publisher is not None:
                    publisher.maybe_publish(state, steps)
            jax.block_until_ready(m["loss"])
            dt = min(dt, time.perf_counter() - t0)
        return N_DISPATCH * K_STEPS * cfg.batch_size / dt

    off_eps = run(None)
    tmp = tempfile.mkdtemp(prefix="bench_publish_")
    try:
        # ~3 cadence crossings per trial; in-flight skips (counted below)
        # are the expected steady state when the export outlasts the
        # interval, exactly as in production short-cadence configs.
        pub = Publisher(trainer.model, cfg, tmp,
                        every_steps=N_DISPATCH * K_STEPS // 3)
        on_eps = run(pub)
        pub.close()
        stats = pub.stats()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "publish_off_ex_per_s": round(off_eps, 1),
        "publish_on_ex_per_s": round(on_eps, 1),
        "online_publish_overhead_pct": round(
            100.0 * (1.0 - on_eps / max(off_eps, 1e-9)), 2),
        "publish_count": stats["publish_count"],
        "publish_skipped_inflight": stats["publish_skipped_inflight"],
        "publish_latency_p50_s": (
            round(stats["publish_latency_p50_s"], 3)
            if stats["publish_latency_p50_s"] is not None else None),
        "publish_latency_p99_s": (
            round(stats["publish_latency_p99_s"], 3)
            if stats["publish_latency_p99_s"] is not None else None),
        "publish_staleness_steps_max": stats["publish_staleness_steps_max"],
    }


def observability_series() -> dict:
    """Telemetry-plane overhead: ex/s of the same pre-staged dispatch loop
    with ``--trace off`` vs ``ring`` (acceptance: < 2% — cheap enough to
    leave on), the raw per-span cost in each mode, and the metrics
    SnapshotWriter's per-write cost. Honesty: on a 1-core CPU host span
    emission contends with compute for the only core, so the measured
    overhead is an upper bound — on a TPU host the host-side span emit
    overlaps the async-dispatched device step."""
    import tempfile

    import jax

    from deepfm_tpu.obs import metrics as obs_metrics
    from deepfm_tpu.obs import trace as trace_lib

    cfg = _bench_cfg()
    from deepfm_tpu.train import Trainer
    trainer = Trainer(cfg)
    state = trainer.init_state()
    sb = [trainer.put_superbatch(g) for g in _make_groups(cfg, 4)]
    step = trainer.multi_step
    state, m = step(state, sb[0])  # compile
    jax.block_until_ready(m["loss"])

    def run() -> float:
        # The loop as loop.fit instruments it: one train.dispatch span per
        # dispatch (the hot-path span density; the staging spans fire on
        # the transfer path, absent with pre-staged superbatches).
        nonlocal state
        dt = float("inf")
        for _ in range(N_TRIALS):
            t0 = time.perf_counter()
            for i in range(N_DISPATCH):
                with trace_lib.span("train.dispatch", steps=K_STEPS,
                                    examples=cfg.batch_size):
                    state, m = step(state, sb[i % 4])
            jax.block_until_ready(m["loss"])
            dt = min(dt, time.perf_counter() - t0)
        return N_DISPATCH * K_STEPS * cfg.batch_size / dt

    def span_cost_ns(n: int = 20000) -> float:
        t0 = time.perf_counter_ns()
        for _ in range(n):
            with trace_lib.span("bench.probe", i=0):
                pass
        return (time.perf_counter_ns() - t0) / n

    trace_lib.reset()
    off_eps = run()
    off_ns = span_cost_ns()
    trace_lib.configure("ring", export_env=False)
    ring_eps = run()
    ring_ns = span_cost_ns()
    dropped = trace_lib.dropped()
    trace_lib.reset()

    # SnapshotWriter cost with the live registry (whatever stat objects
    # this process auto-registered so far).
    with tempfile.TemporaryDirectory() as d:
        w = obs_metrics.SnapshotWriter(os.path.join(d, "metrics.jsonl"),
                                       period_secs=0.02)
        time.sleep(0.3)
        w.close()
        writes, write_s = w.writes, w.write_s

    overhead_pct = 100.0 * (1.0 - ring_eps / max(off_eps, 1e-9))
    return {
        "trace_off_ex_per_s": round(off_eps, 1),
        "trace_ring_ex_per_s": round(ring_eps, 1),
        "trace_overhead_pct": round(overhead_pct, 2),
        "trace_overhead_lt_2pct": bool(overhead_pct < 2.0),
        "span_cost_off_ns": round(off_ns, 1),
        "span_cost_ring_ns": round(ring_ns, 1),
        "ring_dropped_spans": dropped,
        "snapshot_writes": writes,
        "snapshot_write_ms_mean": round(1000.0 * write_s / max(writes, 1),
                                        3),
        "overhead_basis": "1-core-CPU-host-upper-bound",
    }


def export_serving_artifacts(workdir: str) -> str:
    """Two complete bench-config artifacts + LATEST->1 under ``workdir``
    (the mid-run swap is then a pure pointer move + off-to-the-side load,
    as in production — the publisher never writes into a live artifact
    dir). Returns ``workdir``. Split out so a sweep exports ONCE and runs
    many engine configurations against the same artifacts."""
    from deepfm_tpu.train import Trainer
    from deepfm_tpu.utils import export as export_lib

    cfg = _bench_cfg()
    trainer = Trainer(cfg)
    state = trainer.init_state()
    orig_tf = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # not served
    try:
        for version in ("1", "2"):
            export_lib.export_serving(
                trainer.model, state, cfg, os.path.join(workdir, version))
    finally:
        export_lib._export_tf_savedmodel = orig_tf
    export_lib.write_latest(workdir, "1")
    return workdir


def serving_series(replicas: int = 1, inflight: int = 2,
                   small_rows: int = 4, run_secs: float = 3.0,
                   n_clients: int = 4,
                   artifact_dir: "str | None" = None) -> dict:
    """Serving runtime under synthetic closed-loop load, with a hot swap
    mid-run: per-request latency p50/p99 (global and per priority lane),
    QPS, batch occupancy, and the measured swap blackout (swap instant ->
    first completed flush that EXECUTED the new model version).

    Parameterized for the scale-out sweep (``scripts/bench_serving.py``):
    ``replicas`` > 1 runs a ReplicatedEngine fleet (sticky client
    affinity, staggered swaps), ``inflight`` sets the pipelined batching
    depth, ``small_rows`` the priority-lane threshold. ``artifact_dir``
    reuses pre-exported artifacts (export once, sweep many).

    Honesty fields mirror the train series: ``device_kind`` names the chip
    that actually served; ``load_kind`` labels the traffic as a
    closed-loop synthetic driver (``n_clients`` in-process clients, batch
    1..32), NOT a production trace — occupancy/QPS are properties of that
    load; ``host_cpu_count`` is what a replica-scaling reading must be
    judged against (replicas time-slice the same cores on this box)."""
    import shutil
    import tempfile
    import threading

    import jax

    from deepfm_tpu.serve import ReplicatedEngine, ServingEngine
    from deepfm_tpu.utils import export as export_lib

    cfg = _bench_cfg()
    max_req = 32
    tmp = artifact_dir or export_serving_artifacts(
        tempfile.mkdtemp(prefix="bench_serving_"))
    export_lib.write_latest(tmp, "1")   # reset for sweep re-entry
    orig_tf = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # not served
    try:
        engine_kw = dict(poll_secs=0.05, max_batch=256, max_delay_ms=2.0,
                         inflight=inflight, small_rows=small_rows)
        if replicas > 1:
            engine = ReplicatedEngine.serve_latest(
                tmp, replicas=replicas, **engine_kw)
            watchers = [e.watcher for e in engine.engines]
        else:
            engine = ServingEngine.serve_latest(tmp, **engine_kw)
            watchers = [engine.watcher]
        stop = threading.Event()
        failures = []

        def client(seed):
            rng = np.random.default_rng(seed)
            kw = ({"affinity": seed}
                  if getattr(engine, "supports_affinity", False) else {})
            while not stop.is_set():
                n = int(rng.integers(1, max_req + 1))
                ids = rng.integers(0, cfg.feature_size,
                                   (n, cfg.field_size)).astype(np.int32)
                vals = rng.normal(size=(n, cfg.field_size)).astype(np.float32)
                try:
                    engine.predict(ids, vals, timeout=30, **kw)
                except Exception as e:  # noqa: BLE001 — the honesty counter
                    failures.append(repr(e))
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(n_clients)]
        for t in threads:
            t.start()
        try:
            time.sleep(run_secs / 2)
            export_lib.write_latest(tmp, "2")   # the hot swap, under load
            time.sleep(run_secs / 2)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        if replicas > 1:
            summary = engine.summary()
            blackout_per_replica = summary["swap_blackout_ms_per_replica"]
        else:
            summary = engine.stats.summary()
            blackout_per_replica = [summary["swap_blackout_ms"]]
        swaps = min(w.swap_count for w in watchers)
        swap_failures = sum(w.swap_failures for w in watchers)
        engine.close()
    finally:
        export_lib._export_tf_savedmodel = orig_tf
        if artifact_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return {
        "replicas": replicas,
        "serve_inflight": inflight,
        "serve_small_rows": small_rows,
        "serving_p50_ms": summary["serving_p50_ms"],
        "serving_p99_ms": summary["serving_p99_ms"],
        "serving_small_p50_ms": summary["serving_small_p50_ms"],
        "serving_small_p99_ms": summary["serving_small_p99_ms"],
        "serving_large_p50_ms": summary["serving_large_p50_ms"],
        "serving_large_p99_ms": summary["serving_large_p99_ms"],
        "serving_qps": summary["serving_qps"],
        "batch_occupancy_pct": summary["batch_occupancy_pct"],
        "swap_blackout_ms": summary["swap_blackout_ms"],
        "swap_blackout_ms_per_replica": blackout_per_replica,
        "serving_requests": summary["serving_requests"],
        "serving_failed": summary["serving_failed"] + len(failures),
        "serving_overloads": summary["serving_overloads"],
        "hot_swaps": swaps,
        "swap_failures": swap_failures,
        "clients": n_clients,
        "load_kind": "synthetic-closed-loop",
        "device_kind": jax.devices()[0].device_kind,
        "host_cpu_count": os.cpu_count(),
    }


def experiment_series(n_requests: int = 150, max_req: int = 4,
                      permille: int = 100, qps: float = 50.0,
                      rounds: int = 5) -> dict:
    """Cost of the gated-deployment plane, in three numbers.

    1. **Shadow overhead** — primary-lane p99 for the SAME deterministic
       paced request stream served bare (one engine) vs. through an
       ``ExperimentRouter`` in shadow mode (``permille``/1000 of requests
       duplicated to a second engine on the side lane). The acceptance bar
       is < 10% p99 overhead on this host. Two design choices make the
       number mean something on a 1-core box: the load is a PACED open
       loop below saturation (production serving is not run at 100% CPU —
       a back-to-back closed loop would measure core time-slicing, not
       router overhead), and the challenger engine batches its shadow rows
       with a generous ``max_delay_ms`` — the shadow lane's response is
       never returned to anyone, so it is latency-insensitive by
       definition, and delaying its flush schedules challenger compute
       into the pacing gaps instead of on top of the primary's own
       service window. Baseline and shadow passes ALTERNATE for
       ``rounds`` rounds and the reported p99s are medians-of-rounds, so
       host drift (the dominant noise source here) hits both arms
       equally.
    2. **Promotion pointer-move latency** — wall time of the
       ``PromotionController.observe()`` call that PROMOTES (history
       append + atomic ``LATEST`` move), sampled over fresh controllers.
       This is the control-plane step a canary waits on after its last
       passing window.
    3. **Rollback detection windows** — health windows observed until each
       poison kind (NaN, absolute-latency, calibration, staleness) flips
       the decision to ``rollback``. Gate evaluation is a pure function of
       the window, so every breach kind must detect in exactly 1 window —
       this series is the regression trip-wire for that contract (a value
       > 1 means a guardrail went soft).

    Honesty fields: ``device_kind`` names the serving chip; ``load_kind``
    labels the stream (single paced client at ``qps``, not a production
    trace); ``host_cpu_count`` says how independent the two arms' compute
    really is on this box — both arms time-slice the same core(s), which
    INFLATES measured shadow overhead relative to a host with real spare
    capacity, so the < 10% bar is conservative here."""
    import shutil
    import tempfile

    import jax

    from deepfm_tpu.serve import ARM_CHALLENGER, ExperimentRouter, \
        ServingEngine
    from deepfm_tpu.train import promote as promote_lib
    from deepfm_tpu.utils import export as export_lib

    cfg = _bench_cfg()
    tmp = export_serving_artifacts(tempfile.mkdtemp(prefix="bench_exp_"))
    try:
        buckets = export_lib.serving_buckets(16)
        control = ServingEngine(
            export_lib.load_serving(os.path.join(tmp, "1"),
                                    buckets=tuple(buckets)),
            max_batch=16, max_delay_ms=0.5, buckets=buckets)
        challenger = ServingEngine(
            export_lib.load_serving(os.path.join(tmp, "2"),
                                    buckets=tuple(buckets)),
            max_batch=16, max_delay_ms=25.0, buckets=buckets)

        rng = np.random.default_rng(7)
        stream = []
        for rid in range(n_requests):
            n = int(rng.integers(1, max_req + 1))
            ids = rng.integers(0, cfg.feature_size,
                               (n, cfg.field_size)).astype(np.int32)
            vals = rng.normal(size=(n, cfg.field_size)).astype(np.float32)
            stream.append((rid, ids, vals))
        for eng in (control, challenger):    # compile every bucket up front
            for n in range(1, max_req + 1):
                eng.predict(np.zeros((n, cfg.field_size), np.int32),
                            np.zeros((n, cfg.field_size), np.float32),
                            timeout=60)

        def drive(submit):
            lat = []
            t0 = time.monotonic()
            for i, (rid, ids, vals) in enumerate(stream):
                wait = t0 + i / qps - time.monotonic()
                if wait > 0:
                    time.sleep(wait)
                s = time.monotonic()
                submit(rid, ids, vals)
                lat.append((time.monotonic() - s) * 1000.0)
            lat.sort()
            return (lat[len(lat) // 2],
                    lat[min(len(lat) - 1, int(0.99 * len(lat)))])

        router = ExperimentRouter(control, challenger, mode="shadow",
                                  seed=7, challenger_permille=permille,
                                  shadow_slo_ms=0.0)
        base_p50s, base_p99s, shadow_p50s, shadow_p99s = [], [], [], []
        for _ in range(rounds):
            b50, b99 = drive(lambda rid, ids, vals:
                             control.predict(ids, vals, timeout=30))
            s50, s99 = drive(lambda rid, ids, vals:
                             router.predict(ids, vals, rid, timeout=30))
            base_p50s.append(b50)
            base_p99s.append(b99)
            shadow_p50s.append(s50)
            shadow_p99s.append(s99)

        def med(xs):
            return round(sorted(xs)[len(xs) // 2], 3)
        base_p50, base_p99 = med(base_p50s), med(base_p99s)
        shadow_p50, shadow_p99 = med(shadow_p50s), med(shadow_p99s)
        shadowed = rounds * sum(1 for rid, _, _ in stream
                                if router.assign(rid) == ARM_CHALLENGER)
        deadline = time.monotonic() + 30.0    # drain the side lane before
        while time.monotonic() < deadline:    # reading its counters
            s = router.summary()
            if (s["shadow_completed"] + s["shadow_errors"]
                    >= s["shadow_submitted"]):
                break
            time.sleep(0.01)
        router_summary = router.summary()
        router.close()
        for eng in (control, challenger):
            eng.close()

        # --- promotion pointer-move latency (control plane, no serving) --
        gates = promote_lib.GateConfig(
            min_samples=1, min_auc_delta=-0.05, max_p99_ratio=10.0,
            max_p99_ms=1000.0, max_nonfinite=0, max_calibration_err=0.25,
            max_candidate_age_s=3600.0, windows_required=1)
        healthy = dict(arm=1, n=1000, auc=0.75, p99_latency_ms=5.0,
                       nonfinite=0, mean_pred=0.5, observed_ctr=0.5,
                       calibration_err=0.0)
        ctl_health = dict(healthy, arm=0)
        promote_ms = []
        for _ in range(5):
            export_lib.write_latest(tmp, "1")
            ctl = promote_lib.PromotionController(tmp, gates=gates)
            assert ctl.offer("2")
            t0 = time.monotonic()
            d = ctl.observe(healthy, ctl_health)
            promote_ms.append((time.monotonic() - t0) * 1000.0)
            assert d.action == "promote", d
        promote_ms.sort()

        # --- rollback detection windows per poison kind ------------------
        poisons = {
            "nan": (dict(healthy, nonfinite=7),
                    promote_lib.REASON_NONFINITE, None),
            "latency": (dict(healthy, p99_latency_ms=5000.0),
                        promote_lib.REASON_LATENCY, None),
            "calibration": (dict(healthy, mean_pred=0.9,
                                 calibration_err=0.4),
                            promote_lib.REASON_CALIBRATION, None),
            "stale": (healthy, promote_lib.REASON_STALE, 7200.0),
        }
        detection = {}
        for kind, (health, reason, age_s) in poisons.items():
            ctl = promote_lib.PromotionController(tmp, gates=gates)
            assert ctl.offer("1", now_s=0.0 if age_s is not None else None)
            windows = 0
            while True:
                windows += 1
                kw = {"now_s": age_s} if age_s is not None else {}
                d = ctl.observe(health, ctl_health, **kw)
                if d.action == "rollback":
                    break
                assert windows < 10, f"{kind} never detected"
            detection[kind] = {"windows": windows,
                               "reason_typed": reason in d.reasons}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "requests_per_round": n_requests,
        "rounds": rounds,
        "offered_qps": qps,
        "challenger_permille": permille,
        "shadow_duplicated": shadowed,
        "baseline_p50_ms": base_p50,
        "baseline_p99_ms": base_p99,
        "shadow_p50_ms": shadow_p50,
        "shadow_p99_ms": shadow_p99,
        "baseline_p99_ms_rounds": [round(x, 3) for x in base_p99s],
        "shadow_p99_ms_rounds": [round(x, 3) for x in shadow_p99s],
        "shadow_p99_overhead_pct": round(
            (shadow_p99 - base_p99) / base_p99 * 100.0, 2)
        if base_p99 > 0 else None,
        "shadow_errors": router_summary["shadow_errors"],
        "shadow_nonfinite": router_summary["shadow_nonfinite"],
        "promotion_pointer_move_p50_ms": round(
            promote_ms[len(promote_ms) // 2], 3),
        "promotion_pointer_move_max_ms": round(promote_ms[-1], 3),
        "rollback_detection": detection,
        "load_kind": "synthetic-open-loop-paced-median-of-rounds",
        "device_kind": jax.devices()[0].device_kind,
        "host_cpu_count": os.cpu_count(),
    }


#: Fleet shape shared by the saturation probe and every flood point — a
#: deliberately SMALL queue (512 rows -> 256-row shed watermark) so the
#: post-window drain stays short and the admission gate, not the queue
#: depth, is what absorbs the flood.
_FLOOD_ENGINE_KW = dict(poll_secs=5.0, max_batch=64, max_delay_ms=2.0,
                        inflight=2, small_rows=0, queue_rows=512)


def serving_saturation_qps(artifact_dir: str, *, replicas: int = 2,
                           probe_secs: float = 1.5,
                           n_clients: int = 32,
                           warmup_secs: float = 0.4) -> float:
    """Measured saturation throughput for the flood fleet shape: a short
    closed-loop probe (``n_clients`` threads, 1-row requests — the flood
    plan's request shape) against the SAME engine configuration the
    overload series floods, with no admission gate and no hedging, so the
    number is the fleet's raw service rate. ``n_clients`` is the in-flight
    depth — it must be large enough to fill the batcher's buckets, or the
    probe measures round-trip serialization instead of service rate — and
    ``warmup_secs`` keeps bucket JIT compiles out of the measured window.
    The flood sweep expresses its offered loads as multiples of this
    measurement — "4x saturation" means the same thing on a laptop and a
    TPU host."""
    import threading

    from deepfm_tpu.serve import ReplicatedEngine

    cfg = _bench_cfg()
    engine = ReplicatedEngine.serve_latest(
        artifact_dir, replicas=replicas, **_FLOOD_ENGINE_KW)
    stop = threading.Event()
    done = [0] * n_clients

    def client(k):
        rng = np.random.default_rng(k)
        while not stop.is_set():
            ids = rng.integers(0, cfg.feature_size,
                               (1, cfg.field_size)).astype(np.int32)
            vals = rng.normal(size=(1, cfg.field_size)).astype(np.float32)
            try:
                engine.predict(ids, vals, timeout=30, affinity=k)
                done[k] += 1
            except Exception:  # noqa: BLE001 — probe counts successes only
                pass

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(warmup_secs)
    base = sum(done)
    t0 = time.monotonic()
    time.sleep(probe_secs)
    count = sum(done) - base
    elapsed = max(time.monotonic() - t0, 1e-9)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    engine.close()
    return max(1.0, count / elapsed)


def serving_drain_qps(artifact_dir: str, *, replicas: int = 2,
                      rows: int = 6144, warmup_rows: int = 512,
                      queue_rows: int = 32_768,
                      submit_threads: int = 4) -> float:
    """Open-loop drain throughput for the flood fleet shape: pre-fill the
    queue with a burst of 1-row requests submitted flat-out and measure
    completions/second while the backlog drains. This is the capacity
    number an overload flood actually fights — past saturation the
    executor runs back-to-back FULL batches off a deep queue, a regime a
    closed-loop probe (bounded in-flight depth, per-request round trips)
    underestimates by 30-50%. The fast-path A/B keys its "Nx saturation"
    multipliers off THIS number so "2x" reliably means a growing backlog.

    ``warmup_rows`` are burned first (bucket JIT compiles out of the
    window); the measured burst then drains with the queue never empty,
    so rows/elapsed IS the service rate."""
    import threading

    from deepfm_tpu.serve import ReplicatedEngine

    cfg = _bench_cfg()
    kw = dict(_FLOOD_ENGINE_KW)
    kw["queue_rows"] = int(queue_rows)
    engine = ReplicatedEngine.serve_latest(
        artifact_dir, replicas=replicas, **kw)
    rng = np.random.default_rng(0)

    def burst(n, affinity_base):
        reqs = [(rng.integers(0, cfg.feature_size,
                              (1, cfg.field_size)).astype(np.int32),
                 rng.normal(size=(1, cfg.field_size)).astype(np.float32))
                for _ in range(n)]
        futs = [None] * n
        per = (n + submit_threads - 1) // submit_threads

        def feeder(k):
            lo = k * per
            for j, (ids, vals) in enumerate(reqs[lo:lo + per]):
                # Per-request affinity: hash-spreads rows over replicas.
                futs[lo + j] = engine.submit(
                    ids, vals, affinity=affinity_base + lo + j)

        threads = [threading.Thread(target=feeder, args=(k,))
                   for k in range(submit_threads)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result(timeout=60)
        return time.monotonic() - t0

    try:
        burst(warmup_rows, 0)
        elapsed = burst(rows, submit_threads)
    finally:
        engine.close()
    return max(1.0, rows / max(elapsed, 1e-9))


def overload_point(engine, plan, *, slo_ms: float,
                   resolve_timeout_s: float) -> dict:
    """Drive one ``FloodTrafficPlan`` open-loop against a live fleet and
    tally the full accounting: every offered request ends as exactly ONE
    of completed / shed / overload / timeout / failed — the
    zero-silent-drop identity the flood gate asserts (``accounting_ok``).

    Open-loop means the driver submits on the plan's clock regardless of
    completions — past saturation it does NOT self-throttle, which is the
    whole point; ``offered_qps_achieved`` records what the single-threaded
    submitter actually sustained so a fast plan on a slow host is labeled
    rather than silently rescaled. Goodput counts only in-SLO completions
    over the offered window.

    With the serving fast path armed the identity grows one bucket:
    ``coalesced`` counts successes that joined an in-flight leader instead
    of executing (completed + coalesced + sheds + overloads + timeouts +
    failed == offered); ``cache_hits`` counts successes answered from the
    result cache (a hit IS a completion — it consumed no device time, not
    no request)."""
    from deepfm_tpu.serve import (AdmissionShed, ServerOverloaded,
                                  ServeTimeout)

    futs = []
    sheds = overloads = 0
    t0 = time.monotonic()
    for r in plan.requests:
        wait = t0 + r.t_s - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        try:
            futs.append(engine.submit(r.ids, r.vals, affinity=r.user_id,
                                      value=r.value))
        except AdmissionShed:
            sheds += 1
        except ServerOverloaded:
            overloads += 1
    submit_elapsed = max(time.monotonic() - t0, 1e-9)
    completed = in_slo = timeouts = failed = 0
    coalesced = cache_hits = 0
    lat: list = []
    deadline = time.monotonic() + resolve_timeout_s
    for fut in futs:
        try:
            fut.result(timeout=max(0.05, deadline - time.monotonic()))
        except ServeTimeout:
            timeouts += 1
            fut.cancel()
            continue
        except Exception:  # noqa: BLE001 — typed into the identity
            failed += 1
            continue
        if getattr(fut, "coalesced", False):
            coalesced += 1
        else:
            completed += 1
        if getattr(fut, "cache_hit", False):
            cache_hits += 1
        ms = fut.latency_ms
        if ms is not None:
            lat.append(ms)
            if ms <= slo_ms:
                in_slo += 1
    offered = len(plan.requests)
    succeeded = completed + coalesced
    lat.sort()
    return {
        "offered_requests": offered,
        "offered_qps_target": round(plan.offered_qps, 1),
        "offered_qps_achieved": round(offered / submit_elapsed, 1),
        "completed": completed,
        "coalesced": coalesced,
        "cache_hits": cache_hits,
        "cache_hit_rate": (round(cache_hits / succeeded, 4)
                           if succeeded else None),
        "coalesce_rate": (round(coalesced / succeeded, 4)
                          if succeeded else None),
        "in_slo": in_slo,
        "goodput_qps": round(in_slo / plan.duration_s, 1),
        "sheds": sheds,
        "overloads": overloads,
        "timeouts": timeouts,
        "failed": failed,
        "accounting_ok": (completed + coalesced + sheds + overloads
                          + timeouts + failed) == offered,
        "p50_ms": round(lat[len(lat) // 2], 3) if lat else None,
        "p99_ms": (round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 3)
                   if lat else None),
    }


def overload_series(run_secs: float = 1.5,
                    mults=(1.0, 2.0, 4.0),
                    replicas: int = 2, slo_ms: float = 50.0,
                    hedge_ms: float = 25.0, shed_watermark: int = 256,
                    users: int = 1_000_000,
                    artifact_dir: "str | None" = None,
                    saturation_qps: "float | None" = None,
                    population=None, seed: int = 0,
                    cache_rows: int = 0, cache_ttl_s: float = 0.0,
                    coalesce: bool = False,
                    repeat_p: float = 0.0,
                    queue_rows: "int | None" = None) -> dict:
    """The overload plane under open-loop Zipf flood: goodput (in-SLO
    completions/s), p50/p99, and shed/overload/hedge counts at multiples
    of the MEASURED saturation QPS, with the zero-silent-drop accounting
    identity asserted per point. Each point gets a fresh fleet (admission
    gate + hedging armed) so its counters and queue state are clean; the
    user population is shared across points, so head users carry history
    continuity through the whole sweep.

    Honesty fields: ``load_kind`` labels the traffic as an open-loop
    synthetic Zipf flood (``users`` synthetic users, NOT a production
    trace); ``saturation_qps`` is measured on THIS host immediately before
    the sweep, so the multiples survive host-speed changes;
    ``host_cpu_count`` is what any scaling reading must be judged against
    (the driver, hedger, and both replicas time-slice the same cores).

    The serving fast path rides on four knobs: ``cache_rows``/
    ``cache_ttl_s``/``coalesce`` arm each replica's result cache and
    in-flight coalescing, and ``repeat_p`` makes the flood replay each
    returning user's previous request byte-identically with that
    probability — fresh randoms never repeat, so without it a flood
    cannot exercise the cache at all. All four default off, keeping
    existing sweeps bit-comparable."""
    import shutil
    import tempfile

    import jax

    from deepfm_tpu.loop.traffic import FloodTrafficPlan, ZipfUserPopulation
    from deepfm_tpu.serve import ReplicatedEngine
    from deepfm_tpu.utils import export as export_lib

    cfg = _bench_cfg()
    tmp = artifact_dir or export_serving_artifacts(
        tempfile.mkdtemp(prefix="bench_flood_"))
    orig_tf = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # not served
    try:
        export_lib.write_latest(tmp, "1")
        if saturation_qps is None:
            saturation_qps = serving_saturation_qps(
                tmp, replicas=replicas, probe_secs=max(1.0, run_secs))
        pop = population if population is not None else ZipfUserPopulation(
            seed, users=users)
        fast_kw = dict(_FLOOD_ENGINE_KW)
        fast_kw.update(cache_rows=cache_rows, cache_ttl_s=cache_ttl_s,
                       coalesce=coalesce)
        if queue_rows is not None:
            fast_kw["queue_rows"] = int(queue_rows)
        points = []
        for i, mult in enumerate(mults):
            plan = FloodTrafficPlan(
                seed + 100 + i, offered_qps=mult * saturation_qps,
                duration_s=run_secs, population=pop,
                field_size=cfg.field_size, feature_size=cfg.feature_size,
                repeat_p=repeat_p)
            # shed_watermark <= 0 parks the admission gate entirely (the
            # fast-path A/B: shedding clamps p99 identically in both arms,
            # hiding the backlog the cache exists to absorb).
            adm_kw = ({"slo_ms": slo_ms, "shed_watermark": shed_watermark}
                      if shed_watermark > 0 else {})
            engine = ReplicatedEngine.serve_latest(
                tmp, replicas=replicas, hedge_ms=hedge_ms,
                hedge_poll_secs=0.02, admission_kw=adm_kw,
                **fast_kw)
            try:
                point = overload_point(
                    engine, plan, slo_ms=slo_ms,
                    resolve_timeout_s=max(10.0, 4.0 * run_secs))
                s = engine.summary()
            finally:
                engine.close()
            point.update({
                "offered_mult": mult,
                "repeat_requests": plan.repeat_requests,
                "hedges_fired": s["hedges_fired"],
                "hedges_won": s["hedges_won"],
                "hedges_cancelled": s["hedges_cancelled"],
                "sheds_by_class": s["serving_sheds_by_class"],
                "admission_transitions": s["admission_transitions"],
                "engine_cache_hits": s.get("serving_cache_hits", 0),
                "engine_cache_misses": s.get("serving_cache_misses", 0),
                "engine_coalesced": s.get("serving_coalesced", 0),
            })
            points.append(point)
    finally:
        export_lib._export_tf_savedmodel = orig_tf
        if artifact_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return {
        "saturation_qps": round(float(saturation_qps), 1),
        "replicas": replicas,
        "serve_slo_ms": slo_ms,
        "serve_hedge_ms": hedge_ms,
        "serve_shed_watermark": shed_watermark,
        "serve_cache_rows": cache_rows,
        "serve_cache_ttl_s": cache_ttl_s,
        "serve_coalesce": coalesce,
        "flood_repeat_p": repeat_p,
        "users": pop.users,
        "zipf_q": pop.zipf_q,
        "touched_users": pop.touched_users,
        "points": points,
        "load_kind": "synthetic-open-loop-zipf-flood",
        "device_kind": jax.devices()[0].device_kind,
        "host_cpu_count": os.cpu_count(),
    }


def serving_fastpath_series(run_secs: float = 1.5,
                            mults=(0.5, 1.0, 2.0, 4.0),
                            replicas: int = 2, slo_ms: float = 50.0,
                            hedge_ms: float = 25.0,
                            users: int = 1_000_000,
                            repeat_p: float = 0.5,
                            cache_rows: int = 4096,
                            cache_ttl_s: float = 0.0,
                            queue_rows: int = 16_384,
                            seed: int = 0) -> dict:
    """Fast-path A/B under the SAME flood: one artifact, one measured
    saturation, identical per-arm traffic (fresh same-seed populations →
    bit-identical plans), cache+coalescing OFF vs ON. The deltas are the
    headline: with ``repeat_p`` of returning-user requests replayed
    byte-identically, the ON arm answers repeats from the version-keyed
    cache (and coalesces concurrent twins) instead of spending device
    time, so p99 at and past saturation should drop while the accounting
    identity still closes at every point.

    Unlike ``overload_series``'s defaults, BOTH arms here run with the
    admission gate effectively parked (huge shed watermark) and a deep
    queue: shedding/queue-full refusals clamp p99 at the queue cap in
    both arms, which would hide exactly the backlog the fast path exists
    to absorb. The A/B therefore measures queueing honestly — the off arm
    pays the full backlog past saturation, the on arm's repeats skip it.

    Two structural defenses against shared-host noise (the probe and the
    flood share cores with whatever else the machine runs):

    * saturation is the BEST of three closed-loop probes — capacity is
      the highest sustained rate, and background contention only ever
      biases a probe downward, so max-of-N converges on the true number
      while mean-of-N would undershoot and quietly deflate every "Nx"
      offered load;
    * the arms are PAIRED per multiplier (off then on, back-to-back)
      instead of sweeping one full series after the other, so a drift in
      background load lands on at most one point of the comparison, not
      on an entire arm.

    Honesty fields: both arms inherit ``overload_series``'s labels
    (synthetic Zipf flood, host-measured saturation, shared cores);
    ``repeat_p`` is the workload assumption the speedup is conditional
    on — a flood with no repeats (repeat_p=0) gives the cache nothing."""
    import shutil
    import tempfile

    from deepfm_tpu.loop.traffic import ZipfUserPopulation

    tmp = export_serving_artifacts(tempfile.mkdtemp(prefix="bench_fast_"))
    try:
        # Drain-rate saturation, best of 3: the open-loop burst probe
        # measures the full-batch service rate an overloaded flood
        # actually drains at (a closed-loop probe underestimates it by
        # 30-50%, which would quietly deflate every "Nx" offered load
        # until "2x" no longer overloads); max-of-N because background
        # contention only ever biases a probe downward.
        base = max(serving_drain_qps(tmp, replicas=replicas,
                                     queue_rows=queue_rows)
                   for _ in range(3))
        common = dict(run_secs=run_secs, replicas=replicas,
                      slo_ms=slo_ms, hedge_ms=hedge_ms,
                      shed_watermark=0, artifact_dir=tmp,
                      saturation_qps=base, seed=seed, repeat_p=repeat_p,
                      queue_rows=queue_rows)
        off_pts, on_pts = [], []
        for m in mults:
            off_m = overload_series(
                mults=(m,),
                population=ZipfUserPopulation(seed, users=users), **common)
            on_m = overload_series(
                mults=(m,),
                population=ZipfUserPopulation(seed, users=users),
                cache_rows=cache_rows, cache_ttl_s=cache_ttl_s,
                coalesce=True, **common)
            off_pts.append(off_m["points"][0])
            on_pts.append(on_m["points"][0])
        off = dict(off_m, points=off_pts)
        on = dict(on_m, points=on_pts)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    comparison = []
    for p_off, p_on in zip(off["points"], on["points"]):
        p99_off, p99_on = p_off["p99_ms"], p_on["p99_ms"]
        comparison.append({
            "offered_mult": p_off["offered_mult"],
            "p50_ms_off": p_off["p50_ms"], "p50_ms_on": p_on["p50_ms"],
            "p99_ms_off": p99_off, "p99_ms_on": p99_on,
            "p99_improvement_pct": (
                round(100.0 * (p99_off - p99_on) / p99_off, 1)
                if p99_off and p99_on is not None else None),
            "goodput_qps_off": p_off["goodput_qps"],
            "goodput_qps_on": p_on["goodput_qps"],
            "cache_hit_rate_on": p_on["cache_hit_rate"],
            "coalesce_rate_on": p_on["coalesce_rate"],
            "accounting_ok": (p_off["accounting_ok"]
                              and p_on["accounting_ok"]),
        })
    return {
        "saturation_qps": round(float(base), 1),
        "repeat_p": repeat_p,
        "serve_cache_rows": cache_rows,
        "serve_cache_ttl_s": cache_ttl_s,
        "off": off,
        "on": on,
        "comparison": comparison,
    }


def multitask_series() -> dict:
    """Multi-task head comparison: per-task AUC + train ex/s for a
    single-task baseline vs shared_bottom vs MMoE over the SAME data,
    shared-bottom capacity, optimizer, and step budget.

    Honesty fields: the data is synthetic two-label CTR/CVR (click-gated
    conversions over hidden linear weights, ``libsvm.generate_synthetic_ctr
    num_labels=2``), so the AUC DELTAS between variants are the meaningful
    signal, not the absolute values; ex/s times the full ``Trainer.fit``
    loop over pre-decoded in-memory batches (no disk decode in the window,
    but host->device transfer included) — it is a relative head-cost
    series, not the headline throughput anchor."""
    import glob as glob_mod
    import tempfile

    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.data import libsvm
    from deepfm_tpu.data.pipeline import CtrPipeline
    from deepfm_tpu.train import Trainer

    fs, fields, bs = 20000, 13, 512
    out = {
        "data_kind": "synthetic-two-label",
        "device_kind": jax.devices()[0].device_kind,
    }
    with tempfile.TemporaryDirectory() as d:
        libsvm.generate_synthetic_ctr(
            d, num_files=2, examples_per_file=8192, feature_size=fs,
            field_size=fields, prefix="tr", seed=0, num_labels=2)
        libsvm.generate_synthetic_ctr(
            d, num_files=1, examples_per_file=8192, feature_size=fs,
            field_size=fields, prefix="va", seed=1, num_labels=2)
        tr_files = sorted(glob_mod.glob(os.path.join(d, "tr*.tfrecords")))
        va_files = sorted(glob_mod.glob(os.path.join(d, "va*.tfrecords")))

        def batches(files, shuffle, epochs=1):
            return list(CtrPipeline(
                files, field_size=fields, batch_size=bs, num_epochs=epochs,
                shuffle=shuffle, shuffle_files=shuffle, seed=0,
                drop_remainder=True, prefetch_batches=0, num_labels=2))

        train_b = batches(tr_files, shuffle=True, epochs=2)
        val_b = batches(va_files, shuffle=False)

        def run(**kw):
            cfg = Config(
                feature_size=fs, field_size=fields, embedding_size=16,
                deep_layers="64,32", dropout="1.0,1.0", batch_size=bs,
                learning_rate=1e-3, optimizer="Adam", l2_reg=1e-5,
                compute_dtype="float32", log_steps=0, seed=0,
                scale_lr_by_world=False, **kw)
            trainer = Trainer(cfg)
            state = trainer.init_state()
            state, _ = trainer.fit(state, train_b[:2])  # compile warmup
            t0 = time.perf_counter()
            state, m = trainer.fit(state, train_b)
            dt = time.perf_counter() - t0
            ev = trainer.evaluate(state, val_b)
            entry = {
                "ex_per_s": round(int(m["steps"]) * bs / dt, 1),
                "auc_ctr": round(float(ev.get("auc_ctr", ev["auc"])), 4),
            }
            if "auc_cvr" in ev:
                entry["auc_cvr"] = round(float(ev["auc_cvr"]), 4)
            return entry

        out["single_task_baseline"] = run()
        out["shared_bottom"] = run(tasks="ctr,cvr",
                                   multitask="shared_bottom")
        out["mmoe"] = run(tasks="ctr,cvr", multitask="mmoe",
                          mmoe_experts=4)
        base = out["single_task_baseline"]["ex_per_s"]
        for key in ("shared_bottom", "mmoe"):
            out[key]["ex_per_s_vs_single_task"] = round(
                out[key]["ex_per_s"] / max(base, 1e-9), 3)
    return out


def production_day_series() -> dict:
    """Closed-loop production-day drill (``scripts/production_drill.py``
    smoke variant): the serve->log->join->train->publish loop in one
    process, with the seeded publish crash live. Reports the loop's
    operational envelope — end-to-end staleness percentiles, request loss
    across hot swaps, serving latency under diurnal load, and the
    windowed online-vs-frozen AUC — from ONE drill run.

    Honesty fields mirror the serving series: ``device_kind`` names the
    chip; ``load_kind`` labels the traffic as the seeded diurnal synthetic
    plan (not a production trace); ``baseline_kind`` labels the AUC
    comparator as the frozen bootstrap artifact, not a tuned champion.
    ``chaos_fingerprint`` pins the exact fault plan the numbers were
    measured under."""
    import sys as _sys

    import jax

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    import shutil
    import tempfile

    import production_drill

    tmp = tempfile.mkdtemp(prefix="bench_production_")
    try:
        r = production_drill.run_smoke(tmp, verbose=False)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "device_kind": jax.devices()[0].device_kind,
        "load_kind": r["load_kind"],
        "baseline_kind": r["baseline_kind"],
        "chaos_fingerprint": r["chaos"]["fingerprint"],
        "requests": r["traffic"]["requests"],
        "rows": r["traffic"]["rows"],
        "hot_swaps": r["request_loss"]["hot_swaps"],
        "requests_failed": r["request_loss"]["failed"],
        "publish_crash_fired": r["chaos"]["publish_crash_fired"],
        "staleness_p50_s": r["staleness"]["staleness_p50_s"],
        "staleness_p95_s": r["staleness"]["staleness_p95_s"],
        "staleness_uncovered_rows": r["staleness"]["uncovered_rows"],
        "serving_p50_ms": (round(r["serving"]["serving_p50_ms"], 3)
                           if r["serving"]["serving_p50_ms"] is not None
                           else None),
        "serving_p99_ms": (round(r["serving"]["serving_p99_ms"], 3)
                           if r["serving"]["serving_p99_ms"] is not None
                           else None),
        "skew_mismatches": r["skew"]["mismatches"],
        "windowed_auc": r["windowed_auc"],
        "drill_elapsed_s": r["elapsed_s"],
    }


def cascade_series() -> dict:
    """Retrieval→ranking cascade: end-to-end ``recommend()`` latency (user
    tower -> candidate index -> packed ranking batch -> top-k) p50/p99 and
    QPS, the ANN index's measured recall@k against the brute-force oracle,
    and the train-throughput cost of sequence features — the SAME DIN graph
    fit over the same batches WITH the history columns vs with them
    stripped (the stripped run rides the empty-history fallback, so the
    delta prices target attention + history transfer, not a different
    model).

    Honesty fields mirror the serving series: ``device_kind`` names the
    chip; ``load_kind`` labels the latency loop as a SEQUENTIAL synthetic
    driver (one recommend() per call, one in-process caller) — p50/p99 are
    closed-loop single-stream numbers, not concurrent-traffic tails; and
    recall@k is measured on this run's synthetic corpus, never assumed
    (brute is measured too — it must read 1.0)."""
    import glob as glob_mod
    import shutil
    import tempfile

    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.data import libsvm
    from deepfm_tpu.data.pipeline import CtrPipeline
    from deepfm_tpu.models.twin_tower import train_twin_tower
    from deepfm_tpu.rec.cascade import CascadeEngine, export_cascade
    from deepfm_tpu.rec.index import CandidateIndex
    from deepfm_tpu.train import Trainer
    from deepfm_tpu.utils import export as export_lib

    fs, fields, hist, bs = 5000, 5, 8, 256
    retrieve_k, rank_k, recall_k = 50, 10, 50
    cfg = Config(
        feature_size=fs, field_size=fields, embedding_size=8,
        deep_layers="32,16", dropout="1.0,1.0", batch_size=bs,
        learning_rate=1e-3, optimizer="Adam", l2_reg=1e-5,
        compute_dtype="float32", log_steps=0, seed=0,
        scale_lr_by_world=False, model="din", history_max_len=hist)
    out = {
        "device_kind": jax.devices()[0].device_kind,
        "load_kind": "synthetic-sequential",
        "corpus_items": fs,
        "retrieve_k": retrieve_k,
        "rank_k": rank_k,
    }
    tmp = tempfile.mkdtemp(prefix="bench_cascade_")
    orig_tf = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # not served
    try:
        libsvm.generate_synthetic_ctr(
            tmp, num_files=2, examples_per_file=4096, feature_size=fs,
            field_size=fields, prefix="tr", seed=0, history=hist)
        files = sorted(glob_mod.glob(os.path.join(tmp, "tr*.tfrecords")))
        hist_b = list(CtrPipeline(
            files, field_size=fields, batch_size=bs, num_epochs=1,
            shuffle=True, shuffle_files=True, seed=0, drop_remainder=True,
            prefetch_batches=0, history=True, history_max_len=hist))
        plain_b = [{k: v for k, v in b.items()
                    if k not in ("hist_ids", "hist_mask")} for b in hist_b]

        # --- sequence-feature train cost: history columns on vs off -----
        def train_eps(batches):
            trainer = Trainer(cfg)
            state = trainer.init_state()
            state, _ = trainer.fit(state, batches[:2])  # compile warmup
            t0 = time.perf_counter()
            state, m = trainer.fit(state, batches)
            return state, trainer, int(m["steps"]) * bs / (
                time.perf_counter() - t0)

        _, _, off_eps = train_eps(plain_b)
        state, trainer, on_eps = train_eps(hist_b)
        out["train_ex_per_s_history_on"] = round(on_eps, 1)
        out["train_ex_per_s_history_off"] = round(off_eps, 1)
        out["history_on_over_off_ratio"] = round(
            on_eps / max(off_eps, 1e-9), 3)

        # --- retrieval stage: towers + index, recall measured ----------
        tower_model, tower_params, _ = train_twin_tower(cfg, hist_b)
        items = tower_model.all_item_embeddings(tower_params, fs)
        queries = np.asarray(tower_model.user_embed(
            tower_params, hist_b[0]["hist_ids"], hist_b[0]["hist_mask"]))
        brute = CandidateIndex(items, kind="brute")
        ann = CandidateIndex(items, kind="ann", seed=0)
        # A second measured operating point on the recall-vs-latency curve
        # (TUNING.md §2.14): same corpus, half the partitions probed.
        ann_wide = CandidateIndex(items, kind="ann", seed=0,
                                  num_partitions=32, nprobe=16)
        out["recall_at_k"] = recall_k
        out["brute_recall"] = round(brute.recall_at_k(queries, recall_k), 4)

        def ann_point(idx):
            r = idx.recall_at_k(queries, recall_k)
            t0 = time.perf_counter()
            idx.search(queries, recall_k)
            ms = 1000 * (time.perf_counter() - t0) / queries.shape[0]
            return {"num_partitions": idx.num_partitions,
                    "nprobe": idx.nprobe,
                    "recall": round(r, 4),
                    "search_ms_per_query": round(ms, 4)}

        out["ann_default"] = ann_point(ann)
        out["ann_wide_probe"] = ann_point(ann_wide)
        out["ann_recall"] = out["ann_default"]["recall"]

        # --- end-to-end recommend() latency over a live artifact -------
        publish_dir = os.path.join(tmp, "publish")
        export_cascade(
            trainer.model, state, cfg, os.path.join(publish_dir, "1"),
            tower_params=tower_params, index=ann,
            index_meta={"recall_at_50": out["ann_recall"]})
        export_lib.write_latest(publish_dir, "1")
        engine = CascadeEngine(
            publish_dir, retrieve_k=retrieve_k, max_batch=64,
            max_delay_ms=1.0, watcher_kw={"poll_secs": 3600, "start": False})
        try:
            # (the watcher's constructor already did the initial check_once)
            assert engine.watcher.swap_count >= 1, "cascade artifact not loaded"
            rng = np.random.default_rng(7)

            def one_request():
                ln = int(rng.integers(1, hist + 1))
                h_ids = np.zeros((hist,), np.int32)
                h_ids[:ln] = rng.integers(1, fs, ln)
                h_mask = (np.arange(hist) < ln).astype(np.float32)
                ids = rng.integers(0, fs, fields).astype(np.int32)
                vals = rng.normal(size=fields).astype(np.float32)
                return engine.recommend(h_ids, h_mask, ids, vals, k=rank_k)

            for _ in range(5):  # compile/warm both stages + buckets
                one_request()
            lat = []
            t_all = time.perf_counter()
            for _ in range(60):
                t0 = time.perf_counter()
                cand, probs = one_request()
                lat.append(1000 * (time.perf_counter() - t0))
                assert np.all(np.isfinite(probs)), probs
            wall = time.perf_counter() - t_all
            out["e2e_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
            out["e2e_p99_ms"] = round(float(np.percentile(lat, 99)), 3)
            out["e2e_qps"] = round(len(lat) / wall, 1)
        finally:
            engine.close()
    finally:
        export_lib._export_tf_savedmodel = orig_tf
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def pallas_ab_device_ratio() -> dict:
    """Interleaved Pallas-vs-XLA A/B over the device-only staged multi-step
    (no transfer inside the timed window) — the regression canary for the
    fused FM kernel. The variants alternate trial-by-trial so tunnel/host
    weather hits both equally; best-of-N each; the RATIO is the stable
    series (both numerators ride the same window)."""
    import jax

    from deepfm_tpu.train import Trainer

    setups = {}
    for pallas in (True, False):
        cfg = _bench_cfg(use_pallas=pallas)
        tr = Trainer(cfg)
        st = tr.init_state()
        sb = [tr.put_superbatch(g) for g in _make_groups(cfg, 2)]
        st, m = tr.multi_step(st, sb[0])  # compile
        jax.block_until_ready(m["loss"])
        setups[pallas] = [tr, st, sb]
    trials = []
    for _ in range(N_TRIALS):
        pair = {}
        for pallas in (True, False):
            tr, st, sb = setups[pallas]
            t0 = time.perf_counter()
            for i in range(N_DISPATCH):
                st, m = tr.multi_step(st, sb[i % 2])
            jax.block_until_ready(m["loss"])
            setups[pallas][1] = st
            pair[pallas] = time.perf_counter() - t0
        trials.append(pair)
    # The ratio is taken WITHIN one trial pair (the cleanest-window pair,
    # by combined time) — taking each variant's independent best could mix
    # measurements from different weather windows and report a ratio no
    # single window ever exhibited.
    pair = min(trials, key=lambda p: p[True] + p[False])
    denom = N_DISPATCH * K_STEPS
    leg_pallas_ms = 1000 * pair[True] / denom
    leg_xla_ms = 1000 * pair[False] / denom
    # Self-gating cleanliness (VERDICT r5 #1): a clean-weather window puts
    # BOTH legs at the device-bound ~0.015 ms/step; a congested tunnel
    # inflates dispatch latency 10-100x on whichever leg it hits, and a
    # ratio from such a window records launch noise, not kernel speed.
    # clean=False means "discard this ratio", not "kernel regressed".
    clean_thresh = 0.02
    return {
        "pallas_ms_per_step": round(
            1000 * min(p[True] for p in trials) / denom, 4),
        "xla_ms_per_step": round(
            1000 * min(p[False] for p in trials) / denom, 4),
        "pallas_over_xla_ratio": round(pair[True] / pair[False], 3),
        "clean_pair_pallas_ms_per_step": round(leg_pallas_ms, 4),
        "clean_pair_xla_ms_per_step": round(leg_xla_ms, 4),
        "clean_threshold_ms_per_step": clean_thresh,
        "clean": bool(leg_pallas_ms <= clean_thresh
                      and leg_xla_ms <= clean_thresh),
    }


def embedding_kernels_series() -> dict:
    """Fused-embedding-plane regression canary: dense vs seed-sparse
    (``--embedding_kernels off``) vs fused-sparse (``auto``) ms/step at
    the EMBED bench shape, few steps (compile excluded). The claims under
    guard: the fused sparse step stays at or under dense
    (``sparse_beats_dense``, EMBED_r02 headline) and well under the seed
    formulation. Full per-kernel A/Bs + per-stage breakdown live in
    scripts/bench_embedding.py; this is the cheap canary that rides the
    main bench."""
    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    v, b, f, nb = 100_000, 1024, 39, 16
    rng = np.random.default_rng(3)
    batches = [dict(
        feat_ids=rng.integers(0, v, size=(b, f)).astype(np.int32),
        feat_vals=rng.normal(size=(b, f)).astype(np.float32),
        label=rng.integers(0, 2, size=(b,)).astype(np.float32))
        for _ in range(nb + 2)]
    out = {"V": v, "B": b, "steps": nb}
    for label, kw in (
            ("dense", dict(embedding_update="dense")),
            ("sparse_seed", dict(embedding_update="sparse",
                                 embedding_kernels="off")),
            ("sparse_fused", dict(embedding_update="sparse",
                                  embedding_kernels="auto"))):
        cfg = Config(
            feature_size=v, field_size=f, embedding_size=8,
            deep_layers="32,16", dropout="1.0,1.0", batch_size=b,
            compute_dtype="float32", l2_reg=0.0, learning_rate=0.001,
            log_steps=0, seed=11, scale_lr_by_world=False, mesh_data=1,
            mesh_model=1, steps_per_loop=1, transfer_ahead=0, **kw)
        tr = Trainer(cfg)
        st = tr.init_state()
        st, _ = tr.fit(st, batches[:2])  # compile
        t0 = time.perf_counter()
        st, summary = tr.fit(st, batches[2:])
        jax.block_until_ready(st.params)
        out[f"{label}_ms_per_step"] = round(
            (time.perf_counter() - t0) * 1000.0 / max(summary["steps"], 1),
            3)
    out["fused_over_dense_ratio"] = round(
        out["sparse_fused_ms_per_step"] / out["dense_ms_per_step"], 3)
    out["fused_speedup_vs_seed"] = round(
        out["sparse_seed_ms_per_step"] / out["sparse_fused_ms_per_step"], 2)
    out["sparse_beats_dense"] = bool(
        out["sparse_fused_ms_per_step"] <= out["dense_ms_per_step"])
    return out


def scaling_probe() -> None:
    """--scaling mode (run in a subprocess): 1-dev vs 8-dev DP vs 4x2
    DP x row-shard on a virtual CPU mesh; prints one JSON line. The value
    is wiring-level (the collective programs compile and execute over the
    full mesh, including the masked-gather+psum embedding lookup on the
    'model' axis); the ratios measure host time-slicing, not hardware."""
    from __graft_entry__ import _provision_virtual_devices
    _provision_virtual_devices(8)

    # Wiring check, not a measurement: cut the trial budget so the three
    # virtual-mesh legs (1-dev, DP8, DP4xMP2) stay well under any harness
    # timeout on a 1-core host (best-of-5 x 12 here would triple the cost
    # for a number that only reflects time-slicing anyway).
    global N_TRIALS, N_DISPATCH
    N_TRIALS, N_DISPATCH = 2, 6

    r1 = measure(_bench_cfg(batch_size=1024, mesh_data=1))
    r8 = measure(_bench_cfg(batch_size=8 * 1024, mesh_data=8))
    out = {
        "one_dev_eps": round(r1["total_eps"], 1),
        "eight_dev_eps": round(r8["total_eps"], 1),
        "aggregate_ratio_8v1": round(
            r8["total_eps"] / (8 * r1["total_eps"]), 3),
    }
    # The 4x2 leg must not sink the (older) DP-only signal if it breaks.
    try:
        r42 = measure(_bench_cfg(batch_size=4 * 1024, mesh_data=4,
                                 mesh_model=2))
        out["dp4_mp2_eps"] = round(r42["total_eps"], 1)
        out["dp4_mp2_loss_finite"] = bool(np.isfinite(r42["loss"]))
    except Exception as e:
        out["dp4_mp2_error"] = str(e)[:300]
    print(json.dumps(out))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scaling", action="store_true",
                    help="internal: run the CPU-mesh scaling probe")
    ap.add_argument("--no-scaling", action="store_true",
                    help="skip the scaling-efficiency subprocess")
    args = ap.parse_args()

    if args.scaling:
        scaling_probe()
        return

    # Pallas compiled-path smoke FIRST (subprocess, before this process
    # claims the chip): fwd+bwd of the fused FM kernel vs the jnp oracle on
    # real TPU + one full train step (scripts/tpu_smoke.py). Recorded in the
    # headline JSON so the "compiled Pallas path works on hardware" claim
    # ships with every bench run instead of resting on prose.
    pallas_smoke = None
    try:
        smoke = subprocess.run(
            [sys.executable, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "scripts", "tpu_smoke.py")],
            capture_output=True, text=True, timeout=600)
        # Parse the machine-readable token (the script's last stdout line),
        # not free-form narration (ADVICE r3: substring matching here was
        # one stray word away from misclassifying a failure).
        token = None
        for ln in smoke.stdout.splitlines():
            if ln.startswith("TPU_SMOKE_JSON "):
                try:
                    token = json.loads(ln[len("TPU_SMOKE_JSON "):])
                except ValueError:
                    pass  # truncated token (crash mid-flush) -> fail below
        if smoke.returncode == 0 and token is not None:
            pallas_smoke = token["status"]
        else:
            pallas_smoke = "fail"
            print(f"bench: pallas smoke FAILED:\n{smoke.stdout[-1500:]}"
                  f"\n{smoke.stderr[-1500:]}", file=sys.stderr)
    except (subprocess.TimeoutExpired, OSError) as e:
        pallas_smoke = f"error: {e}"

    import jax

    print(f"bench: devices={jax.devices()} pallas_smoke={pallas_smoke}",
          file=sys.stderr)
    cfg = _bench_cfg()
    r = measure(cfg)
    print(
        f"bench: {r['ms_per_step']:.3f} ms/step, total {r['total_eps']:,.0f} "
        f"ex/s on {r['devices']} device(s), loss={r['loss']:.4f}",
        file=sys.stderr)

    scaling = None
    if not args.no_scaling:
        # Subprocess: the scaling probe must own backend init (virtual CPU
        # mesh), which cannot coexist with this process's TPU backend.
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--scaling"],
                capture_output=True, text=True, timeout=1200, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
            line = [ln for ln in out.stdout.splitlines()
                    if ln.startswith("{")]
            if line:
                scaling = json.loads(line[-1])
            else:
                print(f"bench: scaling probe failed:\n{out.stderr[-2000:]}",
                      file=sys.stderr)
        except (subprocess.TimeoutExpired, OSError) as e:
            print(f"bench: scaling probe error: {e}", file=sys.stderr)

    try:
        host_series = host_stage_series()
    except Exception as e:  # never let the canary sink the headline number
        print(f"bench: host series error: {e}", file=sys.stderr)
        host_series = {"error": str(e)}

    try:
        pallas_ab = pallas_ab_device_ratio()
    except Exception as e:
        print(f"bench: pallas A/B error: {e}", file=sys.stderr)
        pallas_ab = {"error": str(e)}

    try:
        embedding_kernels = embedding_kernels_series()
    except Exception as e:
        print(f"bench: embedding-kernels series error: {e}", file=sys.stderr)
        embedding_kernels = {"error": str(e)}

    try:
        device_resident = device_resident_series()
    except Exception as e:
        print(f"bench: device-resident series error: {e}", file=sys.stderr)
        device_resident = {"error": str(e)}

    try:
        online_publish = online_publish_series()
    except Exception as e:
        print(f"bench: online publish series error: {e}", file=sys.stderr)
        online_publish = {"error": str(e)}

    try:
        serving = serving_series()
    except Exception as e:
        print(f"bench: serving series error: {e}", file=sys.stderr)
        serving = {"error": str(e)}

    try:
        overload = overload_series()
    except Exception as e:
        print(f"bench: overload series error: {e}", file=sys.stderr)
        overload = {"error": str(e)}

    try:
        serving_fastpath = serving_fastpath_series()
    except Exception as e:
        print(f"bench: serving fast-path series error: {e}", file=sys.stderr)
        serving_fastpath = {"error": str(e)}

    try:
        experiment = experiment_series()
    except Exception as e:
        print(f"bench: experiment series error: {e}", file=sys.stderr)
        experiment = {"error": str(e)}

    try:
        multitask = multitask_series()
    except Exception as e:
        print(f"bench: multitask series error: {e}", file=sys.stderr)
        multitask = {"error": str(e)}

    try:
        cascade = cascade_series()
    except Exception as e:
        print(f"bench: cascade series error: {e}", file=sys.stderr)
        cascade = {"error": str(e)}

    try:
        production_day = production_day_series()
    except Exception as e:
        print(f"bench: production-day series error: {e}", file=sys.stderr)
        production_day = {"error": str(e)}

    try:
        observability = observability_series()
    except Exception as e:
        print(f"bench: observability series error: {e}", file=sys.stderr)
        observability = {"error": str(e)}

    nominal_per_accel_baseline = 250_000.0 / 4.0
    # MFU from the device-only series (no transfer in the window): model
    # FLOPs/example x device-only examples/sec/chip over the device peak.
    # mfu_basis says where that peak came from: the chip spec sheet
    # (measured-device-peak), a labeled nominal host estimate on the CPU
    # backend (nominal-estimate), or nowhere (unavailable, null MFU) —
    # see BASELINE.md. The tiny number it yields is the honest headline:
    # DeepFM at batch 1024 is lookup/update-bound, so "fast" here means
    # low step LATENCY, and MFU quantifies distance from a FLOP wall.
    from deepfm_tpu.utils import mfu as mfu_lib
    flops_per_example = _model_flops_per_example(cfg)
    device_only_eps_per_chip = (
        cfg.batch_size / (r["device_only_ms_per_step"] / 1000.0)
        / max(r["devices"], 1))
    device_only_mfu_pct, mfu_basis, device_kind = mfu_lib.mfu_pct(
        flops_per_example, device_only_eps_per_chip)
    result = {
        "metric": "deepfm_criteo_train_throughput_per_chip",
        "value": round(r["per_chip_eps"], 1),
        "unit": "examples/sec",
        "vs_baseline": round(r["per_chip_eps"] / nominal_per_accel_baseline, 3),
        # The anchor is a documented nominal ESTIMATE of the reference
        # 4xV100 recipe (no published number exists) — labeled in-band so
        # downstream readers can't mistake the ratio for a measured-vs-
        # measured comparison (VERDICT r5 #9).
        "baseline_kind": "nominal-estimate",
        "devices": r["devices"],
        "aggregate_eps": round(r["total_eps"], 1),
        "device_only_ms_per_step": round(r["device_only_ms_per_step"], 4),
        "device_kind": device_kind,
        "model_flops_per_example": flops_per_example,
        "device_only_mfu_pct": device_only_mfu_pct,
        "mfu_basis": mfu_basis,
        "host_series": host_series,
        "pallas_ab_device": pallas_ab,
        "embedding_kernels": embedding_kernels,
        "device_resident": device_resident,
        "online_publish": online_publish,
        "serving": serving,
        "overload": overload,
        "serving_fastpath": serving_fastpath,
        "experiment": experiment,
        "multitask": multitask,
        "cascade": cascade,
        "production_day": production_day,
        "observability": observability,
        "pallas_smoke": pallas_smoke,
    }
    if scaling is not None:
        # Deliberately NOT named "scaling efficiency": 8 VIRTUAL XLA devices
        # time-slice this host's core(s), so the aggregate ratio mostly
        # measures time-slicing (~1/8 on a 1-core host), not hardware
        # scaling. Its value here is wiring-level: the 8-way DP collective
        # program AND the 4x2 DP x row-shard program (masked-gather+psum
        # embedding lookup over 'model') compiled and executed. Real
        # scaling needs real chips.
        result["dp8_virtual_cpu_mesh_check"] = {
            "ok": True,
            "aggregate_ratio_8v1_timeslicing": scaling["aggregate_ratio_8v1"],
            "dp4_mp2_ok": bool(scaling.get("dp4_mp2_loss_finite", False)),
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
