#!/usr/bin/env python
"""Benchmark harness: DeepFM training throughput on the reference config.

Measures steady-state examples/sec of the full jitted train step (forward +
backward + Adam update) at the reference benchmark anchors (BASELINE.md):
feature_size=117581, field_size=39, embedding_size=32, deep_layers 128/64/32,
global batch 1024, Adam lr 5e-4 — on whatever accelerator JAX exposes (the
driver runs this on one real TPU chip).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec", "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
comparison anchor is a documented nominal estimate of the reference Horovod
recipe: ~250k examples/sec aggregate on the 4xV100 p3.8xlarge (TF1 DeepFM at
batch 1024/GPU is input/update-bound, not FLOP-bound). Per-accelerator
baseline = 62.5k examples/sec; vs_baseline = measured_per_chip / 62.5k.
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    cfg = Config(
        feature_size=117581,
        field_size=39,
        embedding_size=32,
        deep_layers="128,64,32",
        dropout="0.5,0.5,0.5",
        batch_size=1024,
        learning_rate=5e-4,
        optimizer="Adam",
        l2_reg=1e-4,
        compute_dtype="bfloat16",
        mesh_data=0,  # all available devices on the data axis
        mesh_model=1,
        log_steps=0,
        seed=0,
    )
    n_dev = len(jax.devices())
    print(f"bench: devices={jax.devices()}", file=sys.stderr)

    trainer = Trainer(cfg)
    state = trainer.init_state()

    # Pre-staged rotating host batches: measures the device step, with host
    # batch transfer included but disk/decode excluded (decode is benched
    # separately; the native decoder sustains >1M ex/s, see tests).
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(8):
        batches.append({
            "feat_ids": rng.integers(
                0, cfg.feature_size, (cfg.batch_size, cfg.field_size)
            ).astype(np.int32),
            "feat_vals": rng.normal(
                size=(cfg.batch_size, cfg.field_size)).astype(np.float32),
            "label": (rng.random((cfg.batch_size, 1)) < 0.25).astype(np.float32),
        })

    step = trainer.train_step
    # Warmup/compile.
    for i in range(5):
        state, m = step(state, trainer.put_batch(batches[i % 8]))
    jax.block_until_ready(m["loss"])

    # Several trials, best wins: at ~0.5 ms/step the host/tunnel jitter
    # dominates a single trial, and the fastest trial is the honest
    # steady-state device throughput.
    n_steps = 100
    n_trials = 5
    dt = float("inf")
    for _ in range(n_trials):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, m = step(state, trainer.put_batch(batches[i % 8]))
        jax.block_until_ready(m["loss"])
        dt = min(dt, time.perf_counter() - t0)

    total_eps = n_steps * cfg.batch_size / dt
    per_chip = total_eps / max(n_dev, 1)
    nominal_per_accel_baseline = 250_000.0 / 4.0
    result = {
        "metric": "deepfm_criteo_train_throughput_per_chip",
        "value": round(per_chip, 1),
        "unit": "examples/sec",
        "vs_baseline": round(per_chip / nominal_per_accel_baseline, 3),
    }
    print(f"bench: {n_steps} steps in {dt:.3f}s, "
          f"{1000 * dt / n_steps:.2f} ms/step, total {total_eps:,.0f} ex/s "
          f"on {n_dev} device(s), loss={float(m['loss']):.4f}",
          file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
