#!/usr/bin/env bash
# Runnable L5 launcher: create (or reuse) a TPU slice and run a deepfm_tpu
# task across all its hosts — the TPU-native analog of the SageMaker
# launcher notebooks (reference 1-ps-cpu/deepfm-sagemaker-ps-cpu.ipynb:71-143:
# pick instances, spot, distribution, channels, then estimator.fit).
#
# Usage:
#   scripts/launch_slice.sh \
#     --tpu-name deepfm-v5e --zone us-west4-a --accel-type v5litepod-8 \
#     [--create] [--spot] [--worker-per-host N] [--repo-tar] \
#     -- --task_type train --data_dir gs://bucket/criteo --model_dir gs://bucket/ckpt \
#        --feature_size 117581 --field_size 39 --batch_size 1024 --num_epochs 10
#
# Everything after `--` is passed to the per-host entry point verbatim.
#
# What it does:
#   1. (--create) gcloud creates the slice — queued-resources with --spot
#      gives the reference's spot-instance economics (preemption tolerance =
#      checkpoint resume, same as the reference's SageMaker spot story).
#   2. Ships the repo to every host (--repo-tar) or assumes a shared image.
#   3. Runs the task on ALL hosts simultaneously via
#      `gcloud ... tpu-vm ssh --worker=all`:
#        worker_per_host == 1 -> `python -m deepfm_tpu.launch --dist_mode 2`
#          (jax.distributed discovers the slice topology itself)
#        worker_per_host  > 1 -> `python -m deepfm_tpu.fanout` spawns N local
#          processes per host with explicit rank math (MPI
#          processes_per_host analog, ref hvd-gpu.ipynb:87-92), rendezvousing
#          on host 0's port 12355.
set -euo pipefail

TPU_NAME=""
ZONE=""
ACCEL_TYPE="v5litepod-8"
VERSION="tpu-ubuntu2204-base"
CREATE=0
SPOT=0
WORKER_PER_HOST=1
SHIP_REPO=0
COORD_PORT=12355

while [[ $# -gt 0 ]]; do
  case "$1" in
    --tpu-name) TPU_NAME="$2"; shift 2 ;;
    --zone) ZONE="$2"; shift 2 ;;
    --accel-type) ACCEL_TYPE="$2"; shift 2 ;;
    --version) VERSION="$2"; shift 2 ;;
    --create) CREATE=1; shift ;;
    --spot) SPOT=1; shift ;;
    --worker-per-host) WORKER_PER_HOST="$2"; shift 2 ;;
    --repo-tar) SHIP_REPO=1; shift ;;
    --) shift; break ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done
TASK_ARGS=("$@")

[[ -n "$TPU_NAME" && -n "$ZONE" ]] || {
  echo "required: --tpu-name and --zone" >&2; exit 2; }

GC=(gcloud compute tpus tpu-vm)

if [[ "$CREATE" == 1 ]]; then
  echo ">> creating TPU slice $TPU_NAME ($ACCEL_TYPE) in $ZONE"
  CREATE_FLAGS=(--zone "$ZONE" --accelerator-type "$ACCEL_TYPE"
                --version "$VERSION")
  [[ "$SPOT" == 1 ]] && CREATE_FLAGS+=(--spot)
  "${GC[@]}" create "$TPU_NAME" "${CREATE_FLAGS[@]}"
fi

# Host topology from the slice description.
NUM_HOSTS=$("${GC[@]}" describe "$TPU_NAME" --zone "$ZONE" \
              --format='value(networkEndpoints.length())')
HOST0_IP=$("${GC[@]}" describe "$TPU_NAME" --zone "$ZONE" \
             --format='value(networkEndpoints[0].ipAddress)')
echo ">> slice $TPU_NAME: $NUM_HOSTS host(s), host0=$HOST0_IP, " \
     "worker_per_host=$WORKER_PER_HOST"

if [[ "$SHIP_REPO" == 1 ]]; then
  echo ">> shipping repo to all hosts"
  REPO_ROOT=$(cd "$(dirname "$0")/.." && pwd)
  TAR=/tmp/deepfm_tpu_ship.tgz
  tar -czf "$TAR" -C "$REPO_ROOT" --exclude .git --exclude '__pycache__' .
  "${GC[@]}" scp "$TAR" "$TPU_NAME":/tmp/ --zone "$ZONE" --worker=all
  "${GC[@]}" ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command="mkdir -p ~/deepfm_tpu_run && tar -xzf /tmp/deepfm_tpu_ship.tgz -C ~/deepfm_tpu_run"
fi

QUOTED_ARGS=$(printf ' %q' "${TASK_ARGS[@]}")

if [[ "$WORKER_PER_HOST" == 1 ]]; then
  # One process per host: jax.distributed discovers the slice topology.
  REMOTE_CMD="cd ~/deepfm_tpu_run 2>/dev/null || true; \
python -m deepfm_tpu.launch --dist_mode 2 --worker_per_host 1$QUOTED_ARGS"
  echo ">> running on all hosts: $REMOTE_CMD"
  "${GC[@]}" ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command="$REMOTE_CMD"
else
  # N processes per host: fanout computes per-process ranks; every host
  # rendezvouses on host 0.
  echo ">> fanning out $WORKER_PER_HOST workers/host across $NUM_HOSTS hosts"
  PIDS=()
  for (( h=0; h<NUM_HOSTS; h++ )); do
    REMOTE_CMD="cd ~/deepfm_tpu_run 2>/dev/null || true; \
python -m deepfm_tpu.fanout --worker_per_host $WORKER_PER_HOST \
--num_hosts $NUM_HOSTS --host_index $h \
--coordinator_address $HOST0_IP:$COORD_PORT$QUOTED_ARGS"
    "${GC[@]}" ssh "$TPU_NAME" --zone "$ZONE" --worker="$h" \
      --command="$REMOTE_CMD" &
    PIDS+=($!)
  done
  RC=0
  for (( h=0; h<NUM_HOSTS; h++ )); do
    if ! wait "${PIDS[$h]}"; then
      echo ">> host $h FAILED" >&2
      RC=1
    fi
  done
  [[ "$RC" == 0 ]] || { echo ">> launch failed" >&2; exit "$RC"; }
fi
echo ">> done"
