#!/usr/bin/env python
"""Fault drill: train under injected I/O faults, assert parity with clean.

The executable acceptance check for the fault-tolerance layer:

  1. **Read-fault + bad-record parity.** Dataset B is dataset A plus one
     extra record whose data CRC is then flipped. Training on B with
     ``on_bad_record=skip`` under injected transient read faults (every
     k-th read fails once, healed by ResilientStream) must produce
     bit-identical final parameters to a clean run on A — the surviving
     record streams are equal — and ``DataHealth`` must report the exact
     injected retry count and exactly one skipped record per epoch.
  2. **Raise policy.** The same corrupt input with ``on_bad_record=raise``
     fails with an error naming the file path and absolute byte offset.
  3. **Checkpoint-save hardening.** An injected transient save failure does
     not abort training; a later interval save succeeds, the final forced
     save lands, and resume-from-latest works.

Run on CPU:  JAX_PLATFORMS=cpu python scripts/fault_drill.py
"""

import argparse
import os
import struct
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from deepfm_tpu.config import Config
from deepfm_tpu.data import libsvm, tfrecord
from deepfm_tpu.train import Trainer, tasks
from deepfm_tpu.utils import checkpoint as ckpt_lib
from deepfm_tpu.utils import faults
from deepfm_tpu.utils import retry as retry_lib

FEATURE_SIZE = 64
FIELD_SIZE = 5
NUM_FILES = 4
RECORDS_PER_FILE = 60
VICTIM_FILE_IDX = 1
VICTIM_RECORD_IDX = 30


def _cfg(data_dir, model_dir, **kw):
    base = dict(
        task_type="train", data_dir=data_dir, model_dir=model_dir,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=16, num_epochs=2,
        compute_dtype="float32", mesh_data=1, log_steps=0,
        scale_lr_by_world=False, seed=17, verify_crc=True,
        # Zero backoff keeps the drill fast; the jittered-sleep path is
        # covered by tests/test_retry.py with a fake clock.
        io_retry_backoff_secs=0.0)
    base.update(kw)
    return Config(**base)


def frame_offsets(path):
    """[(frame_start, payload_len), ...] for a clean TFRecord file."""
    out = []
    data = open(path, "rb").read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        out.append((pos, length))
        pos += 12 + length + 4
    return out


def build_datasets(root):
    """Write faulty-dir B, then clean-dir A = B minus the victim record;
    flip the victim's data CRC in B. Returns (clean, faulty, victim_path,
    victim_offset)."""
    faulty = os.path.join(root, "data_faulty")
    clean = os.path.join(root, "data_clean")
    os.makedirs(clean, exist_ok=True)
    files = sorted(libsvm.generate_synthetic_ctr(
        faulty, num_files=NUM_FILES, examples_per_file=RECORDS_PER_FILE,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="tr",
        seed=5))
    victim_path = files[VICTIM_FILE_IDX]
    for path in files:
        records = tfrecord.read_all_records(path)
        out = os.path.join(clean, os.path.basename(path))
        with tfrecord.TFRecordWriter(out) as w:
            for i, rec in enumerate(records):
                if path == victim_path and i == VICTIM_RECORD_IDX:
                    continue
                w.write(rec)
    frames = frame_offsets(victim_path)
    victim_offset, victim_len = frames[VICTIM_RECORD_IDX]
    with open(victim_path, "r+b") as f:
        f.seek(victim_offset + 12 + victim_len)  # first data-CRC byte
        crc0 = f.read(1)
        f.seek(victim_offset + 12 + victim_len)
        f.write(bytes([crc0[0] ^ 0xFF]))
    return clean, faulty, victim_path, victim_offset


def final_params(cfg):
    trainer = Trainer(cfg)
    with ckpt_lib.CheckpointManager(cfg.model_dir) as mgr:
        state = mgr.restore(trainer.init_state())
    return jax.tree.map(np.asarray, state.params), int(state.step)


def assert_tree_equal(a, b, what):
    la, _ = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    assert len(la) == len(lb), f"{what}: tree structure differs"
    for xa, xb in zip(la, lb):
        if not np.array_equal(np.asarray(xa), np.asarray(xb)):
            raise AssertionError(f"{what}: parameter mismatch "
                                 f"(max abs diff "
                                 f"{np.abs(np.asarray(xa) - np.asarray(xb)).max()})")


def run_drill(workdir, *, read_fail_every=7, verbose=True):
    def say(msg):
        if verbose:
            print(f"[fault_drill] {msg}")

    clean_dir, faulty_dir, victim_path, victim_offset = build_datasets(workdir)
    say(f"datasets ready; victim {os.path.basename(victim_path)} "
        f"at byte {victim_offset}")

    # 1a. Clean baseline on A.
    clean_ckpt = os.path.join(workdir, "ckpt_clean")
    res_clean = tasks.run(_cfg(clean_dir, clean_ckpt))
    assert res_clean["bad_records"] == 0 and res_clean["read_retries"] == 0
    params_clean, step_clean = final_params(_cfg(clean_dir, clean_ckpt))
    say(f"clean run done: {step_clean} steps")

    # 1b. Faulty run on B: injected read faults + skip-one-bad-record.
    faulty_ckpt = os.path.join(workdir, "ckpt_faulty")
    cfg_faulty = _cfg(faulty_dir, faulty_ckpt, on_bad_record="skip",
                      max_bad_records=1)
    with faults.FlakyFS(read_fail_every=read_fail_every) as fs:
        res_faulty = tasks.run(cfg_faulty)
    n_epochs = cfg_faulty.num_epochs
    assert fs.injected_read_faults > 0, (
        f"read_fail_every={read_fail_every} injected nothing; dataset too "
        f"small for the cadence")
    assert res_faulty["read_retries"] == fs.injected_read_faults, (
        f"DataHealth retries {res_faulty['read_retries']} != injected "
        f"{fs.injected_read_faults}")
    # One skip per pass over the victim file: each epoch trains once and
    # runs the post-epoch eval once over the same (faulty) directory.
    assert res_faulty["bad_records"] == 2 * n_epochs, (
        f"expected 1 skip per train + eval pass ({2 * n_epochs}), got "
        f"{res_faulty['bad_records']}")
    params_faulty, step_faulty = final_params(cfg_faulty)
    assert step_faulty == step_clean, (
        f"step count diverged: {step_faulty} vs {step_clean}")
    assert_tree_equal(params_clean, params_faulty,
                      "clean-vs-faulty final params")
    say(f"faulty run done: params bit-identical to clean; "
        f"{fs.injected_read_faults} read faults healed, "
        f"{int(res_faulty['bad_records'])} records skipped")

    # 2. Same corrupt input, on_bad_record=raise: path+offset error.
    try:
        tasks.run(_cfg(faulty_dir, os.path.join(workdir, "ckpt_raise")))
    except IOError as e:
        msg = str(e)
        assert victim_path in msg and f"at byte {victim_offset}" in msg, (
            f"error lacks path+offset: {msg}")
        say(f"raise policy: correct error ({msg.splitlines()[0][:100]})")
    else:
        raise AssertionError("raise policy did not raise on corrupt record")

    # 3. Checkpoint-save hardening: first interval save fails, training
    # continues, a later save + the final forced save succeed, resume works.
    hard_ckpt = os.path.join(workdir, "ckpt_hardened")
    cfg_hard = _cfg(clean_dir, hard_ckpt, save_checkpoints_steps=4,
                    steps_per_loop=4)
    with faults.FlakyFS(save_failures=1) as fs:
        res_hard = tasks.run(cfg_hard)
    assert fs.injected_save_faults == 1, "save fault was never injected"
    assert res_hard["steps"] == step_clean, "save failure aborted training"
    _, step_hard = final_params(cfg_hard)
    assert step_hard == step_clean, "final forced save missing"
    res_resume = tasks.run(cfg_hard.replace(num_epochs=3))
    assert res_resume["steps"] > res_hard["steps"], (
        "resume-from-latest did not continue training")
    say(f"checkpoint drill done: 1 save fault tolerated, resumed "
        f"{int(res_hard['steps'])} -> {int(res_resume['steps'])} steps")

    # 4. Same faulty input through the decoded-epoch cache and the
    # device-resident fit: the healed/skipped record stream feeds the cache
    # build instead of the per-batch decode, and the final params must STILL
    # be bit-identical to the clean staged baseline — fault tolerance holds
    # across every input path, not just the one it was written against.
    from deepfm_tpu.data import cache as cache_lib
    for label, extra in (("decoded_cache=ram", dict(decoded_cache="ram")),
                         ("device_dataset", dict(decoded_cache="ram",
                                                 device_dataset=True))):
        ckpt = os.path.join(workdir, f"ckpt_{label.split('=')[0]}")
        cfg_path = _cfg(faulty_dir, ckpt, on_bad_record="skip",
                        max_bad_records=1, **extra)
        # Drop the process-global RAM epoch cache so this run re-decodes
        # through the injected-fault filesystem instead of hitting the
        # previous label's cached columns.
        cache_lib.clear_ram_cache()
        with faults.FlakyFS(read_fail_every=read_fail_every) as fs_p:
            res_path = tasks.run(cfg_path)
        assert fs_p.injected_read_faults > 0, f"{label}: nothing injected"
        params_path, step_path = final_params(cfg_path)
        assert step_path == step_clean, (
            f"{label}: step count diverged: {step_path} vs {step_clean}")
        assert_tree_equal(params_clean, params_path,
                          f"clean-vs-faulty final params ({label})")
        say(f"{label} run done: params bit-identical to clean "
            f"({fs_p.injected_read_faults} read faults healed, "
            f"{int(res_path['bad_records'])} records skipped)")

    return {
        "steps": step_clean,
        "read_faults_injected": fs_read_faults(res_faulty),
        "bad_records": int(res_faulty["bad_records"]),
    }


def fs_read_faults(res):
    return int(res["read_retries"])


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: a fresh TemporaryDirectory)")
    ap.add_argument("--read_fail_every", type=int, default=7,
                    help="every k-th stream read raises once (default 7)")
    args = ap.parse_args()
    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        summary = run_drill(args.workdir,
                            read_fail_every=args.read_fail_every)
    else:
        with tempfile.TemporaryDirectory(prefix="fault_drill_") as d:
            summary = run_drill(d, read_fail_every=args.read_fail_every)
    print(f"[fault_drill] PASS {summary}")


if __name__ == "__main__":
    main()
