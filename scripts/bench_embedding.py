#!/usr/bin/env python
"""Embedding-scale benchmark: sparse-vs-dense updates, kernel-plane
A/Bs with per-stage breakdown, beyond-HBM vocab scaling, hot/cold
tiering overlap, and (``--sharded``) the row-sharded model-parallel
A/B. Emits ``EMBED_r03.json``.

Sections (all single-device except ``row_sharding``):

* ``sparse_vs_dense`` — identical synthetic CTR training with
  ``--embedding_update dense`` vs ``sparse`` at the seed formulation
  (``--embedding_kernels off``) vs the fused formulation (``auto``):
  ms/step three-way plus the final max param divergence vs dense (the
  lazy-Adam idle-row tail; see tests/test_embedding_sparse.py for the
  pinned tolerance). The headline claim: ``sparse_beats_dense`` — the
  fused sparse step is at or under the dense step at V=100k.
* ``kernels`` — the embedding-plane kernel ledger: a per-stage ms
  breakdown of the fused sparse step (plan build, gradient scatter,
  masked Adam sweep, cache install) and a per-kernel A/B table where
  every optimized leg must beat its reference leg to be ``chosen``;
  ties/losses keep the reference (the select-writeback leg is recorded
  as rejected on parity, not speed). ``killswitch_parity`` pins the
  ``--embedding_kernels off`` contract measured here: losses bit-equal,
  params within the documented Adam-tail ULP band.
* ``scaling`` — sparse ms/step over 1M/10M/100M *hashed* vocabs with the
  physical tables capped by ``--embedding_buckets``, and over batch sizes
  at the largest vocab. The claim under test: sparse step cost scales
  with unique-ids-per-batch, NOT with vocab (dense at 100M would update
  every row every step — it isn't even run above the base vocab).
* ``hot_cold`` — tiered training (HBM-hot cache over host cold store) at
  lookahead depth 0 vs 2: hit rate, cold-fetch wall time, and the
  fraction of fetch time that ran on the staging thread overlapped with
  device compute (the ``overlap`` column; acceptance is >= 0.5 at
  depth 2).
* ``row_sharding`` (``--sharded``) — replicated vs ``--embedding_shard
  rows`` at a fixed global batch: per-device embedding HBM (tables +
  lazy-Adam m/v/tau, from addressable shard sizes; the capacity claim
  is ~1/D), the analytic all-to-all exchange payload per step, the
  gradient-reduce payload, and the trajectory drift vs the replicated
  leg. ``scaling_efficiency`` is refused in-band on this time-sliced
  host.

Honesty labels: ``device_kind`` records what the timings ran on (CPU
numbers are A/B-relative, not TPU-absolute); ``load_kind`` records that
the input is synthetic CTR, not Criteo.

Usage: python scripts/bench_embedding.py [--quick] [--sharded] [--out X]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _synth_batches(nb, b, f, v, seed=3):
    import numpy as np
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(nb):
        out.append(dict(
            feat_ids=rng.integers(0, v, size=(b, f)).astype(np.int64 if
                                  v > 2**31 - 1 else np.int32),
            feat_vals=rng.normal(size=(b, f)).astype(np.float32),
            label=rng.integers(0, 2, size=(b,)).astype(np.float32)))
    return out


def _mean_unique(batches):
    import numpy as np
    return float(np.mean([np.unique(b["feat_ids"]).size for b in batches]))


def _cfg(**kw):
    from deepfm_tpu.config import Config
    base = dict(field_size=39, embedding_size=8, deep_layers="32,16",
                dropout="1.0,1.0", compute_dtype="float32", l2_reg=0.0,
                learning_rate=0.001, log_steps=0, seed=11,
                scale_lr_by_world=False, mesh_data=1, mesh_model=1,
                steps_per_loop=1, transfer_ahead=0)
    base.update(kw)
    return Config(**base)


def _timed_fit(cfg, batches, warmup=2):
    """(ms_per_step, trainer, final_state): fit over ``warmup`` batches to
    compile, then the timed fit reuses the cached step program."""
    import jax
    from deepfm_tpu.train import Trainer
    tr = Trainer(cfg)
    state = tr.init_state()
    state, _ = tr.fit(state, batches[:warmup])
    t0 = time.perf_counter()
    state, summary = tr.fit(state, batches[warmup:])
    jax.block_until_ready(state.params)
    ms = (time.perf_counter() - t0) * 1000.0 / max(summary["steps"], 1)
    return ms, tr, state


def bench_sparse_vs_dense(quick):
    import numpy as np
    v, b, nb = 100_000, 1024, (8 if quick else 24)
    batches = _synth_batches(nb + 2, b, 39, v)
    out = {"V": v, "B": b, "steps": nb}
    states = {}
    for label, kw in (
            ("dense", dict(embedding_update="dense")),
            ("sparse_seed", dict(embedding_update="sparse",
                                 embedding_kernels="off")),
            ("sparse", dict(embedding_update="sparse",
                            embedding_kernels="auto"))):
        ms, _, st = _timed_fit(
            _cfg(feature_size=v, batch_size=b, **kw), batches)
        out[f"{label}_ms_per_step"] = round(ms, 3)
        states[label] = st
    out["dense_over_sparse"] = round(
        out["dense_ms_per_step"] / out["sparse_ms_per_step"], 2)
    out["sparse_over_dense"] = round(
        out["sparse_ms_per_step"] / out["dense_ms_per_step"], 3)
    out["sparse_beats_dense"] = bool(
        out["sparse_ms_per_step"] <= out["dense_ms_per_step"])
    out["fused_speedup_vs_seed"] = round(
        out["sparse_seed_ms_per_step"] / out["sparse_ms_per_step"], 2)
    out["max_param_divergence"] = round(max(
        float(np.abs(np.asarray(states["dense"].params[n], np.float32)
                     - np.asarray(states["sparse"].params[n],
                                  np.float32)).max())
        for n in ("fm_w", "fm_v")), 6)
    out["unique_ids_per_batch"] = round(_mean_unique(batches[2:]), 1)
    return out


def _time_jit(fn, *args, iters=20, reps=3):
    """Best-of-reps mean ms for a jitted callable (compile excluded)."""
    import jax
    jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) * 1000.0 / iters)
    return best


def bench_kernels(quick, sparse_vs_dense):
    """Per-stage breakdown of the fused sparse step plus the per-kernel
    A/B ledger. Every ``opt`` leg must beat its ``ref`` leg to be
    ``chosen``; ties and losses keep the reference path — exactly the
    fallback the trainer takes (``pallas_supported`` records whether the
    compiled Pallas leg was even eligible on this backend)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepfm_tpu.data import hot_cold as hc
    from deepfm_tpu.ops import embedding as emb_ops
    from deepfm_tpu.ops import pallas_embedding as pemb
    from deepfm_tpu.train import Trainer

    v, b, f, d = 100_000, 1024, 39, 8
    iters = 5 if quick else 20
    tr = Trainer(_cfg(feature_size=v, batch_size=b,
                      embedding_update="sparse", embedding_kernels="auto"))
    state = tr.init_state()
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, v, (b, f)).astype(np.int32))
    tabs = {n: state.params[n] for n in tr._embed_names}
    vp = tabs[tr._embed_names[0]].shape[0]  # padded_vocab(v) table height
    g_views = {
        n: jnp.asarray(rng.standard_normal(
            (b, f) + (() if tabs[n].ndim == 1 else (d,))).astype(np.float32))
        for n in tr._embed_names}

    # --- stage breakdown of the fused step (auto path) ---
    plan_ref = jax.jit(lambda i: emb_ops.make_plan(i, vp))
    plan_opt = jax.jit(lambda i: emb_ops.make_plan_counting(i, vp))
    grad_fn = jax.jit(lambda t, i, g: tr._fused_grad_ext(t, i, g))
    gext = grad_fn(tabs, ids, g_views)
    count = jnp.asarray(1, jnp.int32)
    apply_fn = jax.jit(
        lambda st, t, ge, c: tr._fused_apply(st, t, ge, c))

    hot, p = 24_576, 1024
    iw = jnp.asarray(rng.standard_normal((hot, d)).astype(np.float32))
    im = jnp.zeros((hot, d), jnp.float32)
    iv = jnp.zeros((hot, d), jnp.float32)
    itau = jnp.zeros((hot,), jnp.int32)
    slots = jnp.asarray(
        rng.choice(hot, p, replace=False).astype(np.int32))
    wv = jnp.asarray(rng.standard_normal((p, d)).astype(np.float32))
    tv = jnp.full((p,), 3, jnp.int32)
    install_opt = _time_jit(
        lambda: pemb.install_rows(iw, im, iv, itau, slots, wv, wv, wv, tv,
                                  mode="xla"), iters=iters)
    install_ref = _time_jit(
        lambda: (hc._jit_install(iw, slots, wv),
                 hc._jit_install(im, slots, wv),
                 hc._jit_install(iv, slots, wv),
                 hc._jit_install(itau, slots, tv)), iters=iters)

    stage = {
        "plan_build_ms": round(_time_jit(plan_opt, ids, iters=iters), 3),
        "gather_grad_ms": round(
            _time_jit(grad_fn, tabs, ids, g_views, iters=iters), 3),
        "apply_ms": round(
            _time_jit(apply_fn, state, tabs, gext, count, iters=iters), 3),
        "install_ms": round(min(install_opt, install_ref), 3),
        "note": ("fused monolithic path builds no plan (direct batch-view "
                 "gather); plan_build_ms is the counting build used by the "
                 "hashed/tiered plan path at the same id load"),
    }

    # --- per-kernel A/B ledger ---
    def entry(kernel, ref_ms, opt_ms, seam):
        chosen = "opt" if opt_ms < ref_ms else "ref"
        return {"kernel": kernel, "seam": seam,
                "ref_ms": round(ref_ms, 3), "opt_ms": round(opt_ms, 3),
                "pallas_supported": bool(
                    pemb.supported(kernel, num_rows=v, n_ids=b * f)),
                "chosen": chosen}

    ab = [
        entry("plan", _time_jit(plan_ref, ids, iters=iters),
              _time_jit(plan_opt, ids, iters=iters),
              "sort-based plan build vs counting (bincount+cumsum) build"),
        entry("take", sparse_vs_dense["sparse_seed_ms_per_step"],
              sparse_vs_dense["sparse_ms_per_step"],
              "end-to-end step: plan-based seed backward vs fused "
              "batch-view backward + masked table sweep"),
        entry("install", install_ref, install_opt,
              "four per-array cache-install scatters vs one fused "
              "w/m/v/tau install"),
    ]

    # The select-writeback leg is element-exact and competitive on time,
    # but a vocab-shaped where in the update graph perturbs XLA:CPU's
    # fusion of the model backward (~1 ULP), breaking the kill-switch
    # bit-parity pin — rejected on parity, not speed (loop._sparse_apply).
    plan_c = plan_opt(ids)
    new_rows = jnp.asarray(rng.standard_normal(
        (int(plan_c.uids.shape[0]), d)).astype(np.float32))
    tab = tabs["fm_v"]
    sc_fn = jax.jit(lambda t, r: emb_ops.scatter_rows(
        t, plan_c._replace(touched=None, rank=None), r))
    sel_fn = jax.jit(lambda t, r: emb_ops.scatter_rows(t, plan_c, r))
    ab.append({
        "kernel": "select_writeback", "seam":
            "row writeback: ids scatter vs touched/rank select",
        "ref_ms": round(_time_jit(sc_fn, tab, new_rows, iters=iters), 3),
        "opt_ms": round(_time_jit(sel_fn, tab, new_rows, iters=iters), 3),
        "pallas_supported": False,
        "chosen": "ref",
        "rejected_for": "parity: vocab-shaped select perturbs backward "
                        "fusion ~1 ULP; trainer strips touched/rank "
                        "(kill-switch bit-pin wins over the A/B)",
    })

    # --- kill-switch parity at a trainer-visible shape ---
    pv, pb, steps = 5_000, 256, 6
    batches = _synth_batches(steps, pb, 13, pv, seed=9)
    runs = {}
    for kern in ("off", "auto"):
        trp = Trainer(_cfg(feature_size=pv, batch_size=pb, field_size=13,
                           embedding_update="sparse",
                           embedding_kernels=kern, l2_reg=1e-4))
        stp = trp.init_state()
        step = trp._make_train_step()
        losses = []
        for bt in batches:
            stp, m = step(stp, trp.put_batch(bt))
            losses.append(float(np.asarray(m["loss"])))
        runs[kern] = (stp, losses)
    diverg = max(
        float(np.abs(np.asarray(runs["off"][0].params[n])
                     - np.asarray(runs["auto"][0].params[n])).max())
        for n in ("fm_w", "fm_v"))
    parity = {
        "steps": steps, "V": pv, "B": pb, "l2_reg": 1e-4,
        "losses_bitequal": bool(runs["off"][1] == runs["auto"][1]),
        "max_param_divergence": float(f"{diverg:.3e}"),
        "contract": ("off-vs-auto: losses bit-equal, params within the "
                     "Adam-tail ULP band (optimizers.sparse_adam_masked "
                     "docstring); auto-vs-xla and hashed off-vs-auto are "
                     "bit-exact (tests/test_pallas_embedding.py)"),
    }

    return {"stage_breakdown": stage, "ab": ab,
            "killswitch_parity": parity}


def bench_scaling(quick):
    # Physical rows capped by hashing: 4 tables x 262144 buckets = 1M rows
    # regardless of the hashed vocab — feature_size can exceed any single
    # allocation. Unique-ids-per-batch is what the step cost must track.
    buckets = ",".join(["262144"] * 4)
    b, nb = 1024, (6 if quick else 16)
    rows = []
    for v in (1_000_000, 10_000_000, 100_000_000):
        batches = _synth_batches(nb + 2, b, 39, v)
        ms, _, _ = _timed_fit(
            _cfg(feature_size=v, batch_size=b, embedding_update="sparse",
                 embedding_buckets=buckets), batches)
        rows.append({"V": v, "B": b, "physical_rows": 4 * 262144,
                     "sparse_ms_per_step": round(ms, 3),
                     "unique_ids_per_batch":
                         round(_mean_unique(batches[2:]), 1)})
    # Same (largest) vocab, varying batch -> varying uniques: the cost
    # driver, isolated from vocab.
    for b2 in (256, 4096):
        batches = _synth_batches(nb + 2, b2, 39, 100_000_000)
        ms, _, _ = _timed_fit(
            _cfg(feature_size=100_000_000, batch_size=b2,
                 embedding_update="sparse", embedding_buckets=buckets),
            batches)
        rows.append({"V": 100_000_000, "B": b2,
                     "physical_rows": 4 * 262144,
                     "sparse_ms_per_step": round(ms, 3),
                     "unique_ids_per_batch":
                         round(_mean_unique(batches[2:]), 1)})
    flat = (rows[2]["sparse_ms_per_step"]
            / max(rows[0]["sparse_ms_per_step"], 1e-9))
    return {"rows": rows,
            "ms_ratio_100M_over_1M": round(flat, 2),
            "cost_tracks_uniques_not_vocab": bool(flat < 3.0)}


def bench_hot_cold(quick):
    # One B=256 x F=39 group touches ~10k unique rows; 24k hot rows fit
    # the depth-2 pinned lookahead (two groups) with room to evict.
    v, b, nb = 200_000, 256, (10 if quick else 30)
    hot = 24_576
    batches = _synth_batches(nb, b, 39, v)
    out = {"V": v, "B": b, "hot_rows": hot, "steps": nb,
           "cold_dtype": "float32", "series": []}
    for depth in (0, 2):
        from deepfm_tpu.train import Trainer
        cfg = _cfg(feature_size=v, batch_size=b, embedding_update="sparse",
                   embedding_tiering="hot_cold", embedding_hot_rows=hot,
                   transfer_ahead=depth)
        tr = Trainer(cfg)
        state = tr.init_state()
        t0 = time.perf_counter()
        state, summary = tr.fit(state, batches)
        wall = time.perf_counter() - t0
        st = tr._tier.stats
        out["series"].append({
            "transfer_ahead": depth,
            "ms_per_step": round(wall * 1000 / max(summary["steps"], 1), 3),
            "hit_rate": round(tr._tier.hit_rate(), 4),
            "evictions": int(st["evictions"]),
            "installs": int(st["installs"]),
            "prefetch_fetch_s": round(st["prefetch_fetch_s"], 4),
            "apply_fetch_s": round(st["apply_fetch_s"], 4),
            "overlap_fraction": round(tr._tier.overlap_fraction(), 4),
        })
    out["overlap_at_depth2"] = out["series"][-1]["overlap_fraction"]
    out["overlap_ok"] = bool(out["overlap_at_depth2"] >= 0.5)
    return out


def _per_device_embed_bytes(tr, state):
    """Max-over-devices bytes of the embedding plane (tables + lazy-Adam
    m/v/tau) actually resident per device — addressable shard sizes, so a
    row-sharded table counts its 1/D slice, a replicated one its whole."""
    import jax
    total = 0
    for n in tr._embed_names:
        leaves = (jax.tree.leaves(state.params[n])
                  + jax.tree.leaves(state.opt_state["embed"][n]))
        for x in leaves:
            shards = getattr(x, "addressable_shards", None)
            if shards:
                total += max(int(s.data.nbytes) for s in shards)
            else:
                total += int(x.nbytes)
    return total


def bench_row_sharded(devices, quick):
    """Replicated vs row-sharded (--embedding_shard rows) A/B at a FIXED
    global batch: the capacity claim under test is per-device embedding
    HBM ~ 1/D (tables AND optimizer moments), with the per-step price an
    all-to-all row exchange whose payload is analytic
    (ops.embedding.exchange_payload_bytes; TUNING §2.11).

    ``scaling_efficiency`` is REFUSED on this host: the virtual shards
    time-slice one CPU, so wall-clock across mesh shapes measures
    scheduler interleaving, not parallel speedup. ms/step is recorded
    per leg for completeness only.
    """
    import numpy as np
    from deepfm_tpu.ops import embedding as emb_ops

    v, b, f, k = 100_000, 1024, 39, 8
    nb = (4 if quick else 12)
    # the mesh batch spec declares [B, 1] labels
    batches = [{**bt, "label": bt["label"].reshape(-1, 1)}
               for bt in _synth_batches(nb + 2, b, f, v)]
    legs = [("replicated_1dev", 1, 1), ("rows_1x2", 1, 2),
            ("rows_1x4", 1, 4)]
    if devices >= 8 and not quick:
        legs.append(("rows_2x4", 2, 4))

    out = {"V": v, "B_global": b, "F": f, "K": k, "steps": nb,
           "series": []}
    params_by_leg = {}
    for label, data, m in legs:
        cfg = _cfg(feature_size=v, batch_size=b,
                   embedding_update="sparse",
                   embedding_shard=("rows" if m > 1 else "off"),
                   mesh_data=data, mesh_model=m)
        ms, tr, st = _timed_fit(cfg, batches)
        params_by_leg[label] = {n: np.asarray(st.params[n])
                                for n in ("fm_w", "fm_v")}
        # per-replica uid slots: each data shard plans its local batch
        n_ids = (b // data) * f
        exch = (emb_ops.exchange_payload_bytes(n_ids, k, m)
                + emb_ops.exchange_payload_bytes(n_ids, 1, m))
        out["series"].append({
            "leg": label, "mesh": f"{data}x{m}",
            "ms_per_step": round(ms, 3),
            "per_device_embed_hbm_bytes": _per_device_embed_bytes(tr, st),
            "exchange_payload_bytes_per_step": exch,
            "grad_reduce_payload_bytes": tr._grad_payload_bytes(),
        })

    base = out["series"][0]["per_device_embed_hbm_bytes"]
    for row in out["series"]:
        row["hbm_fraction_of_replicated"] = round(
            row["per_device_embed_hbm_bytes"] / base, 4)
    # ~1/D: exact for the sharded leaves; tau (int32 [rows]) shards too,
    # so the whole embedding plane divides cleanly.
    out["hbm_scales_with_shards"] = bool(all(
        abs(row["hbm_fraction_of_replicated"] * shards - 1.0) < 0.05
        for row, shards in zip(out["series"], (1, 2, 4, 4))))
    out["trajectory_max_abs_diff_vs_replicated"] = {
        lab: float(max(
            np.abs(params_by_leg["replicated_1dev"][n][:v]
                   - params_by_leg[lab][n][:v]).max()
            for n in ("fm_w", "fm_v")))
        for lab in params_by_leg if lab != "replicated_1dev"}
    out["scaling_efficiency"] = None
    out["scaling_efficiency_refused"] = (
        "virtual shards time-slice one host CPU: cross-mesh wall-clock "
        "measures scheduler interleaving, not parallel speedup — refused; "
        "per-leg ms_per_step is recorded for completeness only")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small step counts (CI drill wrapper)")
    ap.add_argument("--sharded", action="store_true",
                    help="also run the replicated vs row-sharded "
                         "(--embedding_shard rows) A/B on a virtual "
                         "device mesh")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="artifact path (default EMBED_r03.json at repo "
                         "root; '-' to skip writing)")
    args = ap.parse_args()

    if args.sharded:
        from __graft_entry__ import _provision_virtual_devices
        _provision_virtual_devices(args.devices)

    import jax
    svd = bench_sparse_vs_dense(args.quick)
    report = {
        "bench": "embedding_scale",
        "device_kind": jax.devices()[0].device_kind,
        "load_kind": "synthetic-ctr",
        "quick": bool(args.quick),
        "sparse_vs_dense": svd,
        "kernels": bench_kernels(args.quick, svd),
        "scaling": bench_scaling(args.quick),
        "hot_cold": bench_hot_cold(args.quick),
    }
    if args.sharded:
        report["row_sharding"] = bench_row_sharded(args.devices, args.quick)

    print(json.dumps(report, indent=1))
    if args.out != "-":
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "EMBED_r03.json")
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
        print(f"wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
