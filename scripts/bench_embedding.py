#!/usr/bin/env python
"""A/B the row-sharded embedding lookup strategies (SURVEY hard-part #1).

Compares ``masked_psum`` (local masked gather + psum of activations) vs
``allgather_table`` (reassemble table, plain gather) under shard_map on a
virtual 8-device mesh: forward+backward wall time at CTR shapes, plus the
analytic per-step collective traffic that decides the winner on real ICI
(virtual CPU devices share one memory — the timing here captures compute
and program overhead only, NOT interconnect cost; the bytes column is the
hardware-relevant signal).

Usage: python scripts/bench_embedding.py [--devices 8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _provision_virtual_devices  # noqa: E402


def bench(v: int, k: int, b: int, f: int, m: int, data: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from deepfm_tpu.ops import embedding as emb

    devs = np.array(jax.devices()[:m * data]).reshape(data, m)
    mesh = Mesh(devs, ("data", "model"))
    vp = emb.padded_vocab(v, m)
    table = jax.device_put(
        np.random.default_rng(0).normal(size=(vp, k)).astype(np.float32),
        jax.sharding.NamedSharding(mesh, P("model", None)))
    ids = jax.device_put(
        np.random.default_rng(1).integers(0, v, (b, f)).astype(np.int32),
        jax.sharding.NamedSharding(mesh, P("data", None)))

    def make(strategy):
        def loss(tab, i):
            e = emb.lookup(tab, i, axis_name="model", strategy=strategy)
            return jnp.sum(e * e)
        def step(tab, i):
            l, g = jax.value_and_grad(loss)(tab, i)
            # pmean over both axes: value-level no-op on already-replicated
            # losses, but lets shard_map's VMA checker prove replication.
            return jax.lax.pmean(jax.lax.pmean(l, "data"), "model"), g
        return jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=(P("model", None), P("data", None)),
            out_specs=(P(), P("model", None))))

    rows = {}
    for strategy in ("masked_psum", "allgather_table"):
        fn = make(strategy)
        l, g = fn(table, ids)  # compile
        jax.block_until_ready(g)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(5):
                l, g = fn(table, ids)
            jax.block_until_ready(g)
            best = min(best, (time.perf_counter() - t0) / 5)
        rows[strategy] = best * 1000

    # Analytic per-step collective traffic per device link (ring, fwd+bwd):
    # masked_psum: psum([B/data, F, K]) fwd + nothing extra bwd (cotangent is
    #   already local after masking) -> 2*(m-1)/m * B/data*F*K words.
    # allgather_table: all_gather(V/m..V) fwd + reduce_scatter grad bwd
    #   -> 2*(m-1)/m * V*K words.
    act_words = (b // data) * f * k
    psum_traffic = 2 * (m - 1) / m * act_words * 4
    ag_traffic = 2 * (m - 1) / m * vp * k * 4
    print(json.dumps({
        "shape": {"V": v, "K": k, "B": b, "F": f,
                  "mesh": f"{data}x{m}"},
        "masked_psum_ms": round(rows["masked_psum"], 3),
        "allgather_table_ms": round(rows["allgather_table"], 3),
        "masked_psum_traffic_MB": round(psum_traffic / 1e6, 2),
        "allgather_table_traffic_MB": round(ag_traffic / 1e6, 2),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()
    _provision_virtual_devices(args.devices)

    # Reference CTR shape: activations << table -> psum should win on ICI.
    bench(v=117_581, k=32, b=1024, f=39, m=2, data=args.devices // 2)
    bench(v=117_581, k=32, b=1024, f=39, m=args.devices, data=1)
    # Small-table / huge-batch regime: table << activations -> all_gather.
    bench(v=4_096, k=32, b=16_384, f=39, m=args.devices, data=1)


if __name__ == "__main__":
    main()
