#!/usr/bin/env python
"""Serving scale-out sweep: QPS/p50/p99 per replica count and in-flight
depth -> ``SERVING_r0N.json``.

The measurement half of ROADMAP item 1's serving receipt (the correctness
half is ``scripts/serving_drill.py``, re-run here so the committed report
carries BOTH):

  1. **Sweep.** ``bench.serving_series`` over replicas {1, 2, 4} x
     in-flight depth {1, 2} against ONE pre-exported artifact pair, same
     closed-loop synthetic load for every point. ``inflight=1`` on one
     replica is the PR 7-style strict flush-then-refill engine — the
     within-report baseline the pipelined points are read against.
  2. **Drill gates.** The 2-replica pipelined drill re-asserts the PR 12
     serving gates (zero dropped/failed/overloaded across >= 3 staggered
     swaps, blackout <= 100 ms PER replica); its report is embedded.
  3. **Acceptance.** The headline point (1 replica, pipelined depth 2)
     must beat the SERVING_r01 baseline: p99 below 236 ms at >= 185 QPS.
  4. **Scaling honesty.** On a host with fewer cores than replicas, the
     replica axis time-slices the same core(s), so the report REFUSES a
     scaling-efficiency claim (``scaling_efficiency: null`` + reason, the
     SCALING_r01.json rule per BASELINE.md) while still publishing the
     measured per-point QPS/p99 curve.

Run on CPU:  JAX_PLATFORMS=cpu python scripts/bench_serving.py
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import serving_drill

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REPLICA_COUNTS = (1, 2, 4)
INFLIGHT_DEPTHS = (1, 2)
# SERVING_r01.json — the pre-pipelining engine on this host: the sweep's
# headline point must beat its p99 at equal-or-better QPS.
BASELINE_P99_MS = 236.0
BASELINE_QPS = 185.0


def say(msg):
    print(f"[bench_serving] {msg}", flush=True)


def _next_report_path():
    n = 1
    while os.path.exists(os.path.join(_REPO_ROOT, f"SERVING_r{n:02d}.json")):
        n += 1
    return os.path.join(_REPO_ROOT, f"SERVING_r{n:02d}.json")


def run_sweep(report_path=None, run_secs=3.0, verbose=True):
    global say
    if not verbose:
        say = lambda msg: None  # noqa: E731
    t_start = time.time()
    workdir = tempfile.mkdtemp(prefix="bench_serving_sweep_")
    try:
        say("exporting artifacts once for the whole sweep")
        bench.export_serving_artifacts(workdir)
        series = []
        for replicas in REPLICA_COUNTS:
            for inflight in INFLIGHT_DEPTHS:
                say(f"point replicas={replicas} inflight={inflight}")
                point = bench.serving_series(
                    replicas=replicas, inflight=inflight,
                    run_secs=run_secs, artifact_dir=workdir)
                say(f"  p50={point['serving_p50_ms']:.2f}ms "
                    f"p99={point['serving_p99_ms']:.2f}ms "
                    f"qps={point['serving_qps']}")
                series.append(point)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    say("re-asserting drill gates (2 replicas, pipelined)")
    drill = serving_drill.run_drill(
        report_path=os.path.join(tempfile.mkdtemp(prefix="bench_drill_"),
                                 "drill.json"),
        verbose=verbose, replicas=2)

    headline = next(p for p in series
                    if p["replicas"] == 1 and p["serve_inflight"] == 2)
    pr7_style = next(p for p in series
                     if p["replicas"] == 1 and p["serve_inflight"] == 1)
    assert headline["serving_p99_ms"] < BASELINE_P99_MS, (
        f"headline p99 {headline['serving_p99_ms']:.1f}ms not below the "
        f"SERVING_r01 baseline {BASELINE_P99_MS}ms")
    assert headline["serving_qps"] >= BASELINE_QPS, (
        f"headline QPS {headline['serving_qps']} below the SERVING_r01 "
        f"baseline {BASELINE_QPS}")
    for point in series:
        assert point["serving_failed"] == 0, point

    host_cpus = os.cpu_count() or 1
    if host_cpus < max(REPLICA_COUNTS):
        scaling_efficiency = None
        scaling_reason = (
            f"refused: {max(REPLICA_COUNTS)} replicas time-slice "
            f"{host_cpus} host core(s), so aggregate QPS measures "
            "scheduler interleaving, not replica scaling; the per-point "
            "curve is published for latency/correctness reading only "
            "(BASELINE.md scaling rules)")
    else:
        base_qps = next(p["serving_qps"] for p in series
                        if p["replicas"] == 1 and p["serve_inflight"] == 2)
        top = max((p for p in series if p["serve_inflight"] == 2),
                  key=lambda p: p["replicas"])
        scaling_efficiency = round(
            top["serving_qps"] / (top["replicas"] * base_qps), 3)
        scaling_reason = "aggregate QPS at max replicas over replicas x " \
                         "single-replica QPS (pipelined points)"

    report = {
        "bench": "serving_scaleout",
        "ok": True,
        "baseline": {"source": "SERVING_r01.json",
                     "serving_p99_ms": BASELINE_P99_MS,
                     "serving_qps": BASELINE_QPS},
        "headline": headline,
        "pr7_style_point": pr7_style,
        "series": series,
        "drill": drill,
        "replica_counts": list(REPLICA_COUNTS),
        "inflight_depths": list(INFLIGHT_DEPTHS),
        "host_cpu_count": host_cpus,
        "scaling_efficiency": scaling_efficiency,
        "scaling_efficiency_reason": scaling_reason,
        "load_kind": "synthetic-closed-loop",
        "device_kind": series[0]["device_kind"],
        "elapsed_s": round(time.time() - t_start, 1),
    }
    path = report_path or _next_report_path()
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    say(f"PASS -> {path}")
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--report", default=None,
                    help="report path (default: SERVING_r0N.json, next free N)")
    ap.add_argument("--run_secs", type=float, default=3.0,
                    help="closed-loop load duration per sweep point")
    args = ap.parse_args()
    run_sweep(args.report, run_secs=args.run_secs)


if __name__ == "__main__":
    main()
