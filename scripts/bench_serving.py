#!/usr/bin/env python
"""Serving scale-out sweep: QPS/p50/p99 per replica count and in-flight
depth -> ``SERVING_r0N.json``. With ``--flood``, the overload sweep
instead: open-loop Zipf flood past saturation -> ``FLOOD_r0N.json``.
With ``--fastpath``, the fast-path A/B flood (result cache + in-flight
coalescing off vs on over identical traffic) -> ``SERVING_r0N.json``.

The measurement half of ROADMAP item 1's serving receipt (the correctness
half is ``scripts/serving_drill.py``, re-run here so the committed report
carries BOTH):

  1. **Sweep.** ``bench.serving_series`` over replicas {1, 2, 4} x
     in-flight depth {1, 2} against ONE pre-exported artifact pair, same
     closed-loop synthetic load for every point. ``inflight=1`` on one
     replica is the PR 7-style strict flush-then-refill engine — the
     within-report baseline the pipelined points are read against.
  2. **Drill gates.** The 2-replica pipelined drill re-asserts the PR 12
     serving gates (zero dropped/failed/overloaded across >= 3 staggered
     swaps, blackout <= 100 ms PER replica); its report is embedded.
  3. **Acceptance.** The headline point (1 replica, pipelined depth 2)
     must beat the SERVING_r01 baseline: p99 below 236 ms at >= 185 QPS.
  4. **Scaling honesty.** On a host with fewer cores than replicas, the
     replica axis time-slices the same core(s), so the report REFUSES a
     scaling-efficiency claim (``scaling_efficiency: null`` + reason, the
     SCALING_r01.json rule per BASELINE.md) while still publishing the
     measured per-point QPS/p99 curve.

Run on CPU:  JAX_PLATFORMS=cpu python scripts/bench_serving.py
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import serving_drill

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REPLICA_COUNTS = (1, 2, 4)
INFLIGHT_DEPTHS = (1, 2)
# SERVING_r01.json — the pre-pipelining engine on this host: the sweep's
# headline point must beat its p99 at equal-or-better QPS.
BASELINE_P99_MS = 236.0
BASELINE_QPS = 185.0


def say(msg):
    print(f"[bench_serving] {msg}", flush=True)


def _next_report_path(prefix="SERVING"):
    n = 1
    while os.path.exists(
            os.path.join(_REPO_ROOT, f"{prefix}_r{n:02d}.json")):
        n += 1
    return os.path.join(_REPO_ROOT, f"{prefix}_r{n:02d}.json")


def run_sweep(report_path=None, run_secs=3.0, verbose=True):
    global say
    if not verbose:
        say = lambda msg: None  # noqa: E731
    t_start = time.time()
    workdir = tempfile.mkdtemp(prefix="bench_serving_sweep_")
    try:
        say("exporting artifacts once for the whole sweep")
        bench.export_serving_artifacts(workdir)
        series = []
        for replicas in REPLICA_COUNTS:
            for inflight in INFLIGHT_DEPTHS:
                say(f"point replicas={replicas} inflight={inflight}")
                point = bench.serving_series(
                    replicas=replicas, inflight=inflight,
                    run_secs=run_secs, artifact_dir=workdir)
                say(f"  p50={point['serving_p50_ms']:.2f}ms "
                    f"p99={point['serving_p99_ms']:.2f}ms "
                    f"qps={point['serving_qps']}")
                series.append(point)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    say("re-asserting drill gates (2 replicas, pipelined)")
    drill = serving_drill.run_drill(
        report_path=os.path.join(tempfile.mkdtemp(prefix="bench_drill_"),
                                 "drill.json"),
        verbose=verbose, replicas=2)

    headline = next(p for p in series
                    if p["replicas"] == 1 and p["serve_inflight"] == 2)
    pr7_style = next(p for p in series
                     if p["replicas"] == 1 and p["serve_inflight"] == 1)
    assert headline["serving_p99_ms"] < BASELINE_P99_MS, (
        f"headline p99 {headline['serving_p99_ms']:.1f}ms not below the "
        f"SERVING_r01 baseline {BASELINE_P99_MS}ms")
    assert headline["serving_qps"] >= BASELINE_QPS, (
        f"headline QPS {headline['serving_qps']} below the SERVING_r01 "
        f"baseline {BASELINE_QPS}")
    for point in series:
        assert point["serving_failed"] == 0, point

    host_cpus = os.cpu_count() or 1
    if host_cpus < max(REPLICA_COUNTS):
        scaling_efficiency = None
        scaling_reason = (
            f"refused: {max(REPLICA_COUNTS)} replicas time-slice "
            f"{host_cpus} host core(s), so aggregate QPS measures "
            "scheduler interleaving, not replica scaling; the per-point "
            "curve is published for latency/correctness reading only "
            "(BASELINE.md scaling rules)")
    else:
        base_qps = next(p["serving_qps"] for p in series
                        if p["replicas"] == 1 and p["serve_inflight"] == 2)
        top = max((p for p in series if p["serve_inflight"] == 2),
                  key=lambda p: p["replicas"])
        scaling_efficiency = round(
            top["serving_qps"] / (top["replicas"] * base_qps), 3)
        scaling_reason = "aggregate QPS at max replicas over replicas x " \
                         "single-replica QPS (pipelined points)"

    report = {
        "bench": "serving_scaleout",
        "ok": True,
        "baseline": {"source": "SERVING_r01.json",
                     "serving_p99_ms": BASELINE_P99_MS,
                     "serving_qps": BASELINE_QPS},
        "headline": headline,
        "pr7_style_point": pr7_style,
        "series": series,
        "drill": drill,
        "replica_counts": list(REPLICA_COUNTS),
        "inflight_depths": list(INFLIGHT_DEPTHS),
        "host_cpu_count": host_cpus,
        "scaling_efficiency": scaling_efficiency,
        "scaling_efficiency_reason": scaling_reason,
        "load_kind": "synthetic-closed-loop",
        "device_kind": series[0]["device_kind"],
        "elapsed_s": round(time.time() - t_start, 1),
    }
    path = report_path or _next_report_path()
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    say(f"PASS -> {path}")
    return report


FLOOD_MULTS = (0.5, 1.0, 2.0, 4.0)


def run_flood(report_path=None, run_secs=2.5, users=1_000_000,
              verbose=True):
    """Overload sweep -> ``FLOOD_r0N.json``: the p99-vs-offered-QPS and
    goodput curves from half saturation to 4x past it, over a >= 1M-user
    Zipf population with per-user history continuity, plus the drilled
    degradation-ladder run (``production_drill.run_overload_drill``)
    embedded so the committed report carries BOTH the curve and the
    bit-replayable chaos receipt.

    Gates: every point closes the accounting identity (offered ==
    completed + sheds + overloads + timeouts + failed — zero hangs, zero
    silent drops); at 4x saturation the fleet must SHED (admission
    refusals > 0) while still completing in-SLO work (goodput > 0) —
    degrading, not collapsing; the embedded drill must show the ladder
    engaging under the injected ``executor_slow`` and fully recovering.
    """
    global say
    if not verbose:
        say = lambda msg: None  # noqa: E731
    import production_drill

    t_start = time.time()
    workdir = tempfile.mkdtemp(prefix="bench_flood_")
    try:
        say("exporting artifacts once for the whole flood sweep")
        bench.export_serving_artifacts(workdir)
        say(f"flood sweep at {FLOOD_MULTS} x measured saturation, "
            f"{users} Zipf users")
        flood = bench.overload_series(
            run_secs=run_secs, mults=FLOOD_MULTS, users=users,
            artifact_dir=workdir)
        for p in flood["points"]:
            say(f"  {p['offered_mult']}x offered={p['offered_qps_target']} "
                f"goodput={p['goodput_qps']} p99={p['p99_ms']}ms "
                f"sheds={p['sheds']} overloads={p['overloads']} "
                f"timeouts={p['timeouts']}")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    say("overload drill (degradation ladder under executor_slow chaos)")
    drill_dir = tempfile.mkdtemp(prefix="bench_flood_drill_")
    try:
        drill = production_drill.run_overload_drill(
            drill_dir, verbose=verbose)
    finally:
        shutil.rmtree(drill_dir, ignore_errors=True)

    for p in flood["points"]:
        assert p["accounting_ok"], (
            f"accounting identity broken at {p['offered_mult']}x: {p}")
    top = max(flood["points"], key=lambda p: p["offered_mult"])
    assert top["sheds"] + top["overloads"] > 0, (
        f"no load shedding at {top['offered_mult']}x saturation: {top}")
    assert top["goodput_qps"] > 0 and top["completed"] > 0, (
        f"fleet collapsed at {top['offered_mult']}x saturation: {top}")
    assert drill["ladder_engaged"], drill
    assert drill["recovered"], drill

    report = {
        "bench": "serving_flood",
        "ok": True,
        "flood": flood,
        "overload_drill": drill,
        "offered_mults": list(FLOOD_MULTS),
        "host_cpu_count": os.cpu_count() or 1,
        "load_kind": flood["load_kind"],
        "device_kind": flood["device_kind"],
        "elapsed_s": round(time.time() - t_start, 1),
    }
    path = report_path or _next_report_path("FLOOD")
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    say(f"PASS -> {path}")
    return report


def run_fastpath(report_path=None, run_secs=2.5, users=1_000_000,
                 repeat_p=0.5, cache_rows=4096, verbose=True):
    """Serving fast-path A/B flood -> ``SERVING_r0N.json``: the same
    open-loop Zipf flood (0.5/1/2/4x measured saturation, per-user
    byte-identical repeats at ``repeat_p``) served twice over ONE artifact
    and ONE measured saturation — result cache + coalescing OFF vs ON —
    so the p99/goodput deltas are attributable to the fast path alone.

    Gates: the accounting identity (now offered == completed + coalesced +
    sheds + overloads + timeouts + failed) closes at EVERY point of BOTH
    arms; the ON arm sees real cache traffic (hits > 0 at every point);
    and the headline — p99 at 2x saturation — improves by >= 25% with the
    fast path on.
    """
    global say
    if not verbose:
        say = lambda msg: None  # noqa: E731
    t_start = time.time()
    say(f"fast-path A/B flood at {FLOOD_MULTS} x saturation, "
        f"{users} Zipf users, repeat_p={repeat_p}")
    fast = bench.serving_fastpath_series(
        run_secs=run_secs, mults=FLOOD_MULTS, users=users,
        repeat_p=repeat_p, cache_rows=cache_rows)
    for c in fast["comparison"]:
        say(f"  {c['offered_mult']}x p99 off={c['p99_ms_off']}ms "
            f"on={c['p99_ms_on']}ms ({c['p99_improvement_pct']}%) "
            f"hit_rate={c['cache_hit_rate_on']} "
            f"coalesce_rate={c['coalesce_rate_on']}")

    for arm in ("off", "on"):
        for p in fast[arm]["points"]:
            assert p["accounting_ok"], (
                f"accounting identity broken ({arm} arm, "
                f"{p['offered_mult']}x): {p}")
    for p in fast["on"]["points"]:
        assert p["cache_hits"] > 0, (
            f"no cache hits at {p['offered_mult']}x with the fast path "
            f"on: {p}")
    headline = next(c for c in fast["comparison"]
                    if c["offered_mult"] == 2.0)
    assert headline["p99_improvement_pct"] is not None and \
        headline["p99_improvement_pct"] >= 25.0, (
        f"p99 at 2x saturation improved only "
        f"{headline['p99_improvement_pct']}% with the fast path on "
        f"(need >= 25%): {headline}")

    report = {
        "bench": "serving_fastpath",
        "ok": True,
        "headline": headline,
        "fastpath": fast,
        "offered_mults": list(FLOOD_MULTS),
        "host_cpu_count": os.cpu_count() or 1,
        "load_kind": fast["off"]["load_kind"],
        "device_kind": fast["off"]["device_kind"],
        "elapsed_s": round(time.time() - t_start, 1),
    }
    path = report_path or _next_report_path()
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    say(f"PASS -> {path}")
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--report", default=None,
                    help="report path (default: SERVING_r0N.json or "
                         "FLOOD_r0N.json with --flood, next free N)")
    ap.add_argument("--run_secs", type=float, default=3.0,
                    help="load duration per sweep point")
    ap.add_argument("--flood", action="store_true",
                    help="run the overload flood sweep -> FLOOD_r0N.json "
                         "instead of the scale-out sweep")
    ap.add_argument("--fastpath", action="store_true",
                    help="run the fast-path A/B flood (cache+coalescing "
                         "off vs on) -> SERVING_r0N.json")
    ap.add_argument("--users", type=int, default=1_000_000,
                    help="Zipf user-population size for --flood/--fastpath")
    ap.add_argument("--repeat_p", type=float, default=0.5,
                    help="per-user byte-identical repeat probability for "
                         "--fastpath")
    args = ap.parse_args()
    if args.fastpath:
        run_fastpath(args.report, run_secs=args.run_secs, users=args.users,
                     repeat_p=args.repeat_p)
    elif args.flood:
        run_flood(args.report, run_secs=args.run_secs, users=args.users)
    else:
        run_sweep(args.report, run_secs=args.run_secs)


if __name__ == "__main__":
    main()
