#!/usr/bin/env python
"""Measure multi-process transfer/compute overlap (TUNING.md §4 evidence).

Round 4 and earlier forced ``transfer_ahead=0`` under ``world > 1`` —
host->device staging serialized with step dispatch — because background
staging would have interleaved collectives nondeterministically across
ranks. Round 5 restored the overlap (``Trainer._stage_multiprocess``:
process-local transfers on a staging thread, ALL collectives on the main
thread). This script measures the before/after on the same 2-process
topology the distributed tests use: a real ``jax.distributed`` rendezvous
of 2 OS processes on the CPU backend, training the reference-shaped model.

``--transfer_ahead 0`` reproduces the old serialized behavior;
``--transfer_ahead 2`` (the default) is the overlapped path. Trials are
interleaved (A,B,A,B,...) so host weather hits both variants equally;
best-of-N wins (same methodology as bench.py / BASELINE.md).

Usage: python scripts/bench_multiprocess.py [--trials 3] [--quick]
Prints one JSON line: {"serialized_eps": ..., "overlapped_eps": ...,
"overlap_speedup": ...}.

``--inflate-host-ns N`` adds a synthetic N ns/record stall to the host
emission path of BOTH variants (a GIL-releasing sleep in the pipeline
drain, via the DEEPFM_TPU_SYNTH_HOST_NS_PER_RECORD env var). On a 1-core
host the un-inflated A/B is usually a wash — the CPU backend's "device"
step and the host pipeline time-slice the same core, so there is nothing
to overlap — but a sleep yields the core the way a real TPU dispatch
does, so the overlapped variant hides the synthetic host cost behind the
(time-sliced) step work and the speedup > 1 demonstrates the staging
thread actually overlaps. This is a plumbing demonstration, not a
throughput claim.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUNNER = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
from deepfm_tpu.launch import main
sys.exit(main(sys.argv[1:]))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_once(data_dir: str, model_dir: str, transfer_ahead: int,
             epochs: int, inflate_host_ns: int = 0,
             world: int = 2) -> float:
    """One training run (``world`` processes); returns rank-0
    examples_per_sec. ``world=1`` skips the jax.distributed rendezvous
    entirely — the only topology that runs on jaxlib builds whose CPU
    backend lacks cross-process collectives."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=_REPO,
    )
    args = []
    if inflate_host_ns:
        env["DEEPFM_TPU_SYNTH_HOST_NS_PER_RECORD"] = str(inflate_host_ns)
        # The pipeline's own decode-ahead thread (prefetch_batches) would
        # hide the synthetic stall in BOTH variants, washing out the A/B.
        # Pin it off so the Trainer staging thread is the only overlap
        # mechanism under test.
        args += ["--prefetch_batches", "0"]
    args += [
        "--task_type", "train",
        "--data_dir", data_dir,
        "--val_data_dir", "",
        "--model_dir", model_dir,
        "--clear_existing_model", "true",
        "--feature_size", "117581", "--field_size", "39",
        "--embedding_size", "32", "--deep_layers", "128,64,32",
        "--dropout", "0.5,0.5,0.5", "--batch_size", "1024",
        "--num_epochs", str(epochs), "--learning_rate", "5e-4",
        "--compute_dtype", "bfloat16",
        "--mesh_data", str(world), "--mesh_model", "1",
        "--log_steps", "0", "--save_checkpoints_steps", "0",
        "--transfer_ahead", str(transfer_ahead),
        "--seed", "0",
    ]
    if world > 1:
        args += [
            "--dist_mode", "1",
            "--num_processes", str(world),
            "--coordinator_address", f"localhost:{_free_port()}",
        ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RUNNER] + args + ["--process_id", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        for r in range(world)
    ]
    outs = []
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=900)
        if p.returncode != 0:
            raise RuntimeError(f"rank {r} failed:\n{err[-3000:]}")
        outs.append(out)
    line = [ln for ln in outs[0].splitlines() if ln.startswith("{")][-1]
    return float(json.loads(line)["examples_per_sec"])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--inflate-host-ns", type=int, default=0,
                    help="synthetic host-path stall, ns/record, applied to "
                         "BOTH variants (overlap demonstration on 1 core)")
    ap.add_argument("--single", action="store_true",
                    help="1 process, no jax.distributed: same A/B through "
                         "Trainer._stage's prefetch thread; the only mode "
                         "that runs when the CPU backend lacks cross-"
                         "process collectives")
    args = ap.parse_args()

    from deepfm_tpu.data import libsvm

    # File-mode fits once per epoch with a fresh ThroughputMeter, so each
    # epoch needs >2 dispatch groups (meter warmup) to measure anything:
    # 4 files x 8192 records / 1024 world batch = 32 steps = 4 groups.
    n_files, per_file = 4, 8192
    epochs = 1 if args.quick else 2
    with tempfile.TemporaryDirectory() as root:
        data = os.path.join(root, "data")
        libsvm.generate_synthetic_ctr(
            data, num_files=n_files, examples_per_file=per_file,
            feature_size=117581, field_size=39, prefix="tr", seed=1)

        world = 1 if args.single else 2
        best = {0: 0.0, 2: 0.0}
        for t in range(args.trials):
            for ahead in (0, 2):  # interleaved: weather hits both equally
                eps = run_once(data, os.path.join(root, f"m{t}_{ahead}"),
                               ahead, epochs,
                               inflate_host_ns=args.inflate_host_ns,
                               world=world)
                best[ahead] = max(best[ahead], eps)
                print(f"trial {t} transfer_ahead={ahead}: {eps:,.0f} ex/s",
                      file=sys.stderr)

        out = {
            "topology": f"{world}-process"
                        + ("" if args.single else " jax.distributed")
                        + ", CPU backend, 1 host core",
            "serialized_eps": round(best[0], 1),
            "overlapped_eps": round(best[2], 1),
            "overlap_speedup": round(best[2] / max(best[0], 1e-9), 3),
        }
        if args.inflate_host_ns:
            out["inflate_host_ns_per_record"] = args.inflate_host_ns
        print(json.dumps(out))


if __name__ == "__main__":
    main()
