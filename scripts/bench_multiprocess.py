#!/usr/bin/env python
"""Per-device-count scaling + overlap curve (SCALING_r01.json evidence).

For each device count N this script runs the reference-shaped trainer
twice per trial — ``--staging_buffers 1`` (each dispatch waits for its
own host->device transfer) vs ``--staging_buffers 2`` (dispatch k+1's
transfer overlaps dispatch k's compute) — interleaved A/B so host
weather hits both variants equally, best-of-N wins (same methodology as
bench.py / BASELINE.md). Each row of the emitted curve carries:

- ``examples_per_sec`` (double-buffered) and ``serialized_eps``
  (single-buffered), plus their ratio ``overlap_speedup`` and the
  trainer's measured ``overlap_fraction`` (transfer time hidden behind
  device compute / total transfer time);
- ``mfu_pct`` with an in-band ``mfu_basis`` label
  (measured-device-peak | nominal-estimate | unavailable — see
  deepfm_tpu/utils/mfu.py and BASELINE.md);
- ``topology_kind``: ``real-devices`` when N real accelerator chips ran
  the mesh, ``virtual-mesh-timeslice`` when N virtual XLA CPU devices
  time-sliced this host's core(s);
- ``scaling_efficiency`` = eps(N) / (N * eps(1)) — REFUSED (null, with
  the reason in-band) for time-sliced topologies, where the ratio would
  measure time-slicing overhead and not hardware scaling.

Device counts > 1 run as ONE process over a virtual (or real) mesh; the
legacy 2-process ``jax.distributed`` rendezvous is still available via
``--multiprocess`` for jaxlib builds with CPU cross-process collectives.

``--inflate-host-ns N`` adds a synthetic N ns/record stall to the
host->device TRANSFER leg of BOTH variants (a GIL-releasing sleep inside
the staging ring's timed transfer section, via the
DEEPFM_TPU_SYNTH_TRANSFER_NS_PER_RECORD env var) and pins
``--prefetch_batches 0`` so the staging ring is the only overlap
mechanism under test. On the CPU backend the real transfer is a
core-local copy too cheap to measure, so the un-inflated A/B is a wash;
the stall stands in for a real PCIe/DMA leg. The double-buffered
variant hides it behind the previous dispatch's compute (its fence is
one slot older) while the single-buffered variant serializes it, so
speedup > 1 demonstrates the ring overlaps. That is a plumbing
demonstration, not a throughput claim (and exactly why
scaling_efficiency stays null here).

Usage:
  python scripts/bench_multiprocess.py [--device-counts 1,2] [--trials 2]
      [--quick] [--inflate-host-ns 3000] [--out SCALING_r01.json]
Prints the result JSON and writes it to --out.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUNNER = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
from deepfm_tpu.launch import main
sys.exit(main(sys.argv[1:]))
"""

TIMESLICE = "virtual-mesh-timeslice"
REAL = "real-devices"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _topology() -> tuple:
    """(topology_kind, device_kind) for the devices the children will use.

    The child runs force JAX_PLATFORMS=cpu and split the host into N
    virtual XLA devices whenever the parent itself has no accelerator —
    that is a time-sliced topology, never a scaling claim.
    """
    import jax
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return TIMESLICE, dev.device_kind
    return REAL, dev.device_kind


def _flops_per_example() -> float:
    """Analytic FLOPs/example at the bench shape (bench.py's inventory)."""
    from bench import _model_flops_per_example
    return _model_flops_per_example(types.SimpleNamespace(
        deep_layers="128,64,32", field_size=39, embedding_size=32))


def run_once(data_dir: str, model_dir: str, staging_buffers: int,
             epochs: int, n_devices: int, inflate_host_ns: int = 0,
             multiprocess: bool = False) -> dict:
    """One training run; returns rank-0's result JSON (examples_per_sec,
    staging_overlap_fraction, ...). Single-process mode meshes
    ``n_devices`` virtual (or real) devices; ``multiprocess`` spawns a
    real 2-process jax.distributed rendezvous instead — the only mode
    that exercises cross-process collectives, and unavailable on jaxlib
    builds whose CPU backend lacks them."""
    world = 2 if multiprocess else 1
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count="
                  + str(1 if multiprocess else n_devices),
        PYTHONPATH=_REPO,
    )
    args = []
    if inflate_host_ns:
        env["DEEPFM_TPU_SYNTH_TRANSFER_NS_PER_RECORD"] = str(inflate_host_ns)
        # The pipeline's own decode-ahead thread (prefetch_batches) could
        # reorder host work around the inflated transfers; pin it off so
        # the staging ring is the only overlap mechanism under test.
        args += ["--prefetch_batches", "0"]
    mesh_data = world if multiprocess else n_devices
    args += [
        "--task_type", "train",
        "--data_dir", data_dir,
        "--val_data_dir", "",
        "--model_dir", model_dir,
        "--clear_existing_model", "true",
        "--feature_size", "117581", "--field_size", "39",
        "--embedding_size", "32", "--deep_layers", "128,64,32",
        "--dropout", "0.5,0.5,0.5", "--batch_size", "1024",
        "--num_epochs", str(epochs), "--learning_rate", "5e-4",
        "--compute_dtype", "bfloat16",
        "--mesh_data", str(mesh_data), "--mesh_model", "1",
        "--log_steps", "0", "--save_checkpoints_steps", "0",
        "--staging_buffers", str(staging_buffers),
        "--seed", "0",
    ]
    if multiprocess:
        args += [
            "--dist_mode", "1",
            "--num_processes", str(world),
            "--coordinator_address", f"localhost:{_free_port()}",
        ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RUNNER] + args + ["--process_id", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        for r in range(world)
    ]
    outs = []
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=900)
        if p.returncode != 0:
            raise RuntimeError(f"rank {r} failed:\n{err[-3000:]}")
        outs.append(out)
    line = [ln for ln in outs[0].splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def scaling_efficiency_row(topology_kind: str, n_devices: int,
                           eps_n: float, eps_1: float) -> dict:
    """scaling_efficiency for one curve row — refused off real devices."""
    if topology_kind != REAL:
        return {
            "scaling_efficiency": None,
            "scaling_efficiency_reason": (
                "refused: virtual XLA devices time-slice the host core(s); "
                "the aggregate ratio measures time-slicing overhead, not "
                "hardware scaling (needs topology_kind=real-devices)"),
        }
    if n_devices <= 1 or eps_1 <= 0:
        return {"scaling_efficiency": 1.0 if n_devices == 1 else None}
    return {"scaling_efficiency": round(eps_n / (n_devices * eps_1), 4)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--device-counts", default="1,2",
                    help="comma-separated device counts for the curve")
    ap.add_argument("--trials", type=int, default=2)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--inflate-host-ns", type=int, default=0,
                    help="synthetic host->device transfer stall, ns/record, "
                         "applied to BOTH variants (overlap demonstration "
                         "on hosts whose real transfer is unmeasurable)")
    ap.add_argument("--multiprocess", action="store_true",
                    help="also run the real 2-process jax.distributed A/B "
                         "(requires CPU cross-process collectives)")
    ap.add_argument("--out", default=os.path.join(_REPO, "SCALING_r01.json"))
    args = ap.parse_args()

    from deepfm_tpu.data import libsvm
    from deepfm_tpu.utils import mfu as mfu_lib

    topology_kind, device_kind = _topology()
    flops = _flops_per_example()
    counts = sorted({int(x) for x in args.device_counts.split(",") if x})

    # File-mode fits once per epoch with a fresh ThroughputMeter, so each
    # epoch needs >2 dispatch groups (meter warmup) to measure anything:
    # 4 files x 8192 records / 1024 global batch = 32 steps = 4 groups.
    n_files, per_file = 4, 8192
    epochs = 1 if args.quick else 2
    curve = []
    with tempfile.TemporaryDirectory() as root:
        data = os.path.join(root, "data")
        libsvm.generate_synthetic_ctr(
            data, num_files=n_files, examples_per_file=per_file,
            feature_size=117581, field_size=39, prefix="tr", seed=1)

        eps1 = None
        for n in counts:
            best = {1: (0.0, 0.0), 2: (0.0, 0.0)}  # buffers -> (eps, ovl)
            for t in range(args.trials):
                for buffers in (1, 2):  # interleaved A/B
                    r = run_once(
                        data, os.path.join(root, f"m{n}_{t}_{buffers}"),
                        buffers, epochs, n,
                        inflate_host_ns=args.inflate_host_ns)
                    eps = float(r["examples_per_sec"])
                    ovl = float(r.get("staging_overlap_fraction", 0.0))
                    if eps > best[buffers][0]:
                        best[buffers] = (eps, ovl)
                    print(f"devices={n} trial={t} staging_buffers="
                          f"{buffers}: {eps:,.0f} ex/s overlap={ovl:.3f}",
                          file=sys.stderr)
            eps_n = best[2][0]
            if n == 1 or eps1 is None:
                eps1 = eps_n if n == 1 else eps1
            mfu, basis, _ = mfu_lib.mfu_pct(flops, eps_n / max(n, 1))
            row = {
                "n_devices": n,
                "topology_kind": topology_kind,
                "examples_per_sec": round(eps_n, 1),
                "serialized_eps": round(best[1][0], 1),
                "overlap_speedup": round(eps_n / max(best[1][0], 1e-9), 3),
                "overlap_fraction": round(best[2][1], 4),
                "mfu_pct": mfu,
                "mfu_basis": basis,
            }
            row.update(scaling_efficiency_row(
                topology_kind, n, eps_n, eps1 or 0.0))
            curve.append(row)

        mp = None
        if args.multiprocess:
            mp_best = {1: 0.0, 2: 0.0}
            for t in range(args.trials):
                for buffers in (1, 2):
                    r = run_once(
                        data, os.path.join(root, f"mp_{t}_{buffers}"),
                        buffers, epochs, 1,
                        inflate_host_ns=args.inflate_host_ns,
                        multiprocess=True)
                    mp_best[buffers] = max(mp_best[buffers],
                                           float(r["examples_per_sec"]))
            mp = {
                "topology": "2-process jax.distributed, CPU backend",
                "topology_kind": topology_kind,
                "serialized_eps": round(mp_best[1], 1),
                "overlapped_eps": round(mp_best[2], 1),
                "overlap_speedup": round(
                    mp_best[2] / max(mp_best[1], 1e-9), 3),
            }

    out = {
        "bench": "scaling_overlap",
        "device_kind": device_kind,
        "topology_kind": topology_kind,
        "model_flops_per_example": flops,
        "staging_ab": "staging_buffers 1 (serialized) vs 2 (double-buffered)"
                      ", interleaved trials, best-of-N",
        "curve": curve,
    }
    if args.inflate_host_ns:
        out["inflate_host_ns_per_record"] = args.inflate_host_ns
    if mp is not None:
        out["multiprocess_ab"] = mp
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
