#!/usr/bin/env python
"""Real-TPU smoke test for the compiled (non-interpret) Pallas kernels.

The pytest suite runs on a virtual CPU mesh and exercises the kernels in
interpreter mode only (tests/test_pallas_fm.py); this script is the
compiled-path check to run on actual TPU hardware (ADVICE r1): forward and
backward of the fused FM kernel vs the jnp oracle, with bf16 inputs so the
bf16-residual path is what's exercised, then one full jitted train step.

Usage: python scripts/tpu_smoke.py   (exit 0 = pass)

The LAST stdout line is a machine-readable token for harnesses
(``TPU_SMOKE_JSON {"status": ...}`` with status pass / skip_not_tpu /
skip_unsupported_shape); everything above it is human-oriented narration.
A failure raises (non-zero exit, no token).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _token(status: str) -> None:
    print("TPU_SMOKE_JSON " + json.dumps({"status": status}))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from deepfm_tpu.ops import pallas_fm

    if jax.default_backend() != "tpu":
        print(f"SKIP: backend is {jax.default_backend()!r}, not tpu")
        _token("skip_not_tpu")
        return 0
    if not pallas_fm.supported(39, 32):
        print("SKIP: compiled kernel unsupported at (39, 32)")
        _token("skip_unsupported_shape")
        return 0

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(1024, 39)), jnp.bfloat16)
    vals = jnp.asarray(rng.normal(size=(1024, 39)), jnp.bfloat16)
    xv = jnp.asarray(rng.normal(size=(1024, 39, 32)), jnp.bfloat16)

    out = jax.jit(lambda *a: pallas_fm.fused_fm(*a, False))(w, vals, xv)
    ref = pallas_fm.reference_fm(w, vals, xv)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=0.05, atol=0.2)

    grads = jax.jit(jax.grad(
        lambda *a: jnp.sum(pallas_fm.fused_fm(*a, False)),
        argnums=(0, 1, 2)))(w, vals, xv)
    ref_grads = jax.grad(
        lambda *a: jnp.sum(pallas_fm.reference_fm(*a)),
        argnums=(0, 1, 2))(w, vals, xv)
    for got, want, name in zip(grads, ref_grads, ("w", "vals", "xv")):
        assert got.dtype == jnp.bfloat16, (name, got.dtype)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=0.06, atol=0.25)
    print("pallas compiled kernels: fwd+bwd match oracle (bf16 residuals)")

    # Full train step through the model (kernel embedded in the real graph).
    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    cfg = Config(
        feature_size=117581, field_size=39, embedding_size=32,
        deep_layers="128,64,32", dropout="0.5,0.5,0.5", batch_size=1024,
        compute_dtype="bfloat16", log_steps=0, use_pallas=True)
    tr = Trainer(cfg)
    state = tr.init_state()
    batch = {
        "feat_ids": rng.integers(0, cfg.feature_size, (1024, 39)).astype(np.int32),
        "feat_vals": rng.normal(size=(1024, 39)).astype(np.float32),
        "label": (rng.random((1024, 1)) < 0.25).astype(np.float32),
    }
    state, m = tr.train_step(state, tr.put_batch(batch))
    loss = float(jax.device_get(m["loss"]))
    assert np.isfinite(loss), loss
    print(f"full train step with pallas kernel: loss={loss:.4f}")
    print("TPU smoke: PASS")
    _token("pass")
    return 0


if __name__ == "__main__":
    sys.exit(main())
