#!/usr/bin/env python
"""Prove row-sharding rescues the vocab cliff (BASELINE.md sweep row).

The single-chip vocab sweep (BASELINE.md, measured r3) shows embedding
tables are free to ~10M rows x K=32 and then fall off a cliff: V=25M costs
~9.6 GB of params+Adam moments — HBM pressure pushes the step to 56 ms —
and V=50M fails to compile at all. The claimed rescue is the X1 capability
(the gRPC parameter server's replacement): ``--mesh_model=m`` row-shards
the table and both optimizer moments over the 'model' mesh axis, putting
~1/m of the bytes on each chip.

This script is the rescue's executable proof on the virtual 8-device mesh
(real multi-chip hardware is not available in this environment; the mesh,
shardings, and collectives are identical to real chips — only the physical
placement differs): it builds V=25M with ``mesh_model=8``, compiles and
executes one full training step, and measures per-device bytes of
params+optimizer state, asserting every device holds ~total/8.

Usage: python scripts/vocab_shard_proof.py [--vocab 25000000] [--shards 8]
Prints one JSON line with the measured layout.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=25_000_000)
    ap.add_argument("--shards", type=int, default=8)
    args = ap.parse_args()

    from __graft_entry__ import _provision_virtual_devices
    _provision_virtual_devices(args.shards)

    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    cfg = Config(
        feature_size=args.vocab, field_size=39, embedding_size=32,
        deep_layers="128,64,32", dropout="0.5,0.5,0.5", batch_size=1024,
        learning_rate=5e-4, optimizer="Adam", l2_reg=1e-4,
        compute_dtype="bfloat16", mesh_data=1, mesh_model=args.shards,
        log_steps=0, seed=0)

    t0 = time.perf_counter()
    trainer = Trainer(cfg)
    state = trainer.init_state()
    t_init = time.perf_counter() - t0

    # Per-device resident bytes of params + optimizer state. The embedding
    # table and BOTH Adam moments must be row-sharded (ops/embedding.py +
    # parallel/mesh.py opt_state_pspecs); the dense tower is replicated but
    # is negligible at this scale.
    per_dev = {}
    total = 0
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        seen_dev = set()
        for s in leaf.addressable_shards:
            if s.device.id in seen_dev:
                continue
            seen_dev.add(s.device.id)
            per_dev[s.device.id] = per_dev.get(s.device.id, 0) + s.data.nbytes
            total += s.data.nbytes

    rng = np.random.default_rng(0)
    batch = {
        "feat_ids": rng.integers(
            0, cfg.feature_size, (cfg.batch_size, cfg.field_size)
        ).astype(np.int32),
        "feat_vals": rng.normal(
            size=(cfg.batch_size, cfg.field_size)).astype(np.float32),
        "label": (rng.random((cfg.batch_size, 1)) < 0.25).astype(np.float32),
    }
    t0 = time.perf_counter()
    state, m = trainer.train_step(state, trainer.put_batch(batch))
    jax.block_until_ready(m["loss"])
    t_compile_step = time.perf_counter() - t0
    loss = float(m["loss"])
    assert np.isfinite(loss), loss

    t0 = time.perf_counter()
    state, m = trainer.train_step(state, trainer.put_batch(batch))
    jax.block_until_ready(m["loss"])
    t_step = time.perf_counter() - t0

    shard_bytes = sorted(per_dev.values())
    biggest = shard_bytes[-1]
    # Every device must hold ~total/m: allow 5% slack for the replicated
    # dense tower + scalar opt state.
    assert biggest <= (total / args.shards) * 1.05, (
        f"unbalanced: biggest shard {biggest / 1e9:.2f} GB vs "
        f"total/m {total / args.shards / 1e9:.2f} GB")

    print(json.dumps({
        "vocab": args.vocab,
        "mesh_model": args.shards,
        "total_params_opt_gb": round(total / 1e9, 3),
        "per_shard_gb_min": round(shard_bytes[0] / 1e9, 3),
        "per_shard_gb_max": round(biggest / 1e9, 3),
        "per_shard_over_total_ratio": round(biggest / total, 4),
        "init_s": round(t_init, 1),
        "first_step_incl_compile_s": round(t_compile_step, 1),
        "steady_step_s": round(t_step, 2),
        "loss": round(loss, 4),
        "ok": True,
    }))


if __name__ == "__main__":
    main()
