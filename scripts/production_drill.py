#!/usr/bin/env python
"""Production-day drill: chaos-scheduled serve->log->train->publish loop.

The whole online system exercised as ONE closed loop, the way a production
day actually runs it — and failed the way a production day actually fails:

  1. **Serve.** A seeded diurnal traffic plan (``loop/traffic.py``) drives
     the real ``ServingEngine.serve_latest`` over the publish dir, starting
     from a bootstrap version-0 artifact (which doubles as the frozen
     baseline model for the windowed-AUC comparison).
  2. **Log.** Every served request is written back as impression shards
     (``loop/impressions.py``), bit-identical to what the engine scored.
  3. **Join.** Ground-truth labels arrive on a seeded delay distribution;
     the delayed-label joiner (``loop/join.py``) emits training shards into
     the live stream directory — duplicates, late labels, and past-window
     labels counted, emission exactly-once and in admission order.
  4. **Train + publish.** The real online trainer (``deepfm_tpu.launch``
     with ``--online_mode`` under ``scripts/supervise.py``) tails those
     shards and hot-publishes through the production ``Publisher``; the
     serving engine hot-swaps every version with zero dropped requests.
  5. **Chaos.** One seeded :class:`~deepfm_tpu.utils.faults.ChaosSchedule`
     arms everything: transient read faults inside the trainer's stream,
     one publish crash mid-``os.replace`` sequence (previous artifact stays
     live), and one driver-side SIGTERM preemption with supervised resume.
     Same seed + schedule => byte-identical chaos, traffic, and labels.

Gates (the PRODUCTION_r0N.json contract):
  * zero dropped/failed/overloaded requests across >= 3 hot swaps;
  * training/serving skew: every audited record bit-identical between the
    serving feature path and the training decoder;
  * end-to-end staleness (impression -> first servable model trained on
    it) p95 reported and bounded by join window + label delay + observed
    publish cadence;
  * final online params finite, publish versions monotonic, LATEST = max;
  * the joiner's audit (counters + joined labels) matches a pure logical
    simulation computed from the seeds alone — the executable form of
    "same seed + schedule reproduces identical drill audit results".

Run on CPU:  JAX_PLATFORMS=cpu python scripts/production_drill.py
Fast in-process smoke (no subprocess): ``run_smoke()`` (tier-1 tested).
"""

import argparse
import collections
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import orbax.checkpoint as ocp

from deepfm_tpu.config import Config
from deepfm_tpu.loop import (DelayedLabelJoiner, DiurnalTrafficPlan,
                             ImpressionLogger, LoopHealth, SeededLabelFeed,
                             SkewChecker, staleness_summary, windowed_auc)
from deepfm_tpu.obs import trace as trace_lib
from deepfm_tpu.serve import ServingEngine
from deepfm_tpu.train import Trainer
from deepfm_tpu.train.publish import Publisher
from deepfm_tpu.utils import export as export_lib
from deepfm_tpu.utils import faults as faults_lib

from supervise import run_supervised

FEATURE_SIZE = 64
FIELD_SIZE = 5
BATCH_SIZE = 16
SHARD_RECORDS = 32       # impression rows per logged shard

# Full drill (subprocess trainer, the committed PRODUCTION report).
FULL = dict(duration_s=24.0, base_qps=6.0, peak_qps=22.0, max_rows=6,
            publish_every=6, join_window_s=4.0, delay_s=(0.5, 6.0),
            read_fault_every=11, idle_timeout_s=10.0, auc_windows=4)
# In-process smoke (tier-1): same loop, mini-trainer thread, pace-compressed.
SMOKE = dict(duration_s=8.0, base_qps=8.0, peak_qps=30.0, max_rows=4,
             publish_every=4, join_window_s=3.0, delay_s=(0.3, 4.5),
             read_fault_every=0, idle_timeout_s=0.0, auc_windows=3)

MIN_HOT_SWAPS = 3
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Overload drill (degradation ladder under executor_slow chaos): the flood
# plan's offered rate is sized so the ranking engine saturates only while
# the injected slow window is live — engagement AND recovery both happen
# inside the horizon. Count-based slow window (calls, not wall-clock), so
# the recovery half cannot be starved by a slow host.
OVERLOAD = dict(duration_s=3.0, offered_qps=110.0, users=1_000_000,
                hist_len=6, retrieve_k=12, degrade_retrieve_k=4,
                max_batch=16, queue_rows=96, shed_watermark=32,
                slo_ms=250.0, workers=12, slow_ms=45.0, slow_calls=30,
                timeout_s=12.0)

# Experimentation drill (gated deployment): window sizes are tuned so every
# health window clears min_samples deterministically at the default seed.
# The latency guardrail is the ABSOLUTE p99 ceiling (max_p99_ms): on a
# 1-core drill host the control's own tail is timing noise, so the ratio
# gate is parked out of the way (1e6) and detection rests on the ceiling —
# the degraded challenger's injected sleep exceeds it BY CONSTRUCTION,
# while a healthy warm challenger sits ~20x under it. AUC/calibration
# tolerances are lenient because both arms see a few dozen synthetic rows
# per window; the unit tests pin the tight-threshold behaviour.
EXPERIMENT = dict(duration_s=12.0, base_qps=20.0, peak_qps=20.0,
                  max_rows=4, window_requests=18, permille=600,
                  min_samples=8, min_auc_delta=-0.35,
                  max_p99_ratio=1e6, max_p99_ms=150.0,
                  max_calibration_err=0.75, max_candidate_age_s=120.0,
                  windows_required=2, shadow_slo_ms=60.0,
                  slow_ms=250.0, stale_age_s=600.0,
                  train_steps=4, nan_train_batches=20,
                  serve_max_batch=16, serve_max_delay_ms=1.0)
# Tier-1 smoke overrides: fewer requests per window, shorter injected sleep.
EXPERIMENT_SMOKE = dict(duration_s=8.0, window_requests=8, min_samples=4,
                        train_steps=3, slow_ms=200.0)

#: Shadow-lane impressions log the SAME served row under the challenger
#: arm; offsetting the impression id keeps the log's ids unique while the
#: original id (and its label) stays recoverable by modulus.
SHADOW_IID_OFFSET = 1 << 20


def _say_factory(verbose):
    return (lambda msg: print(f"[production_drill] {msg}", flush=True)) \
        if verbose else (lambda msg: None)


def _flags(data_dir, model_dir, publish_every, idle_timeout_s,
           trace="off", trace_dir=""):
    flags = dict(
        task_type="train", data_dir=data_dir, model_dir=model_dir,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=BATCH_SIZE, num_epochs=1,
        compute_dtype="float32", mesh_data=1, log_steps=0,
        scale_lr_by_world=False, seed=17, verify_crc=True,
        save_checkpoints_steps=0, io_retry_backoff_secs=0.0,
        pipe_mode=1, online_mode=1, steps_per_loop=1,
        publish_every_steps=publish_every,
        stream_poll_secs=0.1, stream_idle_timeout_secs=idle_timeout_s,
        serve_max_batch=64, serve_max_delay_ms=3.0)
    if trace != "off":
        # The trainer (subprocess in the full drill) writes its own
        # trace-<pid>.json next to the drill's; merge() stitches them.
        flags.update(trace=trace, trace_dir=trace_dir)
    return flags


def _cmd(flags):
    argv = [sys.executable, "-m", "deepfm_tpu.launch"]
    for name, value in flags.items():
        argv += [f"--{name}", str(int(value) if isinstance(value, bool)
                                  else value)]
    return argv


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    for k in ("DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS",
              "DEEPFM_TPU_PREEMPT_AFTER_STEPS",
              "DEEPFM_TPU_FAULT_AFTER_STEPS",
              faults_lib.READ_FAULT_ENV, faults_lib.CHAOS_ENV,
              faults_lib.CHAOS_STATE_ENV):
        env.pop(k, None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


class _LogicalClock:
    """Wall time -> drill-logical time: ``pace`` wall seconds per logical
    second. Every join/label/chaos decision is made in logical time, so
    the smoke (pace << 1) and the full drill replay the SAME decisions."""

    def __init__(self, pace):
        self.pace = float(pace)
        self._t0 = time.monotonic()

    def now(self):
        return (time.monotonic() - self._t0) / self.pace


def _bootstrap_v0(cfg, publish_dir, say):
    """Publish the version-0 artifact the engine serves before the trainer
    has produced anything; its params are the frozen AUC baseline."""
    trainer = Trainer(cfg)
    state = trainer.init_state()
    pub = Publisher(trainer.model, cfg, publish_dir)
    pub.publish_now(state, 0)
    pub.close()
    path = os.path.join(publish_dir, "0")
    assert os.path.exists(os.path.join(path, export_lib.COMPLETE_MARKER)), \
        "bootstrap publish did not complete"
    say(f"bootstrap artifact v0 live at {path}")
    return export_lib.load_serving(path)


def _expected_join(plan, feed, join_window_s):
    """Pure logical simulation of every join decision from the seeds alone
    — what the live joiner MUST reproduce bit-exactly."""
    counters = {"labels_joined": 0, "impressions_expired": 0,
                "labels_past_window": 0}
    labels = {}
    for req in plan.requests:
        for k in range(int(req.ids.shape[0])):
            iid = req.first_id + k
            if feed.delay_for(iid) <= join_window_s:
                counters["labels_joined"] += 1
                labels[iid] = float(req.labels[k])
            else:
                counters["impressions_expired"] += 1
                counters["labels_past_window"] += 1
                labels[iid] = DelayedLabelJoiner.DEFAULT_LABEL
    return counters, labels


def _emitted_labels(out_dir):
    """iid -> label actually emitted, read back from the manifest sidecars."""
    out = {}
    for name in sorted(os.listdir(out_dir)):
        if not (name.startswith(".") and name.endswith(".manifest.json")):
            continue
        with open(os.path.join(out_dir, name), encoding="utf-8") as f:
            m = json.load(f)
        out.update({int(i): float(y)
                    for i, y in zip(m["impressions"], m["labels"])})
    return out


def _audit_artifacts(publish_dir, say):
    """Every artifact loads and serves finite probs, marker step == dir
    version, publish order monotonic, LATEST == max. Dot-prefixed staging
    leftovers are counted, not fatal: a leaked ``.staging-*`` dir is the
    EXPECTED evidence of the scheduled publish crash (the crash fires after
    the staging dir is complete, before the rename)."""
    versions, staging = {}, []
    for name in os.listdir(publish_dir):
        path = os.path.join(publish_dir, name)
        if not os.path.isdir(path):
            continue
        if name.startswith("."):
            staging.append(name)
            continue
        versions[int(name)] = path
    assert versions, f"no artifacts under {publish_dir}"
    for step, path in sorted(versions.items()):
        serve = export_lib.load_serving(path)
        probs = serve(np.zeros((2, FIELD_SIZE), np.int64),
                      np.ones((2, FIELD_SIZE), np.float32))
        assert probs.shape[0] == 2 and np.all(np.isfinite(probs)), (
            f"artifact {path} served non-finite output")
        with open(os.path.join(path, export_lib.COMPLETE_MARKER)) as f:
            assert json.load(f)["step"] == step, (
                f"artifact {path} marker step != dir version")
    order = [s for s, _ in sorted(versions.items(),
                                  key=lambda kv: os.path.getmtime(kv[1]))]
    assert order == sorted(order), (
        f"versions not monotonic in publish order: {order}")
    latest = export_lib.read_latest(publish_dir)
    assert latest is not None and int(os.path.basename(latest)) == max(
        versions), f"LATEST resolves to {latest}, newest is {max(versions)}"
    say(f"artifact audit: {len(versions)} version(s) "
        f"{sorted(versions)}, {len(staging)} staging leak(s), "
        f"LATEST={max(versions)}")
    return versions, staging


def _final_params_finite(publish_dir):
    latest = export_lib.read_latest(publish_dir)
    restored = ocp.StandardCheckpointer().restore(
        os.path.join(os.path.abspath(latest), "params.ckpt"))
    import jax
    for leaf in jax.tree_util.tree_leaves(restored):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) \
                and not np.all(np.isfinite(arr)):
            return False
    return True


def _staleness_samples(joiner, served_wall, swap_log):
    """Per-impression end-to-end staleness: serve completion -> the wall
    moment the first model version whose training covered that impression
    became servable (watcher swap observed). Rows past the last published
    training step are 'uncovered' (awaiting the next cadence) — counted,
    excluded from the percentile, reported."""
    observed = sorted((v, w) for v, w, _ in swap_log if v > 0)
    samples, uncovered = [], 0
    cum = 0
    for path, iids in sorted(joiner.manifests.items()):
        cum += len(iids)
        version = next((v for v, _ in observed
                        if v * BATCH_SIZE >= cum), None)
        if version is None:
            uncovered += len(iids)
            continue
        wall = dict(observed)[version]
        samples.extend(wall - served_wall[i] for i in iids)
    return samples, uncovered


def _audit_fingerprint(schedule, plan, counters, labels):
    h = hashlib.sha256()
    h.update(schedule.to_json().encode())
    for r in plan.requests:
        h.update(np.float64(r.t_s).tobytes())
        h.update(np.int64(r.first_id).tobytes())
        h.update(r.labels.tobytes())
    h.update(json.dumps(sorted(counters.items())).encode())
    h.update(json.dumps(sorted(labels.items())).encode())
    return h.hexdigest()[:16]


def _mini_trainer(cfg, data_dir, publish_dir, stop_evt, publish_every, out):
    """In-process stand-in for the ``deepfm_tpu.launch`` subprocess (the
    smoke variant): tail emitted tr-shards in sorted order, train real
    steps, publish through the real ``Publisher`` on the step cadence —
    synchronously, so the publish set is exactly {N, 2N, ...} and the
    armed publish crash deterministically eats the first attempt."""
    from deepfm_tpu.data import example_codec, tfrecord
    try:
        trainer = Trainer(cfg)
        state = trainer.init_state()
        step_fn = trainer._make_train_step()
        pub = Publisher(trainer.model, cfg, publish_dir,
                        every_steps=publish_every)
        consumed = set()
        rows_ids, rows_vals, rows_y = [], [], []
        step = 0
        while True:
            names = [n for n in sorted(os.listdir(data_dir))
                     if n.startswith("tr") and n.endswith(".tfrecords")
                     and n not in consumed]
            if not names:
                if stop_evt.is_set():
                    break
                time.sleep(0.02)
                continue
            for name in names:
                consumed.add(name)
                for rec in tfrecord.iter_records(
                        os.path.join(data_dir, name)):
                    y, ids, vals = example_codec.decode_ctr_example(
                        rec, FIELD_SIZE)
                    rows_ids.append(ids.astype(np.int32))
                    rows_vals.append(vals)
                    rows_y.append(y)
                while len(rows_y) >= cfg.batch_size:
                    b = cfg.batch_size
                    batch = {
                        "label": np.asarray(
                            rows_y[:b], np.float32).reshape(b, 1),
                        "feat_ids": np.stack(rows_ids[:b]),
                        "feat_vals": np.stack(rows_vals[:b]),
                    }
                    del rows_ids[:b], rows_vals[:b], rows_y[:b]
                    state, _ = step_fn(state, trainer.put_batch(batch))
                    step += 1
                    if step % publish_every == 0:
                        pub.publish_now(state, step)
                        pub.drain()
        pub.close()
        out["steps"] = step
        out["publish"] = pub.stats()
        out["leftover_rows"] = len(rows_y)
        out["rc"] = 0
    except BaseException as e:  # noqa: BLE001 — surfaced by the drill
        out["error"] = e
        out["rc"] = 1


def _subprocess_trainer(cmd, env, cell, done_evt, logs, out):
    """The full-drill trainer: ``deepfm_tpu.launch`` under the real
    supervisor. A clean (idle-timeout) exit while the drill is still
    producing shards relaunches — the production pattern of an online
    trainer that must outlive quiet stretches of its stream."""
    def spawn(c):
        p = subprocess.Popen(c, cwd=_REPO_ROOT, env=env)
        cell["proc"] = p
        rc = p.wait()
        cell["proc"] = None
        return rc

    rcs = []
    while True:
        rc = run_supervised(cmd, max_restarts=10, backoff_secs=0.0,
                            spawn=spawn, log=logs.append)
        rcs.append(rc)
        if rc != 0 or done_evt.is_set():
            break
        time.sleep(0.3)
    out["rcs"] = rcs
    out["rc"] = rcs[-1]


def _trace_correlation(doc):
    """The cross-subsystem correlation evidence: a ``serve.flush`` complete
    event stamped with the artifact step it executed (``model_step=N``)
    whose wall interval overlaps a ``publish.stage``/``publish.rename``
    span of a HIGHER version M — i.e. the merged timeline shows a request
    served by version N while version M was still staging."""
    serves, publishes = [], []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        t0 = float(ev.get("ts", 0.0))
        t1 = t0 + float(ev.get("dur", 0.0))
        if ev.get("name") == "serve.flush" and "model_step" in args:
            serves.append((t0, t1, int(args["model_step"]), args))
        elif ev.get("name") in ("publish.stage", "publish.rename") \
                and "version" in args:
            publishes.append((t0, t1, int(args["version"]), ev["name"]))
    first = None
    for s0, s1, mstep, sargs in serves:
        for p0, p1, ver, pname in publishes:
            if ver > mstep and p0 < s1 and s0 < p1:
                found = {"serve_model_step": mstep,
                         "publish_version": ver,
                         "publish_span": pname,
                         "sample_trace_ids":
                             list(sargs.get("trace_ids", []))[:4]}
                # Prefer an overlapping flush that carries request trace
                # ids (some flushes legitimately have none — warmup or
                # untagged clients); which one overlaps first is timing
                # weather, and the evidence wants the ids.
                if found["sample_trace_ids"]:
                    return found
                first = first or found
    return first


def _run_core(workdir, *, mode, seed, pace, say, trace="off", tb_dir=""):
    params = FULL if mode == "full" else SMOKE
    t_start = time.time()
    os.makedirs(workdir, exist_ok=True)
    imp_dir = os.path.join(workdir, "impressions")
    data_dir = os.path.join(workdir, "data")
    model_dir = os.path.join(workdir, "ckpt")
    publish_dir = os.path.join(model_dir, "publish")
    trace_dir = os.path.join(workdir, "trace")
    os.makedirs(data_dir, exist_ok=True)
    if trace != "off":
        trace_lib.configure(trace, trace_dir=trace_dir)

    schedule = faults_lib.ChaosSchedule.generate(
        seed, horizon_s=params["duration_s"],
        read_fault_every=params["read_fault_every"],
        publish_crashes=1, publish_crash_stage="before_rename",
        preemptions=1 if mode == "full" else 0)
    say(f"chaos schedule {schedule.fingerprint()}: "
        + ", ".join(f"{e.kind}@{e.at_s:g}s" for e in schedule.events))

    plan = DiurnalTrafficPlan(
        seed, duration_s=params["duration_s"], base_qps=params["base_qps"],
        peak_qps=params["peak_qps"], feature_size=FEATURE_SIZE,
        field_size=FIELD_SIZE, max_rows=params["max_rows"])
    say(f"traffic plan: {len(plan.requests)} requests / "
        f"{plan.total_rows} rows over {params['duration_s']:g} logical s "
        f"(pace {pace:g})")
    delay_lo, delay_hi = params["delay_s"]
    feed = SeededLabelFeed(seed + 1, delay_min_s=delay_lo,
                           delay_max_s=delay_hi)
    health = LoopHealth()
    logger = ImpressionLogger(imp_dir, shard_records=SHARD_RECORDS,
                              health=health)
    joiner = DelayedLabelJoiner(imp_dir, data_dir, feed,
                                join_window_s=params["join_window_s"],
                                health=health)

    cfg = Config(**_flags(data_dir, model_dir, params["publish_every"],
                          params["idle_timeout_s"], trace, trace_dir))
    baseline_fn = _bootstrap_v0(cfg, publish_dir, say)

    engine = ServingEngine.serve_latest(
        publish_dir, poll_secs=0.05,
        max_batch=cfg.serve_max_batch, max_delay_ms=cfg.serve_max_delay_ms)
    watcher = engine.watcher

    # ---- trainer side -------------------------------------------------
    done_evt = threading.Event()
    trainer_out, sup_logs, cell = {}, [], {"proc": None}
    if mode == "full":
        state_file = os.path.join(workdir, "chaos_state.json")
        sched_file = os.path.join(workdir, "chaos_schedule.json")
        with open(sched_file, "w", encoding="utf-8") as f:
            f.write(schedule.to_json())
        env = _env(DEEPFM_TPU_SKIP_TF_EXPORT=1,
                   **{faults_lib.CHAOS_ENV: "@" + sched_file,
                      faults_lib.CHAOS_STATE_ENV: state_file})
        trainer_thread = threading.Thread(
            target=_subprocess_trainer,
            args=(_cmd(_flags(data_dir, model_dir, params["publish_every"],
                              params["idle_timeout_s"], trace, trace_dir)),
                  env, cell, done_evt, sup_logs, trainer_out))
    else:
        schedule.install(
            state_path=os.path.join(workdir, "chaos_state.json"))
        trainer_thread = threading.Thread(
            target=_mini_trainer,
            args=(cfg, data_dir, publish_dir, done_evt,
                  params["publish_every"], trainer_out))
    trainer_thread.start()

    # ---- the drill loop ----------------------------------------------
    clock = _LogicalClock(pace)
    served = {}           # iid -> (ids, vals) exactly as scored
    served_wall = {}      # iid -> wall completion time
    samples = []          # (t_s, label, online_prob, baseline_prob)
    failures = []
    swap_log = []         # (version, wall, logical) first-observed
    fired, pending_preempts, preempts_sent = set(), [], []
    seen_path = [None]
    tail_ids = np.zeros((2, FIELD_SIZE), np.int32)
    tail_vals = np.ones((2, FIELD_SIZE), np.float32)
    last_tail = [0.0]
    # Labels stay here until the row's impression shard is sealed on disk:
    # a label polled before its impression is visible to the joiner is an
    # orphan by contract (labels_late), and a half-filled logger shard is
    # exactly that window. Deferring the PUSH never moves the ARRIVAL
    # (served_at + delay_for(iid)), so join decisions stay seed-pure.
    label_backlog = collections.deque()
    logger_closed = [False]

    def flush_labels():
        sealed = (plan.total_rows if logger_closed[0]
                  else SHARD_RECORDS * len(logger.shards))
        while label_backlog and label_backlog[0][0] < sealed:
            iid, y, t = label_backlog.popleft()
            feed.push(iid, y, t)

    def pump(now_l):
        flush_labels()
        for _ in joiner.pump(now_l):
            pass
        cur = watcher.current_path
        if cur != seen_path[0]:
            seen_path[0] = cur
            try:
                v = int(os.path.basename(cur))
            except (TypeError, ValueError):
                v = -1
            swap_log.append((v, time.monotonic(), now_l))
            say(f"hot swap -> v{v} at logical {now_l:.1f}s")
        # Driver-side chaos: SIGTERM fires at its scheduled logical time,
        # gated on the trainer having published once (the preempt handler
        # is certainly installed by then; earlier, SIGTERM would hit the
        # interpreter before the listener exists — a different failure
        # than the one this drill schedules).
        if any(v > 0 for v, _, _ in swap_log):
            pending_preempts.extend(schedule.due(now_l, fired))
        for ev in list(pending_preempts):
            proc = cell.get("proc")
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                pending_preempts.remove(ev)
                preempts_sent.append(round(now_l, 2))
                say(f"chaos: SIGTERM to trainer pid {proc.pid} "
                    f"(scheduled {ev.at_s:g}s, fired {now_l:.1f}s)")

    def tail_request():
        # Keep requests flowing outside the plan (drain + trainer tail) so
        # "zero loss across EVERY hot swap" covers the late swaps too.
        if time.monotonic() - last_tail[0] < 0.08:
            return
        last_tail[0] = time.monotonic()
        try:
            # Tail requests are real requests: stamp them too, so every
            # flush the correlation evidence might land on carries ids.
            engine.predict(tail_ids, tail_vals, timeout=60,
                           trace_id=(trace_lib.new_trace_id()
                                     if trace != "off" else None))
        except Exception as e:  # noqa: BLE001 — the loss gate
            failures.append(f"tail: {e!r}")

    for req in plan.requests:
        while clock.now() < req.t_s:
            pump(clock.now())
            time.sleep(min(0.005, max(0.0005, 0.002 * pace)))
        tid = trace_lib.new_trace_id() if trace != "off" else None
        try:
            fut = engine.submit(req.ids, req.vals, trace_id=tid)
            probs = fut.result(timeout=60)
        except Exception as e:  # noqa: BLE001 — the loss gate
            failures.append(f"req@{req.t_s:g}: {e!r}")
            continue
        base = np.asarray(baseline_fn(req.ids, req.vals))
        wall = time.monotonic()
        # Impressions stamp the request's trace_id and the publish version
        # that scored it — the log side of request→model correlation.
        iids = logger.log_request(req.first_id, req.ids, req.vals, req.t_s,
                                  trace_id=tid,
                                  model_version=fut.model_version)
        for k, iid in enumerate(iids):
            served[iid] = (req.ids[k], req.vals[k])
            served_wall[iid] = wall
            label_backlog.append((iid, float(req.labels[k]), req.t_s))
            samples.append((req.t_s, float(req.labels[k]),
                            float(probs[k]), float(base[k])))
    logger.close()
    logger_closed[0] = True
    say(f"traffic done: {len(served)} rows served+logged, "
        f"{len(failures)} failures so far")

    # Drain: pump until every label has arrived and every window closed,
    # so the final counters are the pure function of the seeds (no
    # finalize-forced expiries that a different pace would change).
    while label_backlog or feed.pending or joiner.open_impressions:
        pump(clock.now())
        tail_request()
        time.sleep(0.002)
    joiner.finalize(clock.now())
    done_evt.set()
    say(f"label drain complete at logical {clock.now():.1f}s; "
        f"health={json.dumps({k: v for k, v in health.snapshot().items() if v})}")

    while trainer_thread.is_alive():
        pump(clock.now())
        tail_request()
        time.sleep(0.01)
    trainer_thread.join()
    if trainer_out.get("error") is not None:
        raise trainer_out["error"]
    assert trainer_out.get("rc") == 0, (
        f"trainer failed: {trainer_out}; supervisor log tail "
        f"{sup_logs[-3:]}")

    # Final servable state: LATEST must reach the max published version
    # and the watcher must swap to it.
    expected_max = max(int(n) for n in os.listdir(publish_dir)
                       if os.path.isdir(os.path.join(publish_dir, n))
                       and not n.startswith("."))
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        cur = watcher.current_path
        if cur is not None and os.path.basename(cur) == str(expected_max):
            break
        pump(clock.now())
        tail_request()
        time.sleep(0.02)
    pump(clock.now())

    stats = engine.stats.summary()
    swaps = watcher.swap_count
    swap_failures = watcher.swap_failures
    watcher_errors = watcher.watcher_errors
    engine.close()
    if tb_dir:
        # Serving-side scalars ride the same writer as the trainer's
        # (obs.tensorboard) — stepped at the final published version.
        from deepfm_tpu.obs.tensorboard import TensorBoardWriter
        tbw = TensorBoardWriter(tb_dir)
        tbw.scalar_dict(expected_max, "serving/", stats)
        tbw.scalar_dict(expected_max, "loop/", health.snapshot())
        tbw.close()

    # ---- audits --------------------------------------------------------
    counters = health.snapshot()
    expected, expected_labels = _expected_join(
        plan, feed, params["join_window_s"])
    actual_labels = _emitted_labels(data_dir)
    counters_ok = all(counters[k] == v for k, v in expected.items()) \
        and counters["duplicate_impressions"] == 0 \
        and counters["labels_late"] == 0 \
        and counters["torn_impression_shards"] == 0 \
        and counters["records_emitted"] == plan.total_rows
    labels_ok = actual_labels == expected_labels
    assert counters_ok, (
        f"joiner counters diverged from the seed-pure simulation:\n"
        f"  actual   {counters}\n  expected {expected}")
    assert labels_ok, "emitted labels diverged from the simulation"
    say("determinism: counters + emitted labels match the pure logical "
        "simulation (seed-replayable)")

    checker = SkewChecker(served)
    for shard in joiner.emitted_shards:
        checker.audit_shard(shard)
    assert checker.ok, (
        f"training/serving skew: {checker.mismatches[:5]}")
    assert checker.records_audited == plan.total_rows, (
        f"audited {checker.records_audited} of {plan.total_rows} rows")
    say(f"skew check: {checker.records_audited} records bit-identical "
        "across serving path and training decoder")

    versions, staging = _audit_artifacts(publish_dir, say)
    crashed_version = params["publish_every"]
    crash_fired = crashed_version not in versions and len(staging) >= 1
    finite = _final_params_finite(publish_dir)
    assert finite, "final published params contain non-finite values"

    stale_samples, uncovered = _staleness_samples(
        joiner, served_wall, swap_log)
    stale = staleness_summary(stale_samples)
    pub_walls = sorted(w for v, w, _ in swap_log if v >= 0)
    max_gap = max((b - a for a, b in zip(pub_walls, pub_walls[1:])),
                  default=0.0)
    stale_bound = (params["join_window_s"] + delay_hi) * pace \
        + 2.0 * max_gap + 3.0

    # ---- gates ---------------------------------------------------------
    assert not failures, failures[:5]
    assert stats["serving_failed"] == 0 and stats["serving_overloads"] == 0, \
        stats
    assert swap_failures == 0, f"{swap_failures} failed swaps"
    assert watcher_errors == 0, f"{watcher_errors} watcher errors"
    assert swaps >= MIN_HOT_SWAPS, (
        f"only {swaps} hot swaps (need >= {MIN_HOT_SWAPS})")
    assert crash_fired, (
        f"scheduled publish crash left no evidence: versions "
        f"{sorted(versions)}, staging {staging}")
    if mode == "full":
        assert preempts_sent, "scheduled preemption never fired"
        assert any("restart 1/" in m for m in sup_logs), (
            f"supervisor never restarted after SIGTERM: {sup_logs}")
        assert stale["staleness_p95_s"] is not None \
            and stale["staleness_p95_s"] <= stale_bound, (
            f"staleness p95 {stale['staleness_p95_s']}s exceeds bound "
            f"{stale_bound:.1f}s")

    # ---- merged trace + correlation gate -------------------------------
    trace_section = {"mode": trace}
    if trace != "off":
        trace_lib.export()  # the drill process's own spans
        merged_path = trace_lib.merge(
            trace_dir, os.path.join(trace_dir, "merged_trace.json"))
        with open(merged_path) as f:
            merged = json.load(f)
        correlated = _trace_correlation(merged)
        assert correlated is not None, (
            "merged trace shows no serve-vN flush overlapping a "
            "publish-vM>N staging span")
        trace_section.update(
            merged_path=merged_path,
            merged_from=merged["otherData"]["merged_from"],
            pids=merged["otherData"]["pids"],
            events=len(merged["traceEvents"]),
            dropped_spans=merged["otherData"]["dropped_spans"],
            correlated_serve_publish_overlap=correlated)
        say(f"trace: {len(merged['traceEvents'])} events from "
            f"{merged['otherData']['merged_from']} process(es) -> "
            f"{merged_path}; serve v{correlated['serve_model_step']} "
            f"overlapped publish v{correlated['publish_version']} "
            f"({correlated['publish_span']})")

    import jax
    report = {
        "drill": "production_day",
        "ok": True,
        "mode": mode,
        "seed": seed,
        "pace": pace,
        "chaos": {
            "fingerprint": schedule.fingerprint(),
            "events": json.loads(schedule.to_json())["events"],
            "publish_crash_fired": crash_fired,
            "preemptions_sent_at_logical_s": preempts_sent,
            "supervised_restarts": sum(
                1 for m in sup_logs if "restart" in m and "/" in m),
        },
        "traffic": {
            "requests": len(plan.requests),
            "rows": plan.total_rows,
            "duration_logical_s": params["duration_s"],
            "base_qps": params["base_qps"],
            "peak_qps": params["peak_qps"],
        },
        "loop_health": {k: v for k, v in counters.items()},
        "determinism": {
            "counters_match_simulation": counters_ok,
            "labels_match_simulation": labels_ok,
            "audit_fingerprint": _audit_fingerprint(
                schedule, plan, counters, actual_labels),
        },
        "skew": {"records_audited": checker.records_audited,
                 "mismatches": len(checker.mismatches)},
        "request_loss": {
            "failed": stats["serving_failed"] + len(failures),
            "overloads": stats["serving_overloads"],
            "hot_swaps": swaps,
            "swap_failures": swap_failures,
            "watcher_errors": watcher_errors,
        },
        "serving": {k: stats[k] for k in (
            "serving_requests", "serving_rows", "serving_p50_ms",
            "serving_p99_ms", "serving_qps", "batch_occupancy_pct",
            "swap_blackout_ms")},
        "staleness": dict(
            stale, covered_rows=len(stale_samples),
            uncovered_rows=uncovered,
            bound_s=round(stale_bound, 1),
            max_publish_gap_s=round(max_gap, 1)),
        "windowed_auc": windowed_auc(samples, params["auc_windows"],
                                     params["duration_s"]),
        "publish": {
            "versions": sorted(versions),
            "crashed_version": crashed_version,
            "staging_leaks": len(staging),
            "final_params_finite": finite,
        },
        "trace": trace_section,
        "device_kind": jax.devices()[0].platform,
        "load_kind": "synthetic-diurnal-closed-loop",
        "baseline_kind": "frozen-bootstrap-v0",
        "elapsed_s": round(time.time() - t_start, 1),
    }
    return report


def run_drill(workdir, *, seed=2026, pace=1.0, report_path=None,
              verbose=True, trace="off", tb_dir=""):
    """The full subprocess drill; writes ``PRODUCTION_r0N.json`` unless
    ``report_path`` is falsy-but-not-None (pass "" to skip writing)."""
    say = _say_factory(verbose)
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    try:
        report = _run_core(workdir, mode="full", seed=seed, pace=pace,
                           say=say, trace=trace, tb_dir=tb_dir)
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)
        if trace != "off":
            trace_lib.reset()  # don't leak mode/env into the caller
    say("overload drill (degradation ladder under executor_slow)")
    report["overload"] = run_overload_drill(
        os.path.join(workdir, "overload"), seed=seed, verbose=verbose)
    say("experimentation drill (gated deployment: shadow/canary/promote/"
        "rollback)")
    report["experiment"] = run_experiment_drill(
        os.path.join(workdir, "experiment"), seed=seed, verbose=verbose)
    if report_path is None:
        report_path = _next_report_path()
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        say(f"PASS -> {report_path}")
    return report


def run_smoke(workdir, *, seed=11, pace=0.25, verbose=False, trace="off"):
    """In-process smoke: the same loop with the mini-trainer thread (no
    subprocess, no SIGTERM) — the tier-1 regression surface."""
    say = _say_factory(verbose)
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    try:
        return _run_core(workdir, mode="smoke", seed=seed, pace=pace,
                         say=say, trace=trace)
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)
        faults_lib.set_publish_crash("")  # disarm if the drill died early
        if trace != "off":
            trace_lib.reset()  # don't leak mode/env into the caller


def build_cascade_artifact(publish_dir, *, seed=3, say=None):
    """Train + export ONE small cascade artifact (DIN ranker + twin towers
    + brute index), LATEST -> 1. The overload drill's serving substrate;
    also reused by the overload tests' fixture."""
    say = say or (lambda msg: None)
    from deepfm_tpu.data import libsvm, pipeline as pipeline_lib
    from deepfm_tpu.models.twin_tower import train_twin_tower
    from deepfm_tpu.rec.cascade import ITEM_SLOT, export_cascade
    from deepfm_tpu.rec.index import CandidateIndex

    cfg = Config(
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=32,
        compute_dtype="float32", mesh_data=1, log_steps=0, seed=seed,
        scale_lr_by_world=False, model="din",
        history_max_len=OVERLOAD["hist_len"])
    with tempfile.TemporaryDirectory(prefix="overload_data_") as data_dir:
        files = libsvm.generate_synthetic_ctr(
            data_dir, num_files=1, examples_per_file=256,
            feature_size=cfg.feature_size, field_size=cfg.field_size,
            seed=seed, history=cfg.history_max_len)
        batches = list(pipeline_lib.CtrPipeline(
            files, field_size=cfg.field_size, batch_size=cfg.batch_size,
            num_epochs=1, shuffle=False, prefetch_batches=0, history=True,
            history_max_len=cfg.history_max_len))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step_fn = trainer._make_train_step()
    for b in batches:
        state, _ = step_fn(state, trainer.put_batch(b))
    tower_model, tower_params, _ = train_twin_tower(
        cfg, batches, item_slot=ITEM_SLOT)
    index = CandidateIndex(
        tower_model.all_item_embeddings(tower_params, cfg.feature_size),
        kind="brute")
    export_cascade(trainer.model, state, cfg,
                   os.path.join(publish_dir, "1"),
                   tower_params=tower_params, index=index)
    export_lib.write_latest(publish_dir, "1")
    say(f"cascade artifact v1 live at {publish_dir}")
    return publish_dir


def run_overload_drill(workdir, *, seed=7, verbose=False,
                       publish_dir=None, params=None):
    """Graceful-degradation drill: flood a :class:`CascadeEngine` (admission
    gate + degradation ladder armed) with open-loop Zipf traffic while a
    seeded ``executor_slow`` chaos event throttles the ranking executor,
    then assert the ladder ENGAGED (counted, traced rung transitions > 0),
    the fleet answered every request with a typed outcome (ok / shed /
    overload / timeout — zero hangs, zero silent drops), and the ladder
    fully RECOVERED (rung 0, empty queue) after the slow window drained.

    Bit-replayable: same seed => identical chaos schedule and traffic plan;
    the audit fingerprint hashes the schedule, the plan, the parameters,
    and the asserted outcomes — NOT timing-dependent counters — so two
    same-seed runs on different hosts produce the identical fingerprint."""
    say = _say_factory(verbose)
    P = dict(OVERLOAD)
    P.update(params or {})
    from deepfm_tpu.loop.traffic import FloodTrafficPlan, ZipfUserPopulation
    from deepfm_tpu.rec.cascade import CascadeEngine
    from deepfm_tpu.serve import AdmissionShed, ServerOverloaded, ServeTimeout
    from deepfm_tpu.serve.admission import DEGRADE_RUNGS

    t_start = time.time()
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    try:
        if publish_dir is None:
            publish_dir = build_cascade_artifact(
                os.path.join(workdir, "overload_publish"), say=say)
        schedule = faults_lib.ChaosSchedule.generate(
            seed, horizon_s=P["duration_s"], executor_slow_events=1,
            executor_slow_ms=P["slow_ms"],
            executor_slow_calls=P["slow_calls"])
        population = ZipfUserPopulation(
            seed, users=P["users"], hist_len=P["hist_len"])
        plan = FloodTrafficPlan(
            seed + 1, offered_qps=P["offered_qps"],
            duration_s=P["duration_s"], population=population,
            field_size=FIELD_SIZE, feature_size=FEATURE_SIZE)
        say(f"chaos {schedule.fingerprint()} "
            f"({len(plan.requests)} requests over {P['duration_s']}s, "
            f"{P['users']} Zipf users)")
        eng = CascadeEngine(
            publish_dir, retrieve_k=P["retrieve_k"],
            max_batch=P["max_batch"], max_delay_ms=2.0,
            queue_rows=P["queue_rows"], slo_ms=P["slo_ms"],
            shed_watermark=P["shed_watermark"],
            degrade_retrieve_k=P["degrade_retrieve_k"],
            watcher_kw={"poll_secs": 3600})
        counters = {"ok": 0, "shed": 0, "overload": 0, "timeout": 0,
                    "failed": 0}
        cnt_lock = threading.Lock()
        idx_lock = threading.Lock()
        next_i = [0]
        t0 = time.monotonic()

        def worker():
            while True:
                with idx_lock:
                    i = next_i[0]
                    if i >= len(plan.requests):
                        return
                    next_i[0] = i + 1
                r = plan.requests[i]
                wait = t0 + r.t_s - time.monotonic()
                if wait > 0:
                    time.sleep(wait)
                try:
                    eng.recommend(r.hist_ids, r.hist_mask, r.ids[0],
                                  r.vals[0], k=5, timeout=P["timeout_s"],
                                  value=r.value)
                    outcome = "ok"
                except AdmissionShed:
                    outcome = "shed"
                except ServerOverloaded:
                    outcome = "overload"
                except ServeTimeout:
                    outcome = "timeout"
                except Exception as e:  # noqa: BLE001 — typed into identity
                    say(f"request failed: {e!r}")
                    outcome = "failed"
                with cnt_lock:
                    counters[outcome] += 1

        threads = [threading.Thread(target=worker, name=f"flood-{k}")
                   for k in range(P["workers"])]
        for t in threads:
            t.start()
        fired = set()
        while any(t.is_alive() for t in threads):
            for ev in schedule.due(time.monotonic() - t0, fired):
                if ev.kind == "executor_slow":
                    say(f"chaos: executor_slow {ev.get('delay_ms')}ms x "
                        f"{ev.get('calls')} flushes at t={ev.at_s}s")
                    faults_lib.set_executor_slow(
                        float(ev.get("delay_ms", 0.0)) / 1000.0,
                        int(ev.get("calls", 0)))
            time.sleep(0.01)
        for t in threads:
            t.join()

        # Recovery: drive the ladder idle until it releases (the aged
        # delay signal decays; count-based slow window is exhausted).
        recovery_deadline = time.monotonic() + 10.0
        recovered = False
        while time.monotonic() < recovery_deadline:
            if (eng.ladder_rung() == 0 and eng.engine.pending_rows == 0
                    and faults_lib.executor_slow_remaining() == 0):
                recovered = True
                break
            time.sleep(0.05)
        recovery_s = round(time.monotonic() - t0 - P["duration_s"], 3)
        log = eng.ladder.transition_log
        max_rung = max((new for _, new, _ in log), default=0)
        ladder_engaged = eng.ladder.transitions > 0 and max_rung >= 1
        summary = eng.stats.summary()
        eng.close()
        faults_lib.set_executor_slow(0.0, 0)   # never leak the seam

        total = sum(counters.values())
        accounting_ok = total == len(plan.requests)
        assert accounting_ok, (counters, len(plan.requests))
        assert counters["failed"] == 0, counters
        assert ladder_engaged, (
            f"degradation ladder never engaged: {log}")
        assert recovered, (
            f"ladder did not recover: rung={eng.ladder.rung} "
            f"pending={eng.engine.pending_rows}")
        fingerprint = hashlib.sha256(json.dumps(
            {"schedule": schedule.to_json(),
             "plan": hashlib.sha256(
                 repr(plan.fingerprint_data()).encode()).hexdigest(),
             "params": {k: P[k] for k in sorted(P)},
             "outcomes": {"ladder_engaged": ladder_engaged,
                          "recovered": recovered,
                          "accounting_ok": accounting_ok}},
            sort_keys=True).encode()).hexdigest()[:16]
        say(f"ladder engaged (max rung {max_rung}), recovered in "
            f"{recovery_s}s; counters {counters}")
        return {
            "drill": "overload",
            "seed": seed,
            "params": {k: P[k] for k in sorted(P)},
            "chaos": {"fingerprint": schedule.fingerprint(),
                      "schedule": json.loads(schedule.to_json())},
            "traffic": {"requests": len(plan.requests),
                        "users": population.users,
                        "zipf_q": population.zipf_q,
                        "touched_users": population.touched_users},
            "counters": counters,
            "accounting_ok": accounting_ok,
            "ladder_engaged": ladder_engaged,
            "max_rung": max_rung,
            "rung_names": list(DEGRADE_RUNGS),
            "transition_log": [[prev, new, round(p, 3)]
                               for prev, new, p in log],
            "degrade_transitions": summary["degrade_transitions"],
            "degraded_by_rung": summary["serving_degraded_by_rung"],
            "sheds": summary["serving_sheds"],
            "sheds_by_class": summary["serving_sheds_by_class"],
            "admission_transitions": summary["admission_transitions"],
            "recovered": recovered,
            "recovery_s": recovery_s,
            "audit_fingerprint": fingerprint,
            "elapsed_s": round(time.time() - t_start, 1),
        }
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)
        faults_lib.set_executor_slow(0.0, 0)


# Cache bit-identity drill: the repeat flood is small because the claim is
# correctness (hits occurred, responses byte-equal), not throughput.
CACHE_DRILL = dict(duration_s=1.2, offered_qps=120.0, users=4_000,
                   hist_len=6, retrieve_k=8, max_batch=16,
                   queue_rows=4096, repeat_p=0.5, cache_rows=2048,
                   user_cache_rows=512, k=5, timeout_s=30.0)


def run_cache_drill(workdir, *, seed=7, verbose=False, publish_dir=None,
                    params=None):
    """Serving fast-path bit-identity drill: serve ONE repeat-heavy flood
    plan through the cascade twice over the same artifact — result cache +
    coalescing OFF, then ON — and assert (1) the ON arm actually took the
    fast path (engine cache hits > 0 on a plan with repeats > 0) and
    (2) the audit fingerprint over every request's full recommendation
    (ids AND probability bytes) is IDENTICAL across arms: a cached answer
    is byte-equal to the computed one, end to end through the cascade.

    Requests are served sequentially in plan order (correctness drill, not
    a load drill), so both arms see identical request streams and the
    fingerprints are deterministic."""
    say = _say_factory(verbose)
    P = dict(CACHE_DRILL)
    P.update(params or {})
    from deepfm_tpu.loop.traffic import FloodTrafficPlan, ZipfUserPopulation
    from deepfm_tpu.rec.cascade import CascadeEngine

    t_start = time.time()
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    try:
        if publish_dir is None:
            publish_dir = build_cascade_artifact(
                os.path.join(workdir, "cache_publish"), say=say)

        def serve_arm(cache_on):
            # Fresh same-seed population per arm: identical plans, so the
            # fingerprint delta (none) is attributable to the cache alone.
            population = ZipfUserPopulation(
                seed, users=P["users"], hist_len=P["hist_len"])
            plan = FloodTrafficPlan(
                seed + 1, offered_qps=P["offered_qps"],
                duration_s=P["duration_s"], population=population,
                field_size=FIELD_SIZE, feature_size=FEATURE_SIZE,
                repeat_p=P["repeat_p"])
            kw = {}
            if cache_on:
                kw = dict(cache_rows=P["cache_rows"], coalesce=True,
                          user_cache_rows=P["user_cache_rows"])
            eng = CascadeEngine(
                publish_dir, retrieve_k=P["retrieve_k"],
                max_batch=P["max_batch"], max_delay_ms=0.5,
                queue_rows=P["queue_rows"],
                watcher_kw={"poll_secs": 3600}, **kw)
            h = hashlib.sha256()
            try:
                for r in plan.requests:
                    ids_k, probs_k = eng.recommend(
                        r.hist_ids, r.hist_mask, r.ids[0], r.vals[0],
                        k=P["k"], timeout=P["timeout_s"], value=r.value)
                    h.update(np.asarray(ids_k, np.int64).tobytes())
                    h.update(np.asarray(probs_k, np.float32).tobytes())
                summary = eng.stats.summary()
            finally:
                eng.close()
            return {
                "requests": len(plan.requests),
                "repeat_requests": plan.repeat_requests,
                "fingerprint": h.hexdigest()[:16],
                "cache_hits": summary["serving_cache_hits"],
                "cache_misses": summary["serving_cache_misses"],
                "coalesced": summary["serving_coalesced"],
                "user_cache_hits": eng.user_cache_hits,
            }

        say("cache drill: serving the repeat flood with the fast path OFF")
        off = serve_arm(False)
        say("cache drill: same plan with the fast path ON")
        on = serve_arm(True)
        assert on["repeat_requests"] == off["repeat_requests"] > 0, (
            off, on)
        assert on["cache_hits"] > 0, (
            f"fast path ON served {on['requests']} requests "
            f"({on['repeat_requests']} repeats) with zero cache hits: {on}")
        assert off["cache_hits"] == 0, off
        bit_identical = on["fingerprint"] == off["fingerprint"]
        assert bit_identical, (
            f"cache-on responses diverged from cache-off: "
            f"{off['fingerprint']} vs {on['fingerprint']}")
        say(f"bit-identical arms ({on['fingerprint']}); "
            f"hits={on['cache_hits']} coalesced={on['coalesced']} "
            f"user_hits={on['user_cache_hits']}")
        return {
            "drill": "cache",
            "seed": seed,
            "params": {k: P[k] for k in sorted(P)},
            "off": off,
            "on": on,
            "bit_identical": bit_identical,
            "audit_fingerprint": on["fingerprint"],
            "elapsed_s": round(time.time() - t_start, 1),
        }
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)


def _experiment_batches(plan, batch_size, count):
    """Deterministic training batches built by cycling the traffic plan's
    rows — candidates train on the same distribution they are judged on,
    and the batch stream is a pure function of the plan's seed."""
    ids_rows, vals_rows, y_rows = [], [], []
    for req in plan.requests:
        for r in range(int(req.ids.shape[0])):
            ids_rows.append(np.asarray(req.ids[r], np.int32))
            vals_rows.append(np.asarray(req.vals[r], np.float32))
            y_rows.append(float(req.labels[r]))
    repeats = -(-(batch_size * count) // len(y_rows))
    ids_rows *= repeats
    vals_rows *= repeats
    y_rows *= repeats
    out = []
    for b in range(count):
        sl = slice(b * batch_size, (b + 1) * batch_size)
        out.append({
            "label": np.asarray(y_rows[sl], np.float32).reshape(
                batch_size, 1),
            "feat_ids": np.stack(ids_rows[sl]),
            "feat_vals": np.stack(vals_rows[sl]),
        })
    return out


def _train_candidate(trainer, batches):
    """Fresh init, a few real train steps over ``batches`` (a list or any
    iterable — the NaN scenario passes a ``BatchPoisoner`` wrapper)."""
    state = trainer.init_state()
    step_fn = trainer._make_train_step()
    for b in batches:
        state, _ = step_fn(state, trainer.put_batch(b))
    return state


def run_experiment_drill(workdir, *, seed=7, verbose=False, params=None):
    """Gated-deployment drill: shadow-validate, canary, and auto-promote a
    healthy challenger, then detect / roll back / quarantine a NaN-poisoned,
    a latency-degraded, and a stale-frozen challenger — with ZERO dropped or
    failed primary-lane requests throughout.

    The closed loop is fully serialized (each request's primary AND shadow
    resolution completes before the next submit), so every prediction — and
    therefore every gate decision, pointer move, and the audit fingerprint —
    is a pure function of the seed: same seed + schedule => identical
    ``audit_fingerprint``. Wall-clock latencies drive only the absolute-p99
    guardrail, whose breach/pass margins are structural (an injected sleep
    above the ceiling vs a warm engine ~20x under it), never the
    fingerprint. Per-arm health recomputed offline from the impression log
    (arm + stamped float32 prediction + the plan's labels) must match the
    online accumulation bit-exactly."""
    say = _say_factory(verbose)
    P = dict(EXPERIMENT)
    P.update(params or {})
    from deepfm_tpu.data import tfrecord
    from deepfm_tpu.loop import arm_health
    from deepfm_tpu.loop import impressions as impressions_lib
    from deepfm_tpu.serve.experiment import (ARM_CHALLENGER, ARM_CONTROL,
                                             ExperimentRouter)
    from deepfm_tpu.train import promote as promote_lib

    t_start = time.time()
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    engines = []
    try:
        os.makedirs(workdir, exist_ok=True)
        publish_dir = os.path.join(workdir, "publish")
        imp_dir = os.path.join(workdir, "impressions")

        schedule = faults_lib.ChaosSchedule.generate(
            seed, horizon_s=P["duration_s"],
            challenger_nan_events=1, challenger_nan_batches=2,
            challenger_slow_events=1, challenger_slow_ms=P["slow_ms"],
            challenger_stale_events=1)
        say(f"chaos {schedule.fingerprint()}: "
            + ", ".join(f"{e.kind}@{e.at_s:g}s" for e in schedule.events))
        plan = DiurnalTrafficPlan(
            seed, duration_s=P["duration_s"], base_qps=P["base_qps"],
            peak_qps=P["peak_qps"], feature_size=FEATURE_SIZE,
            field_size=FIELD_SIZE, max_rows=P["max_rows"])
        need = (4 * P["windows_required"]) * P["window_requests"] + 4
        assert len(plan.requests) >= need, (
            f"plan supplies {len(plan.requests)} requests, drill needs "
            f"{need}; raise duration_s/base_qps")

        cfg = Config(feature_size=FEATURE_SIZE, field_size=FIELD_SIZE,
                     embedding_size=4, deep_layers="8", dropout="1.0",
                     batch_size=16, compute_dtype="float32", mesh_data=1,
                     log_steps=0, seed=seed, scale_lr_by_world=False)
        _bootstrap_v0(cfg, publish_dir, say)   # LATEST -> 0 (+history line)

        # ---- candidate builds (what poisons exist, and their arguments,
        # come from the chaos schedule) --------------------------------
        trainer = Trainer(cfg)
        batches = _experiment_batches(plan, cfg.batch_size,
                                      P["nan_train_batches"])
        state1 = _train_candidate(trainer, batches[:P["train_steps"]])
        export_lib.export_serving(trainer.model, state1, cfg,
                                  os.path.join(publish_dir, "1"))
        say("candidate v1 (healthy) exported")

        scenarios = []    # (kind, version, expected breach reason)
        slow_delay_s = 0.0
        nan_poisoned = 0
        fired = set()
        for ev in schedule.due(P["duration_s"] + 1.0, fired):
            if ev.kind == "challenger_nan":
                # The REAL numerical-fault seam: arm the plan, take it the
                # way the train task would, wrap the candidate's pipeline —
                # the candidate's params genuinely go NaN through training.
                faults_lib.set_nan_plan(ev.get("batches"))
                nan_plan = faults_lib.take_nan_plan()
                poisoner = faults_lib.BatchPoisoner(
                    batches, batches=nan_plan["batches"],
                    value=nan_plan["value"], key=nan_plan["key"])
                state2 = _train_candidate(trainer, poisoner)
                nan_poisoned = poisoner.poisoned
                export_lib.export_serving(trainer.model, state2, cfg,
                                          os.path.join(publish_dir, "2"))
                say(f"candidate v2 (NaN-poisoned, {nan_poisoned} batches "
                    f"via set_nan_plan) exported")
                scenarios.append(("challenger_nan", "2",
                                  promote_lib.REASON_NONFINITE))
            elif ev.kind == "challenger_slow":
                # v3 = v1's params behind a degraded engine: only the
                # challenger's predicts are delayed, never the primary's.
                slow_delay_s = float(ev.get("delay_ms", 0.0)) / 1000.0
                scenarios.append(("challenger_slow", "3",
                                  promote_lib.REASON_LATENCY))
            elif ev.kind == "challenger_stale":
                # v4 = a frozen candidate that stopped refreshing; the
                # staleness gate judges its age alone, so it needs no
                # artifact and no traffic.
                scenarios.append(("challenger_stale", "4",
                                  promote_lib.REASON_STALE))
        assert nan_poisoned >= 1, "nan poison seam never fired"
        assert slow_delay_s * 1000.0 > P["max_p99_ms"], (
            f"slow_ms {slow_delay_s * 1e3} must exceed the max_p99_ms "
            f"ceiling {P['max_p99_ms']} for detection-by-construction")

        # ---- engines ---------------------------------------------------
        buckets = export_lib.serving_buckets(P["serve_max_batch"])
        ekw = dict(max_batch=P["serve_max_batch"],
                   max_delay_ms=P["serve_max_delay_ms"], buckets=buckets)
        control = ServingEngine.serve_latest(
            publish_dir, poll_secs=0.05, **ekw)
        engines.append(control)

        def candidate_engine(version, wrap=None):
            fn = export_lib.load_serving(
                os.path.join(publish_dir, version), buckets=tuple(buckets))
            if wrap is not None:
                fn = wrap(fn)
            eng = ServingEngine(fn, **ekw)
            engines.append(eng)
            return eng

        def warm(eng):
            # Compile every bucket a drill request can hit, so measured
            # latencies (the absolute-p99 gate's input) never include a
            # first-flush compile.
            for n in range(1, P["max_rows"] + 1):
                eng.predict(np.zeros((n, FIELD_SIZE), np.int32),
                            np.ones((n, FIELD_SIZE), np.float32),
                            timeout=300)

        # ---- closed serving loop with shadow serialization -------------
        req_iter = iter(plan.requests)
        labels = {}              # impression id -> ground-truth label
        audit_samples = []       # (arm, label, prob, 0.0) in log order
        failures = []
        primary_nonfinite = [0]
        logger = ImpressionLogger(imp_dir, shard_records=SHARD_RECORDS)
        current_req = {}
        window_ch = {"samples": None}
        shadow_evt = threading.Event()

        def on_shadow(rid, probs, latency_ms):
            req = current_req[rid]
            probs = np.asarray(probs)
            logger.log_request(rid + SHADOW_IID_OFFSET, req.ids, req.vals,
                               req.t_s, arm=ARM_CHALLENGER, preds=probs)
            for k in range(int(req.ids.shape[0])):
                p = float(probs[k])
                window_ch["samples"].append(
                    (ARM_CHALLENGER, float(req.labels[k]), p,
                     float(latency_ms)))
                audit_samples.append(
                    (ARM_CHALLENGER, float(req.labels[k]), p, 0.0))
            shadow_evt.set()

        def serve_window(router, n_requests):
            ctl, ch = [], []
            window_ch["samples"] = ch
            for _ in range(n_requests):
                req = next(req_iter)
                current_req[req.first_id] = req
                expect_shadow = (
                    router.mode == "shadow" and not router.killed
                    and router.challenger is not None
                    and router.assign(req.first_id) == ARM_CHALLENGER)
                if expect_shadow:
                    shadow_evt.clear()
                try:
                    fut = router.submit(req.ids, req.vals, req.first_id)
                    probs = np.asarray(fut.result(timeout=60))
                except Exception as e:  # noqa: BLE001 — the loss gate
                    failures.append(f"req {req.first_id}: {e!r}")
                    continue
                if not np.all(np.isfinite(probs)):
                    primary_nonfinite[0] += 1
                # Serialize the shadow lane: this request's duplicate fully
                # resolves (hook included) before the next submit, so
                # challenger flushes never batch across requests and every
                # prediction is bit-stable run to run.
                if expect_shadow and not shadow_evt.wait(30):
                    assert (router.shadow_errors
                            + router.shadow_submit_rejected) > 0, \
                        "shadow lane hung without a typed counter"
                arm = fut.arm if fut.arm is not None else ARM_CONTROL
                lat = float(fut.latency_ms or 0.0)
                logger.log_request(req.first_id, req.ids, req.vals,
                                   req.t_s, model_version=fut.model_version,
                                   arm=arm, preds=probs)
                for k in range(int(req.ids.shape[0])):
                    y = float(req.labels[k])
                    labels[req.first_id + k] = y
                    p = float(probs[k])
                    (ch if arm == ARM_CHALLENGER else ctl).append(
                        (arm, y, p, lat))
                    audit_samples.append((arm, y, p, 0.0))
            return arm_health(ctl + ch)

        gates = promote_lib.GateConfig(
            min_samples=P["min_samples"], min_auc_delta=P["min_auc_delta"],
            max_p99_ratio=P["max_p99_ratio"], max_p99_ms=P["max_p99_ms"],
            max_nonfinite=0, max_calibration_err=P["max_calibration_err"],
            max_candidate_age_s=P["max_candidate_age_s"],
            windows_required=P["windows_required"])
        active_router = [None]

        def kill_switch(version, reason):
            if active_router[0] is not None:
                active_router[0].kill(f"{version}: {reason}")

        controller = promote_lib.PromotionController(
            publish_dir, gates=gates, on_rollback=kill_switch)
        decisions = []

        # ---- phase 1: shadow-validate the healthy challenger -----------
        ch1 = candidate_engine("1")
        warm(control)
        warm(ch1)
        r_shadow = ExperimentRouter(
            control, ch1, mode="shadow", seed=seed,
            challenger_permille=P["permille"],
            shadow_slo_ms=P["shadow_slo_ms"], on_shadow_result=on_shadow)
        active_router[0] = r_shadow
        shadow_windows = []
        for _ in range(P["windows_required"]):
            h = serve_window(r_shadow, P["window_requests"])
            passed, breaches, holds = promote_lib.evaluate_gates(
                h.get(ARM_CHALLENGER, {}), h.get(ARM_CONTROL, {}), gates)
            shadow_windows.append(
                {"passed": passed, "breaches": breaches, "holds": holds,
                 "challenger_n": h.get(ARM_CHALLENGER, {}).get("n", 0)})
            assert passed, (
                f"healthy challenger failed shadow validation: "
                f"breaches={breaches} holds={holds} health={h}")
        sh1 = r_shadow.summary()
        assert sh1["shadow_completed"] > 0 and sh1["shadow_errors"] == 0 \
            and sh1["shadow_nonfinite"] == 0, sh1
        r_shadow.close()
        say(f"shadow validation passed "
            f"({sh1['shadow_completed']} duplicates observed)")

        # ---- phase 2: canary + auto-promote -----------------------------
        assert controller.offer("1", now_s=0.0)
        r_canary = ExperimentRouter(
            control, ch1, mode="canary", seed=seed,
            challenger_permille=P["permille"])
        active_router[0] = r_canary
        for _ in range(P["windows_required"]):
            h = serve_window(r_canary, P["window_requests"])
            d = controller.observe(h.get(ARM_CHALLENGER, {}),
                                   h.get(ARM_CONTROL, {}), now_s=1.0)
            decisions.append(d)
        assert decisions[-1].action == "promote", decisions
        deadline = time.monotonic() + 20
        while os.path.basename(control.watcher.current_path or "") != "1":
            assert time.monotonic() < deadline, \
                "control engine never hot-swapped to the promoted v1"
            time.sleep(0.02)
        serve_window(r_canary, 4)   # zero-loss across the promotion swap
        r_canary.close()
        say("healthy challenger canaried and auto-promoted; LATEST -> 1")

        # ---- phase 3: poisoned challengers ------------------------------
        scen_reports = []
        for kind, version, reason in scenarios:
            if kind == "challenger_stale":
                ds = []
                for _ in range(2):
                    assert controller.offer(version, now_s=0.0)
                    ds.append(controller.observe(
                        {}, {}, now_s=P["stale_age_s"]))
            else:
                if kind == "challenger_nan":
                    eng = candidate_engine(version)
                else:
                    def slowed(fn):
                        def wrapped(ids, vals):
                            time.sleep(slow_delay_s)
                            return fn(ids, vals)
                        return wrapped
                    eng = candidate_engine("1", wrap=slowed)
                warm(eng)
                r = ExperimentRouter(
                    control, eng, mode="shadow", seed=seed,
                    challenger_permille=P["permille"],
                    shadow_slo_ms=P["shadow_slo_ms"],
                    on_shadow_result=on_shadow)
                active_router[0] = r
                ds = []
                for _ in range(2):
                    assert controller.offer(version, now_s=0.0)
                    r.revive()   # each offer earns a fresh shadow shot
                    h = serve_window(r, P["window_requests"])
                    ds.append(controller.observe(
                        h.get(ARM_CHALLENGER, {}),
                        h.get(ARM_CONTROL, {}), now_s=1.0))
                assert r.killed and version in (r.kill_reason or ""), (
                    f"kill-switch never pulled for {kind}: "
                    f"{r.kill_reason!r}")
                if kind == "challenger_slow":
                    assert r.shadow_slo_misses > 0, r.summary()
                r.close()
            assert ds[0].action == "rollback" and reason in ds[0].reasons, \
                (kind, ds)
            assert ds[1].action == "quarantine" \
                and reason in ds[1].reasons, (kind, ds)
            assert not controller.offer(version, now_s=0.0), (
                f"quarantined {version} was re-admitted")
            decisions.extend(ds)
            scen_reports.append({
                "kind": kind, "version": version,
                "expected_reason": reason,
                "decisions": [[d.action, d.version, list(d.reasons)]
                              for d in ds]})
            say(f"{kind}: v{version} rolled back ({reason}) "
                f"and quarantined")
        active_router[0] = None
        logger.close()

        # ---- gates -------------------------------------------------------
        stats = control.stats.summary()
        assert not failures, failures[:5]
        assert primary_nonfinite[0] == 0, (
            f"{primary_nonfinite[0]} primary responses went non-finite — "
            f"challenger poison leaked into the primary lane")
        assert stats["serving_failed"] == 0 \
            and stats["serving_overloads"] == 0, stats
        latest = export_lib.read_latest(publish_dir)
        assert latest is not None \
            and os.path.basename(latest) == "1", latest

        history = [(e["version"], e["actor"], e["reason"])
                   for e in export_lib.pointer_history(publish_dir)]
        actors = [a for _, a, _ in history]
        # One rollback LINE per scenario: the second rollback of the same
        # candidate carries the identical (version, actor, reason) and the
        # sidecar's tail-dedupe (the crash-heal rule) absorbs it — the
        # controller's counters carry the multiplicity.
        assert actors[0] == "publish" and actors.count("promote") == 1 \
            and actors.count("quarantine") == len(scenarios) \
            and actors.count("rollback") == len(scenarios), history
        pstats = controller.stats()
        assert pstats["rollbacks"] == 2 * len(scenarios) \
            and pstats["quarantines"] == len(scenarios) \
            and pstats["promotions"] == 1, pstats

        # ---- per-arm health: online accumulation vs a pure offline
        # recomputation from the impression log (bit-exact) ---------------
        offline_samples = []
        for shard in logger.shards:
            for rec in tfrecord.iter_records(shard):
                s_arm, s_pred = impressions_lib.read_experiment(rec)
                if s_arm is None or s_pred is None:
                    continue
                iid, _, _, _ = impressions_lib.decode_impression(rec)
                offline_samples.append(
                    (s_arm, labels[iid % SHADOW_IID_OFFSET], s_pred, 0.0))
        online_health = arm_health(audit_samples)
        offline_health = arm_health(offline_samples)
        assert online_health == offline_health, (
            f"per-arm health diverged between online accumulation and the "
            f"impression-log recomputation:\n  online  {online_health}\n"
            f"  offline {offline_health}")
        say("per-arm health: online == offline recomputation (bit-exact "
            f"over {len(audit_samples)} samples)")

        fingerprint = hashlib.sha256(json.dumps(
            {"schedule": schedule.to_json(),
             "plan": hashlib.sha256(
                 repr(plan.fingerprint_data()).encode()).hexdigest(),
             "params": {k: P[k] for k in sorted(P)},
             "history": history,
             "decisions": [[d.action, d.version, list(d.reasons)]
                           for d in decisions],
             "arm_health": {
                 str(a): {k: v for k, v in h.items()
                          if k != "p99_latency_ms"}
                 for a, h in online_health.items()},
             "outcomes": {"stable_version": "1",
                          "quarantined": sorted(controller.quarantined),
                          "primary_failed": len(failures),
                          "primary_nonfinite": primary_nonfinite[0],
                          "nan_batches_poisoned": nan_poisoned}},
            sort_keys=True).encode()).hexdigest()[:16]

        import jax
        return {
            "drill": "experiment",
            "ok": True,
            "seed": seed,
            "params": {k: P[k] for k in sorted(P)},
            "chaos": {"fingerprint": schedule.fingerprint(),
                      "events": json.loads(schedule.to_json())["events"]},
            "shadow_validation": shadow_windows,
            "shadow_summary": {k: sh1[k] for k in (
                "shadow_submitted", "shadow_completed", "shadow_errors",
                "shadow_nonfinite", "shadow_slo_misses")},
            "scenarios": scen_reports,
            "promotion": controller.stats(),
            "pointer_history": [
                {"version": v, "actor": a, "reason": r}
                for v, a, r in history],
            "primary": {"requests": stats["serving_requests"],
                        "failed": len(failures),
                        "overloads": stats["serving_overloads"],
                        "nonfinite": primary_nonfinite[0],
                        "hot_swaps": control.watcher.swap_count},
            "arm_health_online": {str(a): h
                                  for a, h in online_health.items()},
            "arm_health_offline_match": True,
            "stable_version": "1",
            "nan_batches_poisoned": nan_poisoned,
            "audit_fingerprint": fingerprint,
            "device_kind": jax.devices()[0].platform,
            "load_kind": "synthetic-closed-loop-serialized",
            "elapsed_s": round(time.time() - t_start, 1),
        }
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)
        faults_lib.take_nan_plan()       # never leak an armed plan
        for eng in engines:
            try:
                eng.close(timeout=5)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def _next_report_path():
    n = 1
    while os.path.exists(
            os.path.join(_REPO_ROOT, f"PRODUCTION_r{n:02d}.json")):
        n += 1
    return os.path.join(_REPO_ROOT, f"PRODUCTION_r{n:02d}.json")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: fresh TemporaryDirectory)")
    ap.add_argument("--seed", type=int, default=2026,
                    help="drill seed: traffic, label delays, and chaos "
                         "schedule all derive from it (default 2026)")
    ap.add_argument("--pace", type=float, default=1.0,
                    help="wall seconds per logical second (default 1.0)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the fast in-process smoke instead")
    ap.add_argument("--report", default=None,
                    help="report path (default: PRODUCTION_r0N.json)")
    ap.add_argument("--trace", default="off",
                    choices=["off", "ring", "full"],
                    help="span tracing for every drill process; the report "
                         "gains a merged Perfetto-loadable trace plus the "
                         "serve-vN/publish-vN+1 correlation evidence")
    ap.add_argument("--tb", default="", dest="tb_dir",
                    help="when set, write serving + loop scalar summaries "
                         "through the shared TensorBoard writer "
                         "(obs.tensorboard) into this directory")
    args = ap.parse_args()
    runner = run_smoke if args.smoke else run_drill
    kw = dict(seed=args.seed, pace=args.pace, verbose=True,
              trace=args.trace)
    if not args.smoke:
        kw["report_path"] = args.report
        kw["tb_dir"] = args.tb_dir
    if args.workdir:
        report = runner(args.workdir, **kw)
    else:
        with tempfile.TemporaryDirectory(prefix="production_drill_") as d:
            report = runner(d, **kw)
    if args.smoke:
        print(json.dumps(report, indent=2))
    print("[production_drill] PASS")


if __name__ == "__main__":
    main()
