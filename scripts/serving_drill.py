#!/usr/bin/env python
"""Serving drill: hot-swapping serving engine against a LIVE publisher.

The executable acceptance check for the TPU-native serving runtime
(``serve/`` + the bucketed-predict seam in ``utils/export.py``):

  1. **Live publisher.** A real training loop (tiny config) runs in this
     process and publishes a servable artifact through the production
     ``Publisher`` every few steps — staging dir, atomic rename, ``LATEST``
     pointer — at least 3 versions.
  2. **Concurrent serving under load.** A replicated fleet (default 2
     pipelined engines with a small-request priority lane, sticky client
     affinity, staggered swaps — ``--replicas 1`` reproduces the single
     PR 7-style engine) over the publish dir serves closed-loop client
     threads the whole time. EVERY replica must hot-swap through >= 2
     version changes (beyond the initial load) with ZERO dropped or
     failed requests and zero failed swaps — and every returned prob
     finite and in [0, 1].
  3. **Near-zero blackout, PER REPLICA.** Each replica's watcher
     pre-warms every serving bucket off-thread before its one-assignment
     swap, and the coordinator staggers the fleet (one replica mid-swap
     at a time), so the measured swap-to-first-new-version-flush blackout
     must stay under ``MAX_BLACKOUT_MS`` on every replica (the pre-warm
     baseline was 239 ms of post-swap compiles, SERVING_r01.json) and
     ``prewarmed_buckets`` must be > 0.
  4. **Bucket parity.** After the run, the final artifact is loaded twice
     — raw and bucket-padded — and the padded outputs must be BIT-EQUAL
     to the unpadded call row-for-row across non-bucket batch sizes.
  5. **Report.** p50/p99 latency, QPS, batch occupancy (> 0 required),
     and measured swap blackout go to ``SERVING_r0N.json`` at the repo
     root (next free N).

Run on CPU:  JAX_PLATFORMS=cpu python scripts/serving_drill.py
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deepfm_tpu.config import Config
from deepfm_tpu.serve import ReplicatedEngine, ServingEngine
from deepfm_tpu.train import Trainer
from deepfm_tpu.train.publish import Publisher
from deepfm_tpu.utils import export as export_lib

FEATURE_SIZE = 120
FIELD_SIZE = 5
TRAIN_STEPS = 16
PUBLISH_EVERY = 4        # versions at steps 4, 8, 12, 16
N_CLIENTS = 3
MAX_REQ_ROWS = 24
REPLICAS = 2             # the fleet under test (1 = the PR 7-style engine)
INFLIGHT = 2             # pipelined batching depth per replica
SMALL_ROWS = 4           # priority-lane threshold (exercised under swaps)
MIN_SWAPS = 3            # initial load + >= 2 hot swaps, PER replica
# Worst-case swap-to-next-flush gap with bucket pre-warm. The pre-warm
# baseline measured 239 ms (SERVING_r01.json) — post-swap bucket compiles
# on the serving path; with the watcher warming every bucket off-thread
# the remaining gap is scheduling noise, bounded well below that.
MAX_BLACKOUT_MS = 100.0

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def say(msg):
    print(f"[serving_drill] {msg}", flush=True)


def _tiny_cfg():
    return Config(
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=32,
        compute_dtype="float32", mesh_data=1, log_steps=0, seed=29,
        scale_lr_by_world=False,
        serve_max_batch=64, serve_max_delay_ms=3.0)


def _train_batch(cfg, rng):
    return {
        "label": (rng.random((cfg.batch_size, 1)) < 0.25).astype(np.float32),
        "feat_ids": rng.integers(0, cfg.feature_size,
                                 (cfg.batch_size, cfg.field_size)
                                 ).astype(np.int32),
        "feat_vals": rng.normal(size=(cfg.batch_size, cfg.field_size)
                                ).astype(np.float32),
    }


def _publish_while_training(cfg, publish_dir, swap_seen):
    """The live side: real train steps, real Publisher, >= 3 versions.
    Publishing is synchronous here so every version lands; between
    versions the loop waits until the serving side has swapped to the
    previous one — the drill must observe every hot swap, not only the
    last (a too-fast publisher would collapse them into one)."""
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step_fn = trainer._make_train_step()
    rng = np.random.default_rng(5)
    pub = Publisher(trainer.model, cfg, publish_dir,
                    every_steps=PUBLISH_EVERY)
    versions = []
    try:
        for step in range(1, TRAIN_STEPS + 1):
            state, _ = step_fn(state, trainer.put_batch(_train_batch(cfg, rng)))
            if step % PUBLISH_EVERY == 0:
                pub.publish_now(state, step)
                versions.append(step)
                say(f"published version {step}")
                deadline = time.monotonic() + 60
                while (swap_seen() < len(versions)
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
    finally:
        pub.close()
    return versions


def _client_loop(engine, seed, stop, counts, failures):
    rng = np.random.default_rng(seed)
    # A replicated fleet routes sticky by client id: each drill client
    # keeps its seed as the affinity key, so every replica sees sustained
    # traffic (the per-replica blackout gate needs post-swap flushes on
    # every replica).
    kw = ({"affinity": seed}
          if getattr(engine, "supports_affinity", False) else {})
    while not stop.is_set():
        n = int(rng.integers(1, MAX_REQ_ROWS + 1))
        ids = rng.integers(0, FEATURE_SIZE, (n, FIELD_SIZE)).astype(np.int32)
        vals = rng.normal(size=(n, FIELD_SIZE)).astype(np.float32)
        try:
            probs = engine.predict(ids, vals, timeout=60, **kw)
        except Exception as e:  # noqa: BLE001 — the drill's core assertion
            failures.append(repr(e))
            continue
        if (probs.shape != (n,) or not np.all(np.isfinite(probs))
                or not np.all((probs >= 0) & (probs <= 1))):
            failures.append(f"bad probs: shape={probs.shape}")
        counts[0] += 1


def _assert_bucket_parity(artifact_dir):
    """Padded-bucket outputs bit-equal to the unpadded call, row-for-row."""
    raw = export_lib.load_serving(artifact_dir)
    bucketed = export_lib.load_serving(artifact_dir, buckets=(4, 16, 64))
    rng = np.random.default_rng(11)
    for n in (1, 3, 5, 16, 23, 64):
        ids = rng.integers(0, FEATURE_SIZE, (n, FIELD_SIZE)).astype(np.int32)
        vals = rng.normal(size=(n, FIELD_SIZE)).astype(np.float32)
        np.testing.assert_array_equal(
            bucketed(ids, vals), np.asarray(raw(ids, vals)),
            err_msg=f"bucket parity broke at n={n}")
    say(f"bucket parity ok (calls_per_bucket={bucketed.calls_per_bucket})")


def _next_report_path():
    n = 1
    while os.path.exists(os.path.join(_REPO_ROOT, f"SERVING_r{n:02d}.json")):
        n += 1
    return os.path.join(_REPO_ROOT, f"SERVING_r{n:02d}.json")


def run_drill(workdir=None, report_path=None, verbose=True,
              replicas=REPLICAS, inflight=INFLIGHT, small_rows=SMALL_ROWS):
    """The whole drill; returns the report dict (also written to disk)."""
    global say
    if not verbose:
        say = lambda msg: None  # noqa: E731
    t_start = time.time()
    # The serving runtime consumes the StableHLO+params artifact; the TF
    # SavedModel sidecar (~10s/publish) only slows the swap cadence here.
    export_lib._export_tf_savedmodel = lambda *a, **k: None
    cfg = _tiny_cfg()
    workdir = workdir or tempfile.mkdtemp(prefix="serving_drill_")
    publish_dir = os.path.join(workdir, "publish")
    say(f"workdir {workdir} replicas={replicas} inflight={inflight} "
        f"small_rows={small_rows}")

    # Serving side first: it must come up BEFORE any artifact exists and
    # start serving the moment version 1 lands.
    engine_kw = dict(
        poll_secs=0.05, max_batch=cfg.serve_max_batch,
        max_delay_ms=cfg.serve_max_delay_ms, inflight=inflight,
        small_rows=small_rows)
    if replicas > 1:
        engine = ReplicatedEngine.serve_latest(
            publish_dir, replicas=replicas, **engine_kw)
        watchers = [e.watcher for e in engine.engines]
    else:
        engine = ServingEngine.serve_latest(publish_dir, **engine_kw)
        watchers = [engine.watcher]
    # The publisher's between-version wait counts the SLOWEST replica:
    # every replica must observe every version (the stagger means they
    # arrive one after another, never together).
    fleet_swaps = lambda: min(w.swap_count for w in watchers)  # noqa: E731
    stop = threading.Event()
    counts = [0]
    failures = []
    clients = [threading.Thread(target=_client_loop,
                                args=(engine, 100 + i, stop, counts, failures))
               for i in range(N_CLIENTS)]

    # The live side runs in the background; the publisher's between-version
    # wait (swap_seen) guarantees client traffic lands on EVERY version.
    versions = []
    pub_error = []

    def publisher_thread():
        try:
            versions.extend(_publish_while_training(
                cfg, publish_dir, swap_seen=fleet_swaps))
        except BaseException as e:  # noqa: BLE001 — re-raised in main
            pub_error.append(e)

    pub_t = threading.Thread(target=publisher_thread)
    pub_t.start()
    # Clients start once version 1 is visible on EVERY replica (before
    # that, predict fails by design: there is nothing to serve) and then
    # run across every subsequent hot swap — the part under test.
    deadline = time.monotonic() + 120
    while fleet_swaps() < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fleet_swaps() >= 1, "first artifact never appeared fleet-wide"
    say(f"first artifact live ({watchers[0].current_path}); "
        "starting clients")
    for c in clients:
        c.start()
    try:
        pub_t.join(timeout=300)
        assert not pub_t.is_alive(), "publisher wedged"
        if pub_error:
            raise pub_error[0]
        deadline = time.monotonic() + 60
        while counts[0] < 200 and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        stop.set()
        for c in clients:
            c.join(timeout=60)
    assert len(versions) >= MIN_SWAPS, versions

    if replicas > 1:
        summary = engine.summary()
        blackouts = summary["swap_blackout_ms_per_replica"]
    else:
        summary = engine.stats.summary()
        blackouts = [summary["swap_blackout_ms"]]
    swaps = fleet_swaps()
    swap_failures = sum(w.swap_failures for w in watchers)
    prewarmed = sum(w.prewarmed_buckets for w in watchers)
    final_artifact = watchers[0].current_path
    engine.close()

    say(f"requests={counts[0]} failures={len(failures)} swaps={swaps} "
        f"(failures={swap_failures}) summary={json.dumps(summary)}")

    # ---- acceptance ----
    assert not failures, failures[:5]
    assert summary["serving_failed"] == 0, summary
    assert summary["serving_overloads"] == 0, summary
    assert swaps >= MIN_SWAPS, \
        f"only {swaps} fleet-wide swaps (need >= {MIN_SWAPS} per replica)"
    assert swap_failures == 0, f"{swap_failures} failed swaps"
    assert counts[0] >= 200, f"only {counts[0]} requests completed"
    assert summary["batch_occupancy_pct"] is not None \
        and summary["batch_occupancy_pct"] > 0, summary
    assert summary["serving_p50_ms"] is not None \
        and summary["serving_p99_ms"] is not None, summary
    # Near-zero blackout ON EVERY REPLICA: each bucket was compiled
    # off-thread before each swap assignment (no post-swap request pays a
    # compile), and flushes are version-stamped so a pre-swap flush
    # completing post-swap (routine under pipelining) cannot close the
    # window early.
    assert prewarmed > 0, "no watcher ever pre-warmed a bucket"
    for i, b in enumerate(blackouts):
        assert b is not None and b < MAX_BLACKOUT_MS, \
            f"replica {i} swap blackout {b}ms >= {MAX_BLACKOUT_MS}ms " \
            f"(per-replica: {blackouts})"
    _assert_bucket_parity(final_artifact)

    report = {
        "drill": "serving",
        "ok": True,
        "replicas": replicas,
        "serve_inflight": inflight,
        "serve_small_rows": small_rows,
        "serving_p50_ms": summary["serving_p50_ms"],
        "serving_p99_ms": summary["serving_p99_ms"],
        "serving_small_p99_ms": summary["serving_small_p99_ms"],
        "serving_large_p99_ms": summary["serving_large_p99_ms"],
        "serving_qps": summary["serving_qps"],
        "batch_occupancy_pct": summary["batch_occupancy_pct"],
        "swap_blackout_ms": summary["swap_blackout_ms"],
        "swap_blackout_ms_per_replica": blackouts,
        "serving_requests": summary["serving_requests"],
        "serving_failed": summary["serving_failed"],
        "serving_overloads": summary["serving_overloads"],
        "hot_swaps": swaps,
        "swap_failures": swap_failures,
        "prewarmed_buckets": prewarmed,
        "versions_published": versions,
        "clients": N_CLIENTS,
        "load_kind": "synthetic-closed-loop",
        "elapsed_s": round(time.time() - t_start, 1),
    }
    path = report_path or _next_report_path()
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    say(f"PASS -> {path}")
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--report", default=None,
                    help="report path (default: SERVING_r0N.json, next free N)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--replicas", type=int, default=REPLICAS,
                    help="fleet size (1 = the single PR 7-style engine)")
    ap.add_argument("--inflight", type=int, default=INFLIGHT,
                    help="pipelined batching depth per replica")
    ap.add_argument("--small_rows", type=int, default=SMALL_ROWS,
                    help="priority-lane row threshold (0 disables)")
    args = ap.parse_args()
    run_drill(args.workdir, args.report, replicas=args.replicas,
              inflight=args.inflight, small_rows=args.small_rows)


if __name__ == "__main__":
    main()
