#!/usr/bin/env python
"""Preemption drill: SIGTERM a live run mid-epoch, resume, assert parity.

The executable acceptance check for the preemption-safe runtime
(``deepfm_tpu/utils/preempt.py`` + the train-task preemption hook +
``scripts/supervise.py``), per path:

  1. **Baseline.** An uninterrupted run -> final params.
  2. **Kill.** Launch the same run as a real ``deepfm_tpu.launch``
     subprocess with ``DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS=N``: after N
     optimizer steps it writes a ``.preempt_hold`` sentinel into model_dir
     and blocks awaiting a signal. The drill SIGTERMs it there —
     a genuine asynchronous preemption mid-epoch — and asserts the
     process force-saved and exited with code 42 (EXIT_PREEMPTED).
  3. **Supervised resume.** Restart through
     ``supervise.run_supervised``, with the relaunches themselves
     preempted every few steps (``DEEPFM_TPU_PREEMPT_AFTER_STEPS``), so
     the supervisor's restart loop is exercised by real exit-42 children
     until the run completes.
  4. **Parity.** Final params must be bit-identical to the baseline —
     the checkpoint + resume-sidecar replay is exact, not approximate.

Runs on the staged host-input path and again on the single-chip
device-resident path (``--decoded_cache ram --device_dataset 1``; the
resumed mid-epoch segment falls back to staged by design — the skip-offset
replay owns the trained-prefix drop — which is exactly the cross-path
bit-identity worth drilling).

Run on CPU:  JAX_PLATFORMS=cpu python scripts/preempt_drill.py
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepfm_tpu.config import Config
from deepfm_tpu.data import libsvm
from deepfm_tpu.train import tasks
from deepfm_tpu.utils import preempt as preempt_lib

from fault_drill import assert_tree_equal, final_params
from supervise import run_supervised

FEATURE_SIZE = 64
FIELD_SIZE = 5
NUM_FILES = 2
RECORDS_PER_FILE = 48
HOLD_AFTER_STEPS = 3     # SIGTERM point: mid-epoch (6 steps/epoch)
RESUME_PREEMPT_EVERY = 4  # supervised relaunches re-preempt this often

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flags(data_dir, model_dir, **kw):
    base = dict(
        task_type="train", data_dir=data_dir, model_dir=model_dir,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=16, num_epochs=2,
        compute_dtype="float32", mesh_data=1, log_steps=0,
        scale_lr_by_world=False, seed=17, verify_crc=True,
        save_checkpoints_steps=0)
    base.update(kw)
    return base


def _cfg(data_dir, model_dir, **kw):
    return Config(**_flags(data_dir, model_dir, **kw))


def _cmd(flags):
    argv = [sys.executable, "-m", "deepfm_tpu.launch"]
    for name, value in flags.items():
        argv += [f"--{name}", str(int(value) if isinstance(value, bool)
                                  else value)]
    return argv


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS", None)
    env.pop("DEEPFM_TPU_PREEMPT_AFTER_STEPS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _drill_path(workdir, data_dir, *, label, extra_flags, verbose=True):
    def say(msg):
        if verbose:
            print(f"[preempt_drill:{label}] {msg}")

    # 1. Uninterrupted baseline.
    base_ckpt = os.path.join(workdir, f"ckpt_base_{label}")
    tasks.run(_cfg(data_dir, base_ckpt, **extra_flags))
    params_base, step_base = final_params(_cfg(data_dir, base_ckpt))
    say(f"baseline done: {step_base} steps")

    # 2. Kill a live subprocess mid-epoch: it holds at the sentinel, we
    # SIGTERM it there, it must force-save and exit 42.
    pre_ckpt = os.path.join(workdir, f"ckpt_pre_{label}")
    flags = _flags(data_dir, pre_ckpt, **extra_flags)
    sentinel = os.path.join(pre_ckpt, ".preempt_hold")
    proc = subprocess.Popen(
        _cmd(flags), cwd=_REPO_ROOT,
        env=_env(DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS=HOLD_AFTER_STEPS))
    deadline = time.time() + 300.0
    while not os.path.exists(sentinel):
        if proc.poll() is not None:
            raise AssertionError(
                f"run exited (code {proc.returncode}) before the hold point")
        if time.time() > deadline:
            proc.kill()
            raise AssertionError("timed out waiting for the hold sentinel")
        time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=300)
    assert rc == preempt_lib.EXIT_PREEMPTED, (
        f"preempted run exited {rc}, expected {preempt_lib.EXIT_PREEMPTED}")
    say(f"SIGTERM at step >= {HOLD_AFTER_STEPS}: exit code {rc}, "
        f"checkpoint + sidecar saved")

    # 3. Supervised resume, itself re-preempted every few steps so the
    # supervisor loop restarts real exit-42 children until completion.
    restarts = []
    rc = run_supervised(
        _cmd(flags), max_restarts=10, backoff_secs=0.0,
        spawn=lambda c: subprocess.call(
            c, cwd=_REPO_ROOT,
            env=_env(DEEPFM_TPU_PREEMPT_AFTER_STEPS=RESUME_PREEMPT_EVERY)),
        log=lambda m: (restarts.append(m), say(m)))
    assert rc == 0, f"supervised resume failed with exit code {rc}"
    assert any("restart 1/" in m for m in restarts), (
        "supervisor never restarted; the re-preempt trigger did not fire")

    # 4. Bit-identity with the uninterrupted baseline.
    params_pre, step_pre = final_params(_cfg(data_dir, pre_ckpt))
    assert step_pre == step_base, (
        f"step count diverged: {step_pre} vs {step_base}")
    assert_tree_equal(params_base, params_pre,
                      f"{label}: interrupted-vs-baseline final params")
    say(f"resume complete: params bit-identical to baseline "
        f"({len(restarts)} supervisor event(s))")


def run_drill(workdir, verbose=True):
    data_dir = os.path.join(workdir, "data")
    libsvm.generate_synthetic_ctr(
        data_dir, num_files=NUM_FILES, examples_per_file=RECORDS_PER_FILE,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="tr",
        seed=5)
    _drill_path(workdir, data_dir, label="staged", extra_flags={},
                verbose=verbose)
    _drill_path(workdir, data_dir, label="device",
                extra_flags=dict(decoded_cache="ram", device_dataset=True),
                verbose=verbose)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: a fresh TemporaryDirectory)")
    args = ap.parse_args()
    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        run_drill(args.workdir)
    else:
        with tempfile.TemporaryDirectory(prefix="preempt_drill_") as d:
            run_drill(d)
    print("[preempt_drill] PASS")


if __name__ == "__main__":
    main()
