#!/usr/bin/env python
"""Summarize a Chrome trace JSON produced by ``deepfm_tpu.obs.trace``.

Input: one ``trace-<pid>.json`` (per-process export) or a ``merge()``d
file. Complete ("X") spans are aggregated per name with wall total, SELF
time (total minus time spent in nested spans on the same thread —
containment reconstructed per (pid, tid) from ts/dur), and nearest-rank
p50/p99 of span duration. Async ("b"/"e") spans — cross-thread waits —
pair by id and aggregate the same way (self == total: they have no
nesting). Ring-buffer drops recorded at export time are surfaced, never
hidden: a wrapped ring means the totals undercount.

Usage:
    python scripts/trace_report.py TRACE.json [--top 20] [--json]
"""

import argparse
import collections
import json
import sys


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    return sorted_vals[max(0, -(-q * n // 100) - 1)]


def _load(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare event-array form is also loadable
        return doc, {}
    return doc.get("traceEvents", []), doc.get("otherData", {})


def _self_times(events):
    """-> {name: [(dur, self)]} for X events, nesting per (pid, tid).

    Within one thread, spans nest by interval containment (a span's
    children start after it and end before it). Sorting by (ts, -dur)
    visits parents before their children; a stack of open spans then
    attributes each child's duration against its direct parent's self
    time."""
    per_thread = collections.defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X":
            per_thread[(ev.get("pid"), ev.get("tid"))].append(ev)
    out = collections.defaultdict(list)
    for evs in per_thread.values():
        evs.sort(key=lambda e: (float(e["ts"]), -float(e.get("dur", 0.0))))
        stack = []  # [name, end_ts, self_us]
        def close_until(ts):
            while stack and stack[-1][1] <= ts:
                name, _, self_us = stack.pop()
                out[name].append(self_us)
        for ev in evs:
            ts = float(ev["ts"])
            dur = float(ev.get("dur", 0.0))
            close_until(ts)
            if stack:
                stack[-1][2] -= dur  # child time is not parent self time
            stack.append([ev["name"], ts + dur, dur])
        close_until(float("inf"))
    return out


def _pair_async(events):
    """-> ({name: [dur]}, unmatched_count) from b/e pairs keyed by id."""
    opens, durs, unmatched = {}, collections.defaultdict(list), 0
    for ev in events:
        ph = ev.get("ph")
        if ph == "b":
            opens[(ev.get("pid"), ev.get("id"))] = ev
        elif ph == "e":
            b = opens.pop((ev.get("pid"), ev.get("id")), None)
            if b is None:
                unmatched += 1
            else:
                durs[b["name"]].append(float(ev["ts"]) - float(b["ts"]))
    return durs, unmatched + len(opens)


def summarize(events):
    """Aggregate rows: one dict per span name, sorted by self time desc."""
    x_self = _self_times(events)
    x_durs = collections.defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X":
            x_durs[ev["name"]].append(float(ev.get("dur", 0.0)))
    async_durs, unmatched = _pair_async(events)
    rows = []
    for name, durs in x_durs.items():
        durs.sort()
        rows.append({
            "name": name, "kind": "span", "count": len(durs),
            "total_ms": sum(durs) / 1e3,
            "self_ms": sum(x_self.get(name, ())) / 1e3,
            "p50_ms": _pct(durs, 50) / 1e3,
            "p99_ms": _pct(durs, 99) / 1e3,
        })
    for name, durs in async_durs.items():
        durs.sort()
        total = sum(durs) / 1e3
        rows.append({
            "name": name, "kind": "async", "count": len(durs),
            "total_ms": total, "self_ms": total,
            "p50_ms": _pct(durs, 50) / 1e3,
            "p99_ms": _pct(durs, 99) / 1e3,
        })
    rows.sort(key=lambda r: -r["self_ms"])
    instants = collections.Counter(
        ev["name"] for ev in events if ev.get("ph") == "i")
    return rows, dict(instants), unmatched


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace-<pid>.json or a merged trace file")
    ap.add_argument("--top", type=int, default=20,
                    help="rows to print, by self time (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output instead of the table")
    args = ap.parse_args(argv)

    events, other = _load(args.trace)
    rows, instants, unmatched = summarize(events)
    dropped = int(other.get("dropped_spans", 0))

    if args.json:
        print(json.dumps({
            "spans": rows[:args.top], "instants": instants,
            "unmatched_async": unmatched, "dropped_spans": dropped,
            "events": len(events), "other": other}, indent=2))
        return 0

    print(f"{len(events)} events"
          + (f" from pids {other['pids']}" if "pids" in other else "")
          + (f"; {dropped} spans DROPPED to ring wraparound"
             if dropped else ""))
    if unmatched:
        print(f"{unmatched} async begin/end events unpaired "
              "(in flight at export, or partner lost to the ring)")
    header = (f"{'span':<24}{'kind':<7}{'count':>7}{'total_ms':>11}"
              f"{'self_ms':>10}{'p50_ms':>9}{'p99_ms':>9}")
    print(header)
    print("-" * len(header))
    for r in rows[:args.top]:
        print(f"{r['name']:<24}{r['kind']:<7}{r['count']:>7}"
              f"{r['total_ms']:>11.2f}{r['self_ms']:>10.2f}"
              f"{r['p50_ms']:>9.3f}{r['p99_ms']:>9.3f}")
    for name, n in sorted(instants.items()):
        print(f"instant {name}: {n}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
