#!/usr/bin/env python
"""Online-training drill: faults + repeated SIGTERMs, artifact parity.

The executable acceptance check for continuous online training with atomic
hot publishing (``data/stream.py`` + ``train/publish.py`` + the online
branch of the train task):

  1. **Live online job under faults.** Launch a real ``deepfm_tpu.launch``
     subprocess in ``--online_mode`` over a directory holding the first
     half of the shards, with ``DEEPFM_TPU_READ_FAULT_EVERY`` injecting
     transient read faults (healed by ResilientStream inside the stream
     source). SIGTERM it at the hold sentinel mid-stream; it must drain
     any in-flight publish, force-save, and exit 42.
  2. **Feed + supervised resume.** New shards land in the directory
     (atomic rename, exactly how a producer should write). The supervised
     relaunches re-preempt themselves every few steps (>= 2 full
     SIGTERM/resume cycles in total) until the stream idle-timeout ends
     the run cleanly.
  3. **Artifact audit.** Every published artifact dir must load via
     ``load_serving`` (completion marker + params + serving fn all
     intact), versions must be strictly monotonic in publish order, and
     ``LATEST`` must resolve to the newest version.
  4. **Replay parity.** A clean, uninterrupted online run over the same
     final shard set (fresh model_dir) must publish bit-identical params
     at every version the two runs share — and both runs must share the
     final version and the same final step count: each record trained
     exactly once across every preemption.

Run on CPU:  JAX_PLATFORMS=cpu python scripts/online_drill.py
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import orbax.checkpoint as ocp

from deepfm_tpu.config import Config
from deepfm_tpu.data import libsvm
from deepfm_tpu.train import tasks
from deepfm_tpu.utils import export as export_lib
from deepfm_tpu.utils import faults as faults_lib
from deepfm_tpu.utils import preempt as preempt_lib

from fault_drill import assert_tree_equal, final_params
from supervise import run_supervised

FEATURE_SIZE = 64
FIELD_SIZE = 5
NUM_FILES = 4            # first half pre-staged, second half fed live
RECORDS_PER_FILE = 48    # batch 16 -> 3 batches/file, 12 steps total
INITIAL_FILES = 2
HOLD_AFTER_STEPS = 3     # SIGTERM point: mid-stream of the initial shards
RESUME_PREEMPT_EVERY = 4  # supervised relaunches re-preempt this often
PUBLISH_EVERY_STEPS = 4   # boundary crossings at steps 4, 8, 12
READ_FAULT_EVERY = 7      # every 7th read fails once (healed in-stream)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flags(data_dir, model_dir, **kw):
    base = dict(
        task_type="train", data_dir=data_dir, model_dir=model_dir,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=16, num_epochs=1,
        compute_dtype="float32", mesh_data=1, log_steps=0,
        scale_lr_by_world=False, seed=17, verify_crc=True,
        save_checkpoints_steps=0, io_retry_backoff_secs=0.0,
        pipe_mode=1, online_mode=1, steps_per_loop=1,
        publish_every_steps=PUBLISH_EVERY_STEPS,
        stream_poll_secs=0.1, stream_idle_timeout_secs=2.0)
    base.update(kw)
    return base


def _cmd(flags):
    argv = [sys.executable, "-m", "deepfm_tpu.launch"]
    for name, value in flags.items():
        argv += [f"--{name}", str(int(value) if isinstance(value, bool)
                                  else value)]
    return argv


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    for k in ("DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS",
              "DEEPFM_TPU_PREEMPT_AFTER_STEPS", faults_lib.READ_FAULT_ENV):
        env.pop(k, None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _feed(src_path, data_dir):
    """Deliver one shard the way a producer must: full write to a hidden
    temp name, then atomic rename into the watched directory."""
    tmp = os.path.join(data_dir, "." + os.path.basename(src_path) + ".part")
    shutil.copyfile(src_path, tmp)
    os.replace(tmp, os.path.join(data_dir, os.path.basename(src_path)))


def _artifact_params(artifact_dir):
    restored = ocp.StandardCheckpointer().restore(
        os.path.join(os.path.abspath(artifact_dir), "params.ckpt"))
    return restored["params"]


def _audit_publish_dir(publish_dir, say):
    """Assert every artifact loads, versions are publish-order monotonic,
    LATEST resolves to the newest. Returns {version_step: artifact_dir}."""
    versions = {}
    for name in os.listdir(publish_dir):
        path = os.path.join(publish_dir, name)
        if not os.path.isdir(path):
            continue
        assert not name.startswith("."), (
            f"staging dir {name} leaked into {publish_dir}")
        versions[int(name)] = path
    assert versions, f"no artifacts published under {publish_dir}"
    for step, path in sorted(versions.items()):
        serve = export_lib.load_serving(path)  # raises on any torn artifact
        probs = serve(np.zeros((2, FIELD_SIZE), np.int64),
                      np.ones((2, FIELD_SIZE), np.float32))
        assert probs.shape[0] == 2 and np.all(np.isfinite(probs)), (
            f"artifact {path} served non-finite output")
        with open(os.path.join(path, export_lib.COMPLETE_MARKER)) as f:
            assert json.load(f)["step"] == step, (
                f"artifact {path} marker step != dir version")
    by_mtime = sorted(versions.items(),
                      key=lambda kv: os.path.getmtime(kv[1]))
    published_order = [step for step, _ in by_mtime]
    assert published_order == sorted(published_order), (
        f"versions not monotonic in publish order: {published_order}")
    latest = export_lib.read_latest(publish_dir)
    assert latest is not None and int(os.path.basename(latest)) == max(
        versions), f"LATEST resolves to {latest}, newest is {max(versions)}"
    say(f"audited {len(versions)} artifact(s): all load, "
        f"monotonic, LATEST={max(versions)}")
    return versions


def run_drill(workdir, verbose=True):
    def say(msg):
        if verbose:
            print(f"[online_drill] {msg}")

    # All shards generated up front into a source dir; the live dir starts
    # with the first half and receives the rest mid-run.
    src_dir = os.path.join(workdir, "src")
    shards = sorted(libsvm.generate_synthetic_ctr(
        src_dir, num_files=NUM_FILES, examples_per_file=RECORDS_PER_FILE,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="tr",
        seed=5))
    data_dir = os.path.join(workdir, "data")
    os.makedirs(data_dir)
    for path in shards[:INITIAL_FILES]:
        _feed(path, data_dir)

    # 1. Live online job under injected read faults; SIGTERM at the hold
    # sentinel mid-stream -> drains publish, force-saves, exits 42.
    model_dir = os.path.join(workdir, "ckpt_online")
    flags = _flags(data_dir, model_dir)
    sentinel = os.path.join(model_dir, ".preempt_hold")
    proc = subprocess.Popen(
        _cmd(flags), cwd=_REPO_ROOT,
        env=_env(DEEPFM_TPU_PREEMPT_HOLD_AFTER_STEPS=HOLD_AFTER_STEPS,
                 **{faults_lib.READ_FAULT_ENV: READ_FAULT_EVERY}))
    deadline = time.time() + 300.0
    while not os.path.exists(sentinel):
        if proc.poll() is not None:
            raise AssertionError(
                f"online run exited (code {proc.returncode}) before the "
                f"hold point")
        if time.time() > deadline:
            proc.kill()
            raise AssertionError("timed out waiting for the hold sentinel")
        time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=300)
    assert rc == preempt_lib.EXIT_PREEMPTED, (
        f"preempted online run exited {rc}, "
        f"expected {preempt_lib.EXIT_PREEMPTED}")
    say(f"SIGTERM at step >= {HOLD_AFTER_STEPS} under read faults: "
        f"exit {rc}, checkpoint + stream sidecar saved")

    # 2. The stream grows; supervised resume re-preempts itself every few
    # steps until the idle timeout ends the run cleanly (>= 2 total
    # SIGTERM/resume cycles counting the hold kill above).
    for path in shards[INITIAL_FILES:]:
        _feed(path, data_dir)
    say(f"fed {NUM_FILES - INITIAL_FILES} new shard(s) into the live dir")
    restarts = []
    rc = run_supervised(
        _cmd(flags), max_restarts=10, backoff_secs=0.0,
        spawn=lambda c: subprocess.call(
            c, cwd=_REPO_ROOT,
            env=_env(DEEPFM_TPU_PREEMPT_AFTER_STEPS=RESUME_PREEMPT_EVERY)),
        log=lambda m: (restarts.append(m), say(m)))
    assert rc == 0, f"supervised online resume failed with exit code {rc}"
    assert any("restart 1/" in m for m in restarts), (
        "supervisor never restarted; the re-preempt trigger did not fire")

    # The stream sidecar must have admitted every shard, in sorted order
    # (the producer feeds names in sorted order, so admission == sorted).
    with open(os.path.join(model_dir, "stream_manifest.json")) as f:
        admitted = [os.path.basename(p)
                    for p, _ in json.load(f)["admitted"]]
    expect = [os.path.basename(p) for p in shards]
    assert admitted == expect, (
        f"sidecar admitted {admitted}, expected {expect}")

    # 3. Artifact audit of the interrupted-and-resumed run.
    publish_dir = os.path.join(model_dir, "publish")
    versions_live = _audit_publish_dir(publish_dir, say)

    # 4. Clean uninterrupted replay over the same final shard set.
    clean_model_dir = os.path.join(workdir, "ckpt_clean")
    tasks.run(Config(**_flags(data_dir, clean_model_dir)))
    clean_publish = os.path.join(clean_model_dir, "publish")
    versions_clean = _audit_publish_dir(clean_publish, say)

    _, step_live = final_params(Config(**_flags(data_dir, model_dir)))
    _, step_clean = final_params(
        Config(**_flags(data_dir, clean_model_dir)))
    assert step_live == step_clean, (
        f"final step diverged: interrupted {step_live} vs clean "
        f"{step_clean} — some record trained twice or never")

    final_version = max(versions_clean)
    assert final_version in versions_live, (
        f"final version {final_version} missing from the interrupted run "
        f"({sorted(versions_live)})")
    common = sorted(set(versions_live) & set(versions_clean))
    for step in common:
        assert_tree_equal(
            _artifact_params(versions_live[step]),
            _artifact_params(versions_clean[step]),
            f"published params @ step {step} (interrupted vs clean)")
    say(f"replay parity: {len(common)} common version(s) {common} "
        f"bit-identical; final step {step_live} matches")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="",
                    help="scratch dir (default: a fresh TemporaryDirectory)")
    args = ap.parse_args()
    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        run_drill(args.workdir)
    else:
        with tempfile.TemporaryDirectory(prefix="online_drill_") as d:
            run_drill(d)
    print("[online_drill] PASS")


if __name__ == "__main__":
    main()
