#!/usr/bin/env python
"""Step-time breakdown + batch-size sweep on the current accelerator.

Produces the README Performance table: device-bound cost of each stage
(forward, forward+backward+update, K-step scan, host->device transfer) and
an ms/step vs batch-size sweep, plus a Pallas-vs-XLA A/B. Optionally writes
a jax.profiler trace (--trace_dir) for TensorBoard/Perfetto inspection.

Usage: python scripts/profile_step.py [--trace_dir /tmp/trace] [--quick]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 8


def _batches(cfg, n, bs):
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        out.append({
            "feat_ids": rng.integers(
                0, cfg.feature_size, (bs, cfg.field_size)).astype(np.int32),
            "feat_vals": rng.normal(
                size=(bs, cfg.field_size)).astype(np.float32),
            "label": (rng.random((bs, 1)) < 0.25).astype(np.float32),
        })
    return out


def _time(fn, n_iters, args_fn) -> float:
    """Best-of-3 wall ms per call of fn(args_fn())."""
    import jax
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = None
        for _ in range(n_iters):
            out = fn(args_fn())
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / n_iters)
    return 1000 * best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace_dir", default="")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import jax

    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer
    from deepfm_tpu.utils import profiling as prof_lib

    def cfg_for(bs, use_pallas=True):
        return Config(
            feature_size=117581, field_size=39, embedding_size=32,
            deep_layers="128,64,32", dropout="0.5,0.5,0.5", batch_size=bs,
            learning_rate=5e-4, optimizer="Adam", l2_reg=1e-4,
            compute_dtype="bfloat16", log_steps=0, seed=0,
            use_pallas=use_pallas, steps_per_loop=K)

    print(f"devices: {jax.devices()}  backend: {jax.default_backend()}\n")

    # ---- breakdown at the reference batch size -------------------------
    bs = 1024
    cfg = cfg_for(bs)
    tr = Trainer(cfg)
    state = tr.init_state()
    host = _batches(cfg, 8, bs)
    dev = [tr.put_batch(b) for b in host]
    sb_host = [host[i:i + K] for i in (0,)]
    sb_dev = tr.put_superbatch(sb_host[0])

    # warmup/compile all programs
    probs = tr.predict_step(state, dev[0])
    state, m = tr.train_step(state, dev[1])
    state, m = tr.multi_step(state, sb_dev)
    jax.block_until_ready((probs, m["loss"]))

    n = 30 if args.quick else 100
    i = [0]

    def next_dev():
        i[0] = (i[0] + 1) % 8
        return dev[i[0]]

    t_fwd = _time(lambda b: tr.predict_step(state, b), n, next_dev)
    st = [state]

    def step1(b):
        st[0], mm = tr.train_step(st[0], b)
        return mm["loss"]
    t_step = _time(step1, n, next_dev)

    def stepk(sbx):
        st[0], mm = tr.multi_step(st[0], sbx)
        return mm["loss"]
    t_scan = _time(stepk, max(n // K, 5),
                   lambda: tr.put_superbatch(sb_host[0]))
    t_put1 = _time(lambda b: jax.tree.map(lambda x: x, tr.put_batch(b)),
                   n, lambda: host[i[0] % 8])
    t_putk = _time(lambda g: tr.put_superbatch(g), max(n // K, 5),
                   lambda: sb_host[0])

    print("stage breakdown @ batch 1024 (best-of-3, ms):")
    print(f"  forward only (predict_step, staged)        {t_fwd:8.3f}")
    print(f"  fwd+bwd+Adam (train_step, staged)          {t_step:8.3f}")
    print(f"  host->device transfer, one batch           {t_put1:8.3f}")
    print(f"  K={K} steps: one stacked transfer           {t_putk:8.3f}"
          f"  ({t_putk / K:.3f}/step)")
    print(f"  K={K} steps: scan dispatch incl. transfer   {t_scan:8.3f}"
          f"  ({t_scan / K:.3f}/step)")

    # ---- batch-size sweep ---------------------------------------------
    print("\nbatch-size sweep (train_step, staged batches, ms/step | ex/s):")
    for bs in (256, 1024, 4096, 16384):
        c = cfg_for(bs)
        t2 = Trainer(c)
        s2 = t2.init_state()
        d2 = [t2.put_batch(b) for b in _batches(c, 4, bs)]
        s2, mm = t2.train_step(s2, d2[0])
        jax.block_until_ready(mm["loss"])
        holder = [s2]

        def one(b, holder=holder, t2=t2):
            holder[0], m3 = t2.train_step(holder[0], b)
            return m3["loss"]
        j = [0]

        def nxt(d2=d2, j=j):
            j[0] = (j[0] + 1) % 4
            return d2[j[0]]
        ms = _time(one, 20 if args.quick else 50, nxt)
        print(f"  bs={bs:6d}: {ms:7.3f} ms/step  {1000 * bs / ms:12,.0f} ex/s")

    # ---- Pallas A/B ----------------------------------------------------
    print("\nPallas fused FM vs XLA formulation (train_step, staged):")
    for pallas in (True, False):
        c = cfg_for(1024, use_pallas=pallas)
        t2 = Trainer(c)
        s2 = t2.init_state()
        d2 = [t2.put_batch(b) for b in _batches(c, 4, 1024)]
        s2, mm = t2.train_step(s2, d2[0])
        jax.block_until_ready(mm["loss"])
        holder = [s2]

        def one(b, holder=holder, t2=t2):
            holder[0], m3 = t2.train_step(holder[0], b)
            return m3["loss"]
        j = [0]

        def nxt(d2=d2, j=j):
            j[0] = (j[0] + 1) % 4
            return d2[j[0]]
        ms = _time(one, 20 if args.quick else 50, nxt)
        print(f"  use_pallas={pallas}: {ms:7.3f} ms/step")

    # ---- optional trace ------------------------------------------------
    if args.trace_dir:
        with prof_lib.maybe_trace(args.trace_dir):
            for _ in range(10):
                st[0], mm = tr.multi_step(st[0], tr.put_superbatch(sb_host[0]))
            jax.block_until_ready(mm["loss"])
        print(f"\ntrace written under {args.trace_dir} "
              "(TensorBoard: profile plugin / Perfetto: xplane)")


if __name__ == "__main__":
    main()
