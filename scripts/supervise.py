#!/usr/bin/env python
"""Relaunch-on-preemption supervisor for ``deepfm_tpu.launch`` runs.

The orchestrator half of the preemption contract (see
``deepfm_tpu/utils/preempt.py``): the training process exits with a
RESTARTABLE exit code (42 = graceful preemption, 43 = stall-watchdog abort)
after force-saving its checkpoint + resume sidecar; this wrapper relaunches
it — checkpoint auto-resume makes the restart replay-exact — with a restart
cap and exponential backoff so a crash-looping job cannot spin forever.
Ordinary failures (any other nonzero code) are NOT retried: a code bug or a
bad config should fail fast, not burn a reservation retrying.

Usage:
    python scripts/supervise.py [--max_restarts N] [--backoff_secs S] -- \
        python -m deepfm_tpu.launch --task_type train ...

Everything after ``--`` is the command to supervise.
"""

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepfm_tpu.utils import preempt as preempt_lib


def run_supervised(cmd, *, max_restarts=5, backoff_secs=1.0,
                   healthy_secs=0.0, max_total_restarts=0, sleep=time.sleep,
                   spawn=None, log=print, clock=time.monotonic):
    """Run ``cmd`` until it exits cleanly, restarting on preemption codes.

    Returns the final exit code: 0 on success, the child's code on a
    non-restartable failure, or the last restartable code when the restart
    budget is exhausted. With ``healthy_secs > 0``, a child that ran at
    least that long before a restartable exit resets the restart counter
    and backoff — an online job preempted once a day must not exhaust a
    lifetime budget sized for crash loops. ``max_total_restarts > 0`` is the
    crash-loop breaker on top of that: a LIFETIME cap on restarts that
    ``healthy_secs`` never resets, so a job that keeps limping past the
    healthy threshold and dying again still stops eventually instead of
    cycling forever (0 = unlimited). ``sleep``/``spawn``/``clock`` are
    injectable for tests (``spawn(cmd) -> int`` defaults to
    ``subprocess.call``).
    """
    spawn = spawn if spawn is not None else (lambda c: subprocess.call(c))
    restarts = 0
    total_restarts = 0
    # Exit-code histogram over every nonzero child exit, so a drill audit
    # can assert WHY relaunches happened (42 preemptions vs 43 watchdog
    # aborts vs ordinary crashes), not just how many.
    exits = {preempt_lib.EXIT_PREEMPTED: 0, preempt_lib.EXIT_WATCHDOG: 0,
             "other": 0}

    def summarize():
        log(f"[supervise] exit histogram: "
            f"preempted(42)={exits[preempt_lib.EXIT_PREEMPTED]} "
            f"watchdog(43)={exits[preempt_lib.EXIT_WATCHDOG]} "
            f"other={exits['other']}; total restarts {total_restarts}")
    while True:
        started = clock()
        rc = spawn(cmd)
        ran_secs = clock() - started
        if rc != 0:
            exits[rc if rc in exits else "other"] += 1
        if rc == 0:
            if total_restarts:
                log(f"[supervise] run completed after {total_restarts} "
                    f"restart(s)")
            summarize()
            return 0
        if rc not in preempt_lib.RESTARTABLE_EXIT_CODES:
            log(f"[supervise] child failed with non-restartable exit code "
                f"{rc}; giving up")
            summarize()
            return rc
        if healthy_secs > 0 and ran_secs >= healthy_secs and restarts:
            log(f"[supervise] child ran healthy for {ran_secs:.0f}s "
                f"(>= {healthy_secs:g}s); resetting restart counter")
            restarts = 0
        if restarts >= max_restarts:
            log(f"[supervise] restart budget exhausted "
                f"({restarts}/{max_restarts}); last exit code {rc}")
            summarize()
            return rc
        if max_total_restarts > 0 and total_restarts >= max_total_restarts:
            log(f"[supervise] total restart cap reached "
                f"({total_restarts}/{max_total_restarts}); last exit "
                f"code {rc}")
            summarize()
            return rc
        delay = backoff_secs * (2 ** restarts)
        restarts += 1
        total_restarts += 1
        log(f"[supervise] exit code {rc} "
            f"({'preempted' if rc == preempt_lib.EXIT_PREEMPTED else 'stalled'}"
            f"); restart {restarts}/{max_restarts} in {delay:g}s")
        if delay > 0:
            sleep(delay)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--max_restarts", type=int, default=5,
                    help="restart budget for preemption exits (default 5)")
    ap.add_argument("--backoff_secs", type=float, default=1.0,
                    help="base backoff, doubled per restart (default 1.0)")
    ap.add_argument("--healthy_secs", type=float, default=0.0,
                    help="a child that ran at least this long before a "
                         "restartable exit resets the restart counter "
                         "(0 = lifetime budget; default 0)")
    ap.add_argument("--max_total_restarts", type=int, default=0,
                    help="crash-loop breaker: lifetime restart cap that "
                         "--healthy_secs never resets (0 = unlimited; "
                         "default 0)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to supervise (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no command given (put it after --)")
    return run_supervised(cmd, max_restarts=args.max_restarts,
                          backoff_secs=args.backoff_secs,
                          healthy_secs=args.healthy_secs,
                          max_total_restarts=args.max_total_restarts)


if __name__ == "__main__":
    sys.exit(main())
