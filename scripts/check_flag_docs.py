#!/usr/bin/env python
"""Fail if any ``Config`` field is undocumented in docs/MIGRATION.md.

Every dataclass field of :class:`deepfm_tpu.config.Config` is a ``--flag``
(argparse auto-generates the parser from the dataclass), and MIGRATION.md is
the flag contract page — the one place a reference user looks up every knob.
This check keeps the two from drifting: adding a Config field without a
MIGRATION row breaks tier-1 (``tests/test_flag_docs.py`` wraps this).

A field counts as documented if MIGRATION.md mentions it as ``--name`` or
`` `name` `` (backticked).

Usage: python scripts/check_flag_docs.py  (exit 0 = all documented)
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "MIGRATION.md")


def missing_flags(doc_text=None):
    """Config field names not mentioned in MIGRATION.md."""
    from deepfm_tpu.config import Config
    if doc_text is None:
        with open(DOC, encoding="utf-8") as f:
            doc_text = f.read()
    return [f.name for f in dataclasses.fields(Config)
            if f"--{f.name}" not in doc_text
            and f"`{f.name}`" not in doc_text]


def main():
    missing = missing_flags()
    if missing:
        print(f"docs/MIGRATION.md is missing {len(missing)} flag(s):")
        for name in missing:
            print(f"  --{name}")
        print("add a row (as `--name` or backticked `name`) to "
              "docs/MIGRATION.md")
        return 1
    print("all Config flags documented in docs/MIGRATION.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
