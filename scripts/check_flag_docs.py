#!/usr/bin/env python
"""Fail if Config flags and docs/MIGRATION.md drift — in EITHER direction.

Every dataclass field of :class:`deepfm_tpu.config.Config` is a ``--flag``
(argparse auto-generates the parser from the dataclass), and MIGRATION.md is
the flag contract page — the one place a reference user looks up every knob.
Two drift directions, both break tier-1 (``tests/test_flag_docs.py``):

* **missing**: a Config field MIGRATION.md never mentions (as ``--name`` or
  backticked `` `name` ``) — a new knob shipped undocumented;
* **stale**: a ``--name`` token in MIGRATION.md that is NOT a Config field —
  a deleted/renamed flag the doc still advertises. The doc's convention
  makes this checkable: current flags are written ``--name``; the
  reference repo's old names are backticked without dashes, so they don't
  trip the scan.

Usage: python scripts/check_flag_docs.py  (exit 0 = no drift)
"""

import dataclasses
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "MIGRATION.md")

#: ``--tokens`` in MIGRATION.md that are deliberately not Config fields:
#: the generic ``--flag value`` syntax placeholder, the standalone
#: converter tool's own CLI (``tools/libsvm_to_tfrecord.py``), and the
#: script-local CLIs of ``scripts/production_drill.py`` /
#: ``scripts/supervise.py`` (drill and supervisor knobs, not train flags).
NON_CONFIG_TOKENS = frozenset({
    "flag", "input", "output", "shards",
    "smoke", "pace", "healthy_secs", "max_total_restarts",
})


def _doc(doc_text):
    if doc_text is None:
        with open(DOC, encoding="utf-8") as f:
            doc_text = f.read()
    return doc_text


def missing_flags(doc_text=None):
    """Config field names not mentioned in MIGRATION.md."""
    from deepfm_tpu.config import Config
    doc_text = _doc(doc_text)
    return [f.name for f in dataclasses.fields(Config)
            if f"--{f.name}" not in doc_text
            and f"`{f.name}`" not in doc_text]


def stale_flags(doc_text=None):
    """``--name`` tokens in MIGRATION.md that no longer exist in Config
    (deleted or renamed flags the doc still references)."""
    from deepfm_tpu.config import Config
    doc_text = _doc(doc_text)
    fields = {f.name for f in dataclasses.fields(Config)}
    referenced = set(re.findall(r"--([A-Za-z0-9_]+)", doc_text))
    return sorted(referenced - fields - NON_CONFIG_TOKENS)


def main():
    missing = missing_flags()
    stale = stale_flags()
    if missing:
        print(f"docs/MIGRATION.md is missing {len(missing)} flag(s):")
        for name in missing:
            print(f"  --{name}")
        print("add a row (as `--name` or backticked `name`) to "
              "docs/MIGRATION.md")
    if stale:
        print(f"docs/MIGRATION.md references {len(stale)} flag(s) that no "
              "longer exist in Config:")
        for name in stale:
            print(f"  --{name}")
        print("fix or drop the row (old reference-repo names belong in "
              "backticks without dashes)")
    if missing or stale:
        return 1
    print("docs/MIGRATION.md and Config flags are in sync (both directions)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
