#!/usr/bin/env python
"""Measure the BASELINE.md table cells on the current accelerator.

Generates a reference-shaped synthetic Criteo-like dataset (the reference
trained on real Criteo; shape anchors from BASELINE.md — feature_size=117581,
field_size=39, embedding_size=32, deep 128/64/32, batch 1024, Adam 5e-4) and
runs the measurable configs end-to-end through the task driver, printing one
JSON line per config:

    {"config": ..., "examples_per_sec": ..., "auc": ..., "devices": N}

Usage:  python scripts/measure_baseline.py [--quick] [--configs deepfm,widedeep,dcnv2]
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FEATURE_SIZE = 117581
FIELD_SIZE = 39


def ensure_data(root: str, n_train: int, n_eval: int) -> str:
    from deepfm_tpu.data import libsvm
    d = os.path.join(root, f"criteo_syn_{n_train}")
    if not os.path.isdir(d):
        n_files = 8
        libsvm.generate_synthetic_ctr(
            d, num_files=n_files, examples_per_file=n_train // n_files,
            feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="tr",
            seed=1)
        libsvm.generate_synthetic_ctr(
            d, num_files=1, examples_per_file=n_eval,
            feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="va",
            seed=2)
    return d


def run_config(name: str, model: str, data_dir: str, epochs: int,
               batch_size: int = 1024, learning_rate: float = 5e-4) -> dict:
    import jax
    from deepfm_tpu.config import Config
    from deepfm_tpu.train import tasks

    with tempfile.TemporaryDirectory() as ckpt:
        cfg = Config(
            model=model,
            feature_size=FEATURE_SIZE, field_size=FIELD_SIZE,
            embedding_size=32, deep_layers="128,64,32",
            dropout="0.5,0.5,0.5", batch_size=batch_size,
            learning_rate=learning_rate, optimizer="Adam", l2_reg=1e-4,
            num_epochs=epochs, data_dir=data_dir, val_data_dir=data_dir,
            model_dir=os.path.join(ckpt, "m"), log_steps=200,
            save_checkpoints_steps=10 ** 9, compute_dtype="bfloat16",
        )
        result = tasks.run(cfg)
    out = {
        "config": name,
        "model": model,
        "batch_size": batch_size,
        "examples_per_sec": round(result.get("examples_per_sec", 0.0), 1),
        # Final-epoch eval rate: programs compiled in epoch 1, so this is
        # the steady-state scanned eval dispatch (VERDICT r3 #2 criterion:
        # within ~2x of train at the same batch size).
        "eval_examples_per_sec": round(
            result.get("eval_examples_per_sec", 0.0), 1),
        "auc": round(result.get("auc", 0.0), 5),
        "eval_loss": round(result.get("eval_loss", 0.0), 5),
        "steps": result.get("steps"),
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
    }
    print(json.dumps(out), flush=True)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small dataset / few epochs (smoke)")
    ap.add_argument("--configs", default="deepfm,widedeep,dcnv2,deepfm_bs16k")
    ap.add_argument("--epochs", type=int, default=0,
                    help="override epoch count (default: 10 full, 2 quick)")
    ap.add_argument("--data_root", default="/tmp/deepfm_tpu_bench")
    args = ap.parse_args()

    n_train, n_eval = (20_480, 10_240) if args.quick else (204_800, 51_200)
    epochs = args.epochs or (2 if args.quick else 10)
    data_dir = ensure_data(args.data_root, n_train, n_eval)

    for model in args.configs.split(","):
        if model == "deepfm_bs16k":
            # Large-batch convergence evidence: step time is flat 256->16384
            # on-device (BASELINE.md), so bs=16k multiplies e2e throughput —
            # IF it still reaches comparable AUC. Measured (2026-07-30):
            # UNSCALED lr 5e-4 converges (AUC 0.6456 vs 0.650 at bs=1024);
            # sqrt-scaled lr 2e-3 overshoots on this objective (AUC 0.59,
            # rising eval loss). Default 25 epochs ~ iso-AUC in 300 steps vs
            # 2000; explicit --epochs / --quick are honored as given.
            run_config("deepfm_criteo_shape_bs16k", "deepfm", data_dir,
                       args.epochs or (2 if args.quick else 25),
                       batch_size=16384, learning_rate=5e-4)
        else:
            run_config(f"{model}_criteo_shape", model, data_dir, epochs)


if __name__ == "__main__":
    main()
