"""Serving-runtime tests: bucket math, padded-predict parity, dynamic
batcher policy edges (single-request deadline, queue-full backpressure,
max-batch preemption, drain-on-shutdown), response demux + latency stamps,
hot swap under load (zero dropped/failed requests), the torn-artifact
``swap_failures`` regression, and an end-to-end smoke over a REAL exported
artifact (bucketed output bit-equal to the unpadded call)."""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

from deepfm_tpu.config import Config
from deepfm_tpu.serve import (ServerOverloaded, ServeTimeout, ServingEngine,
                              ServingStats)
from deepfm_tpu.utils import export as export_lib

pytestmark = pytest.mark.serving

FIELD_SIZE = 5


def _rows(n, base=0):
    ids = (base + np.arange(n * FIELD_SIZE, dtype=np.int32)
           ).reshape(n, FIELD_SIZE) % 120
    vals = np.ones((n, FIELD_SIZE), np.float32)
    return ids, vals


def first_col_predict(feat_ids, feat_vals):
    """Row-local fake model: prob = f(row) only, like the real serve fn."""
    return feat_ids[:, 0].astype(np.float32) * 0.001 + feat_vals[:, 0] * 0.1


# ---------------------------------------------------------------------------
# Bucket math + padded predict (satellite 1)
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_pow2_ladder(self):
        assert export_lib.serving_buckets(8) == (1, 2, 4, 8)
        assert export_lib.serving_buckets(1) == (1,)

    def test_non_pow2_max_is_kept(self):
        assert export_lib.serving_buckets(12) == (1, 2, 4, 8, 12)

    def test_next_bucket(self):
        buckets = (1, 2, 4, 8)
        assert [export_lib.next_bucket(n, buckets)
                for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
        with pytest.raises(ValueError, match="exceeds the largest"):
            export_lib.next_bucket(9, buckets)
        with pytest.raises(ValueError):
            export_lib.next_bucket(0, buckets)

    def test_padded_predict_pads_and_strips(self):
        seen = []

        def spy(ids, vals):
            seen.append(ids.shape[0])
            return first_col_predict(ids, vals)

        ids, vals = _rows(5)
        out = export_lib.padded_predict(spy, ids, vals, (1, 2, 4, 8))
        assert seen == [8]                       # padded to the bucket...
        assert out.shape == (5,)                 # ...pad rows stripped
        np.testing.assert_array_equal(out, first_col_predict(ids, vals))

    def test_exact_bucket_size_skips_padding(self):
        seen = []

        def spy(ids, vals):
            seen.append(ids.shape[0])
            return first_col_predict(ids, vals)

        ids, vals = _rows(4)
        export_lib.padded_predict(spy, ids, vals, (1, 2, 4, 8))
        assert seen == [4]

    def test_bucketed_predict_counts_calls(self):
        bp = export_lib.BucketedPredict(first_col_predict, (2, 8))
        assert bp.max_batch == 8
        for n in (1, 2, 3, 8):
            ids, vals = _rows(n)
            np.testing.assert_array_equal(
                bp(ids, vals), first_col_predict(ids, vals))
        assert bp.calls_per_bucket == {2: 2, 8: 2}


# ---------------------------------------------------------------------------
# Batcher policy edges (satellite 3)
# ---------------------------------------------------------------------------

class TestBatcherPolicy:
    def test_single_request_deadline_fires(self):
        """A lone request is never stranded: the deadline (anchored at ITS
        enqueue time) flushes it even though the batch never fills."""
        eng = ServingEngine(first_col_predict, max_batch=64, max_delay_ms=20)
        try:
            ids, vals = _rows(1)
            probs = eng.predict(ids, vals, timeout=10)
            np.testing.assert_array_equal(probs, first_col_predict(ids, vals))
            assert eng.stats.deadline_flushes == 1
            assert eng.stats.max_batch_flushes == 0
        finally:
            eng.close()

    def test_queue_full_is_typed_not_a_hang(self):
        # start=False: nothing drains, so the bound must trip synchronously.
        eng = ServingEngine(first_col_predict, max_batch=4, queue_rows=8,
                            start=False)
        for _ in range(2):
            eng.submit(*_rows(4))
        with pytest.raises(ServerOverloaded, match="queue full"):
            eng.submit(*_rows(1))
        assert eng.stats.overloads == 1
        assert eng.pending_rows == 8

    def test_max_batch_flush_preempts_deadline(self):
        """max_batch rows arriving early flush immediately — the 10s
        deadline never gets a chance (the test would time out if it did)."""
        eng = ServingEngine(first_col_predict, max_batch=8,
                            max_delay_ms=10_000)
        try:
            futs = [eng.submit(*_rows(4, base=i)) for i in range(2)]
            for f in futs:
                f.result(timeout=5)
            assert eng.stats.max_batch_flushes == 1
            assert eng.stats.deadline_flushes == 0
        finally:
            eng.close()

    def test_close_drains_queue(self):
        """Shutdown resolves every admitted request before the batcher
        exits — and later submits get the typed rejection."""
        eng = ServingEngine(first_col_predict, max_batch=64,
                            max_delay_ms=60_000, start=False)
        futs = [eng.submit(*_rows(3, base=i)) for i in range(5)]
        eng.start()
        eng.close(timeout=10)
        for f in futs:
            assert f.result(timeout=0).shape == (3,)
        with pytest.raises(ServerOverloaded, match="shut down"):
            eng.submit(*_rows(1))

    def test_oversized_and_malformed_requests_rejected(self):
        eng = ServingEngine(first_col_predict, max_batch=4, start=False)
        with pytest.raises(ValueError, match="outside 1..max_batch"):
            eng.submit(*_rows(5))
        with pytest.raises(ValueError, match="one \\[n, F\\] shape"):
            eng.submit(np.zeros((2, 3), np.int32), np.zeros((2, 4), np.float32))

    def test_predict_error_fails_only_that_flush(self):
        calls = {"n": 0}

        def flaky(ids, vals):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("device fell over")
            return first_col_predict(ids, vals)

        eng = ServingEngine(flaky, max_batch=4, max_delay_ms=5)
        try:
            with pytest.raises(RuntimeError, match="fell over"):
                eng.predict(*_rows(2), timeout=10)
            assert eng.stats.requests_failed == 1
            # The engine survives: the next request succeeds.
            assert eng.predict(*_rows(2), timeout=10).shape == (2,)
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Demux + latency stamps
# ---------------------------------------------------------------------------

class TestDemux:
    def test_batched_requests_demuxed_row_for_row(self):
        eng = ServingEngine(first_col_predict, max_batch=16,
                            max_delay_ms=10_000, start=False)
        sizes = (1, 5, 2, 8)
        reqs = [(n, *_rows(n, base=17 * i)) for i, n in enumerate(sizes)]
        futs = [eng.submit(ids, vals) for _, ids, vals in reqs]
        eng.start()
        eng.close(timeout=10)
        for fut, (n, ids, vals) in zip(futs, reqs):
            probs = fut.result(timeout=0)
            assert probs.shape == (n,)
            np.testing.assert_array_equal(probs, first_col_predict(ids, vals))
            assert fut.latency_ms is not None and fut.latency_ms >= 0

    def test_result_timeout_is_typed(self):
        """An unresolved future raises ServeTimeout (a TimeoutError
        subclass) — typed so frontends forward it distinctly from a
        predict failure — and the request is NOT abandoned server-side:
        the engine still resolves it on drain."""
        eng = ServingEngine(first_col_predict, max_batch=4,
                            max_delay_ms=10_000, start=False)
        fut = eng.submit(*_rows(2))
        with pytest.raises(ServeTimeout, match="2 rows"):
            fut.result(timeout=0.01)
        assert isinstance(ServeTimeout("x"), TimeoutError)
        eng.start()
        eng.close(timeout=10)
        assert fut.result(timeout=0).shape == (2,)

    def test_flushes_are_bucketed(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=5,
                            buckets=(2, 8))
        try:
            eng.predict(*_rows(1), timeout=10)   # 1 row -> bucket 2
            assert eng.stats.padded_rows == 2 and eng.stats.real_rows == 1
            summary = eng.stats.summary()
            assert summary["batch_occupancy_pct"] == 50.0
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Hot swap under load + the torn-artifact regression (satellite 2)
# ---------------------------------------------------------------------------

def _fake_artifact(publish_dir, version):
    os.makedirs(os.path.join(publish_dir, version))
    export_lib.write_latest(publish_dir, version)


class TestHotSwap:
    def test_swap_under_load_zero_failures(self, tmp_path):
        """Requests keep succeeding across a hot swap; after the swap they
        see the new model; nothing is dropped or failed."""
        pub = str(tmp_path)

        def loader(path):
            v = float(os.path.basename(path))
            return lambda ids, vals: np.full((ids.shape[0],), v, np.float32)

        _fake_artifact(pub, "1")
        watcher = export_lib.watch_latest(pub, loader=loader, start=False)
        eng = ServingEngine(watcher, max_batch=8, max_delay_ms=2)
        try:
            stop = threading.Event()
            results, errors = [], []

            def client():
                while not stop.is_set():
                    try:
                        results.append(float(eng.predict(*_rows(2),
                                                         timeout=10)[0]))
                    except Exception as e:  # noqa: BLE001 - the assertion
                        errors.append(e)

            t = threading.Thread(target=client)
            t.start()
            try:
                while len(results) < 5:          # traffic on model 1
                    time.sleep(0.005)
                _fake_artifact(pub, "2")
                assert watcher.check_once()      # the hot swap, under load
                seen = len(results)
                while len(results) < seen + 5:   # traffic on model 2
                    time.sleep(0.005)
            finally:
                stop.set()
                t.join(timeout=10)
            assert not errors
            assert eng.stats.requests_failed == 0
            assert results[0] == 1.0 and results[-1] == 2.0
            assert watcher.swap_count == 2
        finally:
            eng.close()
            watcher.close()

    def test_torn_artifact_mid_poll_keeps_current_model(self, tmp_path):
        """LATEST moves to a marker-less (torn) artifact while requests are
        in flight: the load fails, ``swap_failures`` counts it, the current
        model keeps serving, and the completed artifact swaps in later."""
        pub = str(tmp_path)

        def loader(path):
            # Real load_serving semantics: no completion marker -> typed
            # failure. (The fake keeps the test jax-free.)
            if not os.path.exists(os.path.join(path, export_lib.COMPLETE_MARKER)):
                raise export_lib.ArtifactIncomplete(path)
            v = float(os.path.basename(path))
            return lambda ids, vals: np.full((ids.shape[0],), v, np.float32)

        os.makedirs(os.path.join(pub, "1"))
        open(os.path.join(pub, "1", export_lib.COMPLETE_MARKER), "w").close()
        export_lib.write_latest(pub, "1")
        watcher = export_lib.watch_latest(pub, loader=loader, start=False)
        assert watcher.swap_failures == 0
        eng = ServingEngine(watcher, max_batch=8, max_delay_ms=2)
        try:
            assert eng.predict(*_rows(2), timeout=10)[0] == 1.0
            # A publisher crashes mid-write: dir + pointer, no marker.
            os.makedirs(os.path.join(pub, "2"))
            export_lib.write_latest(pub, "2")
            assert not watcher.check_once()
            assert watcher.swap_failures == 1
            assert watcher.swap_count == 1
            # In-flight traffic still lands on model 1.
            assert eng.predict(*_rows(2), timeout=10)[0] == 1.0
            # The export completes; the next poll swaps.
            open(os.path.join(pub, "2", export_lib.COMPLETE_MARKER),
                 "w").close()
            assert watcher.check_once()
            assert watcher.swap_failures == 1
            assert eng.predict(*_rows(2), timeout=10)[0] == 2.0
            assert eng.stats.requests_failed == 0
        finally:
            eng.close()
            watcher.close()

    def test_swap_blackout_recorded(self):
        clock = [0.0]
        stats = ServingStats(clock=lambda: clock[0])
        stats.record_flush(4, 4)
        stats.record_swap()
        clock[0] = 0.25
        stats.record_flush(4, 4)
        assert stats.summary()["swap_blackout_ms"] == 250.0

    def test_blackout_versioned_overlapped_flush_regression(self):
        """Under pipelined batching a PRE-swap flush routinely completes
        AFTER the swap instant. The old swap→next-completed-flush measure
        let that old-model flush close the window (under-counting); the
        versioned measure only closes on a flush that EXECUTED the new
        version."""
        clock = [0.0]
        stats = ServingStats(clock=lambda: clock[0])
        stats.record_flush(4, 4, version=1)
        stats.record_swap(version=2)            # swap at t=0
        clock[0] = 0.010
        stats.record_flush(4, 4, version=1)     # in-flight OLD-model flush
        assert stats.summary()["swap_blackout_ms"] is None  # window open
        clock[0] = 0.040
        stats.record_flush(4, 4, version=2)     # first NEW-model flush
        assert stats.summary()["swap_blackout_ms"] == 40.0

    def test_blackout_unversioned_keeps_legacy_measure(self):
        clock = [0.0]
        stats = ServingStats(clock=lambda: clock[0])
        stats.record_swap()
        clock[0] = 0.010
        stats.record_flush(4, 4)                # no version: any flush closes
        assert stats.summary()["swap_blackout_ms"] == 10.0

    def test_engine_stamps_flush_with_executing_version(self):
        """End to end through the engine: flushes carry the version from
        the predict fn's ``current()`` snapshot, so a swap between two
        flushes is measured against the version that actually ran."""
        class VersionedFn:
            def __init__(self):
                self.version = 1

            def current(self):
                v = self.version
                return (lambda ids, vals: first_col_predict(ids, vals)), v

        fn = VersionedFn()
        eng = ServingEngine(fn, max_batch=4, max_delay_ms=1)
        try:
            eng.predict(*_rows(2), timeout=10)
            eng.stats.record_swap(version=2)    # swap announced...
            eng.predict(*_rows(2), timeout=10)  # ...but v1 still executing
            assert eng.stats.summary()["swap_blackout_ms"] is None
            fn.version = 2
            eng.predict(*_rows(2), timeout=10)
            assert eng.stats.summary()["swap_blackout_ms"] is not None
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Pipelined batching (tentpole layer 1)
# ---------------------------------------------------------------------------

class TestPipelining:
    def test_inflight_bound_respected(self):
        """At most ``inflight`` flushes are ever handed off but not
        completed, and the batcher keeps forming while one executes."""
        import threading as th
        gate = th.Event()
        concurrent = [0]
        peak = [0]
        lock = th.Lock()

        def slow_predict(ids, vals):
            with lock:
                concurrent[0] += 1
                peak[0] = max(peak[0], concurrent[0])
            gate.wait(5)
            with lock:
                concurrent[0] -= 1
            return first_col_predict(ids, vals)

        eng = ServingEngine(slow_predict, max_batch=2, max_delay_ms=1,
                            inflight=2)
        try:
            futs = [eng.submit(*_rows(2, base=i)) for i in range(6)]
            time.sleep(0.3)   # batcher forms + hands off while blocked
            # One executor thread: at most one flush EXECUTES at a time,
            # but with the executor wedged the handoff window holds a
            # second formed flush and the batcher has a third in hand —
            # 3 of the 6 queued batches left the queue while ZERO predict
            # calls completed. That overlap IS the pipeline.
            assert not any(f.done() for f in futs)
            assert eng.pending_rows == 6
            gate.set()
            for f in futs:
                f.result(timeout=10)
            assert peak[0] == 1
            assert eng.stats.flushes == 6
        finally:
            eng.close()

    def test_inflight_one_reproduces_strict_engine(self):
        """``inflight=1`` = strict flush-then-refill: identical observable
        results and per-flush accounting to the PR 7 engine."""
        eng = ServingEngine(first_col_predict, max_batch=4, max_delay_ms=1,
                            inflight=1)
        try:
            for i in range(4):
                ids, vals = _rows(3, base=i)
                np.testing.assert_array_equal(
                    eng.predict(ids, vals, timeout=10),
                    first_col_predict(ids, vals))
            assert eng.stats.requests_completed == 4
        finally:
            eng.close()

    def test_close_drains_pipeline_depth(self):
        """Drain-on-close resolves every admitted future even when several
        formed flushes are queued behind a slow executor."""
        def slow_predict(ids, vals):
            time.sleep(0.05)
            return first_col_predict(ids, vals)

        eng = ServingEngine(slow_predict, max_batch=2, max_delay_ms=0,
                            inflight=2, start=False)
        futs = [eng.submit(*_rows(2, base=i)) for i in range(5)]
        eng.start()
        eng.close(timeout=30)
        for f in futs:
            assert f.done()
            assert f.result(timeout=0).shape == (2,)

    def test_repr_surfaces_resolved_policy(self):
        eng = ServingEngine(first_col_predict, max_batch=16, inflight=3,
                            small_rows=2, start=False)
        r = repr(eng)
        assert "queue_rows=128 (resolved from 0)" in r
        assert "inflight=3" in r and "small_rows=2" in r
        eng2 = ServingEngine(first_col_predict, max_batch=16, queue_rows=64,
                             start=False)
        assert "queue_rows=64" in repr(eng2)
        assert "resolved" not in repr(eng2)

    def test_summary_surfaces_resolved_policy(self):
        eng = ServingEngine(first_col_predict, max_batch=16, start=False)
        s = eng.stats.summary()
        assert s["serve_queue_rows"] == 128
        assert s["serve_queue_rows_auto"] is True
        assert s["serve_inflight"] == 2
        assert s["serve_small_rows"] == 0


# ---------------------------------------------------------------------------
# Priority lanes (tentpole layer 2)
# ---------------------------------------------------------------------------

class TestPriorityLane:
    def test_small_request_bypasses_large_backlog(self):
        """A small request admitted behind a queue of max-batch fills rides
        the NEXT forming batch (head-of-line bypass), not the end of the
        large backlog."""
        import threading as th
        gate = th.Event()
        first_flush_done = th.Event()

        def gated_predict(ids, vals):
            if first_flush_done.is_set():
                gate.wait(5)
            first_flush_done.set()
            return first_col_predict(ids, vals)

        eng = ServingEngine(gated_predict, max_batch=4, max_delay_ms=1,
                            inflight=1, small_rows=1)
        try:
            # Backlog: 3 max-batch fills of large requests.
            larges = [eng.submit(*_rows(4, base=i)) for i in range(3)]
            small = eng.submit(*_rows(1, base=99))
            assert small.lane == "small" and larges[0].lane == "large"
            gate.set()
            small.result(timeout=10)
            for f in larges:
                f.result(timeout=10)
            # The small request flushed with the FIRST batch formed after
            # its admission, i.e. before the last large fill completed.
            order = sorted(
                [(f.latency_ms, "large") for f in larges]
                + [(small.latency_ms, "small")])
            assert order[-1][1] == "large", order
        finally:
            eng.close()

    def test_lane_latencies_split_in_summary(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                            small_rows=2)
        try:
            eng.predict(*_rows(1), timeout=10)    # small lane
            eng.predict(*_rows(5), timeout=10)    # large lane
            s = eng.stats.summary()
            assert s["serving_small_requests"] == 1
            assert s["serving_small_p99_ms"] is not None
            assert s["serving_large_p99_ms"] is not None
            assert s["serving_requests"] == 2
        finally:
            eng.close()

    def test_lane_disabled_by_default(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1)
        try:
            eng.predict(*_rows(1), timeout=10)
            assert eng.stats.summary()["serving_small_requests"] == 0
        finally:
            eng.close()

    def test_small_lane_deadline_anchors_earliest_head(self):
        """A small request alone still flushes within the deadline (the
        anchor is the earliest head across BOTH lanes)."""
        eng = ServingEngine(first_col_predict, max_batch=64, max_delay_ms=20,
                            small_rows=4)
        try:
            t0 = time.monotonic()
            eng.predict(*_rows(2), timeout=10)
            assert time.monotonic() - t0 < 5.0
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Config plumbing (satellite 4's flag surface)
# ---------------------------------------------------------------------------

class TestConfig:
    def test_from_config(self):
        cfg = Config(serve_max_batch=16, serve_max_delay_ms=3.0,
                     serve_buckets="4,16")
        eng = ServingEngine.from_config(cfg, first_col_predict, start=False)
        assert eng.max_batch == 16
        assert eng.max_delay_s == pytest.approx(0.003)
        assert eng.buckets == (4, 16)
        assert eng.queue_rows == 8 * 16

    def test_default_buckets_are_pow2_ladder(self):
        eng = ServingEngine.from_config(Config(serve_max_batch=12),
                                        first_col_predict, start=False)
        assert eng.buckets == (1, 2, 4, 8, 12)

    def test_from_config_carries_pipeline_flags(self):
        cfg = Config(serve_max_batch=16, serve_inflight=3, serve_small_rows=2)
        eng = ServingEngine.from_config(cfg, first_col_predict, start=False)
        assert eng.inflight == 3 and eng.small_rows == 2

    def test_validate_serve_inflight(self):
        with pytest.raises(ValueError, match="serve_inflight"):
            Config(serve_inflight=0)

    def test_validate_serve_small_rows(self):
        with pytest.raises(ValueError, match="serve_small_rows"):
            Config(serve_small_rows=-1)
        with pytest.raises(ValueError, match="serve_small_rows"):
            Config(serve_max_batch=8, serve_small_rows=9)
        Config(serve_max_batch=8, serve_small_rows=8)  # boundary ok

    def test_bad_flags_rejected(self):
        with pytest.raises(ValueError, match="serve_buckets"):
            Config(serve_buckets="64", serve_max_batch=32)
        with pytest.raises(ValueError, match="serve_queue_rows"):
            Config(serve_queue_rows=8, serve_max_batch=32)
        with pytest.raises(ValueError, match="serve_max_delay_ms"):
            Config(serve_max_delay_ms=-1)


# ---------------------------------------------------------------------------
# End-to-end smoke over a REAL artifact (satellite 5's fast half)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def real_artifact(tmp_path_factory):
    from deepfm_tpu.train import Trainer
    cfg = Config(
        feature_size=120, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=16,
        compute_dtype="float32", mesh_data=1, log_steps=0, seed=3)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    out = str(tmp_path_factory.mktemp("serve") / "1")
    orig = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # ~10s saved
    try:
        export_lib.export_serving(trainer.model, state, cfg, out)
    finally:
        export_lib._export_tf_savedmodel = orig
    return out


class TestRealArtifact:
    def test_bucketed_output_equals_unpadded(self, real_artifact):
        """The parity the whole shape policy rests on: padded-bucket probs
        are bit-equal to the unpadded call, row for row."""
        raw = export_lib.load_serving(real_artifact)
        bucketed = export_lib.load_serving(real_artifact, buckets=(2, 4, 16))
        rng = np.random.default_rng(0)
        for n in (1, 2, 3, 7, 16):
            ids = rng.integers(0, 120, (n, FIELD_SIZE)).astype(np.int32)
            vals = rng.normal(size=(n, FIELD_SIZE)).astype(np.float32)
            np.testing.assert_array_equal(bucketed(ids, vals),
                                          raw(ids, vals))
        assert bucketed.calls_per_bucket[16] == 2  # n=7 and n=16

    def test_engine_serves_real_model(self, real_artifact):
        fn = export_lib.load_serving(real_artifact)
        eng = ServingEngine(fn, max_batch=16, max_delay_ms=5)
        try:
            rng = np.random.default_rng(1)
            futs = []
            for n in (1, 4, 9):
                ids = rng.integers(0, 120, (n, FIELD_SIZE)).astype(np.int32)
                vals = rng.normal(size=(n, FIELD_SIZE)).astype(np.float32)
                futs.append((eng.submit(ids, vals), ids, vals))
            for fut, ids, vals in futs:
                probs = fut.result(timeout=30)
                assert probs.shape == (ids.shape[0],)
                assert np.all(np.isfinite(probs))
                assert np.all((probs >= 0) & (probs <= 1))
                np.testing.assert_array_equal(probs, np.asarray(fn(ids, vals)))
            summary = eng.stats.summary()
            assert summary["serving_requests"] == 3
            assert summary["serving_failed"] == 0
            assert summary["batch_occupancy_pct"] > 0
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# The full acceptance drill (satellite 5's slow half)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serving_drill_end_to_end(tmp_path):
    """Live publisher + concurrent engine: >= 2 hot swaps under client
    load, zero dropped/failed requests, bucket parity bit-equal, report
    fields populated. Excluded from tier-1; also runs standalone via
    ``scripts/serving_drill.py`` (which writes SERVING_r0N.json)."""
    import serving_drill
    report = serving_drill.run_drill(
        str(tmp_path), report_path=str(tmp_path / "SERVING.json"),
        verbose=False)
    assert report["ok"]
    assert report["hot_swaps"] >= 3          # initial load + >= 2 hot swaps
    assert report["serving_failed"] == 0 and report["swap_failures"] == 0
    assert report["batch_occupancy_pct"] > 0
    assert report["serving_p99_ms"] is not None
