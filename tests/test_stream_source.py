"""Unbounded stream source tests: admission protocol (2-poll size settling,
manifest mode), late/duplicate/torn healing with DataHealth accounting,
high-water-mark sidecar replay, idle-timeout EOF, and the bounded-read
contract. Injectable clock + sleep — no real polling waits."""

import json
import os

import pytest

from deepfm_tpu.data import fileio
from deepfm_tpu.data.health import DataHealth
from deepfm_tpu.data.stream import UnboundedFileStream


class FakeClock:
    """Deterministic monotonic clock; sleeping advances it."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, secs):
        self.t += max(float(secs), 0.01)


def _write(dirpath, name, data):
    path = os.path.join(str(dirpath), name)
    with open(path, "wb") as f:
        f.write(data)
    return path


def _stream(source, tmp_path, **kw):
    clock = kw.pop("clock", None) or FakeClock()
    kw.setdefault("pattern", "*.bin")
    kw.setdefault("poll_secs", 0.1)
    kw.setdefault("health", DataHealth())
    return UnboundedFileStream(source, clock=clock, sleep=clock.sleep, **kw), clock


def _read_all(stream, chunk=1 << 16):
    out = bytearray()
    while True:
        b = stream.read(chunk)
        if not b:
            return bytes(out)
        out += b


class TestAdmission:
    def test_two_poll_settle_then_serve(self, tmp_path):
        _write(tmp_path, "a.bin", b"alpha")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        assert not s.poll_now()   # first sighting: settling
        assert s.poll_now()       # size stable: admitted
        assert s.files_admitted == [os.path.join(str(tmp_path), "a.bin")]
        assert _read_all(s) == b"alpha"

    def test_growth_after_admission_is_ignored(self, tmp_path):
        path = _write(tmp_path, "a.bin", b"12345")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        with open(path, "ab") as f:
            f.write(b"LATE")  # write-once contract violated by the producer
        # Replay-exactness: exactly the admitted 5 bytes are served.
        assert _read_all(s) == b"12345"

    def test_new_files_admitted_mid_stream(self, tmp_path):
        _write(tmp_path, "a.bin", b"one.")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        assert s.read(4) == b"one."
        _write(tmp_path, "b.bin", b"two.")
        s.poll_now(), s.poll_now()
        assert s.read(4) == b"two."

    def test_partial_read_returns_available_bytes(self, tmp_path):
        # The framer treats any non-empty read as progress: a small fresh
        # shard must reach the consumer without filling the whole request.
        _write(tmp_path, "a.bin", b"tiny")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        assert s.read(1 << 20) == b"tiny"

    def test_unbounded_read_rejected(self, tmp_path):
        s, _ = _stream(str(tmp_path), tmp_path)
        with pytest.raises(ValueError):
            s.read(-1)

    def test_empty_file_never_admitted(self, tmp_path):
        _write(tmp_path, "a.bin", b"")
        s, _ = _stream(str(tmp_path), tmp_path)
        assert not s.poll_now() and not s.poll_now()
        assert s.files_admitted == []


class TestAnomalies:
    def test_late_file_admitted_and_counted(self, tmp_path):
        _write(tmp_path, "b.bin", b"bb")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        _write(tmp_path, "a.bin", b"aa")  # sorts before the admitted b.bin
        s.poll_now(), s.poll_now()
        assert s.health.late_files == 1
        assert _read_all(s) == b"bb" + b"aa"  # admission order, not sorted

    def test_duplicate_basename_skipped(self, tmp_path):
        sub = tmp_path / "redelivered"
        sub.mkdir()
        _write(tmp_path, "a.bin", b"original")
        _write(sub, "a.bin", b"duplicate")
        manifest = _write(tmp_path, "manifest.txt", b"")
        with open(manifest, "w") as f:
            f.write(f"{tmp_path}/a.bin\n{sub}/a.bin\n")
        s, _ = _stream(manifest, tmp_path, idle_timeout_secs=1.0)
        s.poll_now()
        assert s.health.duplicate_files == 1
        assert _read_all(s) == b"original"

    def test_vanished_file_counted_torn_and_skipped(self, tmp_path):
        doomed = _write(tmp_path, "a.bin", b"gone")
        _write(tmp_path, "b.bin", b"kept")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        os.unlink(doomed)
        assert _read_all(s) == b"kept"
        assert s.health.torn_files == 1
        assert s.health.bytes_discarded == 4

    def test_shrunk_file_counted_torn(self, tmp_path):
        path = _write(tmp_path, "a.bin", b"0123456789")
        _write(tmp_path, "b.bin", b"next")
        s, _ = _stream(str(tmp_path), tmp_path, idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        with open(path, "wb") as f:
            f.write(b"0123")  # shrinks below admitted size mid-stream
        out = _read_all(s)
        assert out.endswith(b"next")
        assert s.health.torn_files == 1


class TestSidecar:
    def test_replay_exact_restart(self, tmp_path):
        side = str(tmp_path / "side.json")
        _write(tmp_path, "a.bin", b"aaaa")
        s, _ = _stream(str(tmp_path), tmp_path, sidecar_path=side,
                       idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        assert s.read(2) == b"aa"
        s.close()
        # Restart: the sidecar replays the admitted set without a poll, at
        # the recorded sizes — even though the file has since grown.
        with open(os.path.join(str(tmp_path), "a.bin"), "ab") as f:
            f.write(b"GROWTH")
        s2, _ = _stream(str(tmp_path), tmp_path, sidecar_path=side,
                        idle_timeout_secs=1.0)
        assert s2.files_admitted == [os.path.join(str(tmp_path), "a.bin")]
        assert _read_all(s2) == b"aaaa"

    def test_sidecar_written_before_bytes_served(self, tmp_path):
        side = str(tmp_path / "side.json")
        _write(tmp_path, "a.bin", b"x" * 8)
        s, _ = _stream(str(tmp_path), tmp_path, sidecar_path=side)
        s.poll_now(), s.poll_now()
        meta = json.loads(open(side).read())
        assert [os.path.basename(p) for p, _ in meta["admitted"]] == ["a.bin"]
        assert meta["admitted"][0][1] == 8

    def test_corrupt_sidecar_warns_and_starts_fresh(self, tmp_path):
        side = str(tmp_path / "side.json")
        with open(side, "w") as f:
            f.write('{"version": 1, "adm')  # torn write
        _write(tmp_path, "a.bin", b"ok")
        with pytest.warns(RuntimeWarning, match="unreadable"):
            s, _ = _stream(str(tmp_path), tmp_path, sidecar_path=side,
                           idle_timeout_secs=1.0)
        s.poll_now(), s.poll_now()
        assert _read_all(s) == b"ok"

    def test_source_mismatch_ignores_sidecar(self, tmp_path):
        side = str(tmp_path / "side.json")
        fileio.write_atomic(side, json.dumps(
            {"version": 1, "source": "/elsewhere", "pattern": "*",
             "admitted": [["/elsewhere/z.bin", 3]]}))
        with pytest.warns(RuntimeWarning, match="written for source"):
            s, _ = _stream(str(tmp_path), tmp_path, sidecar_path=side)
        assert s.files_admitted == []


class TestEndOfStream:
    def test_idle_timeout_eofs(self, tmp_path):
        _write(tmp_path, "a.bin", b"data")
        s, clock = _stream(str(tmp_path), tmp_path, idle_timeout_secs=0.5)
        s.poll_now(), s.poll_now()
        assert s.read(4) == b"data"
        t0 = clock.t
        assert s.read(4) == b""  # blocks polling until idle expiry
        assert clock.t - t0 >= 0.5

    def test_request_stop_eofs_promptly(self, tmp_path):
        _write(tmp_path, "a.bin", b"data")
        s, _ = _stream(str(tmp_path), tmp_path)  # idle_timeout 0: forever
        s.poll_now(), s.poll_now()
        s.request_stop()
        assert s.read(4) == b"data"  # already-admitted bytes still served
        assert s.read(4) == b""
        assert s.stopped


class TestManifestMode:
    def test_lines_admit_on_existence(self, tmp_path):
        a = _write(tmp_path, "a.bin", b"AA")
        manifest = os.path.join(str(tmp_path), "manifest.txt")
        with open(manifest, "w") as f:
            f.write(f"# comment\n{a}\n{tmp_path}/missing.bin\n")
        s, _ = _stream(manifest, tmp_path, idle_timeout_secs=1.0)
        assert s.poll_now()  # no settling wait in manifest mode
        assert s.files_admitted == [a]
        assert s.read(2) == b"AA"
        # The listed-but-absent file admits once it appears...
        b = _write(tmp_path, "missing.bin", b"BB")
        assert s.poll_now()
        assert s.files_admitted == [a, b]
        # ...and appended lines admit on the next poll.
        c = _write(tmp_path, "c.bin", b"CC")
        with open(manifest, "a") as f:
            f.write(f"{c}\n")
        assert s.poll_now()
        assert _read_all(s) == b"BBCC"
