"""Scaling & overlap suite (TUNING §2.13): gradient accumulation parity,
double-buffered device staging, hierarchical cross-host reduction.

Trajectory contracts pinned here:

- ``--grad_accum_steps k`` applies the optimizer once per k microbatches
  and is numerically the single big-batch step over the concatenated
  microbatches (equal microbatch sizes => mean-of-means == global mean);
  parity is pinned within float-reassociation tolerance for dense AND
  sparse embedding updates. k=1 compiles the exact seed program.
- ``--staging_buffers`` is purely a transfer-scheduling knob: the
  trajectory is BIT-identical across 1 and 2 slots.
- ``mesh.hierarchical_psum`` (intra-host then inter-host grouped psums)
  equals the flat psum on the virtual mesh to reassociation error
  (1-2 ULP), and the hierarchical trainer path keeps every device's
  param copy bit-identical while tracking the single-device trajectory
  (the ground truth for synchronized data parallelism).
"""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepfm_tpu.config import Config
from deepfm_tpu.parallel import mesh as mesh_lib
from deepfm_tpu.train import Trainer
from deepfm_tpu.train.loop import _StagingRing, _staged_records

# 2x2 virtual topology over the first 4 of conftest's 8 devices: rows
# {0,1} and {2,3} play "hosts", stage 2 reduces one representative per
# "host" ({0,2} and {1,3}).
HIER_GROUPS = ([[0, 1], [2, 3]], [[0, 2], [1, 3]])


def _cfg(**kw):
    base = dict(
        feature_size=500, field_size=6, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=64,
        compute_dtype="float32", l2_reg=1e-4, learning_rate=0.01,
        shuffle_buffer=500, log_steps=0, seed=11,
        scale_lr_by_world=False, mesh_data=1, mesh_model=1,
    )
    base.update(kw)
    return Config(**base)


def _batches(n, bs, fields=6, seed=3, feature_size=500):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append({
            "feat_ids": rng.randint(
                0, feature_size, (bs, fields)).astype(np.int32),
            "feat_vals": rng.rand(bs, fields).astype(np.float32),
            "label": (rng.rand(bs, 1) < 0.3).astype(np.float32),
        })
    return out


def _leaves(state):
    return jax.tree.leaves(jax.tree.map(np.asarray, state.params))


def _fit(cfg, batches, **kw):
    tr = Trainer(cfg)
    state = tr.init_state()
    state, out = tr.fit(state, iter(batches), **kw)
    return tr, state, out


class TestGradAccumParity:
    """k microbatches + one apply == one big-batch step (k*B examples)."""

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_dense(self, k):
        micro = _batches(4, 64)
        _, st_a, out_a = _fit(
            _cfg(grad_accum_steps=k, steps_per_loop=4, transfer_ahead=0),
            micro)
        assert out_a["steps"] == 4
        big = [{key: np.concatenate([m[key] for m in micro[i:i + k]])
                for key in micro[0]} for i in range(0, 4, k)]
        _, st_b, _ = _fit(
            _cfg(batch_size=64 * k, steps_per_loop=4 // k,
                 transfer_ahead=0), big)
        # state.step counts microbatches on both sides (resume invariant).
        assert int(st_a.step) == 4
        for la, lb in zip(_leaves(st_a), _leaves(st_b)):
            if k == 1:
                # a==1 compiles the seed program: bit-identical.
                np.testing.assert_array_equal(la, lb)
            else:
                np.testing.assert_allclose(la, lb, rtol=2e-5, atol=1e-6)

    @pytest.mark.embedding
    @pytest.mark.parametrize("k", [2, 4])
    def test_sparse(self, k):
        micro = _batches(4, 64)
        tr_a, st_a, _ = _fit(
            _cfg(grad_accum_steps=k, steps_per_loop=4, transfer_ahead=0,
                 embedding_update="sparse"), micro)
        # Adam count semantics: one optimizer apply per k microbatches.
        assert int(st_a.opt_state["count"]) == 4 // k
        big = [{key: np.concatenate([m[key] for m in micro[i:i + k]])
                for key in micro[0]} for i in range(0, 4, k)]
        _, st_b, _ = _fit(
            _cfg(batch_size=64 * k, steps_per_loop=4 // k,
                 transfer_ahead=0, embedding_update="sparse"), big)
        for la, lb in zip(_leaves(st_a), _leaves(st_b)):
            np.testing.assert_allclose(la, lb, rtol=2e-5, atol=1e-6)

    @pytest.mark.parametrize("k", [1, 2])
    def test_history_model(self, k):
        # Accumulation composes with sequence models: the scanned
        # microbatch body forwards hist_ids/hist_mask like any other
        # column (no exclusion), and k microbatches still equal the
        # concatenated big batch on a DIN trajectory.
        hist = 4
        cfg_kw = dict(model="din", history_max_len=hist, field_size=5,
                      feature_size=100, deep_layers="8,4",
                      transfer_ahead=0)
        rng = np.random.default_rng(7)
        micro = []
        for _ in range(4):
            lens = rng.integers(1, hist + 1, size=64)
            micro.append({
                "feat_ids": rng.integers(
                    0, 100, size=(64, 5)).astype(np.int32),
                "feat_vals": rng.normal(size=(64, 5)).astype(np.float32),
                "label": (rng.random((64, 1)) < 0.3).astype(np.float32),
                "hist_ids": rng.integers(
                    1, 100, size=(64, hist)).astype(np.int32),
                "hist_mask": (np.arange(hist)[None, :]
                              < lens[:, None]).astype(np.float32),
            })
        _, st_a, out_a = _fit(
            _cfg(grad_accum_steps=k, steps_per_loop=4, **cfg_kw), micro)
        assert out_a["steps"] == 4 and np.isfinite(out_a["loss"])
        big = [{key: np.concatenate([m[key] for m in micro[i:i + k]])
                for key in micro[0]} for i in range(0, 4, k)]
        _, st_b, _ = _fit(
            _cfg(batch_size=64 * k, steps_per_loop=4 // k, **cfg_kw), big)
        for la, lb in zip(_leaves(st_a), _leaves(st_b)):
            if k == 1:
                np.testing.assert_array_equal(la, lb)
            else:
                # atol covers the attention output bias: its gradient is
                # ~0 so Adam's m/sqrt(v) amplifies reassociation noise on
                # a ~4e-5 value; every other leaf matches to <4e-8.
                np.testing.assert_allclose(la, lb, rtol=2e-5, atol=5e-5)

    def test_two_virtual_device_smoke(self):
        # Fast tier-1 smoke: accumulation under a 2-device data mesh —
        # scanned microbatches, one collective apply per pair, bookkeeping
        # surfaced through fit's output.
        _, st, out = _fit(
            _cfg(mesh_data=2, grad_accum_steps=2, steps_per_loop=4),
            _batches(4, 64))
        assert out["steps"] == 4 and int(st.step) == 4
        assert np.isfinite(out["loss"])
        assert out["collective_applies"] == 2.0
        assert out["collective_bytes"] > 0
        assert out["collective_strategy"] == "flat"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            _cfg(grad_accum_steps=3, steps_per_loop=4)
        with pytest.raises(ValueError):
            _cfg(grad_accum_steps=0)
        with pytest.raises(ValueError):
            _cfg(staging_buffers=3)


class TestDoubleBufferedStaging:
    def test_bit_identity_across_slot_counts(self):
        outs = {}
        for buffers in (1, 2):
            outs[buffers] = _fit(
                _cfg(staging_buffers=buffers, steps_per_loop=2,
                     transfer_ahead=2), _batches(6, 64))
        s1, o1 = outs[1][1], outs[1][2]
        s2, o2 = outs[2][1], outs[2][2]
        for la, lb in zip(_leaves(s1), _leaves(s2)):
            np.testing.assert_array_equal(la, lb)
        for o in (o1, o2):
            assert 0.0 <= o["staging_overlap_fraction"] <= 1.0
            assert o["staging_transfer_s"] >= 0.0
            assert o["staging_wait_s"] >= 0.0

    def test_ring_fences_and_instrumentation(self):
        ring = _StagingRing(2)
        for i in range(4):
            assert ring.put(lambda i=i: i) == i
            ring.retire(jnp.zeros(()))
        ring.close()
        assert 0.0 <= ring.overlap_fraction() <= 1.0
        assert ring.transfer_s >= 0.0 and ring.wait_s >= 0.0
        # An untouched ring reports full overlap (nothing ever fenced).
        assert _StagingRing(1).overlap_fraction() == 1.0

    def test_staged_records(self):
        b = _batches(1, 16)[0]
        assert _staged_records((b,)) == 16
        assert _staged_records(([b, b],)) == 32
        assert _staged_records((np.zeros(3), 2)) == 0


class TestHierarchicalReduction:
    def test_psum_equals_flat(self):
        # Two-stage grouped psum == flat psum on the 2x2 virtual mesh.
        # Same terms, reassociated by group — XLA compiles the two
        # programs with different reduction orders, so equality is to
        # 1-2 ULP, not bitwise (the same environmental property the
        # mesh_bitexact probe gates).
        devs = np.asarray(jax.devices()[:4]).reshape(4, 1)
        mesh = Mesh(devs, ("data", "model"))
        rng = np.random.RandomState(0)
        tree = {"a": rng.standard_normal((4, 32)).astype(np.float32),
                "b": rng.standard_normal((4, 7, 3)).astype(np.float32)}

        from jax.experimental.shard_map import shard_map

        def flat(t):
            return jax.tree.map(
                lambda x: jax.lax.psum(x, "data"), t)

        def hier(t):
            return mesh_lib.hierarchical_psum(t, "data", HIER_GROUPS)

        specs = jax.tree.map(lambda _: P("data"), tree)
        kw = dict(mesh=mesh, in_specs=(specs,), out_specs=specs,
                  check_rep=False)
        out_f = jax.jit(shard_map(flat, **kw))(tree)
        out_h = jax.jit(shard_map(hier, **kw))(tree)
        for a, b in zip(jax.tree.leaves(out_f), jax.tree.leaves(out_h)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)

    def test_host_groups_single_host_is_none(self):
        # Auto-detect must stay off on a single host: the two-stage
        # program only pays off across a real DCN boundary.
        tr = Trainer(_cfg(mesh_data=4))
        assert mesh_lib.data_axis_host_groups(tr.mesh_info) is None
        assert tr._hier_groups is None

    def test_trainer_hier_keeps_devices_synchronized(self):
        # The property the two-stage reduce actually guarantees: after the
        # explicit grouped psums, every device applies the SAME gradient,
        # so the "replicated" params stay bit-identical across devices.
        tr = Trainer(_cfg(mesh_data=4))
        tr._hier_groups = HIER_GROUPS  # test seam: force the 2x2 program
        st = tr.init_state()
        st, out = tr.fit(st, iter(_batches(6, 64)), max_steps=6)
        assert out["collective_strategy"] == "hierarchical"
        for name in ("fm_w", "fm_v", "fm_b"):
            shards = [np.asarray(s.data)
                      for s in st.params[name].addressable_shards]
            assert len(shards) == 4
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s)

    def test_trainer_hier_matches_single_device(self):
        # Single-device training is the ground truth for synchronized
        # data parallelism; the hierarchical path must track it within
        # reassociation tolerance (mean-of-per-shard-means vs flat mean).
        tr_h = Trainer(_cfg(mesh_data=4))
        tr_h._hier_groups = HIER_GROUPS
        st_h = tr_h.init_state()
        st_h, out_h = tr_h.fit(st_h, iter(_batches(6, 64)), max_steps=6)

        _, st_1, _ = _fit(_cfg(), _batches(6, 64), max_steps=6)
        for la, lb in zip(_leaves(st_h), _leaves(st_1)):
            np.testing.assert_allclose(la, lb, rtol=5e-3, atol=2e-4)

    @pytest.mark.mesh_bitexact
    def test_trainer_hier_matches_flat_mesh(self):
        # On backends whose mesh numerics are bit-stable (probe-gated),
        # the flat psum path and the two-stage path are the same sum
        # reassociated — trajectories must agree within tolerance.
        tr_h = Trainer(_cfg(mesh_data=4))
        tr_h._hier_groups = HIER_GROUPS
        st_h = tr_h.init_state()
        st_h, _ = tr_h.fit(st_h, iter(_batches(6, 64)), max_steps=6)

        _, st_f, out_f = _fit(_cfg(mesh_data=4), _batches(6, 64),
                              max_steps=6)
        assert out_f["collective_strategy"] == "flat"
        for la, lb in zip(_leaves(st_h), _leaves(st_f)):
            np.testing.assert_allclose(la, lb, rtol=5e-3, atol=2e-4)

    def test_collective_bytes_strategy_invariant(self):
        # The payload is a property of the model + mesh, not of the
        # reduction schedule: flat and hierarchical runs report the same
        # bytes for the same number of applies.
        _, _, out_f = _fit(_cfg(mesh_data=4), _batches(4, 64), max_steps=4)
        tr_h = Trainer(_cfg(mesh_data=4))
        tr_h._hier_groups = HIER_GROUPS
        st_h = tr_h.init_state()
        st_h, out_h = tr_h.fit(st_h, iter(_batches(4, 64)), max_steps=4)
        assert out_f["collective_bytes"] == out_h["collective_bytes"] > 0
        assert out_f["collective_applies"] == out_h["collective_applies"]

    def test_grad_payload_bytes_model_sharding(self):
        params = {"emb_w": jnp.zeros((100, 8), jnp.float32),
                  "tower": {"w": jnp.zeros((48, 16), jnp.float32)}}
        full = mesh_lib.grad_payload_bytes(params, ("emb_w",), model_size=1)
        half = mesh_lib.grad_payload_bytes(params, ("emb_w",), model_size=2)
        assert full == 100 * 8 * 4 + 48 * 16 * 4
        assert half == 100 * 8 * 4 // 2 + 48 * 16 * 4


@pytest.mark.multichip
@pytest.mark.slow
class TestRealMultiprocess:
    def test_two_process_overlap_run(self, tmp_path):
        # Real 2-process jax.distributed rendezvous through the rewritten
        # bench harness (gated on the cross-process-collectives probe).
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts"))
        import bench_multiprocess as bmp

        from deepfm_tpu.data import libsvm
        data = str(tmp_path / "data")
        libsvm.generate_synthetic_ctr(
            data, num_files=2, examples_per_file=2048,
            feature_size=500, field_size=6, prefix="tr", seed=1)
        r = bmp.run_once(data, str(tmp_path / "model"), staging_buffers=2,
                         epochs=1, n_devices=1, multiprocess=True)
        assert float(r["examples_per_sec"]) > 0


class TestScalingEfficiencyRefusal:
    def test_refused_off_real_devices(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts"))
        import bench_multiprocess as bmp

        row = bmp.scaling_efficiency_row(bmp.TIMESLICE, 2, 100.0, 60.0)
        assert row["scaling_efficiency"] is None
        assert "refused" in row["scaling_efficiency_reason"]
        row = bmp.scaling_efficiency_row(bmp.REAL, 2, 100.0, 60.0)
        assert row["scaling_efficiency"] == round(100.0 / 120.0, 4)

    def test_mfu_basis_labels(self):
        from deepfm_tpu.utils import mfu as mfu_lib
        peak, kind, basis = mfu_lib.device_peak_flops()
        # conftest pins the CPU backend: the nominal labeled estimate.
        assert basis == mfu_lib.BASIS_NOMINAL
        assert peak == mfu_lib.NOMINAL_CPU_PEAK_FLOPS
        pct, basis2, _ = mfu_lib.mfu_pct(1e6, 1e4)
        assert basis2 == basis
        assert pct == pytest.approx(100.0 * 1e6 * 1e4 / peak, rel=1e-6)
