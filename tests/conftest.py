"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU-native analog of the reference's local-cluster escape hatch
(`set_dist_env()`, 1-ps-cpu/...py:294-339): distributed semantics are tested
on one machine by splitting the host CPU into 8 XLA devices.

Note: the environment's sitecustomize eagerly registers the TPU backend, so
the env var alone is not enough — jax.config must be updated post-import
(before any CPU client exists) for the override to stick.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # tests never target the real TPU
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {jax.devices()}")
