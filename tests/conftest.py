"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU-native analog of the reference's local-cluster escape hatch
(`set_dist_env()`, 1-ps-cpu/...py:294-339): distributed semantics are tested
on one machine by splitting the host CPU into 8 XLA devices.

The provisioning recipe (XLA_FLAGS + JAX_PLATFORMS + post-import
jax.config.update — env vars alone are not enough because the environment's
sitecustomize eagerly registers the TPU backend) lives in ONE place:
``__graft_entry__._provision_virtual_devices``, shared with the driver's
multichip dry run.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_ENABLE_X64", "0")

from __graft_entry__ import _provision_virtual_devices  # noqa: E402

_provision_virtual_devices(8)

import jax  # noqa: E402


def pytest_configure(config):
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {jax.devices()}")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection robustness tests (CPU-only, injected "
        "clock/sleep — no real backoff sleeps)")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "input_service: multi-process shared-memory input service tests "
        "(slab ring protocol in-process; worker-fleet tests spawn real "
        "processes)")
