"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU-native analog of the reference's local-cluster escape hatch
(`set_dist_env()`, 1-ps-cpu/...py:294-339): distributed semantics are tested
on one machine by splitting the host CPU into 8 XLA devices.

The provisioning recipe (XLA_FLAGS + JAX_PLATFORMS + post-import
jax.config.update — env vars alone are not enough because the environment's
sitecustomize eagerly registers the TPU backend) lives in ONE place:
``__graft_entry__._provision_virtual_devices``, shared with the driver's
multichip dry run.
"""
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_ENABLE_X64", "0")

from __graft_entry__ import _provision_virtual_devices  # noqa: E402

_provision_virtual_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {jax.devices()}")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection robustness tests (CPU-only, injected "
        "clock/sleep — no real backoff sleeps)")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "input_service: multi-process shared-memory input service tests "
        "(slab ring protocol in-process; worker-fleet tests spawn real "
        "processes)")
    config.addinivalue_line(
        "markers",
        "device_dataset: device-resident dataset mode (full decoded epoch "
        "uploaded to device memory, on-device shuffle + batch gather)")
    config.addinivalue_line(
        "markers",
        "mesh_bitexact: requires the CPU backend to produce bit-stable "
        "numerics across mesh program variants (sharded vs single-device, "
        "scanned vs sequential); skipped when the environment's XLA drifts")
    config.addinivalue_line(
        "markers",
        "mp_collectives: requires cross-process collectives on the CPU "
        "backend (2+ jax processes); skipped when jaxlib lacks them")
    config.addinivalue_line(
        "markers",
        "multichip: real multi-process scaling/overlap runs (2 OS "
        "processes in a jax.distributed rendezvous); gated on the same "
        "cross-process-collectives probe as mp_collectives")
    config.addinivalue_line(
        "markers",
        "preempt: preemption/self-healing runtime tests (signal-driven "
        "checkpointing, NaN guard policies, stall watchdogs, supervisor)")
    config.addinivalue_line(
        "markers",
        "serving: serving-runtime tests (dynamic batcher, bucketed predict, "
        "hot swap, shared-memory frontend)")
    config.addinivalue_line(
        "markers",
        "embedding: embedding-scale tests (sparse touched-row updates, "
        "hash-bucketed multi-tables, hot/cold tiering); gated on the "
        "backend's scatter-add path being run-to-run deterministic")
    config.addinivalue_line(
        "markers",
        "production: closed-loop production-day drill tests (serve->log->"
        "join->train->publish feedback loop, chaos schedule, staleness/"
        "skew/loss gates); the full multi-process drill is also slow")
    config.addinivalue_line(
        "markers",
        "overload: overload-plane tests (SLO-aware admission/shedding, "
        "request hedging, degradation ladder, Zipf flood traffic); the "
        "full flood sweep is also slow")
    config.addinivalue_line(
        "markers",
        "pallas: embedding-plane Pallas kernel tests (device-side plan "
        "build, fused gather/segment-sum backward, fused cache install) "
        "run through the Pallas interpreter on CPU; gated on interpret "
        "mode working in this jax build")
    config.addinivalue_line(
        "markers",
        "shard: row-sharded embedding tests (--embedding_shard rows: "
        "all-to-all row exchange, sharded lazy-Adam, resharding "
        "checkpoints) that compare mesh vs single-device trajectories; "
        "gated on the mesh_bitexact probe")
    config.addinivalue_line(
        "markers",
        "experiment: gated-deployment plane tests (hash-split A/B/shadow/"
        "canary routing, shadow-lane isolation, promotion controller, "
        "pointer-history audit sidecar, experimentation drill); the "
        "full-parameter drill is also slow")
    config.addinivalue_line(
        "markers",
        "cache: serving fast-path tests (version-keyed result cache, "
        "in-flight coalescing, fused cascade program, repeat-flood "
        "smoke)")


# ---------------------------------------------------------------------------
# Environment capability probes.
#
# Two classes of tier-1 test depend on properties of the *environment* (the
# installed jax/jaxlib/XLA build), not of this repo's code:
#
#  1. Bit-exact mesh parity: the distributed-parity and scanned-dispatch
#     suites assert that the same seeded training step gives identical
#     numerics on an 8-device mesh and on a single device. Some XLA CPU
#     builds reassociate reductions differently per program shape; a ~1-ULP
#     gradient drift flips the sign of Adam's first update on near-zero
#     gradient elements and the trajectories diverge. That is an
#     environmental property — probed here with one real training step.
#
#  2. CPU cross-process collectives: the multi-process tests spawn real
#     2-process jax.distributed clusters on the CPU backend. Some jaxlib
#     builds raise "Multiprocess computations aren't implemented on the CPU
#     backend" on the first collective. Probed with a minimal 2-process
#     broadcast that uses no repo code.
#
# Each probe runs at most once per session, only if a gated test was
# collected. A probe that *crashes* is treated as "capability present" so
# genuine code bugs still surface as failures rather than skips.
# ---------------------------------------------------------------------------

_UNSET = object()
_MESH_BITEXACT_REASON = _UNSET
_MP_COLLECTIVES_REASON = _UNSET
_EMBEDDING_REASON = _UNSET
_PALLAS_REASON = _UNSET


def _probe_pallas_interpret():
    """None if a minimal pallas_call runs under the interpreter on this
    backend, else a skip reason. Unlike the other probes this one catches
    its own exceptions: a crashing interpreter IS the missing capability."""
    try:
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import pallas as pl

        def k(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        out = pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((4,), jnp.float32),
            interpret=True)(jnp.zeros((4,), jnp.float32))
        if not np.array_equal(np.asarray(out), np.ones((4,), np.float32)):
            return "environment: pallas interpret mode returns wrong values"
    except Exception as exc:  # noqa: BLE001
        return ("environment: pallas interpret mode unavailable "
                f"({type(exc).__name__}: {str(exc)[:120]})")
    return None


def _probe_mesh_bitexact():
    """None if mesh-vs-single numerics are bit-stable, else a skip reason."""
    import numpy as np
    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    def _run(**mesh_kw):
        cfg = Config(
            feature_size=500, field_size=6, embedding_size=8,
            deep_layers="16,8", dropout="1.0,1.0", batch_size=64,
            compute_dtype="float32", l2_reg=1e-4, learning_rate=0.01,
            log_steps=0, seed=11, scale_lr_by_world=False, **mesh_kw)
        rng = np.random.default_rng(0)
        batch = {
            "label": rng.integers(0, 2, (64, 1)).astype(np.float32),
            "feat_ids": rng.integers(0, 500, (64, 6)).astype(np.int32),
            "feat_vals": rng.standard_normal((64, 6)).astype(np.float32),
        }
        tr = Trainer(cfg)
        state = tr.init_state()
        step = tr._make_train_step()
        for _ in range(2):
            state, _ = step(state, tr.put_batch(batch))
        return state

    s1 = _run(mesh_data=1, mesh_model=1)
    s8 = _run(mesh_data=8, mesh_model=1)
    drift = max(
        float(np.abs(np.asarray(s1.params[k]) - np.asarray(s8.params[k])).max())
        for k in ("fm_b", "fm_w", "fm_v"))
    if drift > 1e-6:
        return (
            "environment: XLA CPU mesh numerics are not bit-stable vs "
            f"single-device (2-step probe drift {drift:.2e}); bit-exact "
            "mesh parity is unachievable in this jax/jaxlib build")
    return None


_MP_PROBE = """
import sys
rank = int(sys.argv[1]); port = sys.argv[2]
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{port}", 2, rank)
import numpy as np
from jax.experimental import multihost_utils
out = multihost_utils.broadcast_one_to_all(np.ones((), np.float32))
assert float(out) == 1.0, out
"""


def _probe_mp_collectives():
    """None if 2-process CPU collectives work, else a skip reason."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # no virtual-device split in the probe procs
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MP_PROBE, str(r), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for r in range(2)
    ]
    errs = []
    for p in procs:
        try:
            _, err = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            _, err = p.communicate()
        if p.returncode != 0:
            errs.append(err.strip().splitlines()[-1] if err.strip() else
                        f"exit code {p.returncode}")
    if errs:
        return (
            "environment: CPU backend lacks cross-process collectives "
            f"(2-process probe failed: {errs[0][:160]})")
    return None


def _probe_embedding_sparse():
    """None if the sparse-update path (unique + scatter-add segment sums)
    is run-to-run deterministic on this backend, else a skip reason. The
    embedding suites assert bit-exact trajectories (touch-set exactness,
    multi-step dispatch parity, tiered-vs-flat parity); a backend whose
    scatter-add reassociates nondeterministically can't satisfy them."""
    import numpy as np
    from deepfm_tpu.config import Config
    from deepfm_tpu.train import Trainer

    def _run():
        cfg = Config(
            feature_size=200, field_size=4, embedding_size=4,
            deep_layers="8", dropout="1.0", batch_size=32,
            compute_dtype="float32", l2_reg=1e-4, learning_rate=0.01,
            log_steps=0, seed=7, scale_lr_by_world=False,
            mesh_data=1, mesh_model=1, steps_per_loop=1,
            embedding_update="sparse")
        rng = np.random.default_rng(5)
        batches = [{
            "label": rng.integers(0, 2, (32,)).astype(np.float32),
            "feat_ids": rng.integers(0, 200, (32, 4)).astype(np.int32),
            "feat_vals": rng.standard_normal((32, 4)).astype(np.float32),
        } for _ in range(2)]
        tr = Trainer(cfg)
        state = tr.init_state()
        state, _ = tr.fit(state, batches)
        return state

    s1, s2 = _run(), _run()
    drift = max(
        float(np.abs(np.asarray(s1.params[k]) - np.asarray(s2.params[k])).max())
        for k in ("fm_w", "fm_v"))
    if drift != 0.0:
        return (
            "environment: sparse embedding scatter-add is not run-to-run "
            f"deterministic on this backend (2-step probe drift {drift:.2e})")
    return None


def _cached_reason(cache_name, probe):
    reason = globals()[cache_name]
    if reason is _UNSET:
        try:
            reason = probe()
        except Exception:
            reason = None  # probe broke: let the real tests run and report
        globals()[cache_name] = reason
    return reason


def pytest_collection_modifyitems(config, items):
    probes = (
        ("mesh_bitexact", "_MESH_BITEXACT_REASON", _probe_mesh_bitexact),
        # row-sharding parity shares the mesh-bitexact probe (and its
        # cached reason): both compare mesh trajectories to single-device.
        ("shard", "_MESH_BITEXACT_REASON", _probe_mesh_bitexact),
        ("mp_collectives", "_MP_COLLECTIVES_REASON", _probe_mp_collectives),
        # multichip shares the mp_collectives probe (and its cached
        # reason): both need real 2-process collectives on this backend.
        ("multichip", "_MP_COLLECTIVES_REASON", _probe_mp_collectives),
        ("embedding", "_EMBEDDING_REASON", _probe_embedding_sparse),
        ("pallas", "_PALLAS_REASON", _probe_pallas_interpret),
    )
    for marker_name, cache_name, probe in probes:
        gated = [it for it in items if marker_name in it.keywords]
        if not gated:
            continue
        reason = _cached_reason(cache_name, probe)
        if reason is None:
            continue
        skip = pytest.mark.skip(reason=reason)
        for it in gated:
            it.add_marker(skip)
