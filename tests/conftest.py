"""Test configuration: force an 8-device virtual CPU platform BEFORE jax init.

This is the TPU-native analog of the reference's local-cluster escape hatch
(`set_dist_env()`, 1-ps-cpu/...py:294-339): distributed semantics are tested
on one machine by splitting the host CPU into 8 XLA devices.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
