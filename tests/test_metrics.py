"""Streaming AUC + mean metric tests against exact oracles."""

import jax
import jax.numpy as jnp
import numpy as np

from deepfm_tpu.train import metrics


def _data(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.3).astype(np.float32)
    # informative scores: positives skew high
    probs = np.clip(rng.normal(0.4 + 0.25 * labels, 0.2), 0.0, 1.0).astype(np.float32)
    return probs, labels


def test_binned_auc_close_to_exact():
    probs, labels = _data()
    st = metrics.auc_init(200)
    st = metrics.auc_update(st, jnp.asarray(probs), jnp.asarray(labels))
    got = float(metrics.auc_compute(st))
    want = metrics.auc_numpy_reference(probs, labels)
    assert abs(got - want) < 0.005, (got, want)


def test_auc_matches_sklearn_if_available():
    try:
        from sklearn.metrics import roc_auc_score
    except ImportError:
        return
    probs, labels = _data(seed=3)
    want = roc_auc_score(labels, probs)
    st = metrics.auc_update(metrics.auc_init(400), jnp.asarray(probs), jnp.asarray(labels))
    assert abs(float(metrics.auc_compute(st)) - want) < 0.005
    assert abs(metrics.auc_numpy_reference(probs, labels) - want) < 1e-9


def test_streaming_equals_single_shot():
    probs, labels = _data(seed=1)
    st_all = metrics.auc_update(metrics.auc_init(200), jnp.asarray(probs), jnp.asarray(labels))
    st_stream = metrics.auc_init(200)
    for i in range(0, len(probs), 100):
        st_stream = metrics.auc_update(
            st_stream, jnp.asarray(probs[i:i+100]), jnp.asarray(labels[i:i+100]))
    np.testing.assert_allclose(np.asarray(st_all.pos), np.asarray(st_stream.pos))
    np.testing.assert_allclose(
        float(metrics.auc_compute(st_all)), float(metrics.auc_compute(st_stream)))


def test_merge_is_additive():
    p1, l1 = _data(seed=4)
    p2, l2 = _data(seed=5)
    a = metrics.auc_update(metrics.auc_init(100), jnp.asarray(p1), jnp.asarray(l1))
    b = metrics.auc_update(metrics.auc_init(100), jnp.asarray(p2), jnp.asarray(l2))
    merged = metrics.auc_merge(a, b)
    both = metrics.auc_update(a, jnp.asarray(p2), jnp.asarray(l2))
    np.testing.assert_allclose(np.asarray(merged.pos), np.asarray(both.pos))
    np.testing.assert_allclose(np.asarray(merged.neg), np.asarray(both.neg))


def test_degenerate_single_class_is_nan():
    # All-positive (or all-negative) windows have no defined ranking metric:
    # NaN, not a fake 0.5/0.0 that dashboards would average into real AUC.
    st = metrics.auc_update(
        metrics.auc_init(50), jnp.asarray([0.2, 0.8]), jnp.asarray([1.0, 1.0]))
    assert np.isnan(float(metrics.auc_compute(st)))
    st = metrics.auc_update(
        metrics.auc_init(50), jnp.asarray([0.2, 0.8]), jnp.asarray([0.0, 0.0]))
    assert np.isnan(float(metrics.auc_compute(st)))
    assert np.isnan(metrics.auc_numpy_reference(
        np.array([0.2, 0.8]), np.array([1.0, 1.0])))


def test_perfect_separation_is_one():
    probs = np.array([0.1] * 50 + [0.9] * 50, np.float32)
    labels = np.array([0.0] * 50 + [1.0] * 50, np.float32)
    st = metrics.auc_update(metrics.auc_init(200), jnp.asarray(probs), jnp.asarray(labels))
    assert float(metrics.auc_compute(st)) > 0.999


def test_mean_state():
    st = metrics.mean_init()
    st = metrics.mean_update(st, jnp.float32(2.0), 10.0)
    st = metrics.mean_update(st, jnp.float32(4.0), 30.0)
    np.testing.assert_allclose(float(metrics.mean_compute(st)), 3.5)


def test_auc_update_jittable():
    probs, labels = _data(seed=6)
    f = jax.jit(metrics.auc_update)
    st = f(metrics.auc_init(200), jnp.asarray(probs), jnp.asarray(labels))
    want = metrics.auc_numpy_reference(probs, labels)
    assert abs(float(metrics.auc_compute(st)) - want) < 0.01


class TestWindowedAuc:
    """Sliding-window streaming AUC for online eval: slices tagged with the
    training step, evicted once older than the window."""

    def test_single_slice_matches_cumulative(self):
        probs, labels = _data(seed=10)
        w = metrics.WindowedAuc(window_steps=100, num_bins=200)
        w.update(1, probs, labels)
        st = metrics.auc_update(
            metrics.auc_init(200), jnp.asarray(probs), jnp.asarray(labels))
        assert abs(w.compute() - float(metrics.auc_compute(st))) < 1e-6
        assert w.examples == len(probs)

    def test_eviction_drops_stale_slices(self):
        # Slice at step 1 is garbage (inverted scores); the window must
        # forget it once the stream moves window_steps past it.
        probs, labels = _data(seed=11)
        w = metrics.WindowedAuc(window_steps=10, num_bins=200)
        w.update(1, 1.0 - probs, labels)   # anti-predictive slice
        auc_poisoned = w.compute()
        assert auc_poisoned < 0.5
        w.update(12, probs, labels)        # step 1 <= 12 - 10: evicted
        want = metrics.auc_numpy_reference(probs, labels)
        assert abs(w.compute() - want) < 0.01
        assert w.examples == len(probs)    # only the live slice remains

    def test_window_keeps_recent_slices(self):
        probs, labels = _data(seed=12)
        half = len(probs) // 2
        w = metrics.WindowedAuc(window_steps=100, num_bins=200)
        w.update(1, probs[:half], labels[:half])
        w.update(50, probs[half:], labels[half:])  # still inside the window
        want = metrics.auc_numpy_reference(probs, labels)
        assert abs(w.compute() - want) < 0.01
        assert w.examples == len(probs)

    def test_empty_window_is_nan(self):
        w = metrics.WindowedAuc(window_steps=10)
        assert np.isnan(w.compute()) and w.examples == 0

    def test_one_class_window_is_nan(self):
        w = metrics.WindowedAuc(window_steps=10)
        w.update(1, np.array([0.2, 0.8]), np.array([1.0, 1.0]))
        assert np.isnan(w.compute()) and w.examples == 2


class TestWindowedAucDict:
    """Per-task dict of sliding windows for multitask online eval."""

    def test_per_task_matches_numpy_reference(self):
        p1, l1 = _data(seed=20)
        p2, l2 = _data(seed=21)
        w = metrics.WindowedAucDict(("ctr", "cvr"), window_steps=100,
                                    num_bins=400)
        w.update(1, np.stack([p1, p2], axis=1), np.stack([l1, l2], axis=1))
        got = w.compute()
        assert set(got) == {"ctr", "cvr"}
        assert abs(got["ctr"] - metrics.auc_numpy_reference(p1, l1)) < 0.005
        assert abs(got["cvr"] - metrics.auc_numpy_reference(p2, l2)) < 0.005
        assert w.examples == len(p1)

    def test_single_column_update_broadcasts(self):
        probs, labels = _data(seed=22)
        w = metrics.WindowedAucDict(("ctr",), window_steps=100, num_bins=200)
        w.update(1, probs, labels)  # 1-D accepted for a single task
        ref = metrics.WindowedAuc(window_steps=100, num_bins=200)
        ref.update(1, probs, labels)
        assert abs(w.compute()["ctr"] - ref.compute()) < 1e-12

    def test_degenerate_task_is_nan_others_fine(self):
        p1, l1 = _data(seed=23)
        w = metrics.WindowedAucDict(("ctr", "cvr"), window_steps=100,
                                    num_bins=200)
        # cvr column: all-zero labels (no conversion in the window).
        w.update(1, np.stack([p1, p1], axis=1),
                 np.stack([l1, np.zeros_like(l1)], axis=1))
        got = w.compute()
        assert not np.isnan(got["ctr"])
        assert np.isnan(got["cvr"])

    def test_empty_windows_are_nan(self):
        w = metrics.WindowedAucDict(("ctr", "cvr"), window_steps=10)
        got = w.compute()
        assert np.isnan(got["ctr"]) and np.isnan(got["cvr"])
        assert w.examples == 0
