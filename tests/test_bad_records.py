"""Bad-record policy tests: corruption handling parity across decoder paths.

The hard requirement (see utils/retry.py module docs): the native C++ framer
and the pure-Python framer must make IDENTICAL policy decisions — same
surviving records, same DataHealth counts, same error text — for every
corruption class: flipped data CRC (skip one record, keep framing), flipped
length CRC (cannot resync → discard file tail), and a truncated tail.
Transient mid-file read errors must heal to clean-run-identical output.

All tests are CPU-only and sleep-free (zero-backoff RetryPolicy).
"""

import os
import struct

import pytest

from deepfm_tpu.data import libsvm, pipeline, tfrecord
from deepfm_tpu.data.health import BadRecordPolicy, DataHealth
from deepfm_tpu.utils import faults
from deepfm_tpu.utils import retry as retry_lib

pytestmark = pytest.mark.faults

NATIVE = [
    pytest.param(False, id="python"),
    pytest.param(True, id="native", marks=pytest.mark.skipif(
        not pipeline._native_loader(), reason="native loader unavailable")),
]

NO_SLEEP = retry_lib.RetryPolicy(base_delay=0.0, max_delay=0.0)


@pytest.fixture
def data_dir(tmp_path):
    libsvm.generate_synthetic_ctr(
        str(tmp_path), num_files=2, examples_per_file=40, feature_size=64,
        field_size=5, prefix="tr", seed=9)
    return tmp_path


def _files(data_dir):
    return sorted(str(p) for p in data_dir.glob("*.tfrecords"))


def _frames(path):
    """[(frame_start, payload_len), ...] by walking the length headers."""
    data = open(path, "rb").read()
    out, pos = [], 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        out.append((pos, length))
        pos += 16 + length
    return out


def _flip_byte(path, offset):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def _corrupt_data_crc(path, record_idx):
    """Flip a data-CRC byte: framing stays intact, that record is bad."""
    start, length = _frames(path)[record_idx]
    _flip_byte(path, start + 12 + length)
    return start


def _corrupt_length_crc(path, record_idx):
    """Flip a length-CRC byte: framing cannot resync past this record."""
    start, _ = _frames(path)[record_idx]
    _flip_byte(path, start + 8)
    return start


def _read(path, native, policy, retry_policy=NO_SLEEP):
    return list(pipeline._iter_file_records(
        path, native, True, policy=policy, retry_policy=retry_policy))


class TestFlippedDataCrc:
    @pytest.mark.parametrize("native", NATIVE)
    def test_raise_names_path_and_offset(self, data_dir, native):
        path = _files(data_dir)[0]
        offset = _corrupt_data_crc(path, 7)
        with pytest.raises(IOError) as ei:
            _read(path, native, BadRecordPolicy("raise"))
        msg = str(ei.value)
        assert path in msg and f"at byte {offset}" in msg
        assert "data CRC mismatch" in msg

    @pytest.mark.parametrize("native", NATIVE)
    def test_skip_drops_exactly_one_record(self, data_dir, native):
        path = _files(data_dir)[0]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        _corrupt_data_crc(path, 7)
        health = DataHealth()
        out = _read(path, native, BadRecordPolicy("skip", 0, health))
        assert out == clean[:7] + clean[8:]
        snap = health.snapshot()
        assert snap["bad_records"] == 1
        assert snap["truncated_tails"] == 0
        assert snap["per_file"][path]["skipped"] == 1

    def test_skip_parity_between_paths(self, data_dir):
        if not pipeline._native_loader():
            pytest.skip("native loader unavailable")
        path = _files(data_dir)[0]
        _corrupt_data_crc(path, 3)
        _corrupt_data_crc(path, 31)
        results = {}
        for native in (False, True):
            health = DataHealth()
            results[native] = (
                _read(path, native, BadRecordPolicy("skip", 0, health)),
                health.snapshot())
        recs_py, snap_py = results[False]
        recs_nat, snap_nat = results[True]
        assert recs_py == recs_nat
        assert snap_py == snap_nat  # identical counters AND per-file stats
        assert snap_py["bad_records"] == 2


class TestUnrecoverableFraming:
    @pytest.mark.parametrize("native", NATIVE)
    def test_truncated_tail_skip(self, data_dir, native):
        path = _files(data_dir)[0]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 9)  # cuts into the last record's frame
        health = DataHealth()
        out = _read(path, native, BadRecordPolicy("skip", 0, health))
        assert out == clean[:-1]
        snap = health.snapshot()
        assert snap["truncated_tails"] == 1
        assert snap["bad_records"] == 1

    @pytest.mark.parametrize("native", NATIVE)
    def test_truncated_tail_raise(self, data_dir, native):
        path = _files(data_dir)[0]
        last_start, _ = _frames(path)[-1]
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 9)
        with pytest.raises(IOError) as ei:
            _read(path, native, BadRecordPolicy("raise"))
        assert path in str(ei.value)
        assert f"at byte {last_start}" in str(ei.value)

    @pytest.mark.parametrize("native", NATIVE)
    def test_length_crc_discards_tail(self, data_dir, native):
        """A bad length CRC means the length itself is untrusted — framing
        cannot resync, so skip mode drops the rest of the file (counted as
        a truncated tail), not just one record."""
        path = _files(data_dir)[0]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        offset = _corrupt_length_crc(path, 35)
        health = DataHealth()
        out = _read(path, native, BadRecordPolicy("skip", 0, health))
        assert out == clean[:35]
        snap = health.snapshot()
        assert snap["truncated_tails"] == 1
        with pytest.raises(IOError, match=f"at byte {offset}"):
            _read(path, native, BadRecordPolicy("raise"))


class TestSkipBudget:
    @pytest.mark.parametrize("native", NATIVE)
    def test_budget_exceeded_raises(self, data_dir, native):
        path = _files(data_dir)[0]
        _corrupt_data_crc(path, 3)
        _corrupt_data_crc(path, 11)
        with pytest.raises(IOError, match="bad-record budget exceeded"):
            _read(path, native, BadRecordPolicy("skip", max_bad=1))

    @pytest.mark.parametrize("native", NATIVE)
    def test_budget_at_limit_ok(self, data_dir, native):
        path = _files(data_dir)[0]
        _corrupt_data_crc(path, 3)
        _corrupt_data_crc(path, 11)
        out = _read(path, native, BadRecordPolicy("skip", max_bad=2))
        assert len(out) == 38

    @pytest.mark.parametrize("native", NATIVE)
    def test_zero_budget_is_unlimited(self, data_dir, native):
        path = _files(data_dir)[0]
        for idx in (1, 5, 9, 13):
            _corrupt_data_crc(path, idx)
        out = _read(path, native, BadRecordPolicy("skip", max_bad=0))
        assert len(out) == 36


class TestTransientReadErrors:
    @pytest.mark.parametrize("native", NATIVE)
    def test_mid_file_fault_heals_to_clean_output(self, data_dir, native,
                                                  monkeypatch):
        path = _files(data_dir)[1]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        # Size-hinted reads pull a small file in ONE read call, so shrink
        # the chunk size to force genuinely mid-file read boundaries for
        # the every-Nth-read injector to land on.
        monkeypatch.setattr(pipeline, "_NATIVE_CHUNK_BYTES", 512)
        health = DataHealth()
        with faults.FlakyFS(read_fail_every=3) as fs:
            out = _read(path, native, BadRecordPolicy("raise", 0, health))
        assert out == clean  # healed: no records lost, none duplicated
        snap = health.snapshot()
        assert fs.injected_read_faults > 0
        assert snap["read_retries"] == fs.injected_read_faults
        assert snap["bad_records"] == 0
        assert snap["per_file"][path]["retries"] == fs.injected_read_faults

    @pytest.mark.parametrize("native", NATIVE)
    def test_fault_at_specific_offset_heals(self, data_dir, native):
        path = _files(data_dir)[1]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        mid_offset = _frames(path)[20][0]
        health = DataHealth()
        with faults.FlakyFS(
                read_fail_offsets=[(os.path.basename(path), mid_offset)]) as fs:
            out = _read(path, native, BadRecordPolicy("raise", 0, health))
        assert out == clean
        assert fs.injected_read_faults == 1
        assert health.snapshot()["read_retries"] == 1

    @pytest.mark.parametrize("native", NATIVE)
    def test_combined_transient_plus_corrupt(self, data_dir, native):
        """The drill scenario in miniature: transient faults heal AND the
        one corrupt record is skipped; the two fault classes are counted
        separately."""
        path = _files(data_dir)[1]
        clean = list(tfrecord.iter_records(path, verify_crc=True))
        _corrupt_data_crc(path, 20)
        health = DataHealth()
        # Cadence 2: the native path reads whole-file-sized chunks, so a
        # sparser cadence might never fire on a small test file.
        with faults.FlakyFS(read_fail_every=2) as fs:
            out = _read(path, native,
                        BadRecordPolicy("skip", 0, health))
        assert out == clean[:20] + clean[21:]
        snap = health.snapshot()
        assert snap["read_retries"] == fs.injected_read_faults > 0
        assert snap["bad_records"] == 1


class TestPipelineIntegration:
    @pytest.mark.parametrize("native", NATIVE)
    def test_ctr_pipeline_skips_and_reports(self, data_dir, native):
        files = _files(data_dir)
        _corrupt_data_crc(files[0], 7)

        def batches(file_list, **kw):
            p = pipeline.CtrPipeline(
                file_list, field_size=5, batch_size=16, shuffle=False,
                shuffle_files=False, drop_remainder=False, verify_crc=True,
                use_native_decoder=native, prefetch_batches=0,
                retry_policy=NO_SLEEP, **kw)
            return list(p), p.health.snapshot()

        out, snap = batches(files, on_bad_record="skip")
        total = sum(b["label"].shape[0] for b in out)
        assert total == 79  # 80 records minus the skipped one
        assert snap["bad_records"] == 1

        with pytest.raises(IOError, match="data CRC mismatch"):
            batches(files, on_bad_record="raise")

    @pytest.mark.parametrize("native", NATIVE)
    def test_streaming_pipeline_skips_and_reports(self, data_dir, native):
        files = _files(data_dir)
        _corrupt_data_crc(files[0], 7)
        stream = pipeline.ChainedFileStream(
            files, num_epochs=1, retry_policy=NO_SLEEP)
        p = pipeline.StreamingCtrPipeline(
            stream, field_size=5, batch_size=16, drop_remainder=False,
            verify_crc=True, use_native_decoder=native, prefetch_batches=0,
            on_bad_record="skip")
        total = sum(b["label"].shape[0] for b in p)
        assert total == 79
        assert p.health.snapshot()["bad_records"] == 1
