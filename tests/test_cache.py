"""Decoded-epoch cache tests: golden emission parity against the streamed
path, fingerprint staleness (file mtimes, bad-record policy), corrupted-slab
recovery through DataHealth, and the record-sharding guard."""

import os

import numpy as np
import pytest

from deepfm_tpu.data import cache as cache_lib
from deepfm_tpu.data import libsvm, pipeline, sharding

FIELD = 5
FEATURES = 200


@pytest.fixture()
def dataset(tmp_path):
    data = tmp_path / "data"
    libsvm.generate_synthetic_ctr(
        str(data), num_files=3, examples_per_file=60, field_size=FIELD,
        feature_size=FEATURES, seed=9, prefix="tr")
    return sorted(str(p) for p in data.glob("tr*.tfrecords"))


def _make_pipe(files, **kw):
    kw.setdefault("field_size", FIELD)
    kw.setdefault("batch_size", 16)
    kw.setdefault("num_epochs", 2)
    kw.setdefault("shuffle", True)
    kw.setdefault("shuffle_buffer", 1 << 20)  # whole-epoch pool
    kw.setdefault("seed", 13)
    kw.setdefault("drop_remainder", False)
    return pipeline.CtrPipeline(files, **kw)


def _emitted(pipe):
    """All emitted rows, concatenated in emission order."""
    batches = list(pipe)
    return {k: np.concatenate([b[k].reshape(b[k].shape[0], -1)
                               for b in batches]) for k in batches[0]}


class TestCacheGolden:
    def test_cached_emission_matches_streamed(self, dataset, tmp_path):
        """ram, disk-cold, and disk-warm epochs must emit the SAME rows in
        the SAME order as the uncached stream (whole-epoch pool: emission
        is one full permutation, independent of chunk arrival shape)."""
        cache_lib.clear_ram_cache()
        golden = _emitted(_make_pipe(dataset, decoded_cache="off"))
        ram = _emitted(_make_pipe(dataset, decoded_cache="ram"))
        cache_dir = str(tmp_path / "slabs")
        cold = _emitted(_make_pipe(dataset, decoded_cache="disk",
                                   decoded_cache_dir=cache_dir))
        warm = _emitted(_make_pipe(dataset, decoded_cache="disk",
                                   decoded_cache_dir=cache_dir))
        for name, got in (("ram", ram), ("disk-cold", cold),
                          ("disk-warm", warm)):
            for k in golden:
                np.testing.assert_array_equal(
                    golden[k], got[k], err_msg=f"{name}:{k}")
        # The warm pass really was served from an existing entry.
        entries = [d for d in os.listdir(cache_dir) if not d.startswith(".")]
        assert len(entries) == 1

    def test_columns_shape_and_counts(self, dataset):
        cache_lib.clear_ram_cache()
        pipe = _make_pipe(dataset, decoded_cache="ram")
        cols = pipe.decoded_epoch_columns()
        assert cols.num_records == 180
        assert cols.counts.tolist() == [60, 60, 60]
        assert cols.ids.shape == (180, FIELD)
        assert cols.labels.dtype == np.float32


class TestCacheFingerprint:
    def test_touched_file_forces_rebuild(self, dataset, tmp_path):
        cache_dir = str(tmp_path / "slabs")
        p1 = _make_pipe(dataset, decoded_cache="disk",
                        decoded_cache_dir=cache_dir)
        fp1 = p1.decoded_cache_fingerprint()
        p1.decoded_epoch_columns()
        # Same bytes, newer mtime: identity must change (conservative —
        # mtime is the cheap staleness signal, not content hashing).
        st = os.stat(dataset[0])
        os.utime(dataset[0], ns=(st.st_atime_ns, st.st_mtime_ns + 10**9))
        p2 = _make_pipe(dataset, decoded_cache="disk",
                        decoded_cache_dir=cache_dir)
        assert p2.decoded_cache_fingerprint() != fp1
        p2.decoded_epoch_columns()
        entries = [d for d in os.listdir(cache_dir) if not d.startswith(".")]
        assert sorted(entries) == sorted({fp1,
                                          p2.decoded_cache_fingerprint()})

    def test_bad_record_policy_in_identity(self, dataset):
        a = _make_pipe(dataset, decoded_cache="ram", on_bad_record="raise")
        b = _make_pipe(dataset, decoded_cache="ram", on_bad_record="skip")
        assert (a.decoded_cache_fingerprint()
                != b.decoded_cache_fingerprint())


class TestCacheCorruption:
    def test_corrupt_slab_counts_and_rebuilds(self, dataset, tmp_path):
        cache_dir = str(tmp_path / "slabs")
        p1 = _make_pipe(dataset, decoded_cache="disk",
                        decoded_cache_dir=cache_dir)
        golden = _emitted(p1)
        entry = os.path.join(cache_dir, p1.decoded_cache_fingerprint())
        slab = os.path.join(entry, "feat_ids.npy")
        with open(slab, "wb") as f:
            f.write(b"\x93NUMPYgarbage")
        p2 = _make_pipe(dataset, decoded_cache="disk",
                        decoded_cache_dir=cache_dir)
        with pytest.warns(RuntimeWarning, match="rebuilding from source"):
            got = _emitted(p2)
        for k in golden:
            np.testing.assert_array_equal(golden[k], got[k], err_msg=k)
        assert p2.health.snapshot()["bad_records"] >= 1
        # The rebuilt entry is valid again: a third pass loads clean.
        p3 = _make_pipe(dataset, decoded_cache="disk",
                        decoded_cache_dir=cache_dir)
        assert p3._make_cache().load() is not None


class TestCacheGuards:
    def test_record_sharding_disables_cache(self, dataset):
        spec = sharding.ShardSpec(tuple(dataset), record_shard=(2, 0))
        with pytest.warns(RuntimeWarning, match="record-level sharding"):
            pipe = _make_pipe(dataset, decoded_cache="ram", shard=spec)
        assert pipe.decoded_cache == "off"

    def test_disk_requires_dir(self, dataset):
        with pytest.raises(ValueError, match="cache dir"):
            _make_pipe(dataset, decoded_cache="disk").decoded_epoch_columns()
