"""Replica scale-out tests: sticky/spill routing, dead-replica re-route,
fleet-wide drain-on-close, staggered swap coordination, aggregate stats,
frontend client-affinity passthrough, and the tier-1 serving smoke (lane
p99 <= global p99 under a bypass-favoring load; bench serving series emits
every honesty-label field — a schema check, not a perf gate)."""

import threading
import time

import numpy as np
import pytest

from deepfm_tpu.data.shm_ring import THREAD_CTX
from deepfm_tpu.serve import (AdmissionShed, FrontendServer, ReplicatedEngine,
                              ServerOverloaded, ServingClient, ServingEngine,
                              ServingStats, aggregate_summary)
from deepfm_tpu.serve.replicas import HedgedFuture

pytestmark = pytest.mark.serving

FIELD_SIZE = 3


def _rows(n, base=0):
    ids = np.full((n, FIELD_SIZE), base, np.int32)
    vals = np.ones((n, FIELD_SIZE), np.float32)
    return ids, vals


def base_predict(feat_ids, feat_vals):
    return feat_ids[:, 0].astype(np.float32) + 0.5 * feat_vals[:, 0]


def _fleet(n=2, start=True, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 1)
    return ReplicatedEngine(
        [ServingEngine(base_predict, start=start, **kw) for _ in range(n)])


# ---------------------------------------------------------------------------
# Routing: sticky affinity, least-loaded spill, typed refusal
# ---------------------------------------------------------------------------

class TestRouting:
    def test_sticky_affinity_holds_across_reconnect(self):
        """The same affinity key lands on the same replica every time —
        including after a gap with other clients' traffic in between (a
        client that reconnects with its id keeps its replica)."""
        fleet = _fleet(3)
        try:
            for _ in range(4):
                fleet.predict(*_rows(2, base=1), timeout=10, affinity=7)
            before = list(fleet.routed)
            home = before.index(max(before))
            assert before[home] == 4 and sum(before) == 4
            # "Reconnect": other clients hammer (key 1 shares key 7's home
            # replica, 1 ≡ 7 mod 3), then key 7 returns — same replica.
            for other in (0, 1, 2, 5):
                fleet.predict(*_rows(1), timeout=10, affinity=other)
            fleet.predict(*_rows(2, base=1), timeout=10, affinity=7)
            assert fleet.routed[home] == before[home] + 2
        finally:
            fleet.close(timeout=10)

    def test_no_affinity_routes_least_loaded(self):
        fleet = _fleet(2, start=False)
        try:
            # Load replica 0 directly; the router must prefer replica 1.
            fleet.engines[0].submit(*_rows(6))
            fut = fleet.submit(*_rows(2))
            assert fleet.routed == [0, 1]
            assert fleet.engines[1].pending_rows == 2
            assert not fut.done()
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=10)

    def test_overloaded_sticky_replica_spills(self):
        fleet = _fleet(2, start=False, max_batch=4, queue_rows=4)
        try:
            # Fill affinity-0's home replica to its queue bound.
            fleet.submit(*_rows(4), affinity=0)
            fut = fleet.submit(*_rows(2), affinity=0)    # spills to 1
            assert fleet.routed == [1, 1]
            assert fleet.spills == 1
            assert not fut.done()
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=10)

    def test_all_replicas_refusing_is_typed(self):
        fleet = _fleet(2, start=False, max_batch=4, queue_rows=4)
        try:
            fleet.submit(*_rows(4), affinity=0)
            fleet.submit(*_rows(4), affinity=1)
            with pytest.raises(ServerOverloaded, match="all 2 replicas"):
                fleet.submit(*_rows(1))
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=10)

    def test_dead_replica_reroutes_never_hangs(self):
        """A closed (dead) replica is just a refusing replica: requests
        with affinity for it re-route to a live one; when the whole fleet
        is dead the caller gets the typed error, not a hang."""
        fleet = _fleet(2)
        fleet.engines[0].close(timeout=10)     # replica 0 dies
        probs = fleet.predict(*_rows(2, base=4), timeout=10, affinity=0)
        np.testing.assert_array_equal(probs, np.full(2, 4.5, np.float32))
        assert fleet.routed == [0, 1]
        fleet.close(timeout=10)                # whole fleet dead
        with pytest.raises(ServerOverloaded):
            fleet.submit(*_rows(1))

    def test_malformed_request_fails_fast_without_reroute(self):
        fleet = _fleet(2)
        try:
            with pytest.raises(ValueError, match="one \\[n, F\\] shape"):
                fleet.submit(np.zeros((2, 3), np.int32),
                             np.zeros((3, 3), np.float32))
            assert fleet.routed == [0, 0]
        finally:
            fleet.close(timeout=10)


# ---------------------------------------------------------------------------
# Fleet lifecycle: drain-on-close, staggered swaps
# ---------------------------------------------------------------------------

class TestFleetLifecycle:
    def test_close_drains_every_replica(self):
        """Drain-on-close resolves EVERY admitted future across all
        replicas, including formed-but-unflushed pipeline batches."""
        fleet = _fleet(3, start=False, max_batch=2, max_delay_ms=0)
        futs = [fleet.submit(*_rows(2, base=i), affinity=i)
                for i in range(9)]
        assert all(r > 0 for r in fleet.routed)
        for e in fleet.engines:
            e.start()
        fleet.close(timeout=30)
        for f in futs:
            assert f.done()
            assert f.result(timeout=0).shape == (2,)

    def test_staggered_swap_one_replica_at_a_time(self):
        """The coordinator walks the fleet SEQUENTIALLY: each replica's
        swap (load + prewarm + assignment) completes before the next
        replica's begins, so at most one replica is ever mid-swap."""
        active = []
        overlap = []
        order = []

        class FakeWatcher:
            def __init__(self, name):
                self.name = name

            def check_once(self):
                if active:
                    overlap.append((active[0], self.name))
                active.append(self.name)
                time.sleep(0.01)          # a "slow" load+prewarm
                order.append(self.name)
                active.pop()
                return True

            def close(self):
                pass

        fleet = _fleet(3)
        try:
            for i, eng in enumerate(fleet.engines):
                eng._watcher = FakeWatcher(f"r{i}")
            assert fleet.check_swaps_once() == 3
            assert order == ["r0", "r1", "r2"]
            assert not overlap
        finally:
            for eng in fleet.engines:
                eng._watcher = None
            fleet.close(timeout=10)

    def test_swap_fault_counts_and_does_not_stop_the_walk(self):
        class BoomWatcher:
            def check_once(self):
                raise RuntimeError("poll boom")

            def close(self):
                pass

        fleet = _fleet(2)
        try:
            fleet.engines[0]._watcher = BoomWatcher()
            assert fleet.check_swaps_once() == 0
            assert fleet.engines[0].stats.watcher_errors == 1
            assert fleet.engines[1].stats.watcher_errors == 0
        finally:
            for eng in fleet.engines:
                eng._watcher = None
            fleet.close(timeout=10)


# ---------------------------------------------------------------------------
# Per-attempt routing re-snapshot (regression) + request hedging
# ---------------------------------------------------------------------------

class TestRoutingResnapshot:
    def test_spill_burst_spreads_by_live_pending_rows(self):
        """``_next_attempt`` re-reads pending rows at EVERY attempt: a
        burst of spills off a full home replica spreads across the fleet
        instead of piling onto whichever replica was least loaded when the
        first spill was computed."""
        fleet = _fleet(3, start=False, max_batch=8, queue_rows=8)
        try:
            fleet.engines[0].submit(*_rows(8))         # home full
            for _ in range(3):
                fleet.submit(*_rows(4), affinity=0)
            # 1st spill -> r1 (tie, lowest idx), 2nd -> r2 (r1 now has 4),
            # 3rd -> r1 (tie again at 4 rows each).
            assert fleet.routed == [0, 2, 1]
            assert fleet.spills == 3
            assert [e.pending_rows for e in fleet.engines] == [8, 8, 4]
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_blocked_spill_target_reroutes_past_it(self):
        """The least-loaded spill target refusing (a dead replica shows 0
        pending, so it LOOKS least loaded) must not end the attempt walk:
        the next attempt re-snapshots and lands on a live replica."""
        fleet = _fleet(3, start=False, max_batch=4, queue_rows=4)
        try:
            fleet.engines[0].submit(*_rows(4))         # home full
            fleet.engines[2].submit(*_rows(1))
            fleet.engines[1].close(timeout=10)         # blocked: pending 0
            fut = fleet.submit(*_rows(1), affinity=0)
            assert fleet.routed == [0, 0, 1]
            assert fleet.spills == 1
            assert not fut.done()
        finally:
            for e in (fleet.engines[0], fleet.engines[2]):
                e.start()
            fleet.close(timeout=30)


class TestHedging:
    def _hedged_fleet(self, n=2, hedge_ms=5.0, **kw):
        kw.setdefault("max_batch", 8)
        kw.setdefault("max_delay_ms", 1)
        # start=False: no hedger thread — tests drive hedge_pass() by hand.
        return ReplicatedEngine(
            [ServingEngine(base_predict, start=False, **kw)
             for _ in range(n)],
            hedge_ms=hedge_ms, start=False)

    def test_hedge_fires_to_other_replica_and_wins(self):
        """Primary parked on a blocked replica: after the hedge delay the
        monitor re-submits to the least-loaded OTHER replica, the hedge
        resolves first, the caller gets its result, and the loser is
        cancelled — all counted (fired/won/cancelled)."""
        fleet = self._hedged_fleet()
        try:
            hf = fleet.submit(*_rows(1, base=3), affinity=0)
            assert isinstance(hf, HedgedFuture) and not hf.hedged
            # Not yet past the delay: nothing fires.
            assert fleet.hedge_pass(now=hf.t_enqueue) == 0
            assert fleet.hedge_pass(now=hf.t_enqueue + 1.0) == 1
            assert hf.hedged
            assert fleet.engines[1].pending_rows == 1
            # Second pass never double-hedges the same wrapper.
            assert fleet.hedge_pass(now=hf.t_enqueue + 2.0) == 0
            fleet.engines[1].start()
            np.testing.assert_array_equal(
                hf.result(timeout=10), np.full(1, 3.5, np.float32))
            assert hf._primary.cancelled()
            s = fleet.summary()
            assert s["hedges_fired"] == 1
            assert s["hedges_won"] == 1
            assert s["hedges_cancelled"] == 1
            # The resolved wrapper prunes into the p99 window.
            fleet.hedge_pass(now=hf.t_enqueue + 3.0)
            assert len(fleet._recent_latencies) == 1
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_late_loser_never_double_resolves(self):
        """A cancelled loser that was already mid-flush resolving late is
        harmless: the wrapper's result and latency stamp are immutable
        after the winner."""
        fleet = self._hedged_fleet()
        try:
            hf = fleet.submit(*_rows(1, base=3), affinity=0)
            fleet.hedge_pass(now=hf.t_enqueue + 1.0)
            fleet.engines[1].start()
            want = hf.result(timeout=10)
            stamp = hf.latency_ms
            # The loser resolves anyway (as if mid-flush at cancel time).
            hf._primary.set_result(np.full(1, -99.0, np.float32), 0.0)
            np.testing.assert_array_equal(hf.result(timeout=0), want)
            assert hf.latency_ms == stamp
            assert fleet.summary()["hedges_won"] == 1
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_attach_after_race_over_is_refused_and_cancelled(self):
        fleet = self._hedged_fleet()
        try:
            hf = fleet.submit(*_rows(1, base=2), affinity=0)
            hf._primary.set_result(np.full(1, 2.5, np.float32), 1.0)
            late = fleet.engines[1].submit(*_rows(1, base=2))
            assert hf.attach_hedge(late) is False
            assert late.cancelled()
            assert fleet.summary()["hedges_fired"] == 0
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_errored_primary_holds_wrapper_for_healthy_hedge(self):
        """A failed primary with a hedge in flight does NOT resolve the
        wrapper: the caller only sees an error when no leg can succeed."""
        fleet = self._hedged_fleet()
        try:
            hf = fleet.submit(*_rows(1, base=4), affinity=0)
            fleet.hedge_pass(now=hf.t_enqueue + 1.0)
            hf._primary.set_error(RuntimeError("primary boom"))
            assert not hf.done()
            fleet.engines[1].start()
            np.testing.assert_array_equal(
                hf.result(timeout=10), np.full(1, 4.5, np.float32))
            assert fleet.summary()["hedges_won"] == 1
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_both_legs_failing_surfaces_the_error(self):
        fleet = self._hedged_fleet()
        try:
            hf = fleet.submit(*_rows(1, base=4), affinity=0)
            fleet.hedge_pass(now=hf.t_enqueue + 1.0)
            hf._primary.set_error(RuntimeError("primary boom"))
            hf._hedge.set_error(RuntimeError("hedge boom"))
            assert hf.done()
            with pytest.raises(RuntimeError, match="boom"):
                hf.result(timeout=0)
            assert fleet.summary()["hedges_won"] == 0
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_hot_fleet_skips_hedge_and_retries_next_pass(self):
        """When every other replica refuses the hedge submission (full
        queue), the pass skips it — the wrapper stays eligible and hedges
        on a later pass once capacity returns."""
        fleet = self._hedged_fleet(max_batch=4, queue_rows=4)
        try:
            hf = fleet.submit(*_rows(1), affinity=0)
            fleet.engines[1].submit(*_rows(3))   # only 1 row of room left
            fleet.engines[1].submit(*_rows(1))   # ...now zero
            assert fleet.hedge_pass(now=hf.t_enqueue + 1.0) == 0
            assert not hf.hedged
            fleet.engines[1].start()
            fleet.engines[1].close(timeout=10)   # drains; capacity back...
            # ...but a closed replica refuses: still no hedge, no crash.
            assert fleet.hedge_pass(now=hf.t_enqueue + 2.0) == 0
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_cancelled_hedge_loser_never_reaches_the_device(self):
        """Regression: a hedge loser cancelled while its flush sits in the
        batcher->executor handoff must be dropped by the flush prologue,
        not computed and discarded. The primary replica is plugged by an
        in-flight batch (inflight=1 holds the loser's formed flush), the
        hedge wins on the healthy replica, and only then does the plug
        release — if the loser still reached the device, its feature id
        would show up in the plugged replica's seen-set."""
        seen = []
        entered = threading.Event()
        gate = threading.Event()

        def plugged_predict(feat_ids, feat_vals):
            seen.extend(np.asarray(feat_ids)[:, 0].tolist())
            if int(feat_ids[0, 0]) == 999:
                entered.set()
                assert gate.wait(timeout=30)
            return base_predict(feat_ids, feat_vals)

        eng0 = ServingEngine(plugged_predict, max_batch=8, max_delay_ms=1,
                             inflight=1)
        eng1 = ServingEngine(base_predict, max_batch=8, max_delay_ms=1)
        fleet = ReplicatedEngine([eng0, eng1], hedge_ms=5.0, start=False)
        try:
            plug = eng0.submit(*_rows(1, base=999))
            assert entered.wait(timeout=10)
            hf = fleet.submit(*_rows(1, base=777), affinity=0)
            # Wait for the batcher to form the loser's flush (it parks in
            # the handoff behind the plugged inflight slot).
            deadline = time.monotonic() + 10
            while eng0.pending_rows and time.monotonic() < deadline:
                time.sleep(0.005)
            assert eng0.pending_rows == 0
            assert fleet.hedge_pass(now=hf.t_enqueue + 10.0) == 1
            np.testing.assert_array_equal(
                hf.result(timeout=10), np.full(1, 777.5, np.float32))
            assert hf._primary.cancelled()
            gate.set()
            np.testing.assert_array_equal(
                plug.result(timeout=10), np.full(1, 999.5, np.float32))
            fleet.close(timeout=30)
            assert 777 not in seen
            s = fleet.summary()
            assert s["hedges_won"] == 1
            assert s["hedges_cancelled"] == 1
        finally:
            gate.set()
            fleet.close(timeout=30)

    def test_hedge_delay_tracks_fleet_p99_above_floor(self):
        fleet = self._hedged_fleet(hedge_ms=5.0)
        try:
            assert fleet.hedge_delay_s() == pytest.approx(0.005)
            # Under 20 samples the floor still rules.
            fleet._recent_latencies.extend([100.0] * 19)
            assert fleet.hedge_delay_s() == pytest.approx(0.005)
            fleet._recent_latencies.append(100.0)
            assert fleet.hedge_delay_s() == pytest.approx(0.1)
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)

    def test_all_sheds_raise_typed_admission_shed(self):
        """When EVERY replica's refusal was an admission shed, the fleet
        raises AdmissionShed (the fleet CHOSE to refuse the class), not
        ServerOverloaded."""
        fleet = ReplicatedEngine(
            [ServingEngine(base_predict, start=False, max_batch=8,
                           max_delay_ms=1, queue_rows=8,
                           admission_kw={"shed_watermark": 2})
             for _ in range(2)], start=False)
        try:
            for e in fleet.engines:
                e.submit(*_rows(2), value="critical")
            with pytest.raises(AdmissionShed, match="all 2 replicas"):
                fleet.submit(*_rows(1), value="bulk")
        finally:
            for e in fleet.engines:
                e.start()
            fleet.close(timeout=30)


# ---------------------------------------------------------------------------
# Aggregate stats
# ---------------------------------------------------------------------------

class TestAggregateStats:
    def test_fleet_percentiles_and_totals(self):
        clock = [0.0]
        a, b = (ServingStats(clock=lambda: clock[0]) for _ in range(2))
        for ms in (1.0, 2.0, 3.0):
            a.record_request_done(ms)
        for ms in (10.0, 20.0):
            b.record_request_done(ms, lane="small")
        a.record_flush(4, 8)
        clock[0] = 2.0
        b.record_flush(2, 4)
        b.record_overload()
        agg = aggregate_summary([a, b])
        assert agg["replicas"] == 2
        assert agg["serving_requests"] == 5
        assert agg["serving_overloads"] == 1
        # True fleet percentile over the CONCATENATED reservoir — the
        # median of {1,2,3,10,20}, not an average of per-replica medians.
        assert agg["serving_p50_ms"] == 3.0
        assert agg["serving_small_requests"] == 2
        # Union completion window: 5 requests over (2.0 - 0.0) seconds.
        assert agg["serving_qps"] == 2.5
        assert agg["batch_occupancy_pct"] == pytest.approx(50.0)

    def test_empty_window_is_none_and_zero_qps(self):
        """A replica that has served NOTHING yet (startup, or a canary arm
        drained by the kill-switch before its first completion) must
        summarize as 0 QPS with None percentiles — never raise, never
        fabricate a number (regression: the percentile helper used to
        index into an empty reservoir)."""
        s = ServingStats()
        one = s.summary()
        assert one["serving_requests"] == 0
        assert one["serving_qps"] == 0.0
        assert one["serving_p50_ms"] is None
        assert one["serving_p99_ms"] is None
        agg = aggregate_summary([ServingStats(), ServingStats()])
        assert agg["replicas"] == 2
        assert agg["serving_requests"] == 0
        assert agg["serving_qps"] == 0.0
        assert agg["serving_p50_ms"] is None
        assert agg["serving_p99_ms"] is None
        assert agg["batch_occupancy_pct"] is None

    def test_empty_fleet_aggregate(self):
        agg = aggregate_summary([])
        assert agg["replicas"] == 0
        assert agg["serving_requests"] == 0
        assert agg["serving_qps"] == 0.0
        assert agg["serving_p99_ms"] is None

    def test_worst_replica_blackout_and_per_replica_list(self):
        clock = [0.0]
        a, b = (ServingStats(clock=lambda: clock[0]) for _ in range(2))
        a.record_swap(version=2)
        clock[0] = 0.02
        a.record_flush(1, 1, version=2)
        b.record_swap(version=2)
        clock[0] = 0.07
        b.record_flush(1, 1, version=2)
        agg = aggregate_summary([a, b])
        assert agg["swap_blackout_ms"] == 50.0
        assert agg["swap_blackout_ms_per_replica"] == [20.0, 50.0]


# ---------------------------------------------------------------------------
# Frontend passthrough: client id IS the affinity key
# ---------------------------------------------------------------------------

class TestFrontendAffinity:
    def test_client_id_is_sticky_key(self):
        fleet = _fleet(2)
        srv = FrontendServer(fleet, 2, field_size=FIELD_SIZE, ctx=THREAD_CTX)
        t = threading.Thread(target=srv.serve, daemon=True)
        t.start()
        try:
            with ServingClient(srv.handle(0)) as c0, \
                    ServingClient(srv.handle(1)) as c1:
                for base in (1, 2, 3):
                    np.testing.assert_array_equal(
                        c0.predict(*_rows(2, base=base), timeout=10),
                        np.full(2, base + 0.5, np.float32))
                    c1.predict(*_rows(1, base=base), timeout=10)
            t.join(timeout=10)
            assert not t.is_alive()
            # cid 0 -> replica 0, cid 1 -> replica 1, no spills.
            assert fleet.routed == [3, 3]
            assert fleet.spills == 0
        finally:
            srv.stop()
            srv.close()
            fleet.close(timeout=10)

    def test_dead_replica_behind_frontend_stays_live(self):
        """A replica dying under a running frontend degrades to re-routing,
        not client-visible failures or hangs."""
        fleet = _fleet(2)
        srv = FrontendServer(fleet, 2, field_size=FIELD_SIZE, ctx=THREAD_CTX)
        t = threading.Thread(target=srv.serve, daemon=True)
        t.start()
        try:
            fleet.engines[1].close(timeout=10)   # cid 1's home replica dies
            with ServingClient(srv.handle(0)) as c0, \
                    ServingClient(srv.handle(1)) as c1:
                np.testing.assert_array_equal(
                    c1.predict(*_rows(2, base=9), timeout=10),
                    np.full(2, 9.5, np.float32))
                assert c0.predict(*_rows(1), timeout=10).shape == (1,)
            t.join(timeout=10)
            assert not t.is_alive()
            assert srv.errors_sent == 0
            assert fleet.routed[0] == 2      # both clients served by r0
        finally:
            srv.stop()
            srv.close()
            fleet.close(timeout=10)


# ---------------------------------------------------------------------------
# Tier-1 serving smoke (satellite: lane p99 + bench schema)
# ---------------------------------------------------------------------------

class TestServingSmoke:
    def test_lane_p99_at_most_global_p99_under_bypass_load(self):
        """The priority lane's whole job: under a backlog of max-batch
        large fills, head-of-line bypass keeps small-request p99 at or
        under the global p99 (dominated by the queued larges)."""
        def slow_predict(ids, vals):
            time.sleep(0.004)
            return base_predict(ids, vals)

        eng = ServingEngine(slow_predict, max_batch=8, max_delay_ms=1,
                            inflight=2, small_rows=1, queue_rows=512)
        try:
            futs = [eng.submit(*_rows(8, base=i)) for i in range(20)]
            smalls = []
            for i in range(10):
                smalls.append(eng.submit(*_rows(1, base=50 + i)))
                time.sleep(0.005)
            for f in futs + smalls:
                f.result(timeout=30)
            s = eng.stats.summary()
            assert s["serving_small_requests"] == 10
            assert s["serving_small_p99_ms"] <= s["serving_p99_ms"], s
        finally:
            eng.close()

    def test_bench_serving_series_emits_honesty_schema(self):
        """Schema check, not a perf gate: the bench serving series must
        carry every honesty-label and lane/policy field the SERVING_r0N
        reports are read by."""
        import bench
        out = bench.serving_series(run_secs=0.5, n_clients=2)
        required = {
            "replicas", "serve_inflight", "serve_small_rows",
            "serving_p50_ms", "serving_p99_ms",
            "serving_small_p50_ms", "serving_small_p99_ms",
            "serving_large_p50_ms", "serving_large_p99_ms",
            "serving_qps", "batch_occupancy_pct",
            "swap_blackout_ms", "swap_blackout_ms_per_replica",
            "serving_requests", "serving_failed", "serving_overloads",
            "hot_swaps", "swap_failures", "clients",
            "load_kind", "device_kind", "host_cpu_count",
        }
        missing = required - set(out)
        assert not missing, f"bench serving series lost fields: {missing}"
        assert out["load_kind"] == "synthetic-closed-loop"
        assert out["device_kind"]
        assert out["serving_failed"] == 0
