"""Multi-process input service tests (data/workers.py + pipeline wiring).

The contract under test is BIT-IDENTICAL parity: with ``input_workers > 0``
the pipeline must emit byte-for-byte the stream the in-process pooled path
emits (same golden hashes), because resume skip-counting replays along this
exact order. Everything else — crash policy, respawn replay, health
aggregation, eligibility fallbacks — is tested against that same invariant.

These tests spawn real processes (spawn context, like production); they use
small files and ``poll_secs`` well under a second so the whole module stays
inside tier-1 time. Pure protocol mechanics live in tests/test_shm_ring.py.
"""

import glob
import warnings

import numpy as np
import pytest

from deepfm_tpu.data import example_codec, libsvm, pipeline, sharding, tfrecord
from deepfm_tpu.data import workers as workers_mod
from deepfm_tpu.utils import retry as retry_lib

pytestmark = [
    pytest.mark.input_service,
    pytest.mark.skipif(not pipeline._native_loader(),
                       reason="native decoder unavailable"),
]

NO_SLEEP = retry_lib.RetryPolicy(base_delay=0.0, max_delay=0.0)


@pytest.fixture
def data_dir(tmp_path):
    libsvm.generate_synthetic_ctr(
        str(tmp_path), num_files=4, examples_per_file=60, feature_size=300,
        field_size=6, prefix="tr", seed=11)
    return tmp_path


def _files(data_dir):
    return sorted(glob.glob(str(data_dir / "tr*.tfrecords")))


def _emissions(files, k=4, **kw):
    base = dict(field_size=6, batch_size=32, num_epochs=2, shuffle=True,
                shuffle_files=True, shuffle_buffer=150, drop_remainder=True,
                seed=7, prefetch_batches=0)
    base.update(kw)
    out = []
    for rows, m, n_ex in pipeline.CtrPipeline(files, **base) \
            .iter_superbatches(k):
        out.append((m, n_ex, {key: v.copy() for key, v in rows.items()}))
    return out


def _assert_same_emissions(a, b):
    assert len(a) == len(b)
    for (m1, n1, r1), (m2, n2, r2) in zip(a, b):
        assert (m1, n1) == (m2, n2)
        for key in r1:
            np.testing.assert_array_equal(r1[key], r2[key], err_msg=key)


def _reference_rows(files, field_size):
    """All records of ``files`` decoded in file order (codec path — fully
    independent of the chunk reader under test)."""
    labs, idss, valss = [], [], []
    for path in files:
        for rec in tfrecord.read_all_records(path):
            lab, ids, vals = example_codec.decode_ctr_example(rec, field_size)
            labs.append(lab)
            idss.append(ids)
            valss.append(vals)
    return (np.array(labs, np.float32),
            np.stack(idss).astype(np.int32),
            np.stack(valss).astype(np.float32))


def _collect_service_rows(service):
    labs, idss, valss = [], [], []
    with service:
        for labels, ids, vals in service.chunks(copy=True):
            labs.append(labels)
            idss.append(ids)
            valss.append(vals)
    return (np.concatenate(labs), np.concatenate(idss),
            np.concatenate(valss))


class TestPipelineParity:
    def test_shuffle_parity_with_fragmentation(self, data_dir):
        """Worker path == in-process path, bit for bit, across 2 epochs
        (separate service fleets) with slabs forced smaller than a chunk so
        multi-fragment reassembly is exercised."""
        files = _files(data_dir)
        _assert_same_emissions(
            _emissions(files),
            _emissions(files, input_workers=2,
                       input_worker_slab_records=25))

    def test_noshuffle_parity_copy_mode(self, data_dir):
        """shuffle=False consumes the service in copy mode (no scatter ever
        releases the slabs): still identical to in-process."""
        files = _files(data_dir)
        kw = dict(shuffle=False, num_epochs=1)
        _assert_same_emissions(
            _emissions(files, **kw),
            _emissions(files, input_workers=2,
                       input_worker_slab_records=25, **kw))

    def test_worker_path_reproduces_golden_hash(self, tmp_path):
        """The strongest pin: the worker path reproduces the SAME golden
        emission hash TestPooledEmissionGolden freezes for the in-process
        path — the two paths cannot drift without tripping this."""
        import hashlib
        libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=3, examples_per_file=500,
            feature_size=1000, field_size=7, prefix="tr", seed=5)
        files = sorted(str(p) for p in tmp_path.glob("tr*.tfrecords"))
        pipe = pipeline.CtrPipeline(
            files, field_size=7, batch_size=64, num_epochs=2, shuffle=True,
            shuffle_files=True, shuffle_buffer=300, drop_remainder=True,
            seed=9, input_workers=2)
        h = hashlib.sha256()
        for rows, m, n_ex in pipe.iter_superbatches(8):
            h.update(str(m).encode())
            h.update(str(n_ex).encode())
            h.update(rows["feat_ids"].tobytes())
            h.update(rows["feat_vals"].tobytes())
            h.update(rows["label"].tobytes())
        # Must match tests/test_data.py::TestPooledEmissionGolden.GOLDEN
        # for (k=8, bs=64, skip=0, drop=True).
        assert h.hexdigest()[:24] == "26fff204f1d9b877c88d8696"


class TestServiceProtocol:
    def test_chunks_match_reference_decode(self, data_dir):
        files = _files(data_dir)
        got = _collect_service_rows(workers_mod.ShmInputService(
            files, field_size=6, num_workers=2, poll_secs=0.05))
        want = _reference_rows(files, 6)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_worker_count_clamped_to_files(self, data_dir):
        files = _files(data_dir)[:2]
        svc = workers_mod.ShmInputService(
            files, field_size=6, num_workers=8, poll_secs=0.05)
        assert svc.num_workers == 2
        got = _collect_service_rows(svc)
        np.testing.assert_array_equal(got[0], _reference_rows(files, 6)[0])

    def test_empty_files_raise(self, tmp_path):
        empty = str(tmp_path / "empty.tfrecords")
        open(empty, "wb").close()
        with pytest.raises(IOError, match="no records"):
            _collect_service_rows(workers_mod.ShmInputService(
                [empty], field_size=6, num_workers=1, poll_secs=0.05))

    def test_decode_error_reraised_in_parent(self, data_dir):
        """A corrupt record with policy 'raise' fails INSIDE the worker;
        the parent re-raises with matching type (IOError) and the worker's
        detail text."""
        files = _files(data_dir)
        # Flip a data-CRC byte of record 3 of the first file (framing ok).
        import struct
        data = bytearray(open(files[0], "rb").read())
        pos = 0
        for _ in range(3):
            (length,) = struct.unpack_from("<Q", data, pos)
            pos += 16 + length
        (length,) = struct.unpack_from("<Q", data, pos)
        data[pos + 12 + length] ^= 0xFF
        open(files[0], "wb").write(bytes(data))
        with pytest.raises(IOError, match="data CRC mismatch"):
            _collect_service_rows(workers_mod.ShmInputService(
                files, field_size=6, num_workers=1, verify_crc=True,
                on_bad_record="raise", retry_policy=NO_SLEEP,
                poll_secs=0.05))

    def test_invalid_death_policy_rejected(self, data_dir):
        with pytest.raises(ValueError, match="on_worker_death"):
            workers_mod.ShmInputService(
                _files(data_dir), field_size=6, num_workers=1,
                on_worker_death="retry")


class TestWorkerDeath:
    def test_crash_raises_by_default(self, data_dir):
        """A worker hard-killed mid-stream (os._exit — no farewell message)
        must surface as an error, never a silent truncation."""
        svc = workers_mod.ShmInputService(
            _files(data_dir), field_size=6, num_workers=1,
            fault_die_after=1, poll_secs=0.05)
        with pytest.raises(RuntimeError, match="input worker 0 died"):
            _collect_service_rows(svc)

    def test_respawn_replays_exactly(self, data_dir):
        """on_worker_death='respawn': the replacement replays from the
        first sequence number of the incomplete chunk, so the delivered
        stream is exactly the crash-free stream — no loss, no duplicates."""
        files = _files(data_dir)
        got = _collect_service_rows(workers_mod.ShmInputService(
            files, field_size=6, num_workers=1, fault_die_after=2,
            on_worker_death="respawn", max_respawns=2, poll_secs=0.05))
        want = _reference_rows(files, 6)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_respawn_budget_exhausted_raises(self, data_dir):
        svc = workers_mod.ShmInputService(
            _files(data_dir), field_size=6, num_workers=1,
            fault_die_after=1, on_worker_death="respawn", max_respawns=0,
            poll_secs=0.05)
        with pytest.raises(RuntimeError, match="respawns used 0/0"):
            _collect_service_rows(svc)


class TestHealthAggregation:
    def test_worker_bad_records_reach_pipeline_health(self, data_dir):
        """Corruption skipped INSIDE a worker process must land in the
        trainer-side pipeline.health ledger (snapshot deltas at eof/done)."""
        files = _files(data_dir)
        import struct
        data = bytearray(open(files[1], "rb").read())
        (length,) = struct.unpack_from("<Q", data, 0)
        data[12 + length] ^= 0xFF  # record 0's data CRC
        open(files[1], "wb").write(bytes(data))
        pipe = pipeline.CtrPipeline(
            files, field_size=6, batch_size=16, num_epochs=1, shuffle=True,
            shuffle_buffer=150, drop_remainder=False, seed=7, verify_crc=True,
            on_bad_record="skip", retry_policy=NO_SLEEP, prefetch_batches=0,
            input_workers=2)
        total = sum(n_ex for _, _, n_ex in pipe.iter_superbatches(2))
        assert total == 4 * 60 - 1
        snap = pipe.health.snapshot()
        assert snap["bad_records"] == 1
        assert snap["per_file"][files[1]]["skipped"] == 1


class TestEligibilityAndFallback:
    def test_record_shard_uses_in_process_silently(self, data_dir):
        """Record-sharding is ineligible (workers have no global record
        index): the pipeline must use the in-process path with NO warning —
        this is a config choice, not a degradation."""
        files = _files(data_dir)[:1]
        spec = sharding.shard_files(files, rank=1, world_size=3)
        assert spec.record_shard == (3, 1)
        kw = dict(shard=spec, shuffle=False, num_epochs=1,
                  drop_remainder=False, batch_size=8)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            shm = _emissions(files, input_workers=2, **kw)
        _assert_same_emissions(_emissions(files, **kw), shm)

    def test_service_failure_warns_and_falls_back(self, data_dir,
                                                  monkeypatch):
        """If the fleet cannot start (sandboxed /dev/shm, fork server
        restrictions...), the pipeline degrades to in-process with a
        RuntimeWarning — identical output, never a crash."""
        files = _files(data_dir)

        class Unstartable:
            def __init__(self, *a, **kw):
                raise OSError("shm forbidden")

        monkeypatch.setattr(workers_mod, "ShmInputService", Unstartable)
        with pytest.warns(RuntimeWarning, match="input service unavailable"):
            shm = _emissions(files, input_workers=2)
        _assert_same_emissions(_emissions(files), shm)

    def test_config_rejects_negative(self):
        from deepfm_tpu.config import Config
        with pytest.raises(ValueError, match="input_workers"):
            Config(input_workers=-1)

    def test_config_flag_reaches_pipeline(self, data_dir):
        from deepfm_tpu.config import Config
        from deepfm_tpu.train import tasks
        cfg = Config(data_dir=str(data_dir), field_size=6, batch_size=16,
                     input_workers=3)
        pipe = tasks.make_pipeline(cfg, _files(data_dir), epochs=1,
                                   shuffle=True)
        assert pipe.input_workers == 3
