"""Row-sharded embedding tests (--embedding_shard rows).

Contracts pinned here:

- ``build_exchange``/``exchange_rows`` move exactly the plan's touched
  rows between owner shards: the reassembled [U, ...] block is
  BIT-identical to gathering from the full table, for any shard count
  that divides the rows (NumPy oracle + shard_map runs).
- ``owner_scatter_add`` partitions the full-table scatter: concatenating
  every shard's owner-local grad equals the unsharded table-space
  scatter, bit for bit.
- ``--embedding_shard rows`` on ONE device routes to the unchanged
  single-device sparse program — trajectories are bit-identical to
  ``off`` (the tentpole's safety pin).
- Mesh trajectories (1x2, 4x2, hashed) track the single-device sparse
  run within the established mesh tolerance band (``shard``-marked:
  gated on the mesh_bitexact probe like every mesh-vs-single parity
  claim in this suite).
- Checkpoints are mesh-portable: a 2-shard run's params AND lazy-Adam
  moments (m/v/tau) restore bit-exactly unsharded and onto a different
  shard count (vocab padding is a mesh-independent multiple).
- ``grad_payload_bytes`` reports sharded leaves once per owner under
  rows — unit-tested against the analytic value.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.5 (see train/loop.py)
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        del check_vma
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)

from deepfm_tpu.config import Config
from deepfm_tpu.ops import embedding as emb_ops
from deepfm_tpu.ops import pallas_embedding as pemb
from deepfm_tpu.parallel import mesh as mesh_lib
from deepfm_tpu.train import Trainer
from deepfm_tpu.utils import checkpoint as ckpt_lib


def _cfg(**kw):
    base = dict(
        feature_size=500, field_size=6, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=64,
        compute_dtype="float32", l2_reg=1e-4, learning_rate=0.01,
        shuffle_buffer=500, log_steps=0, seed=11,
        scale_lr_by_world=False, mesh_data=1, mesh_model=1,
        embedding_update="sparse", embedding_shard="rows",
    )
    base.update(kw)
    return Config(**base)


def _batches(n, bs, fields=6, seed=3, feature_size=500):
    rng = np.random.RandomState(seed)
    return [{
        "feat_ids": rng.randint(
            0, feature_size, (bs, fields)).astype(np.int32),
        "feat_vals": rng.rand(bs, fields).astype(np.float32),
        "label": (rng.rand(bs, 1) < 0.3).astype(np.float32),
    } for _ in range(n)]


def _fit(cfg, n_steps=8):
    tr = Trainer(cfg)
    state = tr.init_state()
    state, out = tr.fit(state, iter(_batches(n_steps, cfg.batch_size)))
    return tr, state, out


def _embed_leaves(state):
    """(params, m, v, tau) arrays for fm_v's first physical table."""
    tabs = state.params["fm_v"]
    oe = state.opt_state["embed"]["fm_v"]
    key = "table" if not isinstance(tabs, dict) else "t0"
    tab = tabs if not isinstance(tabs, dict) else tabs["t0"]
    return (np.asarray(tab), np.asarray(oe[key].m),
            np.asarray(oe[key].v), np.asarray(oe[key].tau))


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------


class TestConfigValidation:
    def test_rows_requires_sparse(self):
        with pytest.raises(ValueError, match="sparse row plane"):
            _cfg(embedding_update="dense")

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="embedding_shard"):
            _cfg(embedding_shard="cols")

    def test_rows_excludes_tiering(self):
        with pytest.raises(ValueError, match="TUNING"):
            _cfg(embedding_tiering="hot_cold", embedding_hot_rows=64)

    def test_rows_excludes_accum(self):
        with pytest.raises(ValueError, match="single-device"):
            _cfg(grad_accum_steps=2, steps_per_loop=4)

    def test_sparse_mesh_needs_rows(self):
        with pytest.raises(ValueError, match="embedding_shard rows"):
            _cfg(embedding_shard="off", mesh_model=2)

    def test_rows_excludes_history_transitively(self):
        # rows requires sparse; history requires dense -> no rows+history.
        with pytest.raises(ValueError, match="embedding_update=dense"):
            _cfg(model="din", history_max_len=4)

    def test_buckets_must_divide(self):
        with pytest.raises(ValueError, match="divisible"):
            _cfg(mesh_model=2, embedding_buckets="255,128")
        _cfg(mesh_model=2, embedding_buckets="256,128")  # ok


# ---------------------------------------------------------------------------
# Exchange machinery vs NumPy oracle (forward-only collectives)
# ---------------------------------------------------------------------------


def _mesh(d):
    return Mesh(np.asarray(jax.devices()[:d]), ("model",))


def _plan_from_ids(ids, rows):
    return emb_ops.make_plan(jnp.asarray(ids, jnp.int32), rows)


class TestExchangeOracle:
    @pytest.mark.parametrize("d", [2, 4])
    def test_build_exchange_matches_oracle(self, d):
        rows = 64
        rng = np.random.default_rng(5)
        ids = rng.integers(0, rows, size=(24,))
        plan = _plan_from_ids(ids, rows)

        def f():
            ex = emb_ops.build_exchange(plan, d, "model")
            return ex.reqs, ex.flat_idx

        reqs, flat_idx = jax.jit(shard_map(
            f, mesh=_mesh(d), in_specs=(),
            out_specs=(P("model"), P("model"))))()
        reqs = np.asarray(reqs).reshape(d, d, -1)
        flat_idx = np.asarray(flat_idx).reshape(d, -1)
        for r in range(d):
            want_reqs, want_flat = pemb.reference_exchange_numpy(
                np.asarray(plan.uids), rows, d, r)
            np.testing.assert_array_equal(reqs[r], want_reqs)
            np.testing.assert_array_equal(flat_idx[r], want_flat)

    @pytest.mark.parametrize("d", [2, 4, 8])
    @pytest.mark.parametrize("trailing", [(), (5,)])
    def test_exchange_rows_bit_equals_full_gather(self, d, trailing):
        rows = 64
        rng = np.random.default_rng(7)
        table = rng.normal(size=(rows, *trailing)).astype(np.float32)
        ids = rng.integers(0, rows, size=(30,))
        plan = _plan_from_ids(ids, rows)
        want = np.asarray(emb_ops.gather_rows(jnp.asarray(table), plan))

        def f(local):
            ex = emb_ops.build_exchange(plan, d, "model")
            return emb_ops.exchange_rows(local, ex, "model")

        got = jax.jit(shard_map(
            f, mesh=_mesh(d),
            in_specs=(P("model", *([None] * len(trailing))),),
            out_specs=P()))(jnp.asarray(table))
        np.testing.assert_array_equal(np.asarray(got), want)

    @pytest.mark.parametrize("d", [2, 4])
    def test_owner_scatter_add_partitions_full_scatter(self, d):
        rows, k = 64, 3
        rng = np.random.default_rng(9)
        ids = rng.integers(0, rows, size=(20,))
        plan = _plan_from_ids(ids, rows)
        g_rows = rng.normal(size=(plan.uids.shape[0], k)).astype(np.float32)
        # unsharded oracle: plain table-space scatter of the valid uids
        full = np.zeros((rows, k), np.float32)
        uids = np.asarray(plan.uids)
        for j, uid in enumerate(uids):
            if uid < rows:
                full[uid] += g_rows[j]
        full_touched = np.zeros((rows,), bool)
        full_touched[uids[uids < rows]] = True

        def f():
            return emb_ops.owner_scatter_add(
                jnp.asarray(g_rows), plan, d, "model")

        grad, touched = jax.jit(shard_map(
            f, mesh=_mesh(d), in_specs=(),
            out_specs=(P("model"), P("model"))))()
        np.testing.assert_array_equal(np.asarray(grad), full)
        np.testing.assert_array_equal(np.asarray(touched), full_touched)

    def test_owner_scatter_add_unsharded_degenerates(self):
        rows = 32
        ids = np.array([3, 3, 7, 31])
        plan = _plan_from_ids(ids, rows)
        g = np.ones((plan.uids.shape[0], 2), np.float32)
        grad, touched = jax.jit(
            lambda: emb_ops.owner_scatter_add(jnp.asarray(g), plan, 1, None))()
        assert np.asarray(grad).shape == (rows, 2)
        assert set(np.flatnonzero(np.asarray(touched))) == {3, 7, 31}

    def test_build_exchange_rejects_indivisible(self):
        plan = _plan_from_ids(np.array([1, 2]), 65)
        with pytest.raises(ValueError, match="divisible"):
            emb_ops.build_exchange(plan, 2, "model")

    def test_payload_bytes_analytic(self):
        assert emb_ops.exchange_payload_bytes(100, 8, 1) == 0
        # D=4, U=100 -> C=25, block=100: ids 400 B + 2 * 100*8 rows * 4 B
        assert emb_ops.exchange_payload_bytes(100, 8, 4) == (
            100 * 4 + 2 * 100 * 8 * 4)


# ---------------------------------------------------------------------------
# Trainer: 1-device bit identity + sharded runs
# ---------------------------------------------------------------------------


@pytest.mark.embedding
class TestOneDeviceBitIdentity:
    def test_rows_equals_off_bitwise(self):
        _, s_off, _ = _fit(_cfg(embedding_shard="off"))
        _, s_rows, _ = _fit(_cfg())
        for la, lb in zip(jax.tree.leaves(s_off.params),
                          jax.tree.leaves(s_rows.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        for la, lb in zip(jax.tree.leaves(s_off.opt_state),
                          jax.tree.leaves(s_rows.opt_state)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestShardedRun:
    """Structure + liveness of the sharded program (no cross-program
    numerics — those are the shard-marked parity tests below)."""

    def test_tables_and_moments_sharded(self):
        tr, state, out = _fit(_cfg(mesh_data=1, mesh_model=2))
        assert np.isfinite(out["loss"])
        assert state.params["fm_v"].sharding.spec[0] == "model"
        half = tr.model.padded_vocab // 2
        shapes = {tuple(s.data.shape)
                  for s in state.params["fm_v"].addressable_shards}
        assert shapes == {(half, 8)}
        oe = state.opt_state["embed"]["fm_v"]["table"]
        assert oe.m.sharding.spec[0] == "model"
        assert oe.tau.sharding.spec[0] == "model"
        assert {s.data.shape[0] for s in oe.tau.addressable_shards} == {half}

    def test_dp_mp_run_and_payload(self):
        tr, state, out = _fit(_cfg(mesh_data=2, mesh_model=2))
        assert np.isfinite(out["loss"])
        # padding rows never receive gradient
        pad = np.asarray(state.params["fm_v"])[500:]
        assert (pad == 0).all()
        assert tr._grad_payload_bytes() > 0

    def test_eval_predict_on_sharded_state(self):
        cfg = _cfg(mesh_data=1, mesh_model=2)
        tr = Trainer(cfg)
        state = tr.init_state()
        state, _ = tr.fit(state, iter(_batches(4, cfg.batch_size)))
        ev = tr.evaluate(state, iter(_batches(2, cfg.batch_size)))
        assert np.isfinite(ev["loss"]) and 0.0 <= ev["auc"] <= 1.0
        probs = np.concatenate(list(
            tr.predict(state, iter(_batches(2, cfg.batch_size)))), axis=0)
        assert probs.shape[0] == 2 * cfg.batch_size
        assert np.isfinite(probs).all()

    def test_hashed_sharded_run(self):
        cfg = _cfg(mesh_data=1, mesh_model=2, embedding_buckets="256,128")
        tr, state, out = _fit(cfg)
        assert np.isfinite(out["loss"])
        assert state.params["fm_v"]["t0"].sharding.spec[0] == "model"
        ev = tr.evaluate(state, iter(_batches(2, cfg.batch_size)))
        assert np.isfinite(ev["loss"])


# ---------------------------------------------------------------------------
# Mesh-vs-single trajectory parity (gated like every such claim)
# ---------------------------------------------------------------------------


@pytest.mark.shard
class TestShardedParity:
    def _single(self):
        return _fit(_cfg())

    def test_mp2_matches_single(self):
        _, s1, _ = self._single()
        _, s2, _ = _fit(_cfg(mesh_data=1, mesh_model=2))
        np.testing.assert_allclose(
            np.asarray(s1.params["fm_v"])[:500],
            np.asarray(s2.params["fm_v"])[:500], rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(s1.params["fm_w"])[:500],
            np.asarray(s2.params["fm_w"])[:500], rtol=1e-3, atol=1e-5)

    def test_dp4_mp2_matches_single(self):
        _, s1, ev1 = self._single()
        _, s8, ev8 = _fit(_cfg(mesh_data=4, mesh_model=2))
        np.testing.assert_allclose(
            np.asarray(s1.params["fm_v"])[:500],
            np.asarray(s8.params["fm_v"])[:500], rtol=1e-3, atol=1e-5)
        assert abs(ev1["loss"] - ev8["loss"]) < 1e-3

    def test_hashed_mp2_matches_single(self):
        cfg1 = _cfg(embedding_buckets="256,128")
        cfg2 = _cfg(mesh_data=1, mesh_model=2, embedding_buckets="256,128")
        _, s1, _ = _fit(cfg1)
        _, s2, _ = _fit(cfg2)
        for key in ("t0", "t1"):
            np.testing.assert_allclose(
                np.asarray(s1.params["fm_v"][key]),
                np.asarray(s2.params["fm_v"][key]), rtol=1e-3, atol=1e-5)


# ---------------------------------------------------------------------------
# Checkpoint resharding
# ---------------------------------------------------------------------------


class TestCheckpointReshard:
    def _trained_2shard(self, tmp_path):
        cfg = _cfg(mesh_data=1, mesh_model=2)
        tr = Trainer(cfg)
        state = tr.init_state()
        state, _ = tr.fit(state, iter(_batches(4, cfg.batch_size)))
        d = str(tmp_path / "ck")
        with ckpt_lib.CheckpointManager(d) as mgr:
            mgr.save(4, state)
        return d, state

    @pytest.mark.parametrize("mesh_kw", [
        dict(embedding_shard="off", mesh_data=1, mesh_model=1),
        dict(mesh_data=1, mesh_model=4),
        dict(mesh_data=4, mesh_model=2),
    ])
    def test_restore_bit_exact_across_shardings(self, tmp_path, mesh_kw):
        d, state = self._trained_2shard(tmp_path)
        tr2 = Trainer(_cfg(**mesh_kw))
        with ckpt_lib.CheckpointManager(d) as mgr:
            restored = mgr.restore(tr2.init_state())
        t_a, m_a, v_a, tau_a = _embed_leaves(state)
        t_b, m_b, v_b, tau_b = _embed_leaves(restored)
        np.testing.assert_array_equal(t_a, t_b)
        np.testing.assert_array_equal(m_a, m_b)
        np.testing.assert_array_equal(v_a, v_b)
        np.testing.assert_array_equal(tau_a, tau_b)
        # and the restored state trains on the new mesh
        restored, out = tr2.fit(
            restored, iter(_batches(2, 64)), max_steps=2)
        assert np.isfinite(out["loss"])


# ---------------------------------------------------------------------------
# grad_payload_bytes accounting
# ---------------------------------------------------------------------------


class TestGradPayloadBytes:
    def _params(self):
        return {
            "fm_w": jnp.zeros((128,), jnp.float32),
            "fm_v": jnp.zeros((128, 8), jnp.float32),
            "mlp": jnp.zeros((16, 4), jnp.float32),
        }

    def test_rows_counts_each_row_once(self):
        p = self._params()
        # rows, 2 shards: embedding leaves /2, + one int32 touched-union
        # mask [rows_local] counted against the first embedding name.
        got = mesh_lib.grad_payload_bytes(
            p, ("fm_w", "fm_v"), 2, embedding_shard="rows")
        want = (128 * 4) // 2 + (128 * 8 * 4) // 2 + (128 // 2) * 4 \
            + 16 * 4 * 4
        assert got == want

    def test_rows_single_shard_is_full_table(self):
        p = self._params()
        got = mesh_lib.grad_payload_bytes(
            p, ("fm_w", "fm_v"), 1, embedding_shard="rows")
        want = 128 * 4 + 128 * 8 * 4 + 128 * 4 + 16 * 4 * 4
        assert got == want

    def test_dense_unchanged(self):
        p = self._params()
        assert mesh_lib.grad_payload_bytes(p, ("fm_w", "fm_v"), 2) == (
            (128 * 4) // 2 + (128 * 8 * 4) // 2 + 16 * 4 * 4)
        assert mesh_lib.grad_payload_bytes(p, ("fm_w", "fm_v"), 1) == (
            128 * 4 + 128 * 8 * 4 + 16 * 4 * 4)

    def test_trainer_uses_sharded_accounting(self):
        tr_rows = Trainer(_cfg(mesh_data=2, mesh_model=2))
        tr_dense = Trainer(_cfg(embedding_update="dense",
                                embedding_shard="off",
                                mesh_data=2, mesh_model=2))
        # same mesh, same tables: the rows plane adds the touched mask on
        # top of the identical /model_size embedding payload.
        assert tr_rows._grad_payload_bytes() > 0
        assert tr_dense._grad_payload_bytes() > 0
