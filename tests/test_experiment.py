"""Gated-deployment plane tests: seeded hash-split arm assignment, the
shadow-lane isolation contract (a raising / NaN-emitting / slow challenger
surfaces ONLY as typed counters while the primary lane stays bit-identical),
the canary kill-switch, guardrail gate evaluation and the promotion
controller's promote/rollback/quarantine state machine, the append-only
``pointer_history.jsonl`` audit sidecar and its crash-heal idempotence, the
per-arm health window, the impression log's experiment fields, the
challenger-poisoning chaos kinds, the experimentation drill's bit-replayable
audit fingerprint, and the ``bench.experiment_series`` schema smoke. The
full-parameter drill rides behind ``slow``."""

import os
import sys
import time

import numpy as np
import pytest

from deepfm_tpu.loop import arm_health
from deepfm_tpu.loop import impressions as impressions_lib
from deepfm_tpu.serve.engine import ServeFuture
from deepfm_tpu.serve.experiment import (ARM_CHALLENGER, ARM_CONTROL,
                                         ExperimentRouter, assign_arm)
from deepfm_tpu.train import promote as promote_lib
from deepfm_tpu.utils import export as export_lib
from deepfm_tpu.utils import faults

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
import production_drill  # noqa: E402

pytestmark = pytest.mark.experiment


# --------------------------------------------------------------------------
# Hash-split arm assignment.
# --------------------------------------------------------------------------

class TestHashSplit:
    def test_deterministic_and_replayable(self):
        arms = [assign_arm(rid, seed=3, challenger_permille=250)
                for rid in range(2000)]
        again = [assign_arm(rid, seed=3, challenger_permille=250)
                 for rid in range(2000)]
        assert arms == again
        assert set(arms) == {ARM_CONTROL, ARM_CHALLENGER}

    def test_permille_proportions(self):
        n = 20_000
        for permille in (0, 50, 500, 1000):
            frac = sum(assign_arm(rid, seed=9, challenger_permille=permille)
                       for rid in range(n)) / n
            assert abs(frac - permille / 1000.0) < 0.02, (permille, frac)

    def test_seed_changes_split_membership(self):
        a = [assign_arm(rid, seed=1, challenger_permille=500)
             for rid in range(1000)]
        b = [assign_arm(rid, seed=2, challenger_permille=500)
             for rid in range(1000)]
        assert a != b


# --------------------------------------------------------------------------
# Stub engine: the router is jax-free, so isolation tests run against a
# synchronous stand-in with the engine's submit surface.
# --------------------------------------------------------------------------

class StubEngine:
    def __init__(self, fn, *, delay_s=0.0, raise_on_submit=None,
                 error_on_resolve=None):
        self.fn = fn
        self.delay_s = delay_s
        self.raise_on_submit = raise_on_submit
        self.error_on_resolve = error_on_resolve
        self.submits = 0

    def submit(self, ids, vals, trace_id=None, value="default"):
        if self.raise_on_submit is not None:
            raise self.raise_on_submit
        self.submits += 1
        fut = ServeFuture(np.asarray(ids), np.asarray(vals),
                          time.monotonic(), trace_id=trace_id, value=value)
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.error_on_resolve is not None:
            fut.set_error(self.error_on_resolve)
        else:
            fut.set_result(self.fn(np.asarray(ids), np.asarray(vals)), 0.1)
        return fut


def _ctl_fn(ids, vals):
    return (ids[:, 0] % 7).astype(np.float32) / 10.0


def _stream(n=60, rows=3, field=4, seed=0):
    rng = np.random.default_rng(seed)
    return [(rid,
             rng.integers(0, 64, (rows, field)).astype(np.int32),
             rng.normal(size=(rows, field)).astype(np.float32))
            for rid in range(n)]


def _drive(router, stream):
    """Primary results for the whole stream, in order."""
    return [router.predict(ids, vals, rid) for rid, ids, vals in stream]


class TestShadowIsolation:
    """The contract: NOTHING a challenger does reaches the primary lane —
    primary outputs are bit-identical to a single-arm run, and trouble
    surfaces only as typed counters."""

    def _baseline(self, stream):
        return [_ctl_fn(ids, vals) for _, ids, vals in stream]

    def _assert_primary_bitexact(self, got, stream):
        for out, want in zip(got, self._baseline(stream)):
            assert out.dtype == want.dtype
            assert np.array_equal(out, want)

    def test_raising_challenger_is_typed_rejection(self):
        stream = _stream()
        r = ExperimentRouter(
            StubEngine(_ctl_fn),
            StubEngine(_ctl_fn, raise_on_submit=RuntimeError("dead arm")),
            mode="shadow", seed=3, challenger_permille=500)
        self._assert_primary_bitexact(_drive(r, stream), stream)
        expected = sum(1 for rid, _, _ in stream
                       if r.assign(rid) == ARM_CHALLENGER)
        assert expected > 0
        assert r.shadow_submit_rejected == expected
        assert r.shadow_submitted == 0 and r.shadow_errors == 0

    def test_nan_challenger_is_typed_counter(self):
        stream = _stream()
        nan_fn = lambda ids, vals: np.full(  # noqa: E731
            ids.shape[0], np.nan, np.float32)
        r = ExperimentRouter(StubEngine(_ctl_fn), StubEngine(nan_fn),
                             mode="shadow", seed=3, challenger_permille=500)
        got = _drive(r, stream)
        self._assert_primary_bitexact(got, stream)
        assert all(np.all(np.isfinite(p)) for p in got)
        expected = sum(1 for rid, _, _ in stream
                       if r.assign(rid) == ARM_CHALLENGER)
        assert r.shadow_nonfinite == expected > 0
        assert r.shadow_completed == expected

    def test_slow_challenger_is_typed_slo_miss(self):
        stream = _stream(n=20)
        r = ExperimentRouter(
            StubEngine(_ctl_fn), StubEngine(_ctl_fn, delay_s=0.01),
            mode="shadow", seed=3, challenger_permille=500,
            shadow_slo_ms=1.0)
        self._assert_primary_bitexact(_drive(r, stream), stream)
        expected = sum(1 for rid, _, _ in stream
                       if r.assign(rid) == ARM_CHALLENGER)
        assert r.shadow_slo_misses == expected > 0
        assert r.shadow_errors == 0

    def test_erroring_challenger_future_is_typed_error(self):
        stream = _stream()
        r = ExperimentRouter(
            StubEngine(_ctl_fn),
            StubEngine(_ctl_fn, error_on_resolve=ValueError("bad flush")),
            mode="shadow", seed=3, challenger_permille=500)
        self._assert_primary_bitexact(_drive(r, stream), stream)
        assert r.shadow_errors > 0 and r.shadow_nonfinite == 0

    def test_shadow_hook_observes_challenger_output(self):
        seen = []
        stream = _stream(n=30)
        r = ExperimentRouter(
            StubEngine(_ctl_fn),
            StubEngine(lambda ids, vals: np.full(ids.shape[0], 0.25,
                                                 np.float32)),
            mode="shadow", seed=3, challenger_permille=1000,
            on_shadow_result=lambda rid, probs, ms: seen.append(
                (rid, probs.copy())))
        _drive(r, stream)
        assert len(seen) == len(stream)
        assert [rid for rid, _ in seen] == [rid for rid, _, _ in stream]
        assert all(np.all(p == np.float32(0.25)) for _, p in seen)


class TestRouterModes:
    def test_off_and_shadow_always_serve_control(self):
        for mode in ("off", "shadow"):
            ctl, ch = StubEngine(_ctl_fn), StubEngine(_ctl_fn)
            r = ExperimentRouter(ctl, ch, mode=mode, seed=3,
                                 challenger_permille=1000)
            futs = [r.submit(ids, vals, rid) for rid, ids, vals in
                    _stream(n=10)]
            assert all(f.arm == ARM_CONTROL for f in futs)
            assert ctl.submits == 10

    def test_ab_serves_assigned_arm(self):
        ctl, ch = StubEngine(_ctl_fn), StubEngine(_ctl_fn)
        r = ExperimentRouter(ctl, ch, mode="ab", seed=3,
                             challenger_permille=500)
        stream = _stream(n=40)
        futs = [r.submit(ids, vals, rid) for rid, ids, vals in stream]
        want = [r.assign(rid) for rid, _, _ in stream]
        assert [f.arm for f in futs] == want
        assert ch.submits == sum(want) > 0
        assert ctl.submits == len(stream) - ch.submits
        assert r.requests_by_arm[ARM_CHALLENGER] == ch.submits

    def test_mode_and_permille_validated(self):
        with pytest.raises(ValueError):
            ExperimentRouter(StubEngine(_ctl_fn), mode="bogus")
        with pytest.raises(ValueError):
            ExperimentRouter(StubEngine(_ctl_fn), StubEngine(_ctl_fn),
                             mode="ab", challenger_permille=1001)
        with pytest.raises(ValueError):
            ExperimentRouter(StubEngine(_ctl_fn), mode="ab")  # no challenger


class TestKillSwitch:
    def test_canary_kill_collapses_to_control_and_revive_restores(self):
        ctl, ch = StubEngine(_ctl_fn), StubEngine(_ctl_fn)
        r = ExperimentRouter(ctl, ch, mode="canary", seed=3,
                             challenger_permille=1000)
        assert r.submit(*_stream(n=1)[0][1:], 0).arm == ARM_CHALLENGER
        r.kill("2: nonfinite_predictions")
        assert r.killed and r.kills == 1
        assert r.kill_reason == "2: nonfinite_predictions"
        futs = [r.submit(ids, vals, rid) for rid, ids, vals in _stream(n=8)]
        assert all(f.arm == ARM_CONTROL for f in futs)
        assert ch.submits == 1   # nothing after the kill
        r.revive()
        assert not r.killed
        assert r.submit(*_stream(n=1)[0][1:], 0).arm == ARM_CHALLENGER

    def test_shadow_kill_stops_duplication(self):
        ctl, ch = StubEngine(_ctl_fn), StubEngine(_ctl_fn)
        r = ExperimentRouter(ctl, ch, mode="shadow", seed=3,
                             challenger_permille=1000)
        _drive(r, _stream(n=5))
        assert r.shadow_submitted == 5
        r.kill("breach")
        _drive(r, _stream(n=5))
        assert r.shadow_submitted == 5
        assert ctl.submits == 10   # primary lane unaffected


# --------------------------------------------------------------------------
# Guardrail gates (pure function) + promotion controller state machine.
# --------------------------------------------------------------------------

HEALTHY = dict(arm=1, n=500, auc=0.74, p99_latency_ms=5.0, nonfinite=0,
               mean_pred=0.5, observed_ctr=0.5, calibration_err=0.0)
CONTROL = dict(HEALTHY, arm=0, auc=0.73)

GATES = dict(min_samples=10, min_auc_delta=-0.02, max_p99_ratio=3.0,
             max_p99_ms=100.0, max_nonfinite=0, max_calibration_err=0.2,
             max_candidate_age_s=600.0, windows_required=2)


def _gates(**kw):
    return promote_lib.GateConfig(**dict(GATES, **kw))


class TestGateEvaluation:
    def test_healthy_window_passes(self):
        passed, breaches, holds = promote_lib.evaluate_gates(
            HEALTHY, CONTROL, _gates(), candidate_age_s=10.0)
        assert passed and not breaches and not holds

    def test_each_breach_reason_is_typed(self):
        cases = [
            (dict(HEALTHY, nonfinite=1), promote_lib.REASON_NONFINITE),
            (dict(HEALTHY, auc=0.60), promote_lib.REASON_AUC),
            (dict(HEALTHY, p99_latency_ms=5 * CONTROL["p99_latency_ms"]
                  * 3.0), promote_lib.REASON_LATENCY),
            (dict(HEALTHY, calibration_err=0.3),
             promote_lib.REASON_CALIBRATION),
        ]
        for health, reason in cases:
            passed, breaches, _ = promote_lib.evaluate_gates(
                health, CONTROL, _gates(), candidate_age_s=10.0)
            assert not passed and breaches == [reason], (health, breaches)

    def test_absolute_p99_ceiling_is_independent_of_ratio(self):
        """The ceiling fires even when the ratio gate is parked wide open
        (the drill's configuration — ratios are timing noise on a 1-core
        host, the ceiling is detection-by-construction)."""
        slow = dict(HEALTHY, p99_latency_ms=250.0)
        passed, breaches, _ = promote_lib.evaluate_gates(
            slow, CONTROL, _gates(max_p99_ratio=1e6, max_p99_ms=150.0),
            candidate_age_s=10.0)
        assert breaches == [promote_lib.REASON_LATENCY]
        # And 0 disables the ceiling entirely.
        passed, breaches, _ = promote_lib.evaluate_gates(
            slow, CONTROL, _gates(max_p99_ratio=1e6, max_p99_ms=0.0),
            candidate_age_s=10.0)
        assert passed, breaches

    def test_staleness_breaches_on_age_alone(self):
        passed, breaches, _ = promote_lib.evaluate_gates(
            HEALTHY, CONTROL, _gates(), candidate_age_s=601.0)
        assert breaches == [promote_lib.REASON_STALE]
        # ... even on an EMPTY window: a frozen candidate that stopped
        # refreshing must not hide behind a min_samples hold.
        passed, breaches, holds = promote_lib.evaluate_gates(
            {}, {}, _gates(), candidate_age_s=601.0)
        assert promote_lib.REASON_STALE in breaches

    def test_thin_window_is_hold_not_breach(self):
        passed, breaches, holds = promote_lib.evaluate_gates(
            dict(HEALTHY, n=3), CONTROL, _gates(), candidate_age_s=10.0)
        assert not passed and not breaches
        assert holds == [promote_lib.REASON_SAMPLES]

    def test_gate_config_validation(self):
        with pytest.raises(ValueError):
            _gates(max_p99_ms=-1.0)
        with pytest.raises(ValueError):
            _gates(min_samples=0)
        with pytest.raises(ValueError):
            _gates(windows_required=0)


@pytest.fixture
def publish_dir(tmp_path):
    d = str(tmp_path / "publish")
    for version in ("1", "2"):   # read_latest refuses dangling pointers
        os.makedirs(os.path.join(d, version))
    export_lib.write_latest(d, "1")
    return d


class TestPromotionController:
    def test_promotes_after_required_windows(self, publish_dir):
        ctl = promote_lib.PromotionController(publish_dir, gates=_gates())
        assert ctl.offer("2")
        d1 = ctl.observe(HEALTHY, CONTROL)
        assert d1.action == "pass" and d1.version == "2"
        assert os.path.basename(export_lib.read_latest(publish_dir)) == "1"
        d2 = ctl.observe(HEALTHY, CONTROL)
        assert d2.action == "promote"
        assert os.path.basename(export_lib.read_latest(publish_dir)) == "2"
        assert ctl.stable_version == "2" and ctl.candidate is None
        actors = [e["actor"] for e in ctl.history()]
        assert actors[-1] == "promote"

    def test_breach_rolls_back_and_kill_switch_fires_first(
            self, publish_dir):
        calls = []

        def on_rollback(version, reason):
            # The pointer must NOT have moved yet when the hook fires:
            # traffic stops reaching the bad arm before the audit write.
            calls.append((version, reason, os.path.basename(
                export_lib.read_latest(publish_dir))))

        ctl = promote_lib.PromotionController(
            publish_dir, gates=_gates(), on_rollback=on_rollback)
        ctl.offer("2")
        ctl.observe(HEALTHY, CONTROL)   # one passing window, then poison
        d = ctl.observe(dict(HEALTHY, nonfinite=4), CONTROL)
        assert d.action == "rollback"
        assert d.reasons == (promote_lib.REASON_NONFINITE,)
        assert calls == [("2", promote_lib.REASON_NONFINITE, "1")]
        assert os.path.basename(export_lib.read_latest(publish_dir)) == "1"
        assert ctl.rollbacks == 1
        assert ctl.breaches_by_reason == {promote_lib.REASON_NONFINITE: 1}
        # A rollback resets the passing streak: the next offer starts over.
        assert ctl.passing_windows == 0

    def test_second_failure_quarantines_and_refuses_reoffer(
            self, publish_dir):
        ctl = promote_lib.PromotionController(publish_dir, gates=_gates())
        for k in range(promote_lib.QUARANTINE_FAILURES):
            assert ctl.offer("2")
            d = ctl.observe(dict(HEALTHY, calibration_err=0.5), CONTROL)
        assert d.action == "quarantine"
        assert "2" in ctl.quarantined
        assert not ctl.offer("2") and ctl.offers_refused == 1
        # History carries the audit trail: rollback line(s) + quarantine.
        actors = [e["actor"] for e in ctl.history()]
        assert actors.count("quarantine") == 1
        assert ctl.stats()["rollbacks"] == 2
        assert ctl.stats()["quarantines"] == 1

    def test_offering_stable_version_refused(self, publish_dir):
        ctl = promote_lib.PromotionController(publish_dir, gates=_gates())
        assert not ctl.offer("1")
        assert ctl.observe(HEALTHY, CONTROL).action == "hold"


# --------------------------------------------------------------------------
# Pointer-history sidecar: append-then-move protocol, crash-heal
# idempotence through the publish-crash seam.
# --------------------------------------------------------------------------

class TestPointerHistory:
    def test_append_order_and_fields(self, tmp_path):
        d = str(tmp_path)
        export_lib.append_pointer_event(d, "1", "publish", wall_time=5.0)
        export_lib.append_pointer_event(d, "2", "promote",
                                        "passed 2 windows", wall_time=6.0)
        hist = export_lib.pointer_history(d)
        assert [(e["version"], e["actor"]) for e in hist] == \
            [("1", "publish"), ("2", "promote")]
        assert hist[0]["wall_time"] == 5.0
        assert hist[1]["reason"] == "passed 2 windows"
        # The reader rides on read_latest: one surface for pointer +
        # provenance.
        assert export_lib.read_latest.history(d) == hist

    def test_tail_dedupe_is_exact_triple_match(self, tmp_path):
        d = str(tmp_path)
        export_lib.append_pointer_event(d, "1", "publish")
        export_lib.append_pointer_event(d, "1", "publish")   # replay
        assert len(export_lib.pointer_history(d)) == 1
        export_lib.append_pointer_event(d, "1", "rollback", "2: breach")
        export_lib.append_pointer_event(d, "1", "publish")   # NOT the tail
        assert [e["actor"] for e in export_lib.pointer_history(d)] == \
            ["publish", "rollback", "publish"]

    def test_crash_between_history_and_pointer_heals(self, tmp_path):
        """Append-then-move: a crash after the history append but before
        the LATEST write leaves a truthful audit line and a stale pointer;
        the retried publish re-runs both steps and the tail-dedupe absorbs
        the duplicate append — exactly one line, pointer moved."""
        d = str(tmp_path)
        for version in ("1", "2"):
            os.makedirs(os.path.join(d, version))
        export_lib.write_latest(d, "1")

        def publish(version):
            export_lib.append_pointer_event(d, version, "publish")
            faults.check_publish_crash("after_history_before_latest")
            export_lib.write_latest(d, version)

        faults.set_publish_crash("after_history_before_latest")
        with pytest.raises(faults.InjectedFault):
            publish("2")
        assert os.path.basename(export_lib.read_latest(d)) == "1"
        assert len(export_lib.pointer_history(d)) == 1
        publish("2")   # the heal
        assert os.path.basename(export_lib.read_latest(d)) == "2"
        hist = export_lib.pointer_history(d)
        assert len(hist) == 1 and hist[0]["version"] == "2"

    def test_torn_tail_dropped(self, tmp_path):
        d = str(tmp_path)
        export_lib.append_pointer_event(d, "1", "publish")
        with open(os.path.join(d, export_lib.POINTER_HISTORY_FILE),
                  "a") as f:
            f.write('{"version": "2", "actor": "pro')   # crash mid-append
        hist = export_lib.pointer_history(d)
        assert len(hist) == 1 and hist[0]["version"] == "1"


# --------------------------------------------------------------------------
# Per-arm health window + the impression log's experiment fields.
# --------------------------------------------------------------------------

class TestArmHealth:
    def test_known_values(self):
        samples = [
            (0, 1.0, 0.9, 10.0), (0, 0.0, 0.1, 20.0),
            (0, 1.0, 0.8, 30.0), (0, 0.0, 0.2, 40.0),
            (1, 1.0, 0.3, 5.0), (1, 0.0, 0.7, 6.0),
        ]
        h = arm_health(samples)
        assert set(h) == {0, 1}
        ctl = h[0]
        assert ctl["n"] == 4 and ctl["auc"] == 1.0
        assert ctl["nonfinite"] == 0
        assert ctl["mean_pred"] == 0.5 and ctl["observed_ctr"] == 0.5
        assert ctl["calibration_err"] == 0.0
        assert ctl["p99_latency_ms"] == pytest.approx(40.0, abs=1.0)
        assert h[1]["auc"] == 0.0   # perfectly anti-ranked challenger

    def test_nonfinite_rows_counted_but_excluded(self):
        h = arm_health([(1, 1.0, 0.9, 1.0), (1, 0.0, 0.1, 1.0),
                        (1, 1.0, float("nan"), 1.0)])
        a = h[1]
        assert a["n"] == 3 and a["nonfinite"] == 1
        assert a["auc"] == 1.0            # the NaN row poisons no other gate
        assert a["mean_pred"] == 0.5

    def test_one_class_window_has_no_auc(self):
        h = arm_health([(0, 1.0, 0.6, 1.0), (0, 1.0, 0.7, 2.0)])
        assert h[0]["auc"] is None
        assert h[0]["observed_ctr"] == 1.0

    def test_empty_and_deterministic(self):
        assert arm_health([]) == {}
        samples = [(k % 2, float(k % 3 == 0), 0.1 * (k % 10), float(k))
                   for k in range(50)]
        assert arm_health(samples) == arm_health(list(samples))


class TestImpressionExperimentFields:
    def test_arm_and_pred_roundtrip_float32_exact(self):
        ids = np.arange(4, dtype=np.int64)
        vals = np.ones(4, np.float32)
        buf = impressions_lib.encode_impression(
            7, 1.5, ids, vals, arm=ARM_CHALLENGER, pred=0.1)
        arm, pred = impressions_lib.read_experiment(buf)
        assert arm == ARM_CHALLENGER
        assert pred == float(np.float32(0.1))   # the exact served float32
        # Unstamped records read back as None (pre-experiment writers).
        arm, pred = impressions_lib.read_experiment(
            impressions_lib.encode_impression(8, 1.5, ids, vals))
        assert arm is None and pred is None

    def test_logger_stamps_experiment_fields(self, tmp_path):
        from deepfm_tpu.data import tfrecord
        logger = impressions_lib.ImpressionLogger(str(tmp_path))
        ids = np.arange(4, dtype=np.int64)
        logger.log(11, ids, np.ones(4, np.float32), 2.0,
                   arm=ARM_CONTROL, pred=0.75)
        path = logger.close()
        (rec,) = list(tfrecord.iter_records(path))
        assert impressions_lib.read_experiment(rec) == (0, 0.75)
        iid, _, got_ids, _ = impressions_lib.decode_impression(rec)
        assert iid == 11 and np.array_equal(got_ids, ids)


# --------------------------------------------------------------------------
# Challenger-poisoning chaos kinds.
# --------------------------------------------------------------------------

class TestChallengerChaos:
    def test_new_kinds_are_driver_kinds(self):
        for kind in ("challenger_nan", "challenger_stale",
                     "challenger_slow"):
            assert kind in faults.ChaosSchedule.DRIVER_KINDS

    def test_generate_carries_kind_params_and_replays(self):
        kw = dict(horizon_s=10.0, challenger_nan_events=1,
                  challenger_nan_batches=4, challenger_slow_events=1,
                  challenger_slow_ms=250.0, challenger_stale_events=1)
        sched = faults.ChaosSchedule.generate(7, **kw)
        kinds = {e.kind: e for e in sched.events}
        assert len(kinds["challenger_nan"].get("batches")) == 4
        assert kinds["challenger_slow"].get("delay_ms") == 250.0
        assert "challenger_stale" in kinds
        assert sched.fingerprint() == \
            faults.ChaosSchedule.generate(7, **kw).fingerprint()

    def test_old_schedules_bit_identical_without_challenger_events(self):
        """Adding the challenger kinds must not perturb pre-existing
        schedules: the new rng draws happen strictly AFTER the old kinds'
        draws, so a schedule with zero challenger events is byte-for-byte
        what it was before the feature existed."""
        sched = faults.ChaosSchedule.generate(
            11, horizon_s=4.0, executor_slow_events=1,
            executor_slow_ms=40.0, executor_slow_calls=25)
        assert not any(e.kind.startswith("challenger")
                       for e in sched.events)
        assert sched.fingerprint() == faults.ChaosSchedule.generate(
            11, horizon_s=4.0, executor_slow_events=1,
            executor_slow_ms=40.0, executor_slow_calls=25).fingerprint()

    def test_nan_plan_seam_roundtrip(self):
        faults.set_nan_plan([2, 5], value=float("nan"))
        plan = faults.take_nan_plan()
        assert plan is not None and sorted(plan["batches"]) == [2, 5]
        assert faults.take_nan_plan() is None   # one-shot


# --------------------------------------------------------------------------
# The experimentation drill: healthy challenger shadow -> canary ->
# promoted; poisoned challengers detected, rolled back, quarantined — with
# zero primary-lane loss and a bit-replayable audit fingerprint.
# --------------------------------------------------------------------------

class TestExperimentDrill:
    def test_smoke_drill_end_to_end_and_bit_replayable(self, tmp_path):
        reports = [
            production_drill.run_experiment_drill(
                str(tmp_path / f"run{k}"), seed=7,
                params=production_drill.EXPERIMENT_SMOKE)
            for k in range(2)
        ]
        r = reports[0]
        assert r["ok"]
        # Zero primary-lane loss, throughout every phase.
        assert r["primary"]["failed"] == 0
        assert r["primary"]["nonfinite"] == 0
        # The healthy challenger was promoted; LATEST points at it.
        assert r["promotion"]["promotions"] == 1
        assert r["stable_version"] == "1"
        # Every poisoned challenger: detected, rolled back, quarantined,
        # with its typed reason (the drill itself also asserts the
        # re-offer of a quarantined version is refused).
        assert {s["kind"] for s in r["scenarios"]} == \
            {"challenger_nan", "challenger_slow", "challenger_stale"}
        for s in r["scenarios"]:
            actions = [d[0] for d in s["decisions"]]
            assert actions == ["rollback", "quarantine"], s
            assert all(s["expected_reason"] in d[2]
                       for d in s["decisions"]), s
        # Online per-arm health == pure offline recomputation, bit-exact.
        assert r["arm_health_offline_match"]
        # Bit-replayable: same seed => identical audit fingerprint.
        assert reports[0]["audit_fingerprint"] == \
            reports[1]["audit_fingerprint"]

    def test_different_seed_different_fingerprint(self, tmp_path):
        r7 = production_drill.run_experiment_drill(
            str(tmp_path / "a"), seed=7,
            params=production_drill.EXPERIMENT_SMOKE)
        r9 = production_drill.run_experiment_drill(
            str(tmp_path / "b"), seed=9,
            params=production_drill.EXPERIMENT_SMOKE)
        assert r7["audit_fingerprint"] != r9["audit_fingerprint"]

    @pytest.mark.slow
    def test_full_params_drill(self, tmp_path):
        r = production_drill.run_experiment_drill(str(tmp_path / "full"),
                                                  seed=7)
        assert r["ok"] and r["arm_health_offline_match"]
        assert r["primary"]["failed"] == 0


# --------------------------------------------------------------------------
# bench.experiment_series schema smoke.
# --------------------------------------------------------------------------

class TestExperimentBench:
    def test_series_schema_and_detection_contract(self):
        import bench
        out = bench.experiment_series(n_requests=30, qps=200.0, rounds=1)
        for key in ("baseline_p99_ms", "shadow_p99_ms",
                    "shadow_p99_overhead_pct", "shadow_duplicated",
                    "promotion_pointer_move_p50_ms", "rollback_detection",
                    "load_kind", "device_kind", "host_cpu_count"):
            assert key in out, key
        assert out["shadow_errors"] == 0 and out["shadow_nonfinite"] == 0
        assert out["shadow_duplicated"] > 0
        assert out["promotion_pointer_move_p50_ms"] > 0
        # Every poison kind detects in exactly ONE health window — the
        # guardrails-went-soft trip-wire.
        det = out["rollback_detection"]
        assert set(det) == {"nan", "latency", "calibration", "stale"}
        for kind, row in det.items():
            assert row["windows"] == 1 and row["reason_typed"], (kind, row)
