"""Numerical-guard and stall-watchdog tests: TrainHealth accounting,
NonFiniteGuard policy semantics (abort/skip/rollback + shared budget +
EMA loss-spike detector), fit-level skip bit-identity, watchdog firing
with injected clock/abort, and the input-worker ring-read stall timeout.
CPU-only; the watchdog tests use a fake clock (no real timeout sleeps)."""

import threading
import time

import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.data import workers
from deepfm_tpu.data.health import DataHealth
from deepfm_tpu.train import Trainer, tasks
from deepfm_tpu.train import guard as guard_lib

pytestmark = pytest.mark.preempt

NAN = float("nan")


class TestTrainHealth:
    def test_counters_and_snapshot(self):
        th = guard_lib.TrainHealth()
        th.record_preemption()
        th.record_nonfinite_skip()
        th.record_nonfinite_skip()
        th.record_rollback()
        th.record_watchdog_abort()
        th.record_loss_spike()
        th.record_resume_meta_corrupt()
        snap = th.snapshot()
        assert snap == {"preemptions": 1, "nonfinite_skips": 2,
                        "rollbacks": 1, "watchdog_aborts": 1,
                        "loss_spikes": 1, "resume_meta_corrupt": 1}
        assert th.total_events == 7

    def test_merge_into_and_summary(self):
        th = guard_lib.TrainHealth()
        th.record_rollback()
        totals = {"rollbacks": 2.0}
        th.merge_into(totals)
        assert totals["rollbacks"] == 3.0
        assert totals["preemptions"] == 0
        assert "rollbacks=1" in th.summary()

    def test_consume_dirty(self):
        th = guard_lib.TrainHealth()
        assert th.consume_dirty() is False
        th.record_nonfinite_skip()
        assert th.consume_dirty() is True
        assert th.consume_dirty() is False  # one-shot until the next event

    def test_thread_safety(self):
        th = guard_lib.TrainHealth()
        threads = [threading.Thread(
            target=lambda: [th.record_nonfinite_skip() for _ in range(500)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert th.nonfinite_skips == 2000


class TestNonFiniteGuardUnits:
    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError, match="abort"):
            guard_lib.NonFiniteGuard(policy="explode")

    def test_ok_path(self):
        g = guard_lib.NonFiniteGuard(policy="skip")
        assert g.observe(0.5, 1) == "ok"
        assert g.events == 0
        assert g.per_dispatch is True

    def test_abort_raises_with_step(self):
        g = guard_lib.NonFiniteGuard(policy="abort")
        assert g.per_dispatch is False
        with pytest.raises(guard_lib.NonFiniteError, match="step 7"):
            g.observe(NAN, 7)

    def test_abort_on_bad_params_with_finite_loss(self):
        g = guard_lib.NonFiniteGuard(policy="abort")
        with pytest.raises(guard_lib.NonFiniteError,
                           match="non-finite parameters"):
            g.observe(0.3, 9, params_bad=True)

    def test_skip_counts_and_budget(self):
        th = guard_lib.TrainHealth()
        g = guard_lib.NonFiniteGuard(policy="skip", max_events=2, health=th)
        assert g.per_dispatch is True
        assert g.observe(NAN, 1) == "skip"
        assert g.observe(float("inf"), 2) == "skip"
        assert th.nonfinite_skips == 2
        with pytest.raises(guard_lib.NonFiniteError,
                           match="budget exhausted"):
            g.observe(NAN, 3)

    def test_rollback_verdict_shares_budget(self):
        g = guard_lib.NonFiniteGuard(policy="rollback", max_events=1)
        assert g.observe(NAN, 4) == "rollback"
        with pytest.raises(guard_lib.NonFiniteError, match="budget"):
            g.observe(NAN, 5)

    def test_from_config(self):
        cfg = Config(data_dir="/tmp/x", on_nonfinite="rollback",
                     max_rollbacks=7, loss_spike_zscore=4.0)
        g = guard_lib.NonFiniteGuard.from_config(cfg)
        assert g.policy == "rollback" and g.max_events == 7
        assert g.spike_zscore == 4.0

    def test_spike_detector(self):
        th = guard_lib.TrainHealth()
        g = guard_lib.NonFiniteGuard(policy="abort", health=th,
                                     spike_zscore=4.0, spike_warmup=5)
        for i in range(20):  # well-behaved losses (~1 sigma wiggle)
            g.observe(0.7 + 0.01 * (-1) ** i, i)
        assert th.loss_spikes == 0
        ema_before = g._ema
        g.observe(50.0, 21)  # a 100-sigma excursion, still finite
        assert th.loss_spikes == 1
        assert g._ema == ema_before  # a spike must not poison its baseline
        g.observe(0.7, 22)
        assert th.loss_spikes == 1  # back to normal: no new spike

    def test_spike_detector_disabled_by_default(self):
        th = guard_lib.TrainHealth()
        g = guard_lib.NonFiniteGuard(policy="abort", health=th)
        for i in range(30):
            g.observe(0.5 if i != 25 else 1e6, i)
        assert th.loss_spikes == 0

    def test_params_nonfinite_detects(self):
        class S:
            params = {"w": np.ones(4, np.float32),
                      "ids": np.arange(4, dtype=np.int32)}
        g = guard_lib.NonFiniteGuard(policy="skip")
        assert g.params_nonfinite(S()) is False
        S.params = {"w": np.array([1.0, NAN], np.float32)}
        assert g.params_nonfinite(S()) is True
        # int leaves are exempt (isfinite is undefined on them)
        S.params = {"ids": np.arange(4, dtype=np.int32)}
        assert g.params_nonfinite(S()) is False


def _cfg(**kw):
    base = dict(
        feature_size=50, field_size=4, embedding_size=4, deep_layers="8",
        dropout="1.0", batch_size=8, compute_dtype="float32",
        learning_rate=0.05, log_steps=0, seed=13, scale_lr_by_world=False,
        mesh_data=1, mesh_model=1)
    base.update(kw)
    return Config(**base)


def _batches(n, bs=8, fields=4, nan_at=()):
    rng = np.random.default_rng(42)
    out = []
    for i in range(n):
        b = {"feat_ids": rng.integers(0, 50, (bs, fields)).astype(np.int32),
             "feat_vals": rng.normal(size=(bs, fields)).astype(np.float32),
             "label": (rng.random((bs, 1)) < 0.3).astype(np.float32)}
        if i in nan_at:
            b["feat_vals"] = np.full((bs, fields), NAN, np.float32)
        out.append(b)
    return out


def _params(state):
    import jax
    return jax.tree.map(np.asarray, state.params)


def _assert_equal(a, b):
    import jax
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestFitGuardPolicies:
    def test_skip_is_bit_identical_to_clean_run_without_poison(self):
        # Guarded run over [b0, b1, NAN, b2, b3] must equal a clean run
        # over [b0, b1, b2, b3]: the poisoned dispatch is consumed but its
        # update (and its rng/step advance) never happened.
        clean = _batches(4)
        poisoned = clean[:2] + _batches(3, nan_at=(0,))[:1] + clean[2:]
        cfg = _cfg(on_nonfinite="skip")

        tr_clean = Trainer(cfg)
        s_clean, sum_clean = tr_clean.fit(tr_clean.init_state(), clean)

        th = guard_lib.TrainHealth()
        guard = guard_lib.NonFiniteGuard.from_config(cfg, health=th)
        tr = Trainer(cfg)
        s_guard, sum_guard = tr.fit(tr.init_state(), poisoned, guard=guard)

        assert sum_guard["steps"] == sum_clean["steps"] == 4
        assert int(s_guard.step) == int(s_clean.step) == 4
        assert th.nonfinite_skips == 1
        _assert_equal(_params(s_clean), _params(s_guard))
        np.testing.assert_array_equal(np.asarray(s_clean.rng),
                                      np.asarray(s_guard.rng))

    def test_skip_reports_finite_final_loss(self):
        # The last dispatch is poisoned: the summary loss must come from
        # the last ACCEPTED dispatch, not the dropped one.
        cfg = _cfg(on_nonfinite="skip")
        guard = guard_lib.NonFiniteGuard.from_config(cfg)
        tr = Trainer(cfg)
        _, summary = tr.fit(tr.init_state(), _batches(4, nan_at=(3,)),
                            guard=guard)
        assert summary["steps"] == 3
        assert np.isfinite(summary["loss"])

    def test_abort_raises_on_log_cadence(self):
        cfg = _cfg(on_nonfinite="abort", log_steps=1)
        guard = guard_lib.NonFiniteGuard.from_config(cfg)
        tr = Trainer(cfg)
        with pytest.raises(guard_lib.NonFiniteError, match="non-finite"):
            tr.fit(tr.init_state(), _batches(4, nan_at=(1,)), guard=guard)

    def test_rollback_raises_signal(self):
        cfg = _cfg(on_nonfinite="rollback")
        guard = guard_lib.NonFiniteGuard.from_config(cfg)
        tr = Trainer(cfg)
        with pytest.raises(guard_lib.RollbackSignal) as ei:
            tr.fit(tr.init_state(), _batches(4, nan_at=(2,)), guard=guard)
        assert ei.value.step == 3  # step AFTER the poisoned dispatch

    def test_budget_exhaustion_aborts_mid_fit(self):
        cfg = _cfg(on_nonfinite="skip", max_rollbacks=1)
        guard = guard_lib.NonFiniteGuard.from_config(cfg)
        tr = Trainer(cfg)
        with pytest.raises(guard_lib.NonFiniteError, match="budget"):
            tr.fit(tr.init_state(), _batches(6, nan_at=(1, 3)), guard=guard)

    def test_skip_under_steps_per_loop_scan(self):
        # A poisoned batch inside a k=2 scan group drops the WHOLE group's
        # update (the scan is one dispatch); the clean groups still train.
        cfg = _cfg(on_nonfinite="skip", steps_per_loop=2)
        guard = guard_lib.NonFiniteGuard.from_config(cfg)
        tr = Trainer(cfg)
        state, summary = tr.fit(tr.init_state(), _batches(6, nan_at=(2,)),
                                guard=guard)
        assert summary["steps"] == 4  # groups (0,1) and (4,5) accepted
        assert int(state.step) == 4
        assert guard.health.nonfinite_skips == 1


class TestStallWatchdog:
    def _wait_for(self, pred, timeout=5.0):
        deadline = time.time() + timeout
        while not pred():
            if time.time() > deadline:
                raise AssertionError("watchdog condition never became true")
            time.sleep(0.005)

    def test_fires_with_diagnostic_dump(self):
        t = [0.0]
        fired = []
        th = guard_lib.TrainHealth()
        dh = DataHealth()
        wd = guard_lib.StallWatchdog(
            30.0, health=th, data_health=dh, abort=fired.append,
            clock=lambda: t[0], poll_s=0.001)
        with wd:
            wd.beat(17)
            t[0] = 31.0
            self._wait_for(lambda: fired)
        dump = fired[0]
        assert "no dispatch completed" in dump
        assert "step 17" in dump
        assert "data health:" in dump and "train health:" in dump
        assert th.watchdog_aborts == 1
        assert wd.fired is True

    def test_beats_keep_it_quiet(self):
        t = [0.0]
        fired = []
        wd = guard_lib.StallWatchdog(10.0, abort=fired.append,
                                     clock=lambda: t[0], poll_s=0.001)
        with wd:
            for i in range(5):
                t[0] += 9.0  # always under the timeout since the last beat
                wd.beat(i)
                time.sleep(0.005)
        assert not fired and wd.fired is False

    def test_trainer_builds_watchdog_only_when_configured(self):
        tr = Trainer(_cfg(dispatch_timeout_s=0.0))
        assert tr._make_watchdog(None, None) is None
        tr2 = Trainer(_cfg(dispatch_timeout_s=60.0))

        def aborter(dump):
            pass

        tr2.watchdog_abort = aborter
        wd = tr2._make_watchdog(None, None)
        try:
            assert wd is not None and wd._abort is aborter
        finally:
            wd.stop()

    def test_fit_stall_aborts_via_injected_hook(self):
        # Integration: a source that stops producing mid-run trips the
        # watchdog, which calls the injected abort instead of os._exit.
        cfg = _cfg(dispatch_timeout_s=0.15)
        tr = Trainer(cfg)
        fired = threading.Event()
        dumps = []
        tr.watchdog_abort = lambda d: (dumps.append(d), fired.set())

        def stalling_source():
            yield from _batches(2)
            fired.wait(timeout=10.0)  # stall until the watchdog trips

        state, summary = tr.fit(tr.init_state(), stalling_source())
        assert fired.is_set(), "watchdog never fired on the stalled source"
        assert summary["steps"] == 2
        assert "no dispatch completed" in dumps[0]


class TestInputStallTimeout:
    class _EmptyRing:
        def pop(self, timeout):
            raise workers._queue.Empty

    class _AliveProc:
        def is_alive(self):
            return True

    class _DeadProc:
        exitcode = 9

        def is_alive(self):
            return False

    def _service(self, ring, proc, stall_timeout_s):
        svc = workers.ShmInputService.__new__(workers.ShmInputService)
        svc._rings = [ring]
        svc._procs = [proc]
        svc._poll_secs = 0.05  # accounting unit only: pop returns instantly
        svc._stall_timeout_s = stall_timeout_s
        svc.health = DataHealth()
        return svc

    def test_alive_but_silent_worker_raises_stall(self):
        svc = self._service(self._EmptyRing(), self._AliveProc(), 0.2)
        with pytest.raises(workers.InputStallError) as ei:
            svc._pop(0)
        msg = str(ei.value)
        assert "worker 0" in msg and "stall_timeout_s" in msg
        assert "data health" in msg

    def test_zero_timeout_keeps_waiting(self):
        # stall_timeout_s=0 (the default) must preserve the wait-forever
        # behavior: a dead worker still surfaces as _WorkerDied, never as a
        # stall.
        ring = self._EmptyRing()
        svc = self._service(ring, self._DeadProc(), 0.0)
        with pytest.raises(workers._WorkerDied):
            svc._pop(0)

    def test_dead_worker_beats_stall_classification(self):
        svc = self._service(self._EmptyRing(), self._DeadProc(), 10.0)
        with pytest.raises(workers._WorkerDied):
            svc._pop(0)

    def test_pipeline_threads_timeout_to_service(self, tmp_path):
        cfg = _cfg(data_dir=str(tmp_path), dispatch_timeout_s=2.5)
        pipe = tasks.make_pipeline(cfg, ["tr_none.tfrecord"])
        assert pipe.stall_timeout_s == 2.5
