"""True multi-process distributed test: 2 OS processes, jax.distributed
rendezvous, a 4x2 ('data','model') mesh spanning both — DP gradient psum AND
cross-process row-sharded embeddings, end-to-end through the CLI launcher.

This is the "local cluster" validation the reference did by hand-building
TF_CONFIG and launching ps/chief/worker processes (``set_dist_env``,
``1-ps-cpu/...py:294-339``) — here it's automated (SURVEY.md §4).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from deepfm_tpu.data import libsvm

# Every test here spawns a real 2-process jax.distributed cluster on the CPU
# backend; gated on the conftest cross-process-collectives probe. Also
# `slow`: each cluster pays two interpreter+jax cold starts plus a
# rendezvous, minutes per test on a 1-core host — run with `-m slow`
# (tier 2, see README "Running the tests").
pytestmark = [pytest.mark.mp_collectives, pytest.mark.slow]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUNNER = """
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
from deepfm_tpu.launch import main
sys.exit(main(sys.argv[1:]))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def mp_workdir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mp")
    libsvm.generate_synthetic_ctr(
        str(d / "data"), num_files=4, examples_per_file=128,
        feature_size=300, field_size=5, prefix="tr", seed=11)
    libsvm.generate_synthetic_ctr(
        str(d / "data"), num_files=1, examples_per_file=128,
        feature_size=300, field_size=5, prefix="va", seed=12)
    libsvm.generate_synthetic_ctr(
        str(d / "data"), num_files=1, examples_per_file=100,
        feature_size=300, field_size=5, prefix="te", seed=13)
    return d


def test_two_process_train(mp_workdir):
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=_REPO,
    )
    args = [
        "--task_type", "train",
        "--dist_mode", "1",
        "--num_processes", "2",
        "--coordinator_address", f"localhost:{port}",
        "--data_dir", str(mp_workdir / "data"),
        "--val_data_dir", str(mp_workdir / "data"),
        "--model_dir", str(mp_workdir / "ckpt"),
        "--feature_size", "300", "--field_size", "5",
        "--embedding_size", "8", "--deep_layers", "16,8",
        "--dropout", "1.0,1.0", "--batch_size", "64",
        "--num_epochs", "2", "--learning_rate", "0.05",
        "--scale_lr_by_world", "false",
        "--compute_dtype", "float32",
        "--mesh_data", "4", "--mesh_model", "2",
        "--log_steps", "0", "--save_checkpoints_steps", "5",
        "--seed", "3",
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RUNNER] + args + ["--process_id", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        for r in range(2)
    ]
    outs = []
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"rank {r} failed:\n{err[-3000:]}"
        outs.append(out)

    results = []
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        results.append(json.loads(line))

    # Replicated-by-construction training: every rank reports the SAME
    # loss/AUC (the broadcast-hook analog holds through real psum traffic).
    assert results[0]["steps"] == 2 * (4 * 128 // 64)
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], abs=1e-6)
    assert results[0]["auc"] == pytest.approx(results[1]["auc"], abs=1e-6)
    assert results[0]["auc"] > 0.55, results[0]

    # Chief-only checkpointing: rank 0 wrote it, rank 1 did not duplicate.
    assert os.path.isdir(mp_workdir / "ckpt")

    # ---- sharded infer: each rank predicts half the records, chief
    # re-interleaves global order; must match single-process infer exactly.
    infer_args = [a if a != "train" else "infer" for a in args]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RUNNER] + infer_args
            + ["--process_id", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        for r in range(2)
    ]
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"infer rank {r} failed:\n{err[-3000:]}"
    pred_path = mp_workdir / "data" / "pred.txt"
    assert pred_path.exists()
    mp_preds = [float(x) for x in pred_path.read_text().split()]
    assert len(mp_preds) == 100  # 100 te records, odd tail exercised

    # Single-process reference run (1x1 mesh) over the same checkpoint.
    sp_env = dict(env, XLA_FLAGS="--xla_force_host_platform_device_count=1")
    sp_args = [a for a in infer_args]
    for key, val in (("--mesh_data", "1"), ("--mesh_model", "1"),
                     ("--dist_mode", "0"), ("--num_processes", "1")):
        sp_args[sp_args.index(key) + 1] = val
    p = subprocess.run(
        [sys.executable, "-c", _RUNNER] + sp_args + ["--process_id", "0"],
        env=sp_env, capture_output=True, text=True, cwd=_REPO, timeout=420)
    assert p.returncode == 0, f"single-proc infer failed:\n{p.stderr[-3000:]}"
    sp_preds = [float(x) for x in pred_path.read_text().split()]
    assert len(sp_preds) == 100
    assert mp_preds == pytest.approx(sp_preds, abs=2e-6)


def test_fanout_spawns_local_cluster(mp_workdir):
    """ONE fanout command starts worker_per_host local processes that
    rendezvous into a jax.distributed cluster and train (the MPI
    processes_per_host analog, reference hvd-gpu.ipynb:87-92)."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=_REPO,
    )
    # Workers must pin jax to CPU before backend init; fanout children run
    # deepfm_tpu.launch directly, so route through sitecustomize-safe env.
    cmd = [
        sys.executable, "-m", "deepfm_tpu.fanout",
        "--worker_per_host", "2",
        "--task_type", "train",
        "--data_dir", str(mp_workdir / "data"),
        "--val_data_dir", str(mp_workdir / "data"),
        "--feature_size", "300", "--field_size", "5",
        "--embedding_size", "8", "--deep_layers", "16,8",
        "--dropout", "1.0,1.0", "--batch_size", "64",
        "--num_epochs", "1", "--learning_rate", "0.05",
        "--scale_lr_by_world", "false", "--compute_dtype", "float32",
        "--mesh_data", "4", "--mesh_model", "2",
        "--log_steps", "0", "--seed", "3",
    ]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       cwd=_REPO, timeout=420)
    assert p.returncode == 0, f"fanout failed:\n{p.stdout[-2000:]}\n{p.stderr[-2000:]}"
    # Both workers report the same result line (replicated training).
    lines = [ln for ln in p.stdout.splitlines() if '"task": "train"' in ln]
    assert len(lines) == 2, p.stdout[-2000:]
    r0 = json.loads(lines[0].split("] ", 1)[1])
    r1 = json.loads(lines[1].split("] ", 1)[1])
    assert r0["steps"] == 4 * 128 // 64
    assert r0["loss"] == pytest.approx(r1["loss"], abs=1e-6)


@pytest.fixture(scope="module")
def multipath_workdir(tmp_path_factory):
    """Private-channel layout: eval channel + one training channel per local
    worker (the hvd enable_data_multi_path contract, README-EN.md:78-84)."""
    d = tmp_path_factory.mktemp("multipath")
    for i in range(2):
        libsvm.generate_synthetic_ctr(
            str(d / "data" / f"train_{i}"), num_files=2,
            examples_per_file=64, feature_size=300, field_size=5,
            prefix="tr", seed=31 + i)
    libsvm.generate_synthetic_ctr(
        str(d / "data" / "eval"), num_files=1, examples_per_file=64,
        feature_size=300, field_size=5, prefix="va", seed=33)
    return d


def _multipath_args(workdir, port, model_dir):
    return [
        "--task_type", "train",
        "--dist_mode", "1",
        "--num_processes", "2",
        "--coordinator_address", f"localhost:{port}",
        "--data_dir", str(workdir / "data"),
        "--channels", '["eval", "train_0", "train_1"]',
        "--enable_data_multi_path", "true",
        "--worker_per_host", "2",
        "--model_dir", model_dir,
        "--feature_size", "300", "--field_size", "5",
        "--embedding_size", "8", "--deep_layers", "16,8",
        "--dropout", "1.0,1.0", "--batch_size", "64",
        "--num_epochs", "2", "--learning_rate", "0.05",
        "--scale_lr_by_world", "false", "--compute_dtype", "float32",
        "--mesh_data", "2", "--mesh_model", "1",
        "--log_steps", "0", "--seed", "3",
        "--steps_per_loop", "1", "--save_checkpoints_steps", "2",
    ]


def _mp_run(args, extra_env=None, expect_fail=False, timeout=420):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # One local device per process: the ('data','model') mesh is built
        # over ALL global devices, so local device count x processes must
        # equal mesh_data x mesh_model (= 2x1 here).
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=_REPO,
        **(extra_env or {}),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RUNNER] + args + ["--process_id", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        for r in range(2)
    ]
    results = []
    for r, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"rank {r} hung (resume decision desync?)")
        if expect_fail:
            assert p.returncode != 0, f"rank {r} unexpectedly succeeded"
            results.append(err)
            continue
        assert p.returncode == 0, f"rank {r} failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        results.append(json.loads(line))
    return results


def test_multipath_resume_sibling_channel_edit(multipath_workdir):
    """ADVICE r4 high, behaviorally: under enable_data_multi_path each rank
    trains its own private channel, so (pre-fix) per-rank files digests
    diverged and a resume could mid-epoch-skip on the chief while replaying
    on its sibling — desynchronizing the lockstep collectives. The fix makes
    the chief hash ALL local channels and broadcast the resume decision.

    Asserts both halves: (a) an untouched resume mid-epoch-skips exactly on
    every rank; (b) editing a SIBLING channel (one the chief does NOT train
    from) forces cluster-wide epoch-replay — and neither case hangs.

    Schedule on these shards: 128 records/rank, local batch 32 -> 4
    steps/epoch; fault after 3 steps with checkpoints every 2 -> restored
    step 2, 2 steps into epoch 0."""
    # Crash two training runs identically (separate model dirs so each can
    # be resumed under a different condition).
    dirs = {}
    for tag in ("control", "edited"):
        model_dir = str(multipath_workdir / f"ckpt_{tag}")
        dirs[tag] = model_dir
        errs = _mp_run(
            _multipath_args(multipath_workdir, _free_port(), model_dir),
            extra_env={"DEEPFM_TPU_FAULT_AFTER_STEPS": "3"},
            expect_fail=True)
        for err in errs:
            assert "fault injection" in err, err[-1500:]
        meta = json.load(
            open(os.path.join(model_dir, "resume_meta.json")))
        assert meta["step"] == 2 and meta["steps_into_epoch"] == 2

    # (a) Untouched files: exact mid-epoch skip -> 2 epochs x 4 steps.
    results = _mp_run(
        _multipath_args(multipath_workdir, _free_port(), dirs["control"]))
    assert results[0]["steps"] == 2 * 4
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], abs=1e-6)

    # (b) Rename a shard in train_1 — the CHIEF's own channel (train_0) is
    # untouched, so a chief-local digest would wrongly match. The all-
    # channel digest must mismatch -> cluster-wide epoch-replay: restored
    # step 2 + num_epochs*4 fresh steps.
    chan = multipath_workdir / "data" / "train_1"
    victim = sorted(chan.glob("tr*.tfrecords"))[0]
    victim.rename(chan / "tr_renamed.tfrecords")
    results = _mp_run(
        _multipath_args(multipath_workdir, _free_port(), dirs["edited"]))
    assert results[0]["steps"] == 2 + 2 * 4
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], abs=1e-6)
