"""Sparse (touched-rows-only) embedding updates vs the dense reference.

The contract under test (ISSUE: beyond-HBM embedding scale):

* exact-touch-set: a sparse step changes ONLY the rows the batch touched —
  untouched rows are bit-identical, which is the property that lets step
  cost scale with unique-ids-per-batch instead of vocab;
* the lazy/timestamped Adam moments telescope to exactly what dense Adam
  computes for a row under its zero idle gradients;
* the full trajectory matches dense within a pinned tolerance (NOT
  bit-exact — dense Adam moves idle rows by their decaying momentum tail,
  sparse deliberately does not; optimizers.py quantifies the bound);
* padded_vocab pad rows never move (L2 + gradients structurally masked).
"""

import jax
import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.train import Trainer
from deepfm_tpu.train import optimizers as opt_lib

pytestmark = pytest.mark.embedding

V, B, F = 500, 32, 6


def _cfg(**kw):
    base = dict(
        feature_size=V, field_size=F, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=B,
        compute_dtype="float32", l2_reg=1e-4, learning_rate=1e-3,
        log_steps=0, seed=11, scale_lr_by_world=False,
        mesh_data=1, mesh_model=1, steps_per_loop=1)
    base.update(kw)
    return Config(**base)


def _batches(nb, seed=3, v=V, b=B):
    rng = np.random.default_rng(seed)
    return [dict(
        feat_ids=rng.integers(0, v, size=(b, F)).astype(np.int32),
        feat_vals=rng.normal(size=(b, F)).astype(np.float32),
        label=rng.integers(0, 2, size=(b,)).astype(np.float32))
        for _ in range(nb)]


def _fit(cfg, batches):
    tr = Trainer(cfg)
    state = tr.init_state()
    state, summary = tr.fit(state, batches)
    return tr, state, summary


class TestLazyAdamMath:
    def test_telescoped_moments_match_dense_recursion(self):
        """Lazy m/v at a touch == dense Adam's m/v after k zero-gradient
        idle steps, for an arbitrary touch pattern."""
        rng = np.random.default_rng(0)
        b1, b2, lr = 0.9, 0.999, 0.01
        steps = 60
        touched = rng.random(steps) < 0.3
        touched[0] = True
        grads = rng.standard_normal(steps).astype(np.float32)
        # Dense reference: g=0 on idle steps, moments decay every step.
        m_d, v_d = 0.0, 0.0
        m_l = np.zeros((1, 1), np.float32)
        v_l = np.zeros((1, 1), np.float32)
        tau = np.zeros((1,), np.int32)
        w = np.ones((1, 1), np.float32)
        for t in range(1, steps + 1):
            g = grads[t - 1] if touched[t - 1] else 0.0
            m_d = b1 * m_d + (1 - b1) * g
            v_d = b2 * v_d + (1 - b2) * g * g
            if touched[t - 1]:
                w_new, m_new, v_new = opt_lib.sparse_adam_rows(
                    w, np.full((1, 1), grads[t - 1], np.float32),
                    m_l, v_l, tau, np.int32(t), lr=lr)
                m_l, v_l = np.asarray(m_new), np.asarray(v_new)
                tau = np.full((1,), t, np.int32)
                w = np.asarray(w_new)
                np.testing.assert_allclose(m_l[0, 0], m_d, rtol=1e-5,
                                           atol=1e-7)
                np.testing.assert_allclose(v_l[0, 0], v_d, rtol=1e-5,
                                           atol=1e-7)

    def test_every_step_touch_matches_optax_adam(self):
        """With a touch every step the lazy path degenerates to plain
        Adam — compare one row against optax over 10 steps."""
        import optax
        rng = np.random.default_rng(1)
        lr = 0.01
        grads = rng.standard_normal((10, 4)).astype(np.float32)
        tx = optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8)
        w_ref = np.zeros((4,), np.float32)
        opt = tx.init(w_ref)
        w = np.zeros((1, 4), np.float32)
        m = np.zeros((1, 4), np.float32)
        v = np.zeros((1, 4), np.float32)
        tau = np.zeros((1,), np.int32)
        for t in range(1, 11):
            up, opt = tx.update(grads[t - 1], opt, w_ref)
            w_ref = w_ref + np.asarray(up)
            w_new, m_new, v_new = opt_lib.sparse_adam_rows(
                w, grads[t - 1:t], m, v, tau, np.int32(t), lr=lr)
            w, m, v = map(np.asarray, (w_new, m_new, v_new))
            tau = np.full((1,), t, np.int32)
        np.testing.assert_allclose(w[0], w_ref, rtol=1e-5, atol=1e-7)


class TestTouchSet:
    def test_untouched_rows_bit_identical(self):
        """One sparse step: rows outside the batch's id set must not move
        by even one bit; touched rows must move."""
        cfg = _cfg(embedding_update="sparse")
        tr = Trainer(cfg)
        state = tr.init_state()
        w0 = {n: np.asarray(state.params[n]) for n in ("fm_w", "fm_v")}
        batch = _batches(1, seed=5)[0]
        state, _ = tr.fit(state, [batch])
        touched = np.unique(batch["feat_ids"])
        untouched = np.setdiff1d(np.arange(V), touched)
        for n in ("fm_w", "fm_v"):
            w1 = np.asarray(state.params[n])
            np.testing.assert_array_equal(w1[untouched], w0[n][untouched])
            assert not np.array_equal(w1[touched], w0[n][touched])

    def test_opt_state_counts_and_tau(self):
        cfg = _cfg(embedding_update="sparse")
        tr = Trainer(cfg)
        state = tr.init_state()
        batch = _batches(1, seed=5)[0]
        state, _ = tr.fit(state, [batch])
        opt = state.opt_state
        assert int(opt["count"]) == 1
        touched = np.unique(batch["feat_ids"])
        tau = np.asarray(opt["embed"]["fm_w"]["table"].tau)
        assert (tau[touched] == 1).all()
        untouched = np.setdiff1d(np.arange(V), touched)
        assert (tau[untouched] == 0).all()


class TestTrajectoryParity:
    def test_sparse_matches_dense_within_pinned_tolerance(self):
        """20 steps at lr=1e-3, l2 on: the only divergence source is the
        documented idle-row momentum tail (and the touched-rows-only L2).
        Measured max diff ~0.018 on the embedding tables; pinned at 0.05
        (and 0.03 on the shared tower, measured ~0.005)."""
        batches = _batches(20)
        _, sd, _ = _fit(_cfg(embedding_update="dense"), batches)
        _, ss, _ = _fit(_cfg(embedding_update="sparse"), batches)
        for n in ("fm_w", "fm_v"):
            d = np.abs(np.asarray(sd.params[n], np.float32)
                       - np.asarray(ss.params[n], np.float32)).max()
            assert d < 0.05, (n, d)
        tower = max(
            float(np.abs(np.asarray(a, np.float32)
                         - np.asarray(b, np.float32)).max())
            for a, b in zip(jax.tree.leaves(sd.params["tower"]),
                            jax.tree.leaves(ss.params["tower"])))
        assert tower < 0.03, tower

    def test_multi_step_dispatch_bit_identical(self):
        """steps_per_loop=4 (scanned dispatch) must reproduce the
        steps_per_loop=1 sparse trajectory bit-for-bit."""
        batches = _batches(8)
        _, s1, _ = _fit(_cfg(embedding_update="sparse"), batches)
        _, s4, _ = _fit(_cfg(embedding_update="sparse", steps_per_loop=4),
                        batches)
        for n in ("fm_w", "fm_v"):
            np.testing.assert_array_equal(np.asarray(s1.params[n]),
                                          np.asarray(s4.params[n]))

    def test_eval_runs_in_sparse_mode(self):
        batches = _batches(6)
        tr, state, _ = _fit(_cfg(embedding_update="sparse"), batches)
        ev = tr.evaluate(state, _batches(4, seed=9))
        assert np.isfinite(ev["loss"])


class TestPadRows:
    """padded_vocab pad rows (mesh_model row-sharding rounds the vocab up)
    must stay bit-zero under training: L2 and gradients are structurally
    masked, so neither adam nor ftrl can move them."""

    @pytest.mark.parametrize("optimizer", ["adam", "ftrl"])
    def test_pad_rows_stay_bit_zero(self, optimizer):
        cfg = _cfg(mesh_data=1, mesh_model=8, optimizer=optimizer,
                   l2_reg=1e-3, learning_rate=0.01)
        tr = Trainer(cfg)
        state = tr.init_state()
        pv = tr.model.padded_vocab
        assert pv > V, "test requires actual pad rows"
        batches = [dict(b, label=b["label"][:, None]) for b in _batches(4)]
        state, _ = tr.fit(state, batches)
        for n in ("fm_w", "fm_v"):
            w = np.asarray(state.params[n], np.float32)
            assert w.shape[0] == pv
            np.testing.assert_array_equal(
                w[V:], np.zeros_like(w[V:]),
                err_msg=f"{optimizer}: pad rows of {n} moved")


class TestOtherModels:
    @pytest.mark.parametrize("model", ["widedeep", "dcnv2"])
    def test_sparse_smoke(self, model):
        cfg = _cfg(embedding_update="sparse", model=model)
        tr, state, summary = _fit(cfg, _batches(4))
        assert summary["steps"] == 4
        assert np.isfinite(summary["loss"])


class TestGating:
    def test_sparse_requires_adam(self):
        with pytest.raises(ValueError, match="lazy"):
            _cfg(embedding_update="sparse", optimizer="ftrl")

    def test_mesh_falls_back_to_dense(self):
        cfg = _cfg(embedding_update="sparse", mesh_data=8)
        tr = Trainer(cfg)
        assert tr.sparse_embed is False
