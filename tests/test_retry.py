"""Retry/backoff policy unit tests (utils/retry.py).

All time is faked — injected sleep recorder + advancing clock — so the whole
file runs in milliseconds with zero real sleeping.
"""

import random

import pytest

from deepfm_tpu.utils import retry

pytestmark = pytest.mark.faults


class FakeClock:
    """Monotonic clock that advances only when told (or per sleep)."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def __call__(self):
        return self.now

    def sleep(self, secs):
        self.sleeps.append(secs)
        self.now += secs


def _policy(**kw):
    clock = FakeClock()
    base = dict(max_attempts=4, base_delay=0.1, max_delay=5.0,
                sleep=clock.sleep, clock=clock, jitter_seed=0)
    base.update(kw)
    return retry.RetryPolicy(**base), clock


class Flaky:
    """Callable failing the first ``n`` calls with ``exc_factory()``."""

    def __init__(self, n, exc_factory=lambda: IOError("transient")):
        self.failures_left = n
        self.calls = 0
        self._exc = exc_factory

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.failures_left > 0:
            self.failures_left -= 1
            raise self._exc()
        return (args, kwargs)


class TestClassification:
    def test_os_errors_are_retryable(self):
        for exc in (IOError("x"), ConnectionResetError("x"),
                    TimeoutError("x"), OSError(5, "EIO")):
            assert retry.default_is_retryable(exc)

    def test_fatal_os_errors_are_not(self):
        for exc in (FileNotFoundError("x"), PermissionError("x"),
                    IsADirectoryError("x"), NotADirectoryError("x"),
                    FileExistsError("x")):
            assert not retry.default_is_retryable(exc)

    def test_non_os_errors_are_not(self):
        for exc in (ValueError("x"), KeyError("x"), RuntimeError("x")):
            assert not retry.default_is_retryable(exc)

    def test_tf_op_errors_classified_by_name(self):
        """gfile raises tf.errors.OpError subclasses (not OSErrors); the
        classifier matches by MRO class name without importing TF."""
        OpError = type("OpError", (Exception,), {})
        OpError.__module__ = "tensorflow.python.framework.errors_impl"
        Unavailable = type("UnavailableError", (OpError,), {})
        NotFound = type("NotFoundError", (OpError,), {})
        assert retry.default_is_retryable(Unavailable("conn reset"))
        assert not retry.default_is_retryable(NotFound("no such object"))

    def test_lookalike_op_error_outside_tf_is_not_retryable(self):
        OpError = type("OpError", (Exception,), {})
        OpError.__module__ = "someones.custom.module"
        assert not retry.default_is_retryable(OpError("nope"))


class TestBackoff:
    def test_full_jitter_bounds(self):
        pol, _ = _policy(base_delay=0.5, max_delay=4.0)
        rng = random.Random(123)
        for attempt in range(8):
            cap = min(4.0, 0.5 * 2 ** attempt)
            for _ in range(50):
                d = pol.backoff_delay(attempt, rng)
                assert 0.0 <= d <= cap

    def test_jitter_seed_reproducible(self):
        pol, clock = _policy(max_attempts=4, jitter_seed=7)
        pol.call(Flaky(3))
        pol2, clock2 = _policy(max_attempts=4, jitter_seed=7)
        pol2.call(Flaky(3))
        assert clock.sleeps == clock2.sleeps
        assert len(clock.sleeps) == 3


class TestCall:
    def test_success_after_transient_failures(self):
        pol, clock = _policy(max_attempts=4)
        fn = Flaky(2)
        out = pol.call(fn, 1, k=2)
        assert out == ((1,), {"k": 2})
        assert fn.calls == 3
        assert len(clock.sleeps) == 2  # one backoff per healed failure

    def test_gives_up_after_max_attempts(self):
        pol, clock = _policy(max_attempts=3)
        fn = Flaky(99)
        with pytest.raises(IOError, match="failed after 3 attempts"):
            pol.call(fn, op_name="read(f@0)")
        assert fn.calls == 3
        assert len(clock.sleeps) == 2  # no sleep after the final failure

    def test_failure_message_names_the_op(self):
        pol, _ = _policy(max_attempts=2)
        with pytest.raises(IOError, match=r"glob\(gs://b/\*\) failed after"):
            pol.call(Flaky(99), op_name="glob(gs://b/*)")

    def test_non_retryable_propagates_immediately(self):
        pol, clock = _policy()
        fn = Flaky(99, lambda: FileNotFoundError("gone"))
        with pytest.raises(FileNotFoundError):
            pol.call(fn)
        assert fn.calls == 1
        assert clock.sleeps == []

    def test_programming_errors_propagate_immediately(self):
        pol, clock = _policy()
        fn = Flaky(99, lambda: ValueError("bug"))
        with pytest.raises(ValueError):
            pol.call(fn)
        assert fn.calls == 1
        assert clock.sleeps == []

    def test_deadline_stops_retrying(self):
        pol, clock = _policy(max_attempts=100, base_delay=1.0,
                             max_delay=1.0, deadline=2.5)
        fn = Flaky(99)
        with pytest.raises(IOError, match="failed after deadline"):
            pol.call(fn)
        # Attempts stop once the fake clock passes the deadline; with
        # jittered sleeps in [0, 1] that is far fewer than 100 tries.
        assert fn.calls < 100
        assert clock.now >= 2.5

    def test_on_retry_fires_per_healed_failure(self):
        pol, _ = _policy(max_attempts=4)
        seen = []
        pol.call(Flaky(2), on_retry=lambda exc, n: seen.append(n))
        assert seen == [1, 2]  # 1-based failed-attempt numbers

    def test_on_retry_not_fired_on_final_failure(self):
        pol, _ = _policy(max_attempts=2)
        seen = []
        with pytest.raises(IOError):
            pol.call(Flaky(99), on_retry=lambda exc, n: seen.append(n))
        assert seen == [1]

    def test_with_returns_modified_copy(self):
        pol, _ = _policy(max_attempts=4)
        pol2 = pol.with_(max_attempts=9)
        assert pol2.max_attempts == 9 and pol.max_attempts == 4
        assert pol2.sleep is pol.sleep


class TestDecorator:
    def test_retrying_decorator(self):
        pol, clock = _policy(max_attempts=3)
        state = {"left": 2}

        @retry.retrying(pol, op_name="fetch")
        def fetch(x):
            if state["left"] > 0:
                state["left"] -= 1
                raise IOError("flaky")
            return x * 2

        assert fetch(21) == 42
        assert len(clock.sleeps) == 2
        assert fetch.__name__ == "fetch"


class TestPolicyFromConfig:
    def test_reads_config_knobs(self):
        from deepfm_tpu.config import Config
        cfg = Config(io_retries=7, io_retry_backoff_secs=0.25,
                     io_retry_deadline_secs=30.0)
        pol = retry.policy_from_config(cfg)
        assert pol.max_attempts == 7
        assert pol.base_delay == 0.25
        assert pol.deadline == 30.0

    def test_zero_deadline_means_none(self):
        from deepfm_tpu.config import Config
        pol = retry.policy_from_config(Config())
        assert pol.deadline is None
        assert pol.max_attempts >= 1
