"""Profiling subsystem: trace context + throughput meter."""

import os
import time

import jax
import jax.numpy as jnp

from deepfm_tpu.utils import profiling


def test_maybe_trace_disabled_is_noop():
    with profiling.maybe_trace(""):
        pass
    with profiling.maybe_trace(None):
        pass


def test_maybe_trace_writes_xplane(tmp_path):
    out = str(tmp_path / "trace")
    with profiling.maybe_trace(out):
        with profiling.annotate("tiny_matmul"):
            x = jnp.ones((8, 8))
            jax.block_until_ready(x @ x)
    found = []
    for root, _, files in os.walk(out):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {out}"


def test_throughput_meter_summary():
    m = profiling.ThroughputMeter(warmup_steps=1)
    for _ in range(5):
        time.sleep(0.002)
        m.update(100)
    s = m.summary()
    assert s["steps"] == 5.0
    assert s["examples_per_sec"] > 0
    assert s["step_ms_p50"] >= 1.0
    assert s["step_ms_p99"] >= s["step_ms_p50"]


def test_throughput_meter_warmup_only():
    m = profiling.ThroughputMeter(warmup_steps=5)
    m.update(10)
    assert m.summary() == {"steps": 1.0}


def test_step_window_tracer_bounded(tmp_path):
    out = str(tmp_path / "win")
    t = profiling.StepWindowTracer(out, start_step=1, num_steps=2)
    for _ in range(10):  # must stop after the window, not trace all 10
        jax.block_until_ready(jnp.ones((4, 4)) * 2)
        t.on_step()
    assert t._done and not t._active
    t.close()  # idempotent
    found = []
    for root, _, files in os.walk(out):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {out}"


def test_step_window_tracer_close_mid_window(tmp_path):
    out = str(tmp_path / "mid")
    t = profiling.StepWindowTracer(out, start_step=1, num_steps=100)
    t.on_step()  # starts the trace; run ends before the window fills
    t.close()
    assert not t._active


def test_step_window_tracer_disabled():
    t = profiling.StepWindowTracer("")
    for _ in range(5):
        t.on_step()
    t.close()
    assert not t._active and not t._done
