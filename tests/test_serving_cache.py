"""Serving fast-path tests: request fingerprints, the version-keyed LRU
result cache (copy semantics, TTL, eviction), cache/hot-swap interaction
through the engine (hit before swap, stale-version miss after, TTL expiry,
LRU under concurrent submit, shadow bypass never warms), in-flight
coalescing (join/fan-out, leader cancel refusal, error propagation), the
repeat-flood knob, and the tier-1 flood smoke over ``bench.overload_point``
with the extended accounting identity."""

import os
import sys
import threading
import time

import numpy as np
import pytest

from deepfm_tpu.loop.traffic import FloodTrafficPlan, ZipfUserPopulation
from deepfm_tpu.serve import (ReplicatedEngine, ResultCache, ServingEngine,
                              request_fingerprint)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
import production_drill  # noqa: E402

pytestmark = pytest.mark.cache

FIELD_SIZE = 5


def _rows(n, base=0):
    ids = (base + np.arange(n * FIELD_SIZE, dtype=np.int32)
           ).reshape(n, FIELD_SIZE) % 120
    vals = np.ones((n, FIELD_SIZE), np.float32)
    return ids, vals


def first_col_predict(feat_ids, feat_vals):
    """Row-local fake model, same idiom as test_serving."""
    return feat_ids[:, 0].astype(np.float32) * 0.001 + feat_vals[:, 0] * 0.1


# ---------------------------------------------------------------------------
# Request fingerprints
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_identical_bytes_identical_fingerprint(self):
        a, b = _rows(3), _rows(3)
        assert request_fingerprint(*a) == request_fingerprint(*b)
        # Copies (fresh allocations) fingerprint the same — content, not id.
        assert request_fingerprint(a[0].copy(), a[1].copy()) == \
            request_fingerprint(*a)

    def test_value_change_changes_fingerprint(self):
        ids, vals = _rows(3)
        bumped = vals.copy()
        bumped[1, 2] += 1e-6
        assert request_fingerprint(ids, bumped) != \
            request_fingerprint(ids, vals)

    def test_dtype_matters(self):
        ids, vals = _rows(2)
        assert request_fingerprint(ids.astype(np.int64), vals) != \
            request_fingerprint(ids, vals)

    def test_shape_matters_for_same_bytes(self):
        ids, vals = _rows(2)   # [2, 5]
        re_ids = ids.reshape(1, 10)
        re_vals = vals.reshape(1, 10)
        assert request_fingerprint(re_ids, re_vals) != \
            request_fingerprint(ids, vals)


# ---------------------------------------------------------------------------
# ResultCache unit behavior
# ---------------------------------------------------------------------------

class TestResultCache:
    def test_validation(self):
        with pytest.raises(ValueError, match="rows"):
            ResultCache(0)
        with pytest.raises(ValueError, match="ttl"):
            ResultCache(4, ttl_s=-1.0)

    def test_roundtrip_bit_identical_and_version_keyed(self):
        cache = ResultCache(16)
        fp = request_fingerprint(*_rows(2))
        probs = np.asarray([0.25, 0.75], np.float32)
        cache.put(7, fp, probs, rows=2)
        np.testing.assert_array_equal(cache.get(7, fp), probs)
        assert cache.get(8, fp) is None          # other version: miss
        assert cache.get(7, b"other") is None    # other request: miss

    def test_get_returns_copy(self):
        cache = ResultCache(16)
        cache.put(1, b"fp", np.asarray([0.5], np.float32), rows=1)
        out = cache.get(1, b"fp")
        out[0] = 99.0
        assert cache.get(1, b"fp")[0] == np.float32(0.5)

    def test_put_stores_copy(self):
        cache = ResultCache(16)
        probs = np.asarray([0.5], np.float32)
        cache.put(1, b"fp", probs, rows=1)
        probs[0] = 99.0
        assert cache.get(1, b"fp")[0] == np.float32(0.5)

    def test_multitask_dict_values_copied(self):
        cache = ResultCache(16)
        cache.put(1, b"fp", {"ctr": np.asarray([0.5], np.float32)}, rows=1)
        out = cache.get(1, b"fp")
        out["ctr"][0] = 99.0
        assert cache.get(1, b"fp")["ctr"][0] == np.float32(0.5)

    def test_lru_eviction_in_row_units(self):
        cache = ResultCache(4)
        for i in range(3):
            cache.put(1, bytes([i]), np.zeros(2, np.float32), rows=2)
        # 3 x 2 rows over a 4-row budget: entry 0 (LRU tail) evicted.
        assert cache.get(1, bytes([0])) is None
        assert cache.get(1, bytes([1])) is not None
        assert cache.get(1, bytes([2])) is not None
        assert cache.evictions == 1
        assert cache.rows == 4

    def test_get_refreshes_recency(self):
        cache = ResultCache(4)
        cache.put(1, b"a", np.zeros(2, np.float32), rows=2)
        cache.put(1, b"b", np.zeros(2, np.float32), rows=2)
        cache.get(1, b"a")                       # refresh a -> b is LRU
        cache.put(1, b"c", np.zeros(2, np.float32), rows=2)
        assert cache.get(1, b"a") is not None
        assert cache.get(1, b"b") is None

    def test_over_budget_entry_not_cached(self):
        cache = ResultCache(4)
        cache.put(1, b"a", np.zeros(2, np.float32), rows=2)
        cache.put(1, b"big", np.zeros(8, np.float32), rows=8)
        assert cache.get(1, b"big") is None
        assert cache.get(1, b"a") is not None    # and nothing was evicted

    def test_ttl_expires_lazily_with_injected_clock(self):
        clk = [0.0]
        cache = ResultCache(16, ttl_s=5.0, clock=lambda: clk[0])
        cache.put(1, b"fp", np.zeros(1, np.float32), rows=1)
        clk[0] = 4.9
        assert cache.get(1, b"fp") is not None
        clk[0] = 5.1
        assert cache.get(1, b"fp") is None
        assert cache.expirations == 1
        assert len(cache) == 0 and cache.rows == 0

    def test_summary_schema(self):
        cache = ResultCache(8, ttl_s=2.0)
        cache.put(1, b"fp", np.zeros(3, np.float32), rows=3)
        s = cache.summary()
        assert s == {"cache_entries": 1, "cache_rows_used": 3,
                     "cache_capacity_rows": 8, "cache_ttl_s": 2.0,
                     "cache_evictions": 0, "cache_expirations": 0}


# ---------------------------------------------------------------------------
# Engine-level cache x hot-swap interaction
# ---------------------------------------------------------------------------

class VersionedFn:
    """Minimal LatestWatcher stand-in: ``current()`` -> (fn, version)."""

    def __init__(self, fn=first_col_predict):
        self.version = 1
        self.fn = fn

    def current(self):
        v = self.version
        return (lambda ids, vals: self.fn(ids, vals)), v


class TestEngineCache:
    def test_hit_is_bit_identical_and_skips_device(self):
        calls = []

        def spy(ids, vals):
            calls.append(ids.shape[0])
            return first_col_predict(ids, vals)

        eng = ServingEngine(spy, max_batch=8, max_delay_ms=1, cache_rows=64)
        try:
            ids, vals = _rows(3)
            first = eng.submit(ids, vals)
            a = first.result(timeout=10)
            second = eng.submit(ids, vals)
            b = second.result(timeout=10)
            assert not first.cache_hit and second.cache_hit
            np.testing.assert_array_equal(a, b)   # bit-identical to flush
            assert len(calls) == 1                # no second device call
            s = eng.stats.summary()
            assert s["serving_cache_hits"] == 1
            assert s["serving_cache_misses"] == 1
            assert s["serving_cache_hit_rate"] == 0.5
            # A hit still counts as a completed request in the reservoirs.
            assert s["serving_requests"] == 2
        finally:
            eng.close()

    def test_swap_invalidates_for_free(self):
        calls = []
        fn = VersionedFn(lambda ids, vals: (calls.append(1),
                                            first_col_predict(ids, vals))[1])
        eng = ServingEngine(fn, max_batch=8, max_delay_ms=1, cache_rows=64)
        try:
            ids, vals = _rows(2)
            eng.predict(ids, vals, timeout=10)
            assert eng.submit(ids, vals).result(timeout=10) is not None
            assert len(calls) == 1                # second was a hit
            fn.version = 2                        # hot swap
            fut = eng.submit(ids, vals)
            fut.result(timeout=10)
            assert not fut.cache_hit              # stale version: miss
            assert len(calls) == 2                # recomputed under v2
            # And the v2 entry now serves v2 lookups.
            assert eng.submit(ids, vals).result(timeout=10) is not None
            assert len(calls) == 2
        finally:
            eng.close()

    def test_ttl_expiry_through_engine(self):
        clk = [0.0]
        calls = []

        def spy(ids, vals):
            calls.append(1)
            return first_col_predict(ids, vals)

        # max_delay_ms=0: the flush deadline is immediate, so the frozen
        # injected clock never strands the batcher.
        eng = ServingEngine(spy, max_batch=8, max_delay_ms=0,
                            cache_rows=64, cache_ttl_s=5.0,
                            clock=lambda: clk[0])
        try:
            ids, vals = _rows(1)
            eng.predict(ids, vals, timeout=10)
            eng.predict(ids, vals, timeout=10)
            assert len(calls) == 1
            clk[0] = 6.0                          # past the TTL
            eng.predict(ids, vals, timeout=10)
            assert len(calls) == 2
            assert eng.cache.expirations == 1
        finally:
            eng.close()

    def test_lru_eviction_under_concurrent_submit(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                            cache_rows=4)
        try:
            def hammer(base):
                for i in range(8):
                    eng.predict(*_rows(1, base=base + i), timeout=10)

            threads = [threading.Thread(target=hammer, args=(100 * t,))
                       for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert eng.cache.rows <= 4
            assert eng.cache.evictions > 0
            # The cache stayed coherent: a fresh repeat of a cached row is
            # still bit-identical to a recompute.
            ids, vals = _rows(1, base=999)
            a = eng.predict(ids, vals, timeout=10)
            b = eng.predict(ids, vals, timeout=10)
            np.testing.assert_array_equal(a, b)
        finally:
            eng.close()

    def test_bypass_never_reads_nor_warms(self):
        calls = []

        def spy(ids, vals):
            calls.append(1)
            return first_col_predict(ids, vals)

        eng = ServingEngine(spy, max_batch=8, max_delay_ms=1, cache_rows=64,
                            coalesce=True)
        try:
            ids, vals = _rows(2)
            shadow = eng.submit(ids, vals, bypass_cache=True)
            shadow.result(timeout=10)
            assert shadow.fingerprint is None     # never fingerprinted
            assert len(eng.cache) == 0            # never warmed
            # Warm via the normal lane, then bypass again: still recomputes.
            eng.predict(ids, vals, timeout=10)
            assert len(eng.cache) == 1
            again = eng.submit(ids, vals, bypass_cache=True)
            again.result(timeout=10)
            assert not again.cache_hit and not again.coalesced
            assert len(calls) == 3
            assert eng.stats.summary()["serving_cache_hits"] == 0
        finally:
            eng.close()

    def test_arms_never_share_entries(self):
        """Control and challenger engines own separate caches: warming one
        arm leaves the other arm's cache cold (the experiment-plane
        isolation the router relies on)."""
        control = ServingEngine(first_col_predict, max_batch=8,
                                max_delay_ms=1, cache_rows=64)
        challenger = ServingEngine(first_col_predict, max_batch=8,
                                   max_delay_ms=1, cache_rows=64)
        try:
            ids, vals = _rows(2)
            control.predict(ids, vals, timeout=10)
            control.predict(ids, vals, timeout=10)
            assert control.stats.summary()["serving_cache_hits"] == 1
            assert len(challenger.cache) == 0
            fut = challenger.submit(ids, vals)
            fut.result(timeout=10)
            assert not fut.cache_hit              # cold despite control hit
        finally:
            control.close()
            challenger.close()


# ---------------------------------------------------------------------------
# In-flight coalescing
# ---------------------------------------------------------------------------

class TestCoalescing:
    def test_followers_join_one_leader(self):
        calls = []

        def spy(ids, vals):
            calls.append(ids.shape[0])
            return first_col_predict(ids, vals)

        eng = ServingEngine(spy, max_batch=8, max_delay_ms=1,
                            coalesce=True, start=False)
        try:
            ids, vals = _rows(2)
            leader = eng.submit(ids, vals)
            follower = eng.submit(ids, vals)
            other = eng.submit(*_rows(2, base=50))
            assert not leader.coalesced and follower.coalesced
            assert not other.coalesced            # different bytes
            assert eng.pending_rows == 4          # follower never queued
            eng.start()
            a = leader.result(timeout=10)
            b = follower.result(timeout=10)
            other.result(timeout=10)
            np.testing.assert_array_equal(a, b)
            assert b is not a                     # fan-out copies
            assert sum(calls) == 4                # one device pass for the 3
            assert eng.stats.summary()["serving_coalesced"] == 1
        finally:
            eng.close()

    def test_leader_refuses_cancel_with_followers(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                            coalesce=True, start=False)
        try:
            ids, vals = _rows(1)
            leader = eng.submit(ids, vals)
            follower = eng.submit(ids, vals)
            assert follower.coalesced
            assert leader.cancel() is False       # carrying a follower
            assert not leader.cancelled()
            eng.start()
            np.testing.assert_array_equal(leader.result(timeout=10),
                                          follower.result(timeout=10))
        finally:
            eng.close()

    def test_childless_leader_cancel_still_works(self):
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                            coalesce=True, start=False)
        try:
            fut = eng.submit(*_rows(1))
            assert fut.cancel() is True
            # A later identical request must NOT join the cancelled leader.
            fresh = eng.submit(*_rows(1))
            assert not fresh.coalesced
            eng.start()
            fresh.result(timeout=10)
        finally:
            eng.close()

    def test_error_propagates_to_followers(self):
        def boom(ids, vals):
            raise RuntimeError("model exploded")

        eng = ServingEngine(boom, max_batch=8, max_delay_ms=1,
                            coalesce=True, start=False)
        try:
            ids, vals = _rows(1)
            leader = eng.submit(ids, vals)
            follower = eng.submit(ids, vals)
            eng.start()
            with pytest.raises(RuntimeError, match="exploded"):
                leader.result(timeout=10)
            with pytest.raises(RuntimeError, match="exploded"):
                follower.result(timeout=10)
            assert eng.stats.summary()["serving_failed"] == 2
        finally:
            eng.close()

    def test_resolved_leader_not_joined(self):
        """Once the leader resolves, its registry entry retires — a later
        identical request recomputes (possibly via the cache, but never by
        attaching to a done future)."""
        eng = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                            coalesce=True)
        try:
            ids, vals = _rows(1)
            leader = eng.submit(ids, vals)
            leader.result(timeout=10)
            late = eng.submit(ids, vals)
            assert not late.coalesced
            late.result(timeout=10)
        finally:
            eng.close()

    def test_hedge_leg_cache_hit_at_attach_does_not_deadlock(self):
        """Regression: a fired hedge leg can resolve INSIDE submit (warm
        result cache on the other replica), so ``attach_hedge`` adopts an
        ALREADY-DONE future and its done-callback runs synchronously on
        the attaching thread. That callback takes the wrapper lock —
        registering it while still holding the wrapper lock self-deadlocks
        the hedger (non-reentrant lock). The wrapper must resolve as a
        hedge win with the cached answer."""
        eng0 = ServingEngine(first_col_predict, start=False, max_batch=8,
                             max_delay_ms=1, cache_rows=64)
        eng1 = ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                             cache_rows=64)
        fleet = ReplicatedEngine([eng0, eng1], hedge_ms=5.0, start=False)
        try:
            ids, vals = _rows(2, base=7)
            want = eng1.submit(ids, vals).result(timeout=10)  # warm cache
            hf = fleet.submit(ids, vals, affinity=0)  # primary parks: eng0
            # hedge_pass runs on THIS thread — pre-fix it never returned.
            assert fleet.hedge_pass(now=hf.t_enqueue + 10.0) == 1
            assert hf.done()                  # resolved at attach time
            np.testing.assert_array_equal(hf.result(timeout=10), want)
            assert hf.cache_hit
            s = fleet.summary()
            assert s["hedges_won"] == 1
            assert s["serving_cache_hits"] == 1
        finally:
            eng0.start()
            fleet.close(timeout=30)


# ---------------------------------------------------------------------------
# Repeat-flood knob + tier-1 flood smoke with the extended identity
# ---------------------------------------------------------------------------

def _population(seed=5, users=2_000):
    return ZipfUserPopulation(seed, users=users, hist_len=4)


class TestRepeatFlood:
    def test_repeat_p_zero_is_bit_identical_to_legacy(self):
        a = FloodTrafficPlan(9, offered_qps=300.0, duration_s=1.0,
                             population=_population(), field_size=FIELD_SIZE,
                             feature_size=64)
        b = FloodTrafficPlan(9, offered_qps=300.0, duration_s=1.0,
                             population=_population(), field_size=FIELD_SIZE,
                             feature_size=64, repeat_p=0.0)
        assert a.fingerprint_data() == b.fingerprint_data()
        assert b.repeat_requests == 0

    def test_repeats_are_byte_identical_replays(self):
        plan = FloodTrafficPlan(9, offered_qps=300.0, duration_s=1.0,
                                population=_population(),
                                field_size=FIELD_SIZE, feature_size=64,
                                repeat_p=0.6)
        assert plan.repeat_requests > 0
        seen = {}
        replays = 0
        for r in plan.requests:
            fp = request_fingerprint(r.ids, r.vals)
            if r.user_id in seen and fp == seen[r.user_id]:
                replays += 1
            seen[r.user_id] = fp
        assert replays >= plan.repeat_requests

    def test_repeat_p_validation(self):
        with pytest.raises(ValueError, match="repeat_p"):
            FloodTrafficPlan(9, offered_qps=10.0, duration_s=0.5,
                             population=_population(), field_size=FIELD_SIZE,
                             feature_size=64, repeat_p=1.0)

    def test_flood_smoke_fast_path_accounting(self):
        """bench.overload_point over a repeat-heavy flood with the fast
        path armed: the extended identity closes (offered == completed +
        coalesced + sheds + overloads + timeouts + failed) and the cache
        saw real traffic."""
        import bench
        plan = FloodTrafficPlan(9, offered_qps=300.0, duration_s=1.0,
                                population=_population(),
                                field_size=FIELD_SIZE, feature_size=64,
                                repeat_p=0.6)
        fleet = ReplicatedEngine(
            [ServingEngine(first_col_predict, max_batch=8, max_delay_ms=1,
                           cache_rows=256, coalesce=True)
             for _ in range(2)])
        try:
            point = bench.overload_point(fleet, plan, slo_ms=1000.0,
                                         resolve_timeout_s=30.0)
        finally:
            fleet.close(timeout=30)
        assert point["accounting_ok"], point
        assert point["offered_requests"] == (
            point["completed"] + point["coalesced"] + point["sheds"]
            + point["overloads"] + point["timeouts"] + point["failed"])
        assert point["cache_hits"] > 0, point
        assert point["failed"] == 0 and point["timeouts"] == 0, point


# ---------------------------------------------------------------------------
# Production cache drill: bit-identity through the cascade, cache on vs off
# ---------------------------------------------------------------------------

class TestCacheDrill:
    def test_cache_drill_bit_identical_and_hits(self, tmp_path):
        """The drill serves ONE repeat-heavy plan through the cascade with
        the fast path off then on: the ON arm must actually hit the cache,
        and the audit fingerprint over every recommendation's ids AND
        probability bytes must match the OFF arm exactly."""
        r = production_drill.run_cache_drill(
            str(tmp_path), seed=7,
            params=dict(duration_s=1.0, offered_qps=60.0, users=2_000))
        assert r["bit_identical"], r
        assert r["off"]["fingerprint"] == r["on"]["fingerprint"] \
            == r["audit_fingerprint"]
        assert r["on"]["cache_hits"] > 0
        assert r["off"]["cache_hits"] == 0
        assert r["on"]["repeat_requests"] == r["off"]["repeat_requests"] > 0
        # The shadow of the fast path never changes WHAT is served, only
        # what it costs: same request count either way.
        assert r["on"]["requests"] == r["off"]["requests"]
