"""Flood-harness + degradation-drill tests: the million-user Zipf traffic
plan (determinism, skew, per-user history continuity), the count-based
``executor_slow`` chaos seam, the overload drill's bit-replayable audit
fingerprint, and the ``bench.overload_series`` schema/accounting smoke. The
full flood sweep (``scripts/bench_serving.py --flood``) rides behind
``slow``."""

import os
import sys
import time

import numpy as np
import pytest

from deepfm_tpu.loop.traffic import FloodTrafficPlan, ZipfUserPopulation
from deepfm_tpu.serve.admission import DEGRADE_RUNGS, VALUE_CLASSES
from deepfm_tpu.utils import faults

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
import production_drill  # noqa: E402

pytestmark = pytest.mark.overload


# --------------------------------------------------------------------------
# Zipf flood traffic plan.
# --------------------------------------------------------------------------

def _plan(seed=5, users=10_000, qps=400.0, secs=1.0, pop=None):
    pop = pop or ZipfUserPopulation(seed, users=users, hist_len=4)
    return FloodTrafficPlan(seed + 1, offered_qps=qps, duration_s=secs,
                            population=pop, field_size=3, feature_size=64)


class TestFloodTraffic:
    def test_same_seed_bit_identical(self):
        a, b = _plan(), _plan()
        assert a.fingerprint_data() == b.fingerprint_data()
        assert len(a.requests) > 100

    def test_different_seed_differs(self):
        assert _plan(seed=5).fingerprint_data() != \
            _plan(seed=6).fingerprint_data()

    def test_zipf_head_users_dominate(self):
        """rank^-q activity: the top 1% of a 100k-user population must own
        the majority of traffic — the skew DIN-style history models feed
        on, and what makes sticky affinity worth having."""
        pop = ZipfUserPopulation(0, users=100_000)
        rng = np.random.default_rng(0)
        users = pop.sample_users(rng, 20_000)
        assert users.min() >= 0 and users.max() < 100_000
        head_share = float(np.mean(users < 1_000))
        assert head_share > 0.5, f"head share only {head_share:.2f}"
        # And the single hottest user is user 0 by construction.
        ids, counts = np.unique(users, return_counts=True)
        assert ids[np.argmax(counts)] == 0

    def test_history_continuity_snapshot_before_click(self):
        """A user's Nth request carries the history of their first N-1
        clicks (snapshot taken BEFORE the request's own click lands), and
        head users accumulate toward a full mask."""
        pop = ZipfUserPopulation(1, users=50, hist_len=4)
        plan = _plan(seed=1, qps=300.0, pop=pop)
        seen = {}
        for r in plan.requests:
            expect = min(seen.get(r.user_id, 0), 4)
            assert int(r.hist_mask.sum()) == expect, (r.user_id, expect)
            item = int(r.ids[0, 0])
            if expect:
                assert r.hist_ids[expect - 1] == seen[(r.user_id, "last")]
            seen[r.user_id] = seen.get(r.user_id, 0) + 1
            seen[(r.user_id, "last")] = item
        assert any(int(r.hist_mask.sum()) == 4 for r in plan.requests)

    def test_million_user_population_is_lazy(self):
        """1M users must be cheap: one ~8MB cumsum, histories only for
        users traffic actually touched."""
        t0 = time.monotonic()
        pop = ZipfUserPopulation(2, users=1_000_000)
        assert time.monotonic() - t0 < 5.0
        assert pop.touched_users == 0
        plan = _plan(seed=2, qps=300.0, pop=pop)
        assert 0 < pop.touched_users <= len(plan.requests)

    def test_value_mix_uses_admission_classes(self):
        plan = _plan(qps=1000.0)
        got = {r.value for r in plan.requests}
        assert got == set(VALUE_CLASSES)
        # Mix roughly matches the seeded weights (normal is the mode).
        counts = {c: sum(r.value == c for r in plan.requests) for c in got}
        assert max(counts, key=counts.get) == "normal"

    def test_validation(self):
        with pytest.raises(ValueError):
            ZipfUserPopulation(0, users=0)
        with pytest.raises(ValueError):
            _plan(qps=0.0)


# --------------------------------------------------------------------------
# executor_slow chaos seam.
# --------------------------------------------------------------------------

class TestExecutorSlowChaos:
    def teardown_method(self):
        faults.set_executor_slow(0.0, 0)

    def test_count_based_consume(self):
        faults.set_executor_slow(0.5, 2)
        assert faults.executor_slow_remaining() == 2
        assert faults.executor_slow_delay() == 0.5
        assert faults.executor_slow_delay() == 0.5
        assert faults.executor_slow_delay() == 0.0   # exhausted
        assert faults.executor_slow_remaining() == 0

    def test_disarm(self):
        faults.set_executor_slow(0.5, 10)
        faults.set_executor_slow(0.0, 0)
        assert faults.executor_slow_delay() == 0.0

    def test_schedule_generates_driver_side_event(self):
        sched = faults.ChaosSchedule.generate(
            11, horizon_s=4.0, executor_slow_events=1,
            executor_slow_ms=40.0, executor_slow_calls=25)
        evs = [e for e in sched.events if e.kind == "executor_slow"]
        assert len(evs) == 1
        ev = evs[0]
        # Early in the event window so the drill can observe RECOVERY too.
        assert 0.2 * 4.0 <= ev.at_s <= 0.5 * 4.0
        assert ev.get("delay_ms") == 40.0 and ev.get("calls") == 25
        assert "executor_slow" in faults.ChaosSchedule.DRIVER_KINDS
        # Same seed -> same schedule (the replay contract).
        again = faults.ChaosSchedule.generate(
            11, horizon_s=4.0, executor_slow_events=1,
            executor_slow_ms=40.0, executor_slow_calls=25)
        assert again.fingerprint() == sched.fingerprint()


# --------------------------------------------------------------------------
# Overload drill: ladder engages under executor_slow, recovers, and the
# audit fingerprint is bit-replayable.
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cascade_artifact(tmp_path_factory):
    """ONE trained cascade artifact shared by every drill run here."""
    pub = tmp_path_factory.mktemp("overload_publish")
    os.environ["DEEPFM_TPU_SKIP_TF_EXPORT"] = "1"
    try:
        production_drill.build_cascade_artifact(str(pub))
    finally:
        os.environ.pop("DEEPFM_TPU_SKIP_TF_EXPORT", None)
    return str(pub)


class TestOverloadDrill:
    def test_ladder_engages_recovers_and_replays(self, cascade_artifact,
                                                 tmp_path):
        reports = [
            production_drill.run_overload_drill(
                str(tmp_path / f"run{k}"), seed=7,
                publish_dir=cascade_artifact)
            for k in range(2)
        ]
        r = reports[0]
        # The run_overload_drill asserts already gated engagement/recovery;
        # re-check the report surface the flood sweep embeds.
        assert r["ladder_engaged"] and r["recovered"]
        assert r["accounting_ok"]
        assert r["counters"]["failed"] == 0
        assert sum(r["counters"].values()) == r["traffic"]["requests"]
        assert r["max_rung"] >= 1
        assert r["rung_names"] == list(DEGRADE_RUNGS)
        assert r["transition_log"][0][:2] == [0, 1] or \
            r["transition_log"][0][1] >= 1
        # Ladder came back down: the last transition lands on rung 0.
        assert r["transition_log"][-1][1] == 0
        assert r["traffic"]["users"] == 1_000_000
        assert r["degrade_transitions"] == len(r["transition_log"])
        # Bit-replayable: same seed => identical audit fingerprint.
        assert reports[0]["audit_fingerprint"] == \
            reports[1]["audit_fingerprint"]
        # The slow seam never leaks out of the drill.
        assert faults.executor_slow_remaining() == 0

    def test_different_seed_different_fingerprint(self, cascade_artifact,
                                                  tmp_path):
        r7 = production_drill.run_overload_drill(
            str(tmp_path / "a"), seed=7, publish_dir=cascade_artifact)
        r8 = production_drill.run_overload_drill(
            str(tmp_path / "b"), seed=8, publish_dir=cascade_artifact)
        assert r7["audit_fingerprint"] != r8["audit_fingerprint"]


# --------------------------------------------------------------------------
# bench.overload_series schema smoke + slow full sweep.
# --------------------------------------------------------------------------

class TestFloodBench:
    def test_overload_series_schema_and_accounting(self, tmp_path):
        import bench
        workdir = str(tmp_path / "artifacts")
        os.makedirs(workdir)
        bench.export_serving_artifacts(workdir)
        out = bench.overload_series(
            run_secs=0.5, mults=(4.0,), replicas=2, users=20_000,
            artifact_dir=workdir, saturation_qps=200.0, seed=3)
        assert out["saturation_qps"] == 200.0
        assert out["users"] == 20_000
        assert out["load_kind"] == "synthetic-open-loop-zipf-flood"
        assert out["touched_users"] > 0
        (point,) = out["points"]
        assert point["offered_mult"] == 4.0
        assert point["offered_qps_target"] == 800.0
        assert point["accounting_ok"], point
        assert point["offered_requests"] == (
            point["completed"] + point["sheds"] + point["overloads"]
            + point["timeouts"] + point["failed"])
        for key in ("goodput_qps", "p99_ms", "hedges_fired", "hedges_won",
                    "hedges_cancelled", "sheds_by_class",
                    "admission_transitions", "offered_qps_achieved"):
            assert key in point, key

    @pytest.mark.slow
    def test_full_flood_sweep(self, tmp_path):
        import bench_serving
        report = bench_serving.run_flood(
            report_path=str(tmp_path / "FLOOD_test.json"),
            run_secs=1.5, verbose=False)
        assert report["ok"]
        assert report["overload_drill"]["ladder_engaged"]
        top = max(report["flood"]["points"],
                  key=lambda p: p["offered_mult"])
        assert top["sheds"] + top["overloads"] > 0
