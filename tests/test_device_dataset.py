"""Device-resident dataset mode tests: CPU bit-parity against the staged
fit over identical pipelines, eligibility gating, and the over-budget
RuntimeWarning fallback. Single-device (``mesh_data=1``) — staged-vs-device
parity under a mesh inherits the environment's XLA CPU numerics drift."""

import jax.tree_util as jtu
import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.data import cache as cache_lib
from deepfm_tpu.data import libsvm
from deepfm_tpu.train import tasks as tasks_lib
from deepfm_tpu.train.loop import Trainer

pytestmark = pytest.mark.device_dataset

FIELD = 6
FEATURES = 250


@pytest.fixture()
def dataset(tmp_path):
    data = tmp_path / "data"
    libsvm.generate_synthetic_ctr(
        str(data), num_files=2, examples_per_file=80, field_size=FIELD,
        feature_size=FEATURES, seed=4, prefix="tr")
    return sorted(str(p) for p in data.glob("tr*.tfrecords"))


def _cfg(**over):
    kw = dict(feature_size=FEATURES, field_size=FIELD, embedding_size=8,
              deep_layers="16,8", dropout="1.0,1.0", batch_size=16,
              steps_per_loop=4, num_epochs=2, shuffle_buffer=1 << 20,
              learning_rate=0.01, log_steps=0, seed=21, mesh_data=1,
              decoded_cache="ram")
    kw.update(over)
    return Config(**kw)


def _train(cfg, files, max_steps=None):
    """The task driver's per-epoch loop: one pipeline + one fit per epoch,
    routed through the same device/staged dispatcher the train task uses."""
    cache_lib.clear_ram_cache()
    trainer = Trainer(cfg)
    state = trainer.init_state()
    losses = []
    hooks = [lambda s, m: losses.append(
        (float(m["loss"]), int(m.get("steps_done", 0))))]
    for epoch in range(cfg.num_epochs):
        pipe = tasks_lib.make_pipeline(cfg, files, epochs=1, shuffle=True,
                                       epoch_offset=epoch)
        if max_steps is not None:
            if cfg.device_dataset:
                state, fit_m = trainer.fit_device_resident(
                    state, pipe, hooks=hooks, max_steps=max_steps)
            else:
                state, fit_m = trainer.fit(
                    state, pipe, hooks=hooks, max_steps=max_steps)
        else:
            state, fit_m = tasks_lib._fit_epoch(
                trainer, cfg, state, pipe, hooks, None)
    return state, losses, fit_m


class TestDeviceResidentParity:
    def test_matches_staged_bitwise(self, dataset):
        """Same seed => same per-dispatch loss sequence AND bit-identical
        final params: the device gather replays the staged pool's emission
        order exactly (single-drain regime), and rng folds in state.step,
        so dispatch mechanics cannot alter the trajectory."""
        s_staged, l_staged, _ = _train(_cfg(device_dataset=False), dataset)
        s_dev, l_dev, fit_m = _train(_cfg(device_dataset=True), dataset)
        assert l_staged == l_dev
        assert int(s_staged.step) == int(s_dev.step)
        for a, b in zip(jtu.tree_leaves(s_staged.params),
                        jtu.tree_leaves(s_dev.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert fit_m["steps"] * 2 == int(s_dev.step)  # equal epochs

    def test_max_steps_truncation_matches(self, dataset):
        cfg_s = _cfg(device_dataset=False, num_epochs=1)
        cfg_d = _cfg(device_dataset=True, num_epochs=1)
        _, l_staged, m_staged = _train(cfg_s, dataset, max_steps=7)
        _, l_dev, m_dev = _train(cfg_d, dataset, max_steps=7)
        assert m_staged["steps"] == m_dev["steps"] == 7.0
        assert l_staged == l_dev


class TestDeviceDatasetFallback:
    def test_over_budget_falls_back_with_warning(self, dataset):
        cfg = _cfg(device_dataset=True, device_dataset_hbm_fraction=1e-12,
                   num_epochs=1)
        cache_lib.clear_ram_cache()
        trainer = Trainer(cfg)
        state = trainer.init_state()
        pipe = tasks_lib.make_pipeline(cfg, dataset, epochs=1, shuffle=True)
        with pytest.warns(RuntimeWarning, match="fell back to the staged"):
            state, fit_m = tasks_lib._fit_epoch(
                trainer, cfg, state, pipe, [], None)
        assert fit_m["steps"] > 0  # training still happened, staged

    def test_ineligible_reasons(self, dataset):
        trainer = Trainer(_cfg(device_dataset=True))
        # No decoded cache on the pipeline.
        cfg_off = _cfg(decoded_cache="off")
        pipe = tasks_lib.make_pipeline(cfg_off, dataset, epochs=1)
        assert "no decoded cache" in trainer.device_dataset_ineligible(pipe)
        # Pool smaller than the epoch: drain boundaries are arrival-
        # dependent, not reproducible as a device gather.
        cfg_small = _cfg(shuffle_buffer=32)
        pipe = tasks_lib.make_pipeline(cfg_small, dataset, epochs=1)
        assert "pool smaller" in trainer.device_dataset_ineligible(pipe)
        # Mid-epoch resume prefix: owned by the staged skip machinery.
        pipe = tasks_lib.make_pipeline(_cfg(), dataset, epochs=1,
                                       skip_batches=3)
        assert "skip_batches" in trainer.device_dataset_ineligible(pipe)

    def test_eligible_pipeline_reports_none(self, dataset):
        cache_lib.clear_ram_cache()
        trainer = Trainer(_cfg(device_dataset=True))
        pipe = tasks_lib.make_pipeline(_cfg(), dataset, epochs=1)
        assert trainer.device_dataset_ineligible(pipe) is None
