"""Shared-memory slab ring unit tests (data/shm_ring.py).

All protocol mechanics — layout, wraparound, backpressure, out-of-order
release — run in-process with thread queues (``THREAD_CTX``), so the file
is deterministic and sleep-free (the test_retry.py discipline: no real
waiting, every timeout is a non-blocking probe). Process-boundary behavior
is covered by tests/test_input_workers.py.
"""

import numpy as np
import pytest

from deepfm_tpu.data import shm_ring

pytestmark = pytest.mark.input_service


def _ring(slab_records=8, field_size=3, capacity=2):
    spec = shm_ring.SlabSpec(slab_records, field_size)
    return shm_ring.ShmRing.create(spec, capacity, shm_ring.THREAD_CTX)


class TestSlabSpec:
    def test_layout_bytes(self):
        spec = shm_ring.SlabSpec(slab_records=10, field_size=4)
        assert spec.labels_bytes == 40
        assert spec.ids_bytes == 160
        assert spec.slab_bytes == 40 + 160 + 160

    def test_rejects_degenerate_geometry(self):
        with pytest.raises(ValueError):
            shm_ring.SlabSpec(0, 4)
        with pytest.raises(ValueError):
            shm_ring.SlabSpec(8, 0)

    def test_capacity_floor(self):
        spec = shm_ring.SlabSpec(8, 3)
        with pytest.raises(ValueError, match="capacity"):
            shm_ring.ShmRing.create(spec, 1, shm_ring.THREAD_CTX)


class TestSlabArrays:
    def test_views_alias_the_segment(self):
        ring = _ring()
        try:
            labels, ids, vals = ring.arrays(0, 5)
            labels[:] = np.arange(5, dtype=np.float32)
            ids[:] = 7
            vals[:] = 0.5
            lab2, ids2, vals2 = ring.arrays(0, 5)
            np.testing.assert_array_equal(
                lab2, np.arange(5, dtype=np.float32))
            assert ids2.shape == (5, 3) and (ids2 == 7).all()
            assert (vals2 == 0.5).all()
            del labels, ids, vals, lab2, ids2, vals2
        finally:
            ring.close()

    def test_slots_do_not_overlap(self):
        ring = _ring(slab_records=4, field_size=2, capacity=3)
        try:
            for slot in range(3):
                lab, ids, vals = ring.arrays(slot, 4)
                lab[:] = slot
                ids[:] = slot
                vals[:] = slot
                del lab, ids, vals
            for slot in range(3):
                lab, ids, vals = ring.arrays(slot, 4)
                assert (lab == slot).all() and (ids == slot).all() \
                    and (vals == slot).all()
                del lab, ids, vals
        finally:
            ring.close()

    def test_bounds_checked(self):
        ring = _ring(slab_records=8, capacity=2)
        try:
            with pytest.raises(IndexError):
                ring.arrays(2, 1)
            with pytest.raises(ValueError):
                ring.arrays(0, 9)  # more rows than a slab holds
            with pytest.raises(IndexError):
                ring.release(5)
        finally:
            ring.close()


class TestCreditProtocol:
    def test_all_slots_preloaded_free(self):
        ring = _ring(capacity=3)
        try:
            got = {ring.acquire(timeout=0) for _ in range(3)}
            assert got == {0, 1, 2}
            assert ring.acquire(timeout=0) is None
        finally:
            ring.close()

    def test_backpressure_when_consumer_stalls(self):
        """Producer drains the free list and gets None (would block in
        production) until the consumer releases — no busy polling, no
        sleeping, the credit queue IS the flow control."""
        ring = _ring(capacity=2)
        try:
            a = ring.acquire(timeout=0)
            b = ring.acquire(timeout=0)
            assert {a, b} == {0, 1}
            assert ring.acquire(timeout=0) is None  # stalled consumer
            ring.send(("chunk", 0, a))
            ring.send(("chunk", 1, b))
            # Consumer pops one and releases it: exactly one credit returns.
            msg = ring.pop(timeout=0)
            ring.release(msg[2])
            assert ring.acquire(timeout=0) == msg[2]
            assert ring.acquire(timeout=0) is None
        finally:
            ring.close()

    def test_wraparound_slot_reuse(self):
        """7 slabs through a capacity-2 ring: slots recycle; data written
        in each incarnation reads back intact before release."""
        ring = _ring(slab_records=4, field_size=2, capacity=2)
        try:
            pending = []
            produced = consumed = 0
            while consumed < 7:
                slot = ring.acquire(timeout=0) if produced < 7 else None
                if slot is not None:
                    lab, ids, vals = ring.arrays(slot, 3)
                    lab[:] = produced
                    ids[:] = produced
                    vals[:] = produced * 0.5
                    del lab, ids, vals
                    ring.send((produced, slot))
                    produced += 1
                    continue
                tag, slot = ring.pop(timeout=0)
                lab, ids, vals = ring.arrays(slot, 3)
                assert (lab == tag).all() and (ids == tag).all()
                assert (vals == tag * 0.5).all()
                del lab, ids, vals
                ring.release(slot)
                pending.append(slot)
                consumed += 1
            assert produced == consumed == 7
            assert set(pending) == {0, 1}  # only two physical slabs existed
        finally:
            ring.close()

    def test_out_of_order_release(self):
        """Free slots are a set, not a cursor: the consumer may hold an
        early slot (shuffle pool) while later ones recycle repeatedly."""
        ring = _ring(capacity=3)
        try:
            held = ring.acquire(timeout=0)
            for _ in range(5):  # the other two slots keep cycling
                s1 = ring.acquire(timeout=0)
                s2 = ring.acquire(timeout=0)
                assert held not in (s1, s2)
                ring.release(s2)
                ring.release(s1)
            ring.release(held)
            got = {ring.acquire(timeout=0) for _ in range(3)}
            assert got == {0, 1, 2}
        finally:
            ring.close()


class TestHandleAndLifecycle:
    def test_handle_attach_shares_memory(self):
        ring = _ring(slab_records=6, field_size=2)
        try:
            other = shm_ring.ShmRing.attach(ring.handle)
            lab, ids, vals = ring.arrays(1, 6)
            lab[:] = 3.5
            del ids, vals
            lab2, _, _ = other.arrays(1, 6)
            assert (lab2 == 3.5).all()
            del lab, lab2
            other.close()  # non-owner: must not unlink under the owner
            lab3, _, _ = ring.arrays(1, 6)
            assert (lab3 == 3.5).all()
            del lab3
        finally:
            ring.close()

    def test_close_is_idempotent_and_survives_live_views(self):
        ring = _ring()
        lab, ids, vals = ring.arrays(0, 2)
        ring.close()  # live exported views: must not raise
        ring.close()
        assert lab is not None
        del lab, ids, vals


class TestDecodeIntoSlab:
    def test_scatter_decode_parity_with_python_codec(self):
        """A slab is a valid decode_spans_scatter destination: decoding
        records straight into ring views matches decode_batch_python —
        the worker's write path against the reference decoder."""
        from deepfm_tpu.data import example_codec
        from deepfm_tpu.data import pipeline as pipe_mod

        loader = pipe_mod._native_loader()
        if loader is None:
            pytest.skip("native decoder unavailable")
        F = 5
        recs = [example_codec.encode_ctr_example(
            float(i % 2), np.arange(F) + i, np.linspace(0, 1, F) + i)
            for i in range(7)]
        ring = _ring(slab_records=8, field_size=F)
        try:
            buf = b"".join(recs)
            lengths = np.array([len(r) for r in recs], np.int64)
            offsets = np.concatenate(([0], np.cumsum(lengths[:-1])))
            slot = ring.acquire(timeout=0)
            labels, ids, vals = ring.arrays(slot, len(recs))
            loader.decode_spans_scatter(
                buf, offsets, lengths, F,
                np.arange(len(recs), dtype=np.int64), labels, ids, vals)
            ref = pipe_mod.decode_batch_python(recs, F)
            np.testing.assert_array_equal(labels, ref[0])
            np.testing.assert_array_equal(ids, ref[1])
            np.testing.assert_array_equal(vals, ref[2])
            del labels, ids, vals
        finally:
            ring.close()
