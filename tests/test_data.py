"""Data layer tests: codec, TFRecord framing, LibSVM conversion, shard policy,
pipeline semantics. Includes cross-validation against TensorFlow's own
TFRecord/Example implementation when TF is importable (format parity is a
hard requirement: the reference's data files must be readable unmodified)."""

import io
import os

import numpy as np
import pytest

from deepfm_tpu.data import example_codec, libsvm, pipeline, sharding, tfrecord


def _mk_example(label=1.0, f=5, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 1000, size=f).astype(np.int64)
    vals = rng.normal(size=f).astype(np.float32)
    return label, ids, vals


class TestCodec:
    def test_roundtrip(self):
        label, ids, vals = _mk_example()
        buf = example_codec.encode_ctr_example(label, ids, vals)
        label2, ids2, vals2 = example_codec.decode_ctr_example(buf, 5)
        assert label2 == label
        np.testing.assert_array_equal(ids, ids2)
        np.testing.assert_array_equal(vals, vals2)

    def test_negative_int64(self):
        buf = example_codec.encode_example(
            {"x": (np.array([-1, -(2**62), 3], np.int64), "int64")})
        out = example_codec.decode_example(buf)
        kind, val = out["x"]
        assert kind == "int64"
        np.testing.assert_array_equal(val, [-1, -(2**62), 3])

    def test_field_size_validation(self):
        label, ids, vals = _mk_example(f=4)
        buf = example_codec.encode_ctr_example(label, ids, vals)
        with pytest.raises(ValueError):
            example_codec.decode_ctr_example(buf, 5)

    def test_tf_parity_decode_ours(self):
        """TF must parse bytes we encode (writer-side format parity)."""
        tf = pytest.importorskip("tensorflow")
        label, ids, vals = _mk_example(f=7, seed=3)
        buf = example_codec.encode_ctr_example(label, ids, vals)
        ex = tf.train.Example.FromString(buf)
        feat = ex.features.feature
        assert list(feat["label"].float_list.value) == [label]
        # Writer emits the reference's on-disk keys (tools/libsvm_to_tfrecord.py:25-33).
        assert list(feat["ids"].int64_list.value) == ids.tolist()
        np.testing.assert_allclose(
            np.array(feat["values"].float_list.value, np.float32), vals)

    def test_tf_parity_decode_theirs(self):
        """We must parse bytes TF encodes with the REFERENCE schema keys."""
        tf = pytest.importorskip("tensorflow")
        label, ids, vals = _mk_example(f=6, seed=4)
        ex = tf.train.Example(features=tf.train.Features(feature={
            "label": tf.train.Feature(float_list=tf.train.FloatList(value=[label])),
            "ids": tf.train.Feature(int64_list=tf.train.Int64List(value=ids)),
            "values": tf.train.Feature(float_list=tf.train.FloatList(value=vals)),
        }))
        l2, i2, v2 = example_codec.decode_ctr_example(ex.SerializeToString(), 6)
        assert l2 == label
        np.testing.assert_array_equal(i2, ids)
        np.testing.assert_allclose(v2, vals, rtol=1e-6)

    def test_decode_legacy_keys(self):
        """Pre-r3 files keyed feat_ids/feat_vals still decode."""
        label, ids, vals = _mk_example(f=6, seed=5)
        buf = example_codec.encode_example({
            "label": (np.asarray([label], np.float32), "float"),
            "feat_ids": (np.asarray(ids, np.int64), "int64"),
            "feat_vals": (np.asarray(vals, np.float32), "float"),
        })
        l2, i2, v2 = example_codec.decode_ctr_example(buf, 6)
        assert l2 == label
        np.testing.assert_array_equal(i2, ids)
        np.testing.assert_allclose(v2, vals, rtol=1e-6)

    def test_missing_keys_error_names_schema(self):
        buf = example_codec.encode_example(
            {"label": (np.asarray([1.0], np.float32), "float")})
        with pytest.raises(ValueError, match="ids.*values"):
            example_codec.decode_ctr_example(buf, 6)


class TestTFRecordIO:
    def test_crc32c_known_vectors(self):
        # RFC 3720 test vector: crc32c of 32 zero bytes.
        assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA
        assert tfrecord.crc32c(b"123456789") == 0xE3069283

    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "a.tfrecords")
        recs = [os.urandom(n) for n in (1, 10, 1000)]
        with tfrecord.TFRecordWriter(path) as w:
            for r in recs:
                w.write(r)
        assert tfrecord.read_all_records(path) == recs

    def test_corrupt_crc_detected(self, tmp_path):
        path = str(tmp_path / "a.tfrecords")
        with tfrecord.TFRecordWriter(path) as w:
            w.write(b"hello world")
        data = bytearray(open(path, "rb").read())
        data[14] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(data))
        with pytest.raises(IOError):
            tfrecord.read_all_records(path)

    def test_stream_iterator_no_seek(self, tmp_path):
        recs = [b"a" * 5, b"b" * 17]
        path = str(tmp_path / "s.tfrecords")
        with tfrecord.TFRecordWriter(path) as w:
            for r in recs:
                w.write(r)

        class NoSeek(io.RawIOBase):
            def __init__(self, b):
                self._b = io.BytesIO(b)
            def read(self, n=-1):
                return self._b.read(n)
            def seekable(self):
                return False

        out = list(tfrecord.iter_records_from_stream(NoSeek(open(path, "rb").read())))
        assert out == recs

    def test_tf_reads_our_files(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        path = str(tmp_path / "ours.tfrecords")
        label, ids, vals = _mk_example(f=5, seed=9)
        with tfrecord.TFRecordWriter(path) as w:
            w.write(example_codec.encode_ctr_example(label, ids, vals))
        ds = tf.data.TFRecordDataset([path])
        got = list(ds.as_numpy_iterator())
        assert len(got) == 1
        ex = tf.train.Example.FromString(got[0])
        assert list(ex.features.feature["ids"].int64_list.value) == ids.tolist()

    def test_we_read_tf_files(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        path = str(tmp_path / "theirs.tfrecords")
        with tf.io.TFRecordWriter(path) as w:
            w.write(b"payload-1")
            w.write(b"payload-22")
        assert tfrecord.read_all_records(path) == [b"payload-1", b"payload-22"]


class TestLibsvm:
    def test_parse_format_roundtrip(self):
        line = "1 3:0.5 17:1 999:-2.25"
        label, ids, vals = libsvm.parse_libsvm_line(line)
        assert label == 1.0
        np.testing.assert_array_equal(ids, [3, 17, 999])
        np.testing.assert_allclose(vals, [0.5, 1.0, -2.25])
        assert libsvm.format_libsvm_line(label, ids, vals) == line

    def test_convert_and_back(self, tmp_path):
        src = tmp_path / "in.libsvm"
        lines = ["1 0:0.1 1:0.2 2:0.3", "0 3:1 4:1 5:1"]
        src.write_text("\n".join(lines) + "\n")
        out = str(tmp_path / "out.tfrecords")
        n = libsvm.convert_libsvm_file(str(src), out, field_size=3)
        assert n == 2
        back = str(tmp_path / "back.libsvm")
        assert libsvm.tfrecord_to_libsvm(out, back, field_size=3) == 2
        assert open(back).read().strip().split("\n") == lines

    def test_sharded_output(self, tmp_path):
        src = tmp_path / "in.libsvm"
        src.write_text("\n".join(f"{i % 2} {i}:1.0" for i in range(10)) + "\n")
        out = str(tmp_path / "out.tfrecords")
        libsvm.convert_libsvm_file(str(src), out, num_shards=3)
        counts = [
            len(tfrecord.read_all_records(f"{out}-{s:05d}-of-00003"))
            for s in range(3)
        ]
        assert counts == [4, 3, 3]

    def test_synthetic_generator(self, tmp_path):
        paths = libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=2, examples_per_file=8,
            feature_size=100, field_size=4)
        assert len(paths) == 2
        recs = tfrecord.read_all_records(paths[0])
        assert len(recs) == 8
        label, ids, vals = example_codec.decode_ctr_example(recs[0], 4)
        assert label in (0.0, 1.0)
        assert ids.max() < 100


class TestShardPolicy:
    FILES = [f"f{i}" for i in range(8)]

    def test_single_worker_identity(self):
        s = sharding.shard_files(self.FILES)
        assert s.files == tuple(sorted(self.FILES))

    def test_global_shard_covers(self):
        specs = [
            sharding.shard_files(self.FILES, rank=r, world_size=4)
            for r in range(4)
        ]
        sharding.validate_shard_coverage(specs, self.FILES)
        assert all(len(s.files) == 2 for s in specs)

    def test_record_fallback_when_few_files(self):
        s = sharding.shard_files(["only"], rank=2, world_size=4)
        assert s.files == ("only",)
        assert s.record_shard == (4, 2)
        assert [s.shard_records(i) for i in range(8)] == [
            False, False, True, False, False, False, True, False]

    def test_s3_shard_splits_by_local_rank(self):
        specs = [
            sharding.shard_files(
                self.FILES, enable_s3_shard=True, local_rank=lr,
                rank=lr, world_size=8, workers_per_host=4)
            for lr in range(4)
        ]
        sharding.validate_shard_coverage(specs, self.FILES)

    def test_multi_path_s3_sharded_no_shard(self):
        # multi_path + S3-sharded storage: channel already disjoint per
        # host — read everything (README-EN.md:88 row 1).
        s = sharding.shard_files(
            self.FILES, enable_data_multi_path=True, enable_s3_shard=True,
            rank=3, world_size=4)
        assert s.files == tuple(sorted(self.FILES))

    def test_multi_path_replicated_storage_shards_by_host(self):
        # multi_path + replicated storage: worker i on EVERY host reads
        # channel i, so hosts must split it (README-EN.md:89 row 2;
        # reference 2-hvd-gpu/...py:98-102).
        specs = [
            sharding.shard_files(
                self.FILES, enable_data_multi_path=True,
                enable_s3_shard=False, rank=r, world_size=4,
                workers_per_host=1)
            for r in range(4)
        ]
        sharding.validate_shard_coverage(specs, self.FILES)
        assert all(len(s.files) < len(self.FILES) for s in specs)


class TestPipeline:
    @pytest.fixture()
    def data_dir(self, tmp_path):
        libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=3, examples_per_file=50,
            feature_size=200, field_size=6, seed=1)
        return tmp_path

    def _files(self, data_dir):
        return sorted(str(p) for p in data_dir.glob("*.tfrecords"))

    def test_shapes_and_count(self, data_dir):
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=32,
            num_epochs=1, seed=7, use_native_decoder=False)
        batches = list(p)
        assert len(batches) == 150 // 32
        b = batches[0]
        assert b["feat_ids"].shape == (32, 6) and b["feat_ids"].dtype == np.int32
        assert b["feat_vals"].shape == (32, 6) and b["feat_vals"].dtype == np.float32
        assert b["label"].shape == (32, 1)

    def test_no_drop_remainder(self, data_dir):
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=32,
            drop_remainder=False, use_native_decoder=False)
        batches = list(p)
        assert sum(b["label"].shape[0] for b in batches) == 150
        assert batches[-1]["label"].shape[0] == 150 % 32

    def test_epochs_multiply(self, data_dir):
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=50,
            num_epochs=3, shuffle=False, use_native_decoder=False)
        assert len(list(p)) == 9

    def test_deterministic_given_seed(self, data_dir):
        def run():
            p = pipeline.CtrPipeline(
                self._files(data_dir), field_size=6, batch_size=16,
                seed=5, use_native_decoder=False, prefetch_batches=0)
            return [b["feat_ids"] for b in p]
        a, b = run(), run()
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_shuffle_changes_order_across_epochs(self, data_dir):
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=150,
            num_epochs=2, shuffle=True, shuffle_buffer=1000, seed=3,
            drop_remainder=False, use_native_decoder=False)
        e1, e2 = list(p)
        assert not np.array_equal(e1["feat_ids"], e2["feat_ids"])
        # same multiset of examples
        assert (sorted(map(tuple, e1["feat_ids"].tolist()))
                == sorted(map(tuple, e2["feat_ids"].tolist())))

    @pytest.mark.parametrize("native", [False, True])
    def test_epoch_offset_reshuffles_driver_epochs(self, data_dir, native):
        """The task driver recreates the pipeline per epoch with epochs=1
        (reference file-mode shape); epoch_offset must vary the shuffle so
        driver epochs don't replay identical batch order (VERDICT r2 #4),
        while a fixed (seed, offset) stays reproducible."""
        def epoch_ids(offset):
            p = pipeline.CtrPipeline(
                self._files(data_dir), field_size=6, batch_size=150,
                num_epochs=1, shuffle=True, shuffle_buffer=1000, seed=3,
                drop_remainder=False, use_native_decoder=native,
                prefetch_batches=0, epoch_offset=offset)
            (b,) = list(p)
            return b["feat_ids"]
        e0, e1 = epoch_ids(0), epoch_ids(1)
        assert not np.array_equal(e0, e1)
        # same multiset of examples, different order
        assert (sorted(map(tuple, e0.tolist()))
                == sorted(map(tuple, e1.tolist())))
        np.testing.assert_array_equal(e0, epoch_ids(0))  # reproducible

    def test_driver_epochs_differ_end_to_end(self, data_dir):
        """tasks.make_pipeline(epoch_offset=k) feeds the driver epoch into
        the seed: orders must differ between driver epochs."""
        from deepfm_tpu.config import Config
        from deepfm_tpu.train import tasks
        cfg = Config(
            data_dir=str(data_dir), feature_size=200, field_size=6,
            embedding_size=4, deep_layers="8", dropout="1.0",
            batch_size=150, log_steps=0, drop_remainder=False,
            shuffle_buffer=1000, seed=3)
        files = self._files(data_dir)
        orders = []
        for epoch in range(2):
            p = tasks.make_pipeline(cfg, files, epochs=1, shuffle=True,
                                    epoch_offset=epoch)
            orders.append(np.concatenate(
                [b["feat_ids"] for b in p]))
        assert not np.array_equal(orders[0], orders[1])

    @pytest.mark.parametrize("drop", [True, False])
    def test_superbatches_cover_same_examples(self, data_dir, drop):
        """iter_superbatches (the zero-copy K-step feed) must cover exactly
        the records the single-batch path covers: same multiset, same total
        step count, groups of at most k, tail emitted as singles."""
        kw = dict(field_size=6, batch_size=32, num_epochs=1, shuffle=True,
                  shuffle_buffer=1000, seed=3, drop_remainder=drop,
                  prefetch_batches=0)
        singles = pipeline.CtrPipeline(self._files(data_dir), **kw)
        ids_single = np.concatenate(
            [b["feat_ids"] for b in singles])
        n_batches = sum(1 for _ in pipeline.CtrPipeline(
            self._files(data_dir), **kw))

        sb = pipeline.CtrPipeline(self._files(data_dir), **kw)
        total_steps, rows_all = 0, []
        for rows, m, n_ex in sb.iter_superbatches(3):
            assert 1 <= m <= 3
            assert rows["feat_ids"].shape[0] == n_ex
            if m > 1:
                assert n_ex == m * 32  # full groups reshape to [m, bs]
            total_steps += m
            rows_all.append(rows["feat_ids"])
        ids_super = np.concatenate(rows_all)
        assert total_steps == n_batches
        assert (sorted(map(tuple, ids_single.tolist()))
                == sorted(map(tuple, ids_super.tolist())))

    def test_superbatches_python_decoder_fallback(self, data_dir):
        """The non-native path groups plain batches (stack copy) but keeps
        the same contract."""
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=32,
            shuffle=False, drop_remainder=False, prefetch_batches=0,
            use_native_decoder=False)
        total = 0
        for rows, m, n_ex in p.iter_superbatches(2):
            total += n_ex
            assert rows["feat_ids"].shape[0] == n_ex
        assert total == 150

    def test_sharded_pipelines_partition_data(self, data_dir):
        files = self._files(data_dir)
        seen = []
        for r in range(3):
            spec = sharding.shard_files(files, rank=r, world_size=3)
            p = pipeline.CtrPipeline(
                files, field_size=6, batch_size=10, shard=spec,
                shuffle=False, shuffle_files=False, drop_remainder=False,
                use_native_decoder=False)
            for b in p:
                seen.extend(map(tuple, b["feat_ids"].tolist()))
        assert len(seen) == 150
        assert len(set(seen)) == len(seen)  # disjoint coverage

    def test_streaming_superbatches_match_batches(self, data_dir):
        """Streaming iter_superbatches yields the identical batch sequence
        as __iter__ (stream order, no shuffle) — only the grouping differs —
        and honors single-pass FIFO semantics."""
        files = self._files(data_dir)
        raw = b"".join(open(f, "rb").read() for f in files)
        singles = list(pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25,
            prefetch_batches=0, drop_remainder=False))
        sp = pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25,
            prefetch_batches=0, drop_remainder=False)
        total_m, rows_all = 0, []
        for rows, m, n_ex in sp.iter_superbatches(3):
            assert rows["feat_ids"].shape[0] == n_ex
            total_m += m
            rows_all.append(rows["feat_ids"])
        assert total_m == len(singles)
        np.testing.assert_array_equal(
            np.concatenate([b["feat_ids"] for b in singles]),
            np.concatenate(rows_all))
        with pytest.raises(RuntimeError):  # FIFO: no second pass
            next(iter(sp.iter_superbatches(3)))

    def test_streaming_skip_batches(self, data_dir):
        """Resume skip drops exactly the leading batches of the stream."""
        files = self._files(data_dir)
        raw = b"".join(open(f, "rb").read() for f in files)
        full = list(pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25, prefetch_batches=0))
        skipped = list(pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25, prefetch_batches=0,
            skip_batches=2))
        assert len(skipped) == len(full) - 2
        np.testing.assert_array_equal(
            full[2]["feat_ids"], skipped[0]["feat_ids"])

    def test_emission_properties_randomized(self, data_dir):
        """Seeded property sweep over (batch_size, k, skip, drop_remainder):
        for every combination, (a) iter_superbatches covers exactly the
        single-batch stream's multiset and step count, and (b) skip=n
        yields exactly the unskipped superbatch stream minus its first n
        batches — the invariants step-accurate resume rests on."""
        files = self._files(data_dir)
        rng = np.random.default_rng(7)
        for _ in range(12):
            bs = int(rng.choice([8, 16, 32, 50, 64]))
            k = int(rng.choice([2, 3, 4, 8]))
            drop = bool(rng.choice([True, False]))
            kw = dict(field_size=6, batch_size=bs, shuffle=True,
                      shuffle_buffer=int(rng.choice([1, 40, 1000])),
                      seed=int(rng.integers(100)), drop_remainder=drop,
                      prefetch_batches=0)

            def flat_ids(skip=0, use_super=True):
                p = pipeline.CtrPipeline(files, skip_batches=skip, **kw)
                if use_super:
                    out, steps = [], 0
                    for rows, m, n_ex in p.iter_superbatches(k):
                        assert rows["label"].shape[0] == n_ex
                        out.append(rows["feat_ids"])
                        steps += m
                    return (np.concatenate(out) if out
                            else np.zeros((0, 6), np.int32)), steps
                out = [b["feat_ids"] for b in p]
                return (np.concatenate(out) if out
                        else np.zeros((0, 6), np.int32)), len(out)

            singles, n_singles = flat_ids(use_super=False)
            sup, n_sup = flat_ids()
            assert n_sup == n_singles, (bs, k, drop)
            if not drop:
                # Full coverage: both paths must emit every record exactly
                # once. (With drop_remainder the k-group and per-batch
                # drains legitimately drop different tail records when the
                # pool spans multiple drains — counts still agree, and the
                # suffix property below is what resume correctness needs.)
                assert (sorted(map(tuple, singles.tolist()))
                        == sorted(map(tuple, sup.tolist()))), (bs, k, drop)

            skip = int(rng.integers(0, max(n_sup, 1)))
            skipped, n_skipped = flat_ids(skip=skip)
            assert n_skipped == n_sup - skip, (bs, k, skip, drop)
            # suffix property: the skipped stream IS the tail of the full
            # stream (row-for-row), which is what makes resume exact
            tail = sup[sup.shape[0] - skipped.shape[0]:]
            np.testing.assert_array_equal(skipped, tail,
                                          err_msg=str((bs, k, skip, drop)))

    def test_skip_batches_beyond_data_yields_nothing(self, data_dir):
        """Over-skip (resume meta ahead of a shrunken dataset) exhausts
        cleanly instead of erroring; both emission paths."""
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=32,
            prefetch_batches=0, skip_batches=10_000)
        assert list(p) == []
        p = pipeline.CtrPipeline(
            self._files(data_dir), field_size=6, batch_size=32,
            prefetch_batches=0, skip_batches=10_000)
        assert list(p.iter_superbatches(3)) == []

    def test_streaming_single_pass(self, data_dir):
        files = self._files(data_dir)
        raw = b"".join(open(f, "rb").read() for f in files)
        sp = pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25,
            use_native_decoder=False)
        assert len(list(sp)) == 6
        with pytest.raises(RuntimeError):
            list(sp)

    def test_prefetch_propagates_errors(self, tmp_path):
        bad = str(tmp_path / "bad.tfrecords")
        open(bad, "wb").write(b"\x01\x02\x03")
        p = pipeline.CtrPipeline(
            [bad], field_size=6, batch_size=4, prefetch_batches=2,
            use_native_decoder=False)
        with pytest.raises(IOError):
            list(p)


class TestNativeStreaming:
    """Pipe-mode fast path: chunked C framing + vectorized decode off the
    byte stream must be record-for-record identical to the pure-Python
    framer (order, sharding, tail handling)."""

    @pytest.fixture()
    def data_dir(self, tmp_path):
        libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=3, examples_per_file=50,
            feature_size=200, field_size=6, seed=1)
        return tmp_path

    def _files(self, data_dir):
        import glob as _g
        return sorted(_g.glob(str(data_dir / "*.tfrecords")))

    def _run(self, data_dir, native, record_shard=None, drop_remainder=False):
        files = self._files(data_dir)
        raw = b"".join(open(f, "rb").read() for f in files)
        sp = pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25,
            use_native_decoder=native, record_shard=record_shard,
            drop_remainder=drop_remainder, prefetch_batches=0)
        return list(sp)

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    @pytest.mark.parametrize("record_shard", [None, (2, 0), (2, 1), (3, 2)])
    def test_native_matches_python(self, data_dir, record_shard):
        native = self._run(data_dir, True, record_shard)
        python = self._run(data_dir, False, record_shard)
        assert len(native) == len(python)
        for bn, bp in zip(native, python):
            np.testing.assert_array_equal(bn["feat_ids"], bp["feat_ids"])
            np.testing.assert_array_equal(bn["feat_vals"], bp["feat_vals"])
            np.testing.assert_array_equal(bn["label"], bp["label"])

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_native_small_chunks_cross_boundaries(self, data_dir, monkeypatch):
        # Force tiny reads so records straddle chunk boundaries constantly.
        monkeypatch.setattr(pipeline, "_NATIVE_CHUNK_BYTES", 64)
        native = self._run(data_dir, True)
        monkeypatch.setattr(pipeline, "_NATIVE_CHUNK_BYTES", 64 << 20)
        python = self._run(data_dir, False)
        assert len(native) == len(python)
        for bn, bp in zip(native, python):
            np.testing.assert_array_equal(bn["feat_ids"], bp["feat_ids"])

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_native_single_pass_guard(self, data_dir):
        files = self._files(data_dir)
        raw = b"".join(open(f, "rb").read() for f in files)
        sp = pipeline.StreamingCtrPipeline(
            io.BytesIO(raw), field_size=6, batch_size=25,
            use_native_decoder=True)
        assert len(list(sp)) == 6
        with pytest.raises(RuntimeError):
            list(sp)


class TestFileIO:
    """Remote-path seam (the S3/GCS streaming analog, X3): URL-style paths
    dispatch to tf.io.gfile, local paths to POSIX I/O."""

    def test_local_paths_use_posix(self, tmp_path):
        from deepfm_tpu.data import fileio
        p = tmp_path / "x.tfrecords"
        p.write_bytes(b"abc")
        assert not fileio.is_remote(str(p))
        with fileio.open_stream(str(p)) as f:
            assert f.read() == b"abc"
        assert fileio.glob(str(tmp_path / "*.tfrecords")) == [str(p)]
        assert fileio.isdir(str(tmp_path))

    def test_remote_paths_dispatch_to_gfile(self, monkeypatch):
        from deepfm_tpu.data import fileio

        calls = []

        class FakeGFile:
            def __init__(self, path, mode):
                calls.append(("open", path, mode))

        class FakeModule:
            GFile = FakeGFile

            @staticmethod
            def glob(pattern):
                calls.append(("glob", pattern))
                return ["gs://b/tr2.tfrecords", "gs://b/tr1.tfrecords"]

            @staticmethod
            def isdir(path):
                calls.append(("isdir", path))
                return True

        monkeypatch.setattr(fileio, "_gfile_mod", FakeModule)
        assert fileio.is_remote("gs://b/data")
        fileio.open_stream("gs://b/tr1.tfrecords")
        assert fileio.glob("gs://b/*.tfrecords") == [
            "gs://b/tr1.tfrecords", "gs://b/tr2.tfrecords"]  # sorted
        assert fileio.isdir("gs://b/data")
        assert [c[0] for c in calls] == ["open", "glob", "isdir"]

    def test_resolve_files_remote_pattern(self, monkeypatch):
        from deepfm_tpu.data import fileio
        from deepfm_tpu.train import tasks

        patterns = []

        class FakeModule:
            @staticmethod
            def glob(pattern):
                patterns.append(pattern)
                return ["gs://b/criteo/tr1.tfrecords"]

        monkeypatch.setattr(fileio, "_gfile_mod", FakeModule)
        files = tasks.resolve_files("gs://b/criteo/", "tr")
        assert files == ["gs://b/criteo/tr1.tfrecords"]
        assert patterns == ["gs://b/criteo/tr*.tfrecords"]


class TestReferenceSchemaEndToEnd:
    """VERDICT r2 #1: TFRecords produced for the REFERENCE pipeline (on-disk
    keys label/ids/values, tools/libsvm_to_tfrecord.py:25-33) must flow
    through decode -> pipeline -> one train step on BOTH decoder paths."""

    F = 6
    N = 64

    def _write_reference_file(self, path, use_tf):
        rng = np.random.default_rng(7)
        rows = []
        if use_tf:
            tf = pytest.importorskip("tensorflow")
            writer = tf.io.TFRecordWriter(path)
            enc = None
        else:
            writer = tfrecord.TFRecordWriter(path)
            enc = example_codec
        try:
            for i in range(self.N):
                label = float(i % 2)
                ids = rng.integers(0, 500, size=self.F).astype(np.int64)
                vals = rng.normal(size=self.F).astype(np.float32)
                if use_tf:
                    import tensorflow as tf
                    ex = tf.train.Example(features=tf.train.Features(feature={
                        "label": tf.train.Feature(
                            float_list=tf.train.FloatList(value=[label])),
                        "ids": tf.train.Feature(
                            int64_list=tf.train.Int64List(value=ids)),
                        "values": tf.train.Feature(
                            float_list=tf.train.FloatList(value=vals)),
                    }))
                    writer.write(ex.SerializeToString())
                else:
                    writer.write(enc.encode_example({
                        "label": (np.asarray([label], np.float32), "float"),
                        "ids": (ids, "int64"),
                        "values": (vals, "float"),
                    }))
                rows.append((label, ids, vals))
        finally:
            writer.close()
        return rows

    @pytest.mark.parametrize("use_tf_writer", [False, True])
    @pytest.mark.parametrize("native", [False, True])
    def test_pipeline_and_train_step(self, tmp_path, native, use_tf_writer):
        if native:
            from deepfm_tpu.native import loader
            if not loader.available():
                pytest.skip("native toolchain unavailable")
        path = str(tmp_path / "ref.tfrecords")
        rows = self._write_reference_file(path, use_tf_writer)

        p = pipeline.CtrPipeline(
            [path], field_size=self.F, batch_size=32, shuffle=False,
            shuffle_files=False, use_native_decoder=native,
            prefetch_batches=0)
        batches = list(p)
        assert len(batches) == 2
        got_ids = np.concatenate([b["feat_ids"] for b in batches])
        np.testing.assert_array_equal(
            got_ids, np.stack([r[1] for r in rows]).astype(np.int32))
        got_vals = np.concatenate([b["feat_vals"] for b in batches])
        np.testing.assert_allclose(
            got_vals, np.stack([r[2] for r in rows]), rtol=1e-6)

        from deepfm_tpu.config import Config
        from deepfm_tpu.train import Trainer
        cfg = Config(feature_size=500, field_size=self.F, embedding_size=4,
                     deep_layers="8", dropout="1.0", batch_size=32,
                     compute_dtype="float32", log_steps=0, seed=3,
                     mesh_data=1, mesh_model=1)
        tr = Trainer(cfg)
        state = tr.init_state()
        state, summary = tr.fit(
            state,
            pipeline.CtrPipeline(
                [path], field_size=self.F, batch_size=32, shuffle=False,
                shuffle_files=False, use_native_decoder=native,
                prefetch_batches=0),
            max_steps=1)
        assert summary["steps"] == 1
        assert np.isfinite(summary["loss"])

    def test_native_error_message_names_missing_keys(self, tmp_path):
        from deepfm_tpu.native import loader
        if not loader.available():
            pytest.skip("native toolchain unavailable")
        buf = example_codec.encode_example(
            {"label": (np.asarray([1.0], np.float32), "float")})
        with pytest.raises(ValueError, match="required keys missing"):
            loader.decode_batch([buf], self.F)


class TestPooledEmissionGolden:
    """The pooled emission format is a RESUME COMPATIBILITY contract: a
    mid-epoch resume decode-skips along this exact stream, so any change to
    the emission order for identical config silently mis-skips unless the
    pipeline format version (tasks._consumption_layout) is bumped. These
    golden hashes pin the byte-exact emission of the native pooled path;
    they were captured BEFORE the r5 fused scatter-decode landed, proving
    that rewrite emission-identical. If a deliberate format change breaks
    them, bump the layout version and re-capture."""

    GOLDEN = {
        (8, 64, 0, True): "26fff204f1d9b877c88d8696",
        (4, 32, 5, False): "5130307b96f68f89dadc8fa5",
        (1, 64, 0, True): "3d50f093770b87683461989f",
    }

    @pytest.fixture()
    def golden_files(self, tmp_path):
        libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=3, examples_per_file=500,
            feature_size=1000, field_size=7, prefix="tr", seed=5)
        return sorted(str(p) for p in tmp_path.glob("tr*.tfrecords"))

    def _emission_hash(self, files, k, bs, skip, drop, **kw):
        import hashlib
        pipe = pipeline.CtrPipeline(
            files, field_size=7, batch_size=bs, num_epochs=2,
            shuffle=True, shuffle_files=True, shuffle_buffer=300,
            drop_remainder=drop, seed=9, skip_batches=skip, **kw)
        h = hashlib.sha256()
        for rows, m, n_ex in pipe.iter_superbatches(k):
            h.update(str(m).encode())
            h.update(str(n_ex).encode())
            h.update(rows["feat_ids"].tobytes())
            h.update(rows["feat_vals"].tobytes())
            h.update(rows["label"].tobytes())
        return h.hexdigest()[:24]

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_emission_matches_golden(self, golden_files):
        for (k, bs, skip, drop), want in self.GOLDEN.items():
            got = self._emission_hash(golden_files, k, bs, skip, drop)
            assert got == want, (
                f"pooled emission changed for (k={k}, bs={bs}, skip={skip}, "
                f"drop={drop}): {got} != {want} — if deliberate, bump the "
                f"pipeline format version in tasks._consumption_layout and "
                f"re-capture")

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_two_live_iterators_do_not_share_drain_pool(self, golden_files,
                                                        monkeypatch):
        """Two concurrent iterators of ONE pipeline: the drain-decode
        executor is per-iterator, so the first iterator finishing its run
        (its cleanup used to be pipeline-level close(), killing the shared
        pool) must not break the second's still-threaded drains."""
        monkeypatch.setattr(pipeline, "_SCATTER_SPLIT_MIN", 100)
        pipe = pipeline.CtrPipeline(
            golden_files, field_size=7, batch_size=64, num_epochs=1,
            shuffle=True, shuffle_files=True, shuffle_buffer=300,
            drop_remainder=True, seed=9, prefetch_batches=0)
        pipe.reader_threads = 3
        first = pipe.iter_superbatches(4)
        second = pipe.iter_superbatches(4)
        next(second)  # second is mid-epoch with drains pending...
        exhausted = sum(1 for _ in first)  # ...when first fully finishes
        rest = sum(1 for _ in second)
        # Both iterators see the complete, identical emission count (same
        # pipeline state, same seed => same stream).
        assert exhausted == rest + 1

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_parallel_scatter_decode_identical(self, golden_files,
                                               monkeypatch):
        """The multi-threaded drain decode (reader_threads > 1, chunks split
        into disjoint sub-spans) must emit the same bytes as sequential.
        reader_threads is core-clamped at __init__, so force it post-init,
        and lower _SCATTER_SPLIT_MIN so these 500-record chunks actually
        split — exercising the perm[off+s:off+e] sub-span arithmetic that
        production 64MB chunks (100k+ records) hit."""
        import hashlib
        monkeypatch.setattr(pipeline, "_SCATTER_SPLIT_MIN", 100)
        pipe = pipeline.CtrPipeline(
            golden_files, field_size=7, batch_size=64, num_epochs=2,
            shuffle=True, shuffle_files=True, shuffle_buffer=300,
            drop_remainder=True, seed=9)
        pipe.reader_threads = 3
        h = hashlib.sha256()
        for rows, m, n_ex in pipe.iter_superbatches(8):
            h.update(str(m).encode())
            h.update(str(n_ex).encode())
            h.update(rows["feat_ids"].tobytes())
            h.update(rows["feat_vals"].tobytes())
            h.update(rows["label"].tobytes())
        assert h.hexdigest()[:24] == self.GOLDEN[(8, 64, 0, True)]

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_fused_assembly_matches_golden_and_fallback(self, golden_files):
        """The r5 fused decode->assemble drain (native_assembly=True, the
        default) and the forced per-chunk scatter fallback must BOTH emit
        the pinned golden stream — the kill switch changes no bytes."""
        for (k, bs, skip, drop), want in self.GOLDEN.items():
            fused = self._emission_hash(golden_files, k, bs, skip, drop,
                                        native_assembly=True)
            fallback = self._emission_hash(golden_files, k, bs, skip, drop,
                                           native_assembly=False)
            assert fused == want, (
                f"fused emission changed for (k={k}, bs={bs}): {fused}")
            assert fallback == want, (
                f"fallback emission changed for (k={k}, bs={bs}): {fallback}")

    @pytest.mark.skipif(not pipeline._native_loader(),
                        reason="native decoder unavailable")
    def test_bad_record_parity_through_fused_path(self, golden_files,
                                                  monkeypatch):
        """A corrupt record under on_bad_record='skip' must produce the
        same emission through the fused drain as through the per-chunk
        fallback: the skip happens at the framing layer, BEFORE spans reach
        either assembly path, so both see the identical span stream.
        Shrink the chunk size so the file spans several read boundaries."""
        import hashlib
        import struct

        monkeypatch.setattr(pipeline, "_NATIVE_CHUNK_BYTES", 2048)
        # flip one data-CRC byte mid-file: framing intact, record bad
        path = golden_files[0]
        data = bytearray(open(path, "rb").read())
        pos = 0
        for _ in range(100):  # walk to the 101st frame
            (length,) = struct.unpack_from("<Q", data, pos)
            pos += 16 + length
        (length,) = struct.unpack_from("<Q", data, pos)
        data[pos + 12 + length] ^= 0xFF
        open(path, "wb").write(bytes(data))

        def emit(native_assembly):
            pipe = pipeline.CtrPipeline(
                golden_files, field_size=7, batch_size=64, num_epochs=1,
                shuffle=True, shuffle_files=True, shuffle_buffer=300,
                drop_remainder=True, seed=9, verify_crc=True,
                on_bad_record="skip", max_bad_records=5,
                native_assembly=native_assembly)
            h = hashlib.sha256()
            for rows, m, n_ex in pipe.iter_superbatches(4):
                h.update(str(m).encode())
                h.update(rows["feat_ids"].tobytes())
                h.update(rows["label"].tobytes())
            return h.hexdigest(), pipe.health.bad_records

        h_fused, bad_fused = emit(True)
        h_fall, bad_fall = emit(False)
        assert bad_fused == 1  # the corrupt record was actually hit
        assert bad_fall == 1
        assert h_fused == h_fall


class TestAssembleBatchDeque:
    """_assemble_batch runs on a deque (O(1) front pops); emission must be
    identical to the original list-shifting implementation."""

    @staticmethod
    def _reference(pend, bs):
        # The pre-deque list implementation, verbatim.
        take = []
        need = bs
        while need:
            labels, ids, vals = pend[0]
            if len(labels) <= need:
                take.append(pend.pop(0))
                need -= len(labels)
            else:
                take.append((labels[:need], ids[:need], vals[:need]))
                pend[0] = (labels[need:], ids[need:], vals[need:])
                need = 0
        labels = np.concatenate([t[0] for t in take])
        ids = np.concatenate([t[1] for t in take])
        vals = np.concatenate([t[2] for t in take])
        return {
            "feat_ids": np.ascontiguousarray(ids, np.int32),
            "feat_vals": np.ascontiguousarray(vals, np.float32),
            "label": labels.reshape(-1, 1).astype(np.float32),
        }

    def test_matches_list_reference(self):
        import collections
        rng = np.random.default_rng(3)
        chunks = []
        for _ in range(40):
            n = int(rng.integers(1, 97))
            chunks.append((
                rng.random(n).astype(np.float32),
                rng.integers(0, 1000, (n, 7)).astype(np.int32),
                rng.random((n, 7)).astype(np.float32)))
        total = sum(len(c[0]) for c in chunks)
        dq = collections.deque(chunks)
        ref = [tuple(c) for c in chunks]
        bs = 64
        emitted = 0
        while total - emitted >= bs:
            got = pipeline.CtrPipeline._assemble_batch(dq, bs)
            want = self._reference(ref, bs)
            for k in ("label", "feat_ids", "feat_vals"):
                np.testing.assert_array_equal(got[k], want[k])
            emitted += bs
        tail = total - emitted
        if tail:
            got = pipeline.CtrPipeline._assemble_batch(dq, tail)
            want = self._reference(ref, tail)
            for k in ("label", "feat_ids", "feat_vals"):
                np.testing.assert_array_equal(got[k], want[k])
        assert not dq and not ref
