"""Retrieval→ranking cascade: twin tower, candidate index, end-to-end serving.

The executable acceptance for the cascade tentpole (README "Retrieval→ranking
cascade"): a twin tower trained on click-gated synthetic histories, a
candidate index over its item matrix (brute recall == 1.0 by construction —
measured anyway; ANN recall@50 >= 0.95, stamped into the artifact), and a
``CascadeEngine`` serving retrieve→rank over a published artifact through at
least one atomic hot swap with zero failures. Empty-history requests must be
finite end-to-end (the masked-softmax / l2-normalize NaN regressions).
"""

import json
import os

import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.data import libsvm, pipeline
from deepfm_tpu.models.twin_tower import TwinTower, train_twin_tower
from deepfm_tpu.rec.cascade import (
    ITEM_SLOT, TOWERS_CONFIG_FILE, TOWERS_FILE, CascadeEngine,
    _fit_history, cascade_extra_export, export_cascade, load_towers,
    save_towers)
from deepfm_tpu.rec.index import (
    INDEX_FILE, INDEX_META_FILE, CandidateIndex)
from deepfm_tpu.utils import export as export_lib

FEATURE_SIZE = 120
FIELD_SIZE = 5
HIST_LEN = 6
BATCH = 32


def _cfg(**kw):
    base = dict(
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=BATCH,
        compute_dtype="float32", mesh_data=1, log_steps=0, seed=3,
        scale_lr_by_world=False, model="din", history_max_len=HIST_LEN)
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def hist_batches(tmp_path_factory):
    """Pipeline batches over click-gated synthetic history data."""
    data_dir = tmp_path_factory.mktemp("cascade_data")
    files = libsvm.generate_synthetic_ctr(
        str(data_dir), num_files=1, examples_per_file=256,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, seed=7,
        history=HIST_LEN)
    p = pipeline.CtrPipeline(
        files, field_size=FIELD_SIZE, batch_size=BATCH, num_epochs=1,
        shuffle=False, prefetch_batches=0, history=True,
        history_max_len=HIST_LEN)
    batches = list(p)
    assert batches and all("hist_ids" in b for b in batches)
    return batches


@pytest.fixture(scope="module")
def towers(hist_batches):
    """(model, params, stats) — twin tower fit on the history batches."""
    return train_twin_tower(_cfg(), hist_batches, item_slot=ITEM_SLOT)


# ---------------------------------------------------------------------------
# Twin tower
# ---------------------------------------------------------------------------

class TestTwinTower:
    def test_training_converges_finite(self, towers):
        _, _, stats = towers
        assert np.isfinite(stats["loss"]), stats
        assert stats["positive_rows"] > 0, stats
        assert stats["steps"] == 256 // BATCH

    def test_embeddings_unit_norm(self, towers):
        model, params, _ = towers
        rng = np.random.default_rng(0)
        ids = rng.integers(1, FEATURE_SIZE, (8, HIST_LEN)).astype(np.int32)
        mask = np.ones((8, HIST_LEN), np.float32)
        u = np.asarray(model.user_embed(params, ids, mask))
        v = np.asarray(model.item_embed(
            params, np.arange(8, dtype=np.int32)))
        np.testing.assert_allclose(np.linalg.norm(u, axis=1), 1.0, atol=1e-5)
        np.testing.assert_allclose(np.linalg.norm(v, axis=1), 1.0, atol=1e-5)

    def test_empty_history_embeds_finite(self, towers):
        """All-masked history pools zeros; the tower must stay finite (the
        l2-normalize NaN-gradient regression, forward flavor)."""
        model, params, _ = towers
        u = np.asarray(model.user_embed(
            params, np.zeros((2, HIST_LEN), np.int32),
            np.zeros((2, HIST_LEN), np.float32)))
        assert np.all(np.isfinite(u))

    def test_loss_gradient_finite_with_empty_history_rows(self, towers):
        """The backward flavor: a zero-weighted empty-history row must not
        poison the batch gradient with NaN."""
        import jax
        import jax.numpy as jnp
        model, params, _ = towers
        hist_ids = np.zeros((4, HIST_LEN), np.int32)
        hist_mask = np.zeros((4, HIST_LEN), np.float32)
        hist_ids[:2] = np.arange(1, HIST_LEN + 1)
        hist_mask[:2] = 1.0                      # rows 2,3: empty history
        items = np.arange(4, dtype=np.int32)
        weights = np.array([1, 1, 0, 0], np.float32)
        grads = jax.grad(model.loss)(
            params, jnp.asarray(hist_ids), jnp.asarray(hist_mask),
            jnp.asarray(items), jnp.asarray(weights))
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.all(jnp.isfinite(g))) for g in leaves)

    def test_requires_history_batches(self):
        with pytest.raises(ValueError, match="history batches"):
            train_twin_tower(_cfg(), [{
                "label": np.zeros((4, 1), np.float32),
                "feat_ids": np.zeros((4, FIELD_SIZE), np.int32),
                "feat_vals": np.zeros((4, FIELD_SIZE), np.float32)}])

    def test_towers_save_load_roundtrip(self, towers, tmp_path):
        model, params, _ = towers
        save_towers(params, _cfg(), str(tmp_path))
        model2, params2 = load_towers(str(tmp_path))
        ids = np.arange(16, dtype=np.int32)
        np.testing.assert_array_equal(
            np.asarray(model.item_embed(params, ids)),
            np.asarray(model2.item_embed(params2, ids)))


# ---------------------------------------------------------------------------
# Candidate index
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def item_matrix(towers):
    model, params, _ = towers
    vecs = model.all_item_embeddings(params, FEATURE_SIZE)
    assert vecs.shape == (FEATURE_SIZE, model.dim)
    return vecs


@pytest.fixture(scope="module")
def user_queries(towers, hist_batches):
    model, params, _ = towers
    b = hist_batches[0]
    return np.asarray(model.user_embed(
        params, b["hist_ids"], b["hist_mask"]))


class TestCandidateIndex:
    def test_brute_recall_is_exactly_one(self, item_matrix, user_queries):
        idx = CandidateIndex(item_matrix, kind="brute")
        assert idx.recall_at_k(user_queries, 10) == 1.0
        assert idx.recall_at_k(user_queries, 50) == 1.0

    def test_ann_recall_meets_bar(self, item_matrix, user_queries):
        idx = CandidateIndex(item_matrix, kind="ann", seed=0)
        assert idx.recall_at_k(user_queries, 50) >= 0.95

    def test_brute_matches_numpy_argmax(self, item_matrix, user_queries):
        idx = CandidateIndex(item_matrix, kind="brute")
        ids, scores = idx.search(user_queries[:4], 5)
        ref = np.argsort(-(user_queries[:4] @ item_matrix.T), axis=1)[:, :5]
        np.testing.assert_array_equal(ids, ref)
        assert np.all(np.diff(scores, axis=1) <= 1e-6)  # best first

    def test_k_clamped_to_corpus(self, item_matrix, user_queries):
        idx = CandidateIndex(item_matrix, kind="brute")
        ids, _ = idx.search(user_queries[:1], 10 * FEATURE_SIZE)
        assert ids.shape == (1, FEATURE_SIZE)
        assert len(set(map(int, ids[0]))) == FEATURE_SIZE

    def test_custom_ids_mapping(self, item_matrix, user_queries):
        offset_ids = np.arange(FEATURE_SIZE) + 1000
        idx = CandidateIndex(item_matrix, kind="brute", ids=offset_ids)
        ids, _ = idx.search(user_queries[:2], 3)
        assert np.all(ids >= 1000)

    def test_save_load_search_identical(self, item_matrix, user_queries,
                                        tmp_path):
        idx = CandidateIndex(item_matrix, kind="ann", seed=0)
        r50 = idx.recall_at_k(user_queries, 50)
        meta = idx.save(str(tmp_path), extra_meta={"recall_at_50": r50})
        assert meta["recall_at_50"] == r50
        idx2, meta2 = CandidateIndex.load(str(tmp_path))
        assert meta2["recall_at_50"] == r50        # stamp survives the disk
        ids1, s1 = idx.search(user_queries, 10)
        ids2, s2 = idx2.search(user_queries, 10)
        np.testing.assert_array_equal(ids1, ids2)
        np.testing.assert_array_equal(s1, s2)

    def test_validation_errors(self, item_matrix):
        with pytest.raises(ValueError, match="brute\\|ann"):
            CandidateIndex(item_matrix, kind="faiss")
        with pytest.raises(ValueError, match="\\[V, D\\]"):
            CandidateIndex(item_matrix[0])
        idx = CandidateIndex(item_matrix)
        with pytest.raises(ValueError, match="query dim"):
            idx.search(np.zeros((1, idx.dim + 1), np.float32), 5)


# ---------------------------------------------------------------------------
# End-to-end cascade over a real published artifact + hot swap
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cascade_publish(tmp_path_factory, hist_batches, towers, item_matrix,
                    user_queries):
    """Publish dir with cascade version 1 live (DIN ranker + towers + ANN
    index with a measured recall stamp) and the trained pieces to publish
    more versions."""
    from deepfm_tpu.train import Trainer
    cfg = _cfg()
    trainer = Trainer(cfg)
    state = trainer.init_state()
    step_fn = trainer._make_train_step()
    for b in hist_batches:
        state, _ = step_fn(state, trainer.put_batch(b))
    _, tower_params, _ = towers
    index = CandidateIndex(item_matrix, kind="ann", seed=0)
    r50 = index.recall_at_k(user_queries, 50)
    publish_dir = str(tmp_path_factory.mktemp("cascade_pub"))
    orig = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None  # ~10s/version
    try:
        export_cascade(
            trainer.model, state, cfg, os.path.join(publish_dir, "1"),
            tower_params=tower_params, index=index,
            index_meta={"recall_at_50": r50})
        export_lib.write_latest(publish_dir, "1")
        yield {"dir": publish_dir, "trainer": trainer, "state": state,
               "cfg": cfg, "tower_params": tower_params, "index": index,
               "recall_at_50": r50}
    finally:
        export_lib._export_tf_savedmodel = orig


@pytest.fixture(scope="module")
def engine(cascade_publish):
    eng = CascadeEngine(
        cascade_publish["dir"], retrieve_k=20, max_batch=BATCH,
        max_delay_ms=1.0, watcher_kw={"poll_secs": 3600, "start": False})
    try:
        yield eng
    finally:
        eng.close()


class TestCascadeArtifact:
    def test_marker_certifies_all_three_stages(self, cascade_publish):
        v1 = os.path.join(cascade_publish["dir"], "1")
        for name in (export_lib.COMPLETE_MARKER, TOWERS_FILE,
                     TOWERS_CONFIG_FILE, INDEX_FILE, INDEX_META_FILE,
                     "model_config.json"):
            assert os.path.exists(os.path.join(v1, name)), name

    def test_recall_stamp_in_artifact(self, cascade_publish):
        with open(os.path.join(cascade_publish["dir"], "1",
                               INDEX_META_FILE)) as f:
            meta = json.load(f)
        assert meta["kind"] == "ann"
        assert meta["recall_at_50"] == cascade_publish["recall_at_50"]
        assert meta["recall_at_50"] >= 0.95

    def test_signature_is_packed_columns(self, cascade_publish):
        with open(os.path.join(cascade_publish["dir"], "1",
                               "model_config.json")) as f:
            meta = json.load(f)
        assert meta["history_len"] == HIST_LEN
        assert meta["signature"]["inputs"]["feat_ids"][1] \
            == FIELD_SIZE + HIST_LEN


class TestCascadeServing:
    def _request(self, seed=0, hist_rows=4):
        rng = np.random.default_rng(seed)
        hist_ids = rng.integers(
            1, FEATURE_SIZE, (HIST_LEN,)).astype(np.int32)
        hist_mask = np.zeros((HIST_LEN,), np.float32)
        hist_mask[:hist_rows] = 1.0
        feat_ids = rng.integers(
            0, FEATURE_SIZE, (FIELD_SIZE,)).astype(np.int32)
        feat_vals = rng.normal(size=(FIELD_SIZE,)).astype(np.float32)
        return hist_ids, hist_mask, feat_ids, feat_vals

    def test_recommend_end_to_end(self, engine):
        hist_ids, hist_mask, feat_ids, feat_vals = self._request(seed=1)
        items, probs = engine.recommend(
            hist_ids, hist_mask, feat_ids, feat_vals, k=10)
        assert items.shape == (10,) and probs.shape == (10,)
        assert len(set(map(int, items))) == 10          # distinct candidates
        assert np.all(np.isfinite(probs))
        assert np.all((probs >= 0) & (probs <= 1))
        assert np.all(np.diff(probs) <= 0)              # ranker-sorted

    def test_empty_history_finite_end_to_end(self, engine):
        """The cascade's empty-history contract: user tower pools zeros,
        DIN attention contributes exact zeros — finite everywhere."""
        _, _, feat_ids, feat_vals = self._request(seed=2)
        items, probs = engine.recommend(
            np.zeros((HIST_LEN,), np.int32),
            np.zeros((HIST_LEN,), np.float32), feat_ids, feat_vals, k=5)
        assert np.all(np.isfinite(probs))
        assert np.all((probs >= 0) & (probs <= 1))

    def test_retrieve_stage_shapes(self, engine):
        hist_ids, hist_mask, _, _ = self._request(seed=3)
        ids, scores = engine.retrieve(hist_ids, hist_mask, k=7)
        assert ids.shape == (1, 7) and scores.shape == (1, 7)

    def test_rank_consistent_with_direct_ranker(self, engine,
                                                cascade_publish):
        """recommend()'s probabilities are the ranker's own, not a proxy:
        rebuild one candidate row by hand and compare."""
        hist_ids, hist_mask, feat_ids, feat_vals = self._request(seed=4)
        items, probs = engine.recommend(
            hist_ids, hist_mask, feat_ids, feat_vals, k=3)
        model = engine.current()
        row_ids = feat_ids.copy()
        row_ids[ITEM_SLOT] = items[0]
        h_ids, h_mask = _fit_history(hist_ids, hist_mask, model.hist_len)
        packed_ids = np.concatenate([row_ids, h_ids])[None]
        packed_vals = np.concatenate([feat_vals, h_mask])[None]
        direct = np.asarray(model(packed_ids, packed_vals)).reshape(-1)
        np.testing.assert_allclose(probs[0], direct[0], rtol=1e-5)

    def test_context_width_validated(self, engine):
        hist_ids, hist_mask, _, _ = self._request()
        with pytest.raises(ValueError, match="context fields"):
            engine.recommend(hist_ids, hist_mask,
                             np.zeros((FIELD_SIZE + 1,), np.int32),
                             np.zeros((FIELD_SIZE + 1,), np.float32))

    def test_hot_swap_is_atomic_and_prewarmed(self, engine, cascade_publish):
        """Publish version 2, drive one poll: ranker + towers + index all
        move in ONE swap, buckets prewarmed off-thread, zero failures,
        serving uninterrupted."""
        assert engine.watcher.swap_count == 1
        prewarmed_v1 = engine.watcher.prewarmed_buckets
        assert prewarmed_v1 > 0                  # satellite (a): warm before
        before = engine.current()

        pub = cascade_publish
        export_cascade(
            pub["trainer"].model, pub["state"], pub["cfg"],
            os.path.join(pub["dir"], "2"),
            tower_params=pub["tower_params"], index=pub["index"],
            index_meta={"recall_at_50": pub["recall_at_50"]})
        export_lib.write_latest(pub["dir"], "2")
        assert engine.watcher.check_once()

        after = engine.current()
        assert engine.watcher.swap_count == 2
        assert engine.watcher.swap_failures == 0
        assert after is not before
        assert after.path.endswith("2")
        # the composite moved together: new towers + new index objects
        assert after.index is not before.index
        assert after.tower_params is not before.tower_params
        assert engine.watcher.prewarmed_buckets > prewarmed_v1

        hist_ids, hist_mask, feat_ids, feat_vals = self._request(seed=5)
        items, probs = engine.recommend(
            hist_ids, hist_mask, feat_ids, feat_vals, k=10)
        assert np.all(np.isfinite(probs))
        assert engine.stats.summary()["serving_failed"] == 0

    def test_incomplete_artifact_defers_swap(self, engine, cascade_publish):
        """A marker-less version 3 must NOT swap in (and must not take the
        engine down) — LATEST stays serviceable on the previous version."""
        pub = cascade_publish
        v3 = os.path.join(pub["dir"], "3")
        os.makedirs(v3, exist_ok=True)           # torn artifact: no marker
        export_lib.write_latest(pub["dir"], "3")
        failures_before = engine.watcher.swap_failures
        try:
            assert not engine.watcher.check_once()
            assert engine.watcher.swap_failures == failures_before + 1
            assert engine.current().path.endswith("2")
            hist_ids, hist_mask, feat_ids, feat_vals = self._request(seed=6)
            _, probs = engine.recommend(
                hist_ids, hist_mask, feat_ids, feat_vals, k=4)
            assert np.all(np.isfinite(probs))
        finally:
            export_lib.write_latest(pub["dir"], "2")
            engine.watcher.check_once()


class TestPublisherIntegration:
    def test_extra_export_hook_ships_retrieval_stage(self, cascade_publish,
                                                     tmp_path):
        """The Publisher path: ``cascade_extra_export`` stamps towers +
        index into the staging dir BEFORE the marker lands, so the one
        marker certifies the whole cascade."""
        from deepfm_tpu.train.publish import Publisher
        pub = cascade_publish
        pdir = str(tmp_path / "pub")
        orig = export_lib._export_tf_savedmodel
        export_lib._export_tf_savedmodel = lambda *a, **k: None
        try:
            publisher = Publisher(
                pub["trainer"].model, pub["cfg"], pdir,
                extra_export=cascade_extra_export(
                    pub["cfg"], pub["tower_params"], pub["index"],
                    index_meta={"recall_at_50": pub["recall_at_50"]}))
            publisher.publish_now(pub["state"], 7)
            assert publisher.drain(timeout=120)
            publisher.close()
        finally:
            export_lib._export_tf_savedmodel = orig
        assert publisher.published == [7]
        v7 = os.path.join(pdir, "7")
        for name in (export_lib.COMPLETE_MARKER, TOWERS_FILE, INDEX_FILE,
                     INDEX_META_FILE):
            assert os.path.exists(os.path.join(v7, name)), name
        assert export_lib.read_latest(pdir) == v7
        # the published artifact is a complete, loadable cascade
        eng = CascadeEngine(pdir, retrieve_k=8, max_batch=BATCH,
                            watcher_kw={"poll_secs": 3600, "start": False})
        try:
            rng = np.random.default_rng(9)
            items, probs = eng.recommend(
                rng.integers(1, FEATURE_SIZE, (HIST_LEN,)).astype(np.int32),
                np.ones((HIST_LEN,), np.float32),
                rng.integers(0, FEATURE_SIZE,
                             (FIELD_SIZE,)).astype(np.int32),
                rng.normal(size=(FIELD_SIZE,)).astype(np.float32), k=4)
            assert np.all(np.isfinite(probs))
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Fused device-side cascade program (serving fast path)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def brute_publish(tmp_path_factory, cascade_publish, item_matrix):
    """A second publish dir whose version 1 carries a BRUTE index — the
    fusable kind — over the same trained ranker and towers."""
    pub = cascade_publish
    index = CandidateIndex(item_matrix, kind="brute")
    publish_dir = str(tmp_path_factory.mktemp("cascade_pub_brute"))
    orig = export_lib._export_tf_savedmodel
    export_lib._export_tf_savedmodel = lambda *a, **k: None
    try:
        export_cascade(
            pub["trainer"].model, pub["state"], pub["cfg"],
            os.path.join(publish_dir, "1"),
            tower_params=pub["tower_params"], index=index)
        export_lib.write_latest(publish_dir, "1")
    finally:
        export_lib._export_tf_savedmodel = orig
    return publish_dir


class TestFusedCascade:
    def _request(self, seed=0, hist_rows=4):
        rng = np.random.default_rng(seed)
        hist_ids = rng.integers(
            1, FEATURE_SIZE, (HIST_LEN,)).astype(np.int32)
        hist_mask = np.zeros((HIST_LEN,), np.float32)
        hist_mask[:hist_rows] = 1.0
        feat_ids = rng.integers(
            0, FEATURE_SIZE, (FIELD_SIZE,)).astype(np.int32)
        feat_vals = rng.normal(size=(FIELD_SIZE,)).astype(np.float32)
        return hist_ids, hist_mask, feat_ids, feat_vals

    @pytest.fixture()
    def engines(self, brute_publish):
        staged = CascadeEngine(
            brute_publish, retrieve_k=16, max_batch=BATCH,
            max_delay_ms=1.0, watcher_kw={"poll_secs": 3600, "start": False})
        fused = CascadeEngine(
            brute_publish, retrieve_k=16, max_batch=BATCH,
            max_delay_ms=1.0, fused=True,
            watcher_kw={"poll_secs": 3600, "start": False})
        try:
            yield staged, fused
        finally:
            staged.close()
            fused.close()

    def test_artifact_exposes_traceable_ranker(self, engines):
        staged, fused = engines
        model = fused.current()
        assert getattr(model.rank_fn, "raw_call", None) is not None
        assert model.supports_fused

    def test_fused_matches_staged_bit_identical(self, engines):
        """The acceptance pin: the fused single-program path returns the
        SAME items with BIT-IDENTICAL ranker probabilities as the staged
        user_embed -> search -> substitute -> rank -> argsort path."""
        staged, fused = engines
        for seed in (1, 2, 3):
            req = self._request(seed=seed)
            s_items, s_probs = staged.recommend(*req, k=8)
            f_items, f_probs = fused.recommend(*req, k=8)
            np.testing.assert_array_equal(f_items, s_items)
            np.testing.assert_array_equal(f_probs, s_probs)
        assert fused.fused_calls >= 3
        assert staged.fused_calls == 0

    def test_fused_empty_history_finite(self, engines):
        _, fused = engines
        _, _, feat_ids, feat_vals = self._request(seed=7)
        items, probs = fused.recommend(
            np.zeros((HIST_LEN,), np.int32),
            np.zeros((HIST_LEN,), np.float32), feat_ids, feat_vals, k=5)
        assert np.all(np.isfinite(probs))
        assert np.all((probs >= 0) & (probs <= 1))

    def test_recommend_batch_matches_per_row(self, engines):
        staged, fused = engines
        reqs = [self._request(seed=s) for s in (11, 12, 13)]
        h_ids = np.stack([r[0] for r in reqs])
        h_mask = np.stack([r[1] for r in reqs])
        f_ids = np.stack([r[2] for r in reqs])
        f_vals = np.stack([r[3] for r in reqs])
        b_items, b_probs = fused.recommend_batch(
            h_ids, h_mask, f_ids, f_vals, k=6)
        assert b_items.shape == (3, 6) and b_probs.shape == (3, 6)
        for i, req in enumerate(reqs):
            items, probs = staged.recommend(*req, k=6)
            np.testing.assert_array_equal(b_items[i], items)
            # Batched dispatch changes XLA's row vectorization — float-ULP
            # agreement, not bit (the B=1 fused path IS bit-equal, pinned
            # above).
            np.testing.assert_allclose(b_probs[i], probs, rtol=1e-5)

    def test_fused_compile_cache_is_bucketed(self, engines):
        """pow2 compile discipline: batches 1 and 3 share no key with each
        other (bucket 1 vs 4) but batch 3 and 4 share one program."""
        _, fused = engines
        model = fused.current()
        before = len(model._fused_cache)
        reqs = [self._request(seed=s) for s in (21, 22, 23, 24)]
        h_ids = np.stack([r[0] for r in reqs])
        h_mask = np.stack([r[1] for r in reqs])
        f_ids = np.stack([r[2] for r in reqs])
        f_vals = np.stack([r[3] for r in reqs])
        fused.recommend_batch(h_ids[:3], h_mask[:3], f_ids[:3], f_vals[:3],
                              k=4)
        n_after_3 = len(model._fused_cache)
        fused.recommend_batch(h_ids, h_mask, f_ids, f_vals, k=4)
        assert len(model._fused_cache) == n_after_3  # 3 and 4 share bucket 4
        assert n_after_3 <= before + 1

    def test_ann_index_gates_to_staged(self, cascade_publish):
        """fused=True over an ANN artifact serves via the staged path (the
        host-side partition scan cannot be traced) — no error, no fused
        dispatch."""
        eng = CascadeEngine(
            cascade_publish["dir"], retrieve_k=8, max_batch=BATCH,
            fused=True, watcher_kw={"poll_secs": 3600, "start": False})
        try:
            assert not eng.current().supports_fused
            req = self._request(seed=31)
            items, probs = eng.recommend(*req, k=4)
            assert np.all(np.isfinite(probs))
            assert eng.fused_calls == 0
        finally:
            eng.close()


class TestNoHistoryCascade:
    def test_history_free_artifact_serves_end_to_end(
            self, tmp_path_factory, towers, item_matrix):
        """Satellite pin: a ranker exported WITHOUT history columns
        (hist_len == 0) serves the full cascade — no history fitting, no
        zero-length scratch concat, finite output on both the staged and
        fused paths."""
        from deepfm_tpu.train import Trainer
        cfg = _cfg(model="deepfm", history_max_len=0)
        trainer = Trainer(cfg)
        state = trainer.init_state()
        _, tower_params, _ = towers
        index = CandidateIndex(item_matrix, kind="brute")
        publish_dir = str(tmp_path_factory.mktemp("cascade_pub_nohist"))
        orig = export_lib._export_tf_savedmodel
        export_lib._export_tf_savedmodel = lambda *a, **k: None
        try:
            export_cascade(
                trainer.model, state, cfg,
                os.path.join(publish_dir, "1"),
                tower_params=tower_params, index=index)
            export_lib.write_latest(publish_dir, "1")
        finally:
            export_lib._export_tf_savedmodel = orig
        rng = np.random.default_rng(5)
        hist_ids = rng.integers(1, FEATURE_SIZE, (HIST_LEN,)).astype(np.int32)
        hist_mask = np.ones((HIST_LEN,), np.float32)
        feat_ids = rng.integers(0, FEATURE_SIZE,
                                (FIELD_SIZE,)).astype(np.int32)
        feat_vals = rng.normal(size=(FIELD_SIZE,)).astype(np.float32)
        for fused in (False, True):
            eng = CascadeEngine(
                publish_dir, retrieve_k=8, max_batch=BATCH, fused=fused,
                watcher_kw={"poll_secs": 3600, "start": False})
            try:
                assert eng.current().hist_len == 0
                items, probs = eng.recommend(
                    hist_ids, hist_mask, feat_ids, feat_vals, k=4)
                assert items.shape == (4,) and probs.shape == (4,)
                assert np.all(np.isfinite(probs))
            finally:
                eng.close()


class TestFitHistory:
    def test_zero_hist_len_short_circuits(self):
        ids, mask = _fit_history(np.array([3, 4], np.int32),
                                 np.array([1, 1], np.float32), 0)
        assert ids.shape == (0,) and mask.shape == (0,)
        assert ids.dtype == np.int32 and mask.dtype == np.float32

    def test_pad_short_history(self):
        ids, mask = _fit_history(np.array([3, 4], np.int32),
                                 np.array([1, 1], np.float32), 5)
        np.testing.assert_array_equal(ids, [3, 4, 0, 0, 0])
        np.testing.assert_array_equal(mask, [1, 1, 0, 0, 0])

    def test_truncate_keeps_recent_tail(self):
        ids, mask = _fit_history(
            np.arange(1, 7, dtype=np.int32), np.ones((6,), np.float32), 4)
        np.testing.assert_array_equal(ids, [3, 4, 5, 6])
        np.testing.assert_array_equal(mask, [1, 1, 1, 1])

    def test_exact_length_passthrough(self):
        src = np.array([9, 8, 7], np.int32)
        ids, mask = _fit_history(src, np.ones((3,), np.float32), 3)
        np.testing.assert_array_equal(ids, src)
