"""Fault-injection harness tests: FlakyFS determinism, ResilientStream
healing, fileio op retries, checkpoint-save hardening, prefetch-error
attribution. CPU-only, zero real sleeps (zero-backoff policies throughout).
"""

import io
import os

import numpy as np
import pytest

from deepfm_tpu.data import fileio, pipeline
from deepfm_tpu.utils import checkpoint as ckpt_lib
from deepfm_tpu.utils import faults
from deepfm_tpu.utils import retry as retry_lib

pytestmark = pytest.mark.faults

NO_SLEEP = retry_lib.RetryPolicy(base_delay=0.0, max_delay=0.0)


@pytest.fixture
def no_sleep_fileio():
    """Zero out backoff sleeps on the module-level fileio policy."""
    prev = fileio.set_retry_policy(NO_SLEEP)
    try:
        yield
    finally:
        fileio.set_retry_policy(prev)


@pytest.fixture
def datafile(tmp_path):
    path = str(tmp_path / "blob.bin")
    payload = bytes(range(256)) * 64  # 16 KiB, position-identifying bytes
    with open(path, "wb") as f:
        f.write(payload)
    return path, payload


class TestFlakyFSDeterminism:
    def test_same_plan_same_fault_sequence(self, datafile, no_sleep_fileio):
        path, payload = datafile

        def run():
            events = []
            with faults.FlakyFS(read_fail_every=3) as fs:
                s = fileio.open_resilient(
                    path, policy=NO_SLEEP,
                    on_retry=lambda e, n: events.append(str(e)))
                try:
                    data = s.read(-1)
                finally:
                    s.close()
            return data, events, fs.injected_read_faults

        d1, e1, n1 = run()
        d2, e2, n2 = run()
        assert d1 == d2 == payload
        assert e1 == e2 and n1 == n2 > 0

    def test_faults_fire_once_each(self, datafile, no_sleep_fileio):
        path, payload = datafile
        with faults.FlakyFS(read_fail_offsets=[("blob.bin", 100),
                                               ("blob.bin", 9000)]) as fs:
            s = fileio.open_resilient(path, policy=NO_SLEEP)
            try:
                assert s.read(-1) == payload
            finally:
                s.close()
        assert fs.injected_read_faults == 2

    def test_injector_removed_on_exit(self, datafile, no_sleep_fileio):
        path, payload = datafile
        with faults.FlakyFS(read_fail_every=1):
            pass
        with fileio.open_stream(path) as f:  # no injection after __exit__
            assert f.read() == payload


class TestResilientStream:
    def test_heals_with_seek_reposition(self, datafile, no_sleep_fileio):
        path, payload = datafile
        with faults.FlakyFS(read_fail_every=2) as fs:
            s = fileio.open_resilient(path, policy=NO_SLEEP)
            try:
                chunks = [s.read(1000) for _ in range(17)]
            finally:
                s.close()
        assert b"".join(chunks) == payload  # no loss, no duplication
        assert s.reopen_count == fs.injected_read_faults > 0

    def test_heals_without_seek(self, datafile, no_sleep_fileio):
        """Object-store streams often cannot seek: reposition falls back to
        reopen + read-and-discard to the last good offset."""
        path, payload = datafile
        with faults.FlakyFS(read_fail_every=5, hide_seek=True) as fs:
            s = fileio.open_resilient(path, policy=NO_SLEEP)
            try:
                chunks = [s.read(1000) for _ in range(17)]
            finally:
                s.close()
        assert b"".join(chunks) == payload
        assert fs.injected_read_faults > 0

    def test_offset_tracks_delivered_bytes(self, datafile, no_sleep_fileio):
        path, payload = datafile
        s = fileio.open_resilient(path, policy=NO_SLEEP)
        try:
            assert s.read(100) == payload[:100]
            assert s.tell() == 100
            assert s.read(0) == b""
            assert s.tell() == 100
            s.read(-1)
            assert s.tell() == len(payload)
        finally:
            s.close()

    def test_exact_fill_reads(self, datafile, no_sleep_fileio):
        """read(n) returns exactly n bytes except at EOF — the framers rely
        on this, and it keeps clean-path reads byte-identical to plain
        file reads (golden emission hashes)."""
        path, payload = datafile

        class ShortReads(io.RawIOBase):
            def __init__(self, inner):
                super().__init__()
                self._inner = inner

            def readable(self):
                return True

            def read(self, n=-1):
                if n is None or n < 0:
                    return self._inner.read(-1)
                return self._inner.read(min(n, 7))  # dribble 7 bytes max

        s = fileio.ResilientStream(
            path, opener=lambda: ShortReads(open(path, "rb")),
            policy=NO_SLEEP)
        try:
            got = s.read(1000)
        finally:
            s.close()
        assert got == payload[:1000]

    def test_permanent_failure_raises_with_op_name(self, datafile,
                                                   no_sleep_fileio):
        path, _ = datafile
        with faults.FlakyFS(read_fail_every=1):  # every read fails
            s = fileio.open_resilient(
                path, policy=NO_SLEEP.with_(max_attempts=3))
            with pytest.raises(IOError, match="failed after 3 attempts"):
                s.read(10)
            s.close()

    def test_fatal_error_not_retried(self, tmp_path, no_sleep_fileio):
        s = fileio.ResilientStream(str(tmp_path / "nope.bin"),
                                   policy=NO_SLEEP)
        with pytest.raises(FileNotFoundError):
            s.read(1)
        s.close()
        assert s.reopen_count == 0


class TestFileioOpFaults:
    def test_metadata_ops_heal(self, tmp_path, no_sleep_fileio):
        path = str(tmp_path / "a.txt")
        open(path, "w").write("x")
        with faults.FlakyFS(op_failures={"glob": 2, "exists": 1,
                                         "size": 1, "open": 1}) as fs:
            assert fileio.glob(str(tmp_path / "*.txt")) == [path]
            assert fileio.exists(path)
            assert fileio.size(path) == 1
            with fileio.open_stream(path, "rb") as f:
                assert f.read() == b"x"
        assert fs.injected_op_faults == 5

    def test_op_faults_beyond_budget_raise(self, tmp_path, no_sleep_fileio):
        prev = fileio.set_retry_policy(NO_SLEEP.with_(max_attempts=2))
        try:
            with faults.FlakyFS(op_failures={"glob": 10}):
                with pytest.raises(IOError, match="glob.*failed after"):
                    fileio.glob(str(tmp_path / "*"))
        finally:
            fileio.set_retry_policy(prev)


def _state(step=0):
    return {"w": np.arange(8, dtype=np.float32) + step,
            "b": np.full((1,), step, dtype=np.float32)}


class TestCheckpointHardening:
    def test_transient_save_failure_defers(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         max_save_failures=3)
        try:
            with faults.FlakyFS(save_failures=1) as fs:
                assert mgr.save(1, _state(1)) is False  # injected, tolerated
                assert mgr.save(2, _state(2)) is True   # next interval lands
            assert fs.injected_save_faults == 1
            assert mgr.save_failures == 1
            assert mgr.latest_step() == 2
            restored = mgr.restore(_state())
            np.testing.assert_array_equal(restored["w"], _state(2)["w"])
        finally:
            mgr.close()

    def test_consecutive_failures_abort(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         max_save_failures=1)
        try:
            with faults.FlakyFS(save_failures=5):
                assert mgr.save(1, _state(1)) is False
                with pytest.raises(IOError, match="2 consecutive"):
                    mgr.save(2, _state(2))
        finally:
            mgr.close()

    def test_success_resets_consecutive_count(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         max_save_failures=1)
        try:
            with faults.FlakyFS(save_failures=1):
                assert mgr.save(1, _state(1)) is False
            assert mgr.save(2, _state(2)) is True
            with faults.FlakyFS(save_failures=1):
                assert mgr.save(3, _state(3)) is False  # tolerated again
            assert mgr.save_failures == 2
        finally:
            mgr.close()

    def test_zero_tolerance_aborts_on_first_failure(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         max_save_failures=0)
        try:
            with faults.FlakyFS(save_failures=1):
                with pytest.raises(IOError, match="1 consecutive"):
                    mgr.save(1, _state(1))
        finally:
            mgr.close()

    def test_forced_save_always_hard_fails(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False,
                                         max_save_failures=99)
        try:
            with faults.FlakyFS(save_failures=1):
                with pytest.raises(faults.InjectedFault):
                    mgr.save(1, _state(1), force=True)
        finally:
            mgr.close()

    def test_saved_steps_pruned(self, tmp_path):
        """Satellite: the session dedup set must not grow one int per save
        for the lifetime of a weeks-long run."""
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False, max_to_keep=2)
        try:
            for step in range(1, 21):
                assert mgr.save(step, _state(step)) is True
            assert len(mgr._saved_steps) <= max(2, 8)
            # dedup still works for the steps that remain tracked
            assert mgr.save(20, _state(20)) is False
        finally:
            mgr.close()


class TestPrefetchErrorAttribution:
    def test_producer_exception_carries_thread_note(self):
        def boom():
            yield {"a": 1}
            raise IOError("disk on fire")

        it = pipeline._prefetch(boom(), depth=2)
        assert next(it) == {"a": 1}
        with pytest.raises(IOError, match="disk on fire") as ei:
            next(it)
        notes = getattr(ei.value, "__notes__", [])
        assert any("pipeline-prefetch" in n for n in notes)
        assert any("not a trainer fault" in n for n in notes)
        # `raise item from None` severs the misleading queue-internals chain
        assert ei.value.__suppress_context__


@pytest.mark.slow
def test_fault_drill_end_to_end(tmp_path):
    """The full acceptance drill (clean-vs-faulty param parity, raise-policy
    error text, checkpoint-save hardening + resume). Slow: several short
    training runs; excluded from tier-1, run via scripts/fault_drill.py."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import fault_drill
    summary = fault_drill.run_drill(str(tmp_path), verbose=False)
    assert summary["bad_records"] > 0
    assert summary["read_faults_injected"] > 0
