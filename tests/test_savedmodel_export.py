"""TF SavedModel serving-artifact parity: the reference's export target is a
SavedModel with signature {feat_ids: int64[None,F], feat_vals: f32[None,F]}
-> {prob} (``1-ps-cpu/...py:458-467``). The export now emits that exact
artifact via jax2tf alongside the StableHLO one; a TF-Serving deployment (or
tf.saved_model.load) consumes it directly and must agree with the JAX path.
"""

import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.train import Trainer
from deepfm_tpu.utils import export as export_lib


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    cfg = Config(
        feature_size=120, field_size=5, embedding_size=4, deep_layers="8",
        dropout="1.0", batch_size=32, compute_dtype="float32",
        mesh_data=1, log_steps=0, seed=7)
    trainer = Trainer(cfg)
    state = trainer.init_state()
    out = str(tmp_path_factory.mktemp("sv") / "1")
    export_lib.export_serving(trainer.model, state, cfg, out)
    return out


def test_savedmodel_exists_and_matches_jax(artifact):
    tf = pytest.importorskip("tensorflow")
    sm_dir = f"{artifact}/saved_model"
    loaded = tf.saved_model.load(sm_dir)
    sig = loaded.signatures["serving_default"]

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 120, (16, 5))
    vals = rng.normal(size=(16, 5)).astype(np.float32)

    tf_probs = sig(feat_ids=tf.constant(ids, tf.int64),
                   feat_vals=tf.constant(vals))["prob"].numpy()

    jax_serve = export_lib.load_serving(artifact)
    jax_probs = jax_serve(ids.astype(np.int32), vals)

    assert tf_probs.shape == (16,)
    np.testing.assert_allclose(tf_probs, jax_probs, rtol=1e-5, atol=1e-6)


def test_params_only_fallback_matches(artifact, tmp_path):
    """Deleting serving_fn.stablehlo degrades load_serving to the
    rebuild-from-config path with identical outputs (the artifact the
    export writes when platform lowering fails)."""
    import os
    import shutil
    if not os.path.exists(os.path.join(artifact, "serving_fn.stablehlo")):
        pytest.skip("artifact is already params-only on this platform")
    degraded = str(tmp_path / "degraded")
    shutil.copytree(artifact, degraded)
    os.remove(os.path.join(degraded, "serving_fn.stablehlo"))

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 120, (8, 5)).astype(np.int32)
    vals = rng.normal(size=(8, 5)).astype(np.float32)
    full = export_lib.load_serving(artifact)(ids, vals)
    fb = export_lib.load_serving(degraded)(ids, vals)
    np.testing.assert_allclose(full, fb, rtol=1e-5, atol=1e-6)


def test_savedmodel_batch_polymorphic(artifact):
    tf = pytest.importorskip("tensorflow")
    loaded = tf.saved_model.load(f"{artifact}/saved_model")
    sig = loaded.signatures["serving_default"]
    for b in (1, 7, 64):
        out = sig(feat_ids=tf.zeros((b, 5), tf.int64),
                  feat_vals=tf.zeros((b, 5), tf.float32))["prob"]
        assert out.shape == (b,)
