"""Atomic hot-publish tests: crash-at-any-stage atomicity (the `LATEST`
pointer always resolves to a complete previous artifact), deterministic
publish cadence across resume, never-backwards pointer, skip-on-busy
accounting, wedged-publish watchdog, completion-marker enforcement in
``load_serving``, and the hot-swap watcher."""

import json
import os
import threading

import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.train import Trainer
from deepfm_tpu.train.publish import Publisher
from deepfm_tpu.utils import export as export_lib
from deepfm_tpu.utils import faults as faults_lib

FIELD_SIZE = 5


@pytest.fixture(scope="module")
def tiny():
    cfg = Config(
        feature_size=64, field_size=FIELD_SIZE, embedding_size=4,
        deep_layers="8", dropout="1.0", batch_size=16,
        compute_dtype="float32", mesh_data=1, log_steps=0, seed=11)
    trainer = Trainer(cfg)
    return cfg, trainer, trainer.init_state()


@pytest.fixture(autouse=True)
def _skip_tf_savedmodel(monkeypatch):
    # The TF SavedModel write dominates export time (~10s+); the atomicity
    # machinery under test here is independent of which files are staged.
    monkeypatch.setattr(export_lib, "_export_tf_savedmodel",
                        lambda *a, **k: None)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _publisher(tiny, publish_dir, **kw):
    cfg, trainer, _ = tiny
    kw.setdefault("every_steps", 4)
    return Publisher(trainer.model, cfg, str(publish_dir), **kw)


def _stub_jobs(pub):
    """Replace the artifact write with a pure marker of which steps ran —
    cadence/bookkeeping tests don't need real exports."""
    done = []
    pub._do_publish = lambda params, mstate, step: done.append(step) or "ok"
    return done


class TestAtomicity:
    def test_publish_roundtrip(self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path)
        pub.publish_now(state, 2)
        pub.close()
        artifact = export_lib.read_latest(str(tmp_path))
        assert artifact is not None and os.path.basename(artifact) == "2"
        serve = export_lib.load_serving(artifact)
        probs = serve(np.zeros((3, FIELD_SIZE), np.int32),
                      np.ones((3, FIELD_SIZE), np.float32))
        assert probs.shape == (3,) and np.all(np.isfinite(probs))
        assert pub.stats()["published_versions"] == [2]

    def test_crash_before_rename_keeps_previous_artifact(self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path)
        pub.publish_now(state, 1)
        assert pub.drain()
        faults_lib.set_publish_crash("before_rename")
        pub.publish_now(state, 2)
        assert pub.drain()
        # The torn publish is invisible: no final dir, pointer unmoved.
        assert not os.path.isdir(tmp_path / "2")
        latest = export_lib.read_latest(str(tmp_path))
        assert latest is not None and os.path.basename(latest) == "1"
        assert export_lib.load_serving(latest) is not None
        assert pub.publish_failures == 1
        # Retry at the next cadence succeeds and moves the pointer.
        pub.publish_now(state, 3)
        pub.close()
        assert os.path.basename(export_lib.read_latest(str(tmp_path))) == "3"
        assert pub.stats()["published_versions"] == [1, 3]

    def test_crash_between_rename_and_latest_heals_on_retry(
            self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path)
        pub.publish_now(state, 1)
        assert pub.drain()
        faults_lib.set_publish_crash("after_rename_before_latest")
        pub.publish_now(state, 4)
        assert pub.drain()
        # The artifact is fully visible and complete, only the pointer is
        # stale — a reader following LATEST still gets artifact 1.
        assert export_lib.load_serving(str(tmp_path / "4")) is not None
        assert os.path.basename(export_lib.read_latest(str(tmp_path))) == "1"
        assert pub.publish_failures == 1
        # The idempotent republish of the same step skips the export but
        # still advances the pointer.
        pub.publish_now(state, 4)
        pub.close()
        assert os.path.basename(export_lib.read_latest(str(tmp_path))) == "4"

    def test_latest_never_regresses(self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path)
        pub.publish_now(state, 10)
        assert pub.drain()
        # A resumed run republishing an older cadence step must not point
        # serving back in time.
        pub.publish_now(state, 5)
        pub.close()
        assert export_lib.load_serving(str(tmp_path / "5")) is not None
        assert os.path.basename(export_lib.read_latest(str(tmp_path))) == "10"


class TestCadence:
    def test_boundary_crossing_steps(self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path, every_steps=4)
        done = _stub_jobs(pub)
        for step in range(1, 13):
            pub.maybe_publish(state, step)
            pub.drain()
        pub.close()
        assert done == [4, 8, 12]
        assert pub.stats()["published_versions"] == [4, 8, 12]

    def test_seed_cadence_matches_fresh_run(self, tiny, tmp_path):
        # A run restored at step 5 must publish at 8 — the boundary a fresh
        # run would cross — not "restore step + 1".
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path, every_steps=4)
        done = _stub_jobs(pub)
        pub.seed_cadence(5)
        for step in range(6, 10):
            pub.maybe_publish(state, step)
            pub.drain()
        pub.close()
        assert done == [8]

    def test_time_cadence(self, tiny, tmp_path):
        _, _, state = tiny
        clock = FakeClock()
        pub = _publisher(tiny, tmp_path, every_steps=0, every_secs=10.0,
                         clock=clock)
        done = _stub_jobs(pub)
        pub.maybe_publish(state, 1)
        clock.t = 11.0
        pub.maybe_publish(state, 2)
        pub.drain()
        clock.t = 15.0
        pub.maybe_publish(state, 3)  # only 4s since last publish
        pub.close()
        assert done == [2]

    def test_busy_cadence_skipped_not_queued(self, tiny, tmp_path):
        _, _, state = tiny
        pub = _publisher(tiny, tmp_path, every_steps=4)
        gate = threading.Event()
        started = threading.Event()
        pub._do_publish = (
            lambda p, m, s: (started.set(), gate.wait(30), "ok")[-1])
        assert pub.maybe_publish(state, 4)
        assert started.wait(30)
        assert not pub.maybe_publish(state, 8)  # in flight: dropped
        assert pub.skipped_inflight == 1
        gate.set()
        pub.drain()
        assert pub.maybe_publish(state, 12)
        pub.close()
        assert pub.stats()["published_versions"] == [4, 12]
        assert pub.stats()["publish_skipped_inflight"] == 1


class TestWatchdog:
    def test_wedged_publish_trips_abort(self, tiny, tmp_path):
        _, _, state = tiny
        clock = FakeClock()
        aborts = []
        pub = _publisher(tiny, tmp_path, timeout_s=5.0, clock=clock,
                         abort=aborts.append)
        gate = threading.Event()
        pub._do_publish = lambda p, m, s: gate.wait(30)
        pub.publish_now(state, 4)
        clock.t = 4.0
        pub.check_wedged()
        assert not aborts
        clock.t = 6.0
        pub.check_wedged()
        assert len(aborts) == 1 and "publish of step 4" in aborts[0]
        gate.set()
        pub.close()


class TestMarkerEnforcement:
    def test_truncated_artifact_refused(self, tiny, tmp_path):
        # Regression: an artifact dir missing its completion marker (crashed
        # export) must fail with the typed error, not a restore traceback.
        cfg, trainer, state = tiny
        artifact = str(tmp_path / "1")
        export_lib.export_serving(trainer.model, state, cfg, artifact)
        export_lib.load_serving(artifact)  # complete: loads fine
        os.remove(os.path.join(artifact, export_lib.COMPLETE_MARKER))
        with pytest.raises(export_lib.ArtifactIncomplete):
            export_lib.load_serving(artifact)

    def test_empty_dir_refused(self, tmp_path):
        with pytest.raises(export_lib.ArtifactIncomplete):
            export_lib.load_serving(str(tmp_path))

    def test_read_latest_dangling_pointer(self, tmp_path):
        assert export_lib.read_latest(str(tmp_path)) is None
        export_lib.write_latest(str(tmp_path), "7")
        assert export_lib.read_latest(str(tmp_path)) is None  # dir absent
        os.makedirs(tmp_path / "7")
        assert export_lib.read_latest(str(tmp_path)) == str(tmp_path / "7")


class TestLatestWatcher:
    def _fake_artifact(self, publish_dir, version):
        os.makedirs(os.path.join(publish_dir, version))
        export_lib.write_latest(publish_dir, version)

    def test_hot_swap_follows_latest(self, tmp_path):
        pub_dir = str(tmp_path)
        loads = []

        def loader(path):
            loads.append(path)
            return lambda ids, vals: os.path.basename(path)

        self._fake_artifact(pub_dir, "1")
        w = export_lib.watch_latest(pub_dir, loader=loader, start=False)
        assert w.swap_count == 1 and w(None, None) == "1"
        assert not w.check_once()  # pointer unmoved: no reload
        self._fake_artifact(pub_dir, "2")
        assert w.check_once()
        assert w.swap_count == 2 and w(None, None) == "2"
        assert loads == [os.path.join(pub_dir, "1"),
                         os.path.join(pub_dir, "2")]
        w.close()

    def test_failed_load_keeps_current_model(self, tmp_path):
        pub_dir = str(tmp_path)

        def loader(path):
            if path.endswith("13"):
                raise export_lib.ArtifactIncomplete(path)
            return lambda ids, vals: os.path.basename(path)

        self._fake_artifact(pub_dir, "1")
        w = export_lib.watch_latest(pub_dir, loader=loader, start=False)
        self._fake_artifact(pub_dir, "13")  # racing an in-flight publish
        assert not w.check_once()
        assert w(None, None) == "1" and w.swap_count == 1
        w.close()

    def test_no_artifact_yet_raises(self, tmp_path):
        w = export_lib.watch_latest(str(tmp_path), start=False)
        with pytest.raises(RuntimeError, match="no artifact published"):
            w(None, None)
        w.close()


class TestMarkerStep:
    def test_marker_records_step(self, tiny, tmp_path):
        cfg, trainer, state = tiny
        pub = _publisher(tiny, tmp_path)
        pub.publish_now(state, 6)
        pub.close()
        with open(tmp_path / "6" / export_lib.COMPLETE_MARKER) as f:
            assert json.load(f)["step"] == 6
