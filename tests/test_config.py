"""Config-system tests: flag surface, CLI parity, validation."""

import json

import pytest

from deepfm_tpu.config import Config, parse_args


def test_defaults_match_reference_hparams():
    c = Config()
    # reference ipynb:82-90 / flag defaults
    assert c.feature_size == 117581
    assert c.field_size == 39
    assert c.embedding_size == 32
    assert c.batch_size == 1024
    assert c.learning_rate == 5e-4
    assert c.optimizer == "Adam"
    assert c.deep_layer_sizes == [128, 64, 32]


def test_cli_roundtrip():
    c = parse_args([
        "--task_type", "eval", "--batch_size", "64", "--batch_norm", "true",
        "--deep_layers", "32,16", "--model", "dcnv2", "--mesh_model", "2",
    ])
    assert c.task_type == "eval"
    assert c.batch_size == 64
    assert c.batch_norm is True
    assert c.deep_layer_sizes == [32, 16]
    assert c.model == "dcnv2"
    assert c.mesh_model == 2


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        Config(task_type="bogus")
    with pytest.raises(ValueError):
        Config(model="mlp")
    with pytest.raises(ValueError):
        Config(optimizer="lbfgs")
    with pytest.raises(ValueError):
        Config(batch_size=0)


def test_channels_json_and_csv():
    assert Config(channels='["eval", "train_0"]').channel_names == ["eval", "train_0"]
    assert Config(channels="eval,train_0").channel_names == ["eval", "train_0"]
    assert Config().channel_names == []


def test_serialization_roundtrip():
    c = Config(batch_size=128, model="widedeep")
    c2 = Config.from_dict(json.loads(c.to_json()))
    assert c2 == c
