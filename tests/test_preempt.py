"""Preemption-runtime tests: signal listener, exit-code contract, in-process
preempt->resume bit-identity, guard policies at the task level (skip /
rollback / abort via the NaN fault seam), tolerant resume sidecars,
checkpoint read-side retries, exception-safe manager exit, TrainHealth
counters in the result dict + TensorBoard, and the supervisor restart loop.
CPU-only; zero-backoff retry policies (no real sleeps)."""

import os
import signal
import sys
import time

import numpy as np
import pytest

import jax

from deepfm_tpu.config import Config
from deepfm_tpu.data import libsvm
from deepfm_tpu.train import Trainer, tasks
from deepfm_tpu.train import guard as guard_lib
from deepfm_tpu.utils import checkpoint as ckpt_lib
from deepfm_tpu.utils import faults
from deepfm_tpu.utils import preempt as preempt_lib
from deepfm_tpu.utils import retry as retry_lib

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
import supervise  # noqa: E402

pytestmark = pytest.mark.preempt

NO_SLEEP = retry_lib.RetryPolicy(base_delay=0.0, max_delay=0.0)

FEATURE_SIZE = 64
FIELD_SIZE = 5
BATCHES_PER_EPOCH = 6  # 2 files x 48 records / batch_size 16


class TestListener:
    def test_trigger_and_clear(self):
        lst = preempt_lib.PreemptionListener()
        assert not lst.triggered()
        lst.trigger("spot notice")
        assert lst.triggered() and lst.reason == "spot notice"
        lst.clear()
        assert not lst.triggered() and lst.reason == ""

    def test_real_signal_sets_flag(self):
        lst = preempt_lib.PreemptionListener(signals=(signal.SIGTERM,))
        with lst:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5.0
            while not lst.triggered() and time.time() < deadline:
                time.sleep(0.01)
            assert lst.triggered()
            assert lst.reason == f"signal {int(signal.SIGTERM)}"

    def test_uninstall_restores_prior_handler(self):
        prior = signal.getsignal(signal.SIGTERM)
        lst = preempt_lib.PreemptionListener(signals=(signal.SIGTERM,))
        lst.install()
        assert signal.getsignal(signal.SIGTERM) != prior
        lst.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prior

    def test_exit_code_contract(self):
        assert preempt_lib.EXIT_PREEMPTED == 42
        assert preempt_lib.EXIT_WATCHDOG == 43
        assert preempt_lib.RESTARTABLE_EXIT_CODES == {42, 43}
        # 0 (done) and 1 (crash) must never be restartable
        assert 0 not in preempt_lib.RESTARTABLE_EXIT_CODES
        assert 1 not in preempt_lib.RESTARTABLE_EXIT_CODES


class TestSupervisor:
    def _run(self, codes, **kw):
        seq = list(codes)
        sleeps = []
        rc = supervise.run_supervised(
            ["train"], spawn=lambda cmd: seq.pop(0),
            sleep=sleeps.append, log=lambda m: None, **kw)
        return rc, sleeps, seq

    def test_clean_exit_passes_through(self):
        rc, sleeps, _ = self._run([0])
        assert rc == 0 and sleeps == []

    def test_preemption_restarts_with_backoff(self):
        rc, sleeps, left = self._run([42, 43, 0], backoff_secs=1.0)
        assert rc == 0 and left == []
        assert sleeps == [1.0, 2.0]  # exponential per restart

    def test_ordinary_crash_not_retried(self):
        rc, sleeps, left = self._run([1, 0])
        assert rc == 1 and sleeps == [] and left == [0]

    def test_restart_budget_exhausted(self):
        rc, sleeps, _ = self._run([42] * 10, max_restarts=2,
                                  backoff_secs=0.5)
        assert rc == 42
        assert sleeps == [0.5, 1.0]  # two restarts, then give up

    def _run_timed(self, runs, **kw):
        """Each run is (duration_secs, exit_code); injectable clock ticks
        by the child's duration at each spawn."""
        seq = list(runs)
        t = [0.0]
        sleeps = []

        def spawn(cmd):
            secs, rc = seq.pop(0)
            t[0] += secs
            return rc

        rc = supervise.run_supervised(
            ["train"], spawn=spawn, sleep=sleeps.append,
            log=lambda m: None, clock=lambda: t[0], **kw)
        return rc, sleeps, seq

    def test_healthy_run_resets_restart_budget(self):
        # An online job preempted once a day must not exhaust a lifetime
        # budget sized for crash loops: 5 preemptions, each after a run
        # longer than healthy_secs, survive a max_restarts=2 budget.
        rc, sleeps, left = self._run_timed(
            [(100.0, 42)] * 5 + [(100.0, 0)],
            max_restarts=2, backoff_secs=1.0, healthy_secs=50.0)
        assert rc == 0 and left == []
        # The counter resets each time, so backoff never escalates.
        assert sleeps == [1.0] * 5

    def test_short_runs_still_exhaust_budget(self):
        rc, _, left = self._run_timed(
            [(1.0, 42)] * 10, max_restarts=2, backoff_secs=0.0,
            healthy_secs=50.0)
        assert rc == 42 and len(left) == 7  # 1 first run + 2 restarts

    def test_healthy_reset_disabled_by_default(self):
        rc, _, _ = self._run_timed(
            [(100.0, 42)] * 10, max_restarts=2, backoff_secs=0.0)
        assert rc == 42  # long runs don't help without --healthy_secs

    def test_crash_loop_after_healthy_run_still_bounded(self):
        # One healthy run resets the counter once; the subsequent crash
        # loop of short runs still hits the budget.
        rc, _, left = self._run_timed(
            [(1.0, 42), (1.0, 42), (100.0, 42)] + [(1.0, 42)] * 10,
            max_restarts=2, backoff_secs=0.0, healthy_secs=50.0)
        assert rc == 42 and len(left) == 8

    def test_exit_histogram_types_every_relaunch_reason(self):
        """The final summary line types WHY relaunches happened (42
        preemptions vs 43 watchdog aborts vs ordinary crashes), not just
        how many — and it is emitted on every exit path, including the
        non-restartable one."""
        logs = []
        seq = [42, 43, 1]
        rc = supervise.run_supervised(
            ["train"], spawn=lambda cmd: seq.pop(0),
            sleep=lambda s: None, log=logs.append)
        assert rc == 1 and seq == []
        hist = [m for m in logs if "exit histogram" in m]
        assert hist == ["[supervise] exit histogram: preempted(42)=1 "
                        "watchdog(43)=1 other=1; total restarts 2"]

    def test_exit_histogram_on_clean_and_exhausted_paths(self):
        logs = []
        rc = supervise.run_supervised(
            ["train"], spawn=lambda cmd: 0, sleep=lambda s: None,
            log=logs.append)
        assert rc == 0
        assert [m for m in logs if "exit histogram" in m] == [
            "[supervise] exit histogram: preempted(42)=0 watchdog(43)=0 "
            "other=0; total restarts 0"]
        logs = []
        seq = [42] * 3
        rc = supervise.run_supervised(
            ["train"], spawn=lambda cmd: seq.pop(0), max_restarts=2,
            sleep=lambda s: None, log=logs.append)
        assert rc == 42
        assert [m for m in logs if "exit histogram" in m] == [
            "[supervise] exit histogram: preempted(42)=3 watchdog(43)=0 "
            "other=0; total restarts 2"]

    def test_total_cap_breaks_healthy_crash_loop(self):
        # The pathological case --healthy_secs alone cannot bound: a child
        # that keeps limping past the healthy threshold and dying again
        # resets the window budget forever. The lifetime cap still stops it.
        rc, _, left = self._run_timed(
            [(100.0, 42)] * 10, max_restarts=2, backoff_secs=0.0,
            healthy_secs=50.0, max_total_restarts=4)
        assert rc == 42
        assert len(left) == 5   # 1 first run + 4 capped restarts

    def test_total_cap_alone_without_healthy_reset(self):
        # The cap is independent of the per-window budget: a huge
        # max_restarts doesn't get past it.
        rc, _, left = self._run([42] * 10, max_restarts=99,
                                backoff_secs=0.0, max_total_restarts=3)
        assert rc == 42 and len(left) == 6   # 1 first run + 3 restarts

    def test_total_cap_zero_is_unlimited(self):
        rc, _, left = self._run_timed(
            [(100.0, 42)] * 5 + [(100.0, 0)],
            max_restarts=2, backoff_secs=0.0, healthy_secs=50.0,
            max_total_restarts=0)
        assert rc == 0 and left == []

    def test_total_cap_not_hit_on_success(self):
        rc, _, left = self._run([42, 42, 0], max_restarts=5,
                                backoff_secs=0.0, max_total_restarts=2)
        assert rc == 0 and left == []


def _state(step=0):
    return {"w": np.arange(8, dtype=np.float32) + step,
            "b": np.full((1,), step, dtype=np.float32)}


class TestCheckpointReadRetries:
    def _mgr(self, tmp_path, **kw):
        return ckpt_lib.CheckpointManager(
            str(tmp_path / "c"), async_save=False,
            retry_policy=NO_SLEEP, **kw)

    def test_latest_step_heals_transient_fault(self, tmp_path):
        mgr = self._mgr(tmp_path)
        try:
            mgr.save(3, _state(3))
            original = mgr._mgr.latest_step
            calls = []

            def flaky():
                calls.append(1)
                if len(calls) == 1:
                    raise IOError("transient storage error")
                return original()

            mgr._mgr.latest_step = flaky
            try:
                assert mgr.latest_step() == 3
            finally:
                mgr._mgr.latest_step = original
            assert len(calls) == 2
        finally:
            mgr.close()

    def test_restore_heals_transient_fault(self, tmp_path):
        mgr = self._mgr(tmp_path)
        try:
            mgr.save(5, _state(5))
            original = mgr._mgr.restore
            calls = []

            def flaky(step, args=None):
                calls.append(1)
                if len(calls) == 1:
                    raise IOError("transient storage error")
                return original(step, args=args)

            mgr._mgr.restore = flaky
            try:
                restored = mgr.restore(_state())
            finally:
                mgr._mgr.restore = original
            assert len(calls) == 2
            np.testing.assert_array_equal(restored["w"], _state(5)["w"])
        finally:
            mgr.close()

    def test_shape_mismatch_not_retried(self, tmp_path):
        """ValueError is fatal (default_is_retryable): the shape-mismatch
        guidance must surface after ONE attempt, not a retry storm."""
        mgr = self._mgr(tmp_path)
        try:
            mgr.save(1, _state(1))
            calls = []
            original = mgr._mgr.restore

            def mismatch(step, args=None):
                calls.append(1)
                raise ValueError(
                    "shape (8,) not compatible with the stored shape (4,)")

            mgr._mgr.restore = mismatch
            try:
                with pytest.raises(RuntimeError,
                                   match="do not match this run's config"):
                    mgr.restore(_state())
            finally:
                mgr._mgr.restore = original
            assert len(calls) == 1
        finally:
            mgr.close()

    def test_permanent_read_failure_names_op(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(
            str(tmp_path / "c"), async_save=False,
            retry_policy=NO_SLEEP.with_(max_attempts=2))
        try:
            original = mgr._mgr.latest_step
            mgr._mgr.latest_step = lambda: (_ for _ in ()).throw(
                IOError("gone"))
            try:
                with pytest.raises(IOError, match="failed after 2 attempts"):
                    mgr.latest_step()
            finally:
                mgr._mgr.latest_step = original
        finally:
            mgr.close()


class TestCheckpointExitSafety:
    def test_exception_unwind_drains_async_save(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                            async_save=True) as mgr:
                mgr.save(1, _state(1), force=True)
                raise RuntimeError("boom")  # async save may be in flight
        with ckpt_lib.CheckpointManager(str(tmp_path / "c")) as mgr2:
            assert mgr2.latest_step() == 1  # the save became durable

    def test_close_failure_does_not_mask_original(self, tmp_path):
        mgr = ckpt_lib.CheckpointManager(str(tmp_path / "c"),
                                         async_save=False)
        original_close = mgr.close

        def bad_close():
            raise IOError("storage vanished during unwind")

        mgr.close = bad_close
        try:
            with pytest.raises(RuntimeError, match="boom"):
                with mgr:
                    raise RuntimeError("boom")
        finally:
            mgr.close = original_close
            mgr.close()


class TestResumeMetaTolerance:
    def test_corrupt_sidecar_returns_none_and_counts(self, tmp_path):
        d = str(tmp_path)
        with open(os.path.join(d, tasks._RESUME_META), "w") as f:
            f.write('{"step": 7, "epo')  # torn mid-json.dump write
        th = guard_lib.TrainHealth()
        assert tasks._read_resume_meta(d, health=th) is None
        assert th.resume_meta_corrupt == 1

    def test_valid_sidecar_reads_back(self, tmp_path):
        d = str(tmp_path)
        tasks._write_resume_meta(d, {"step": 7, "epoch": 1})
        th = guard_lib.TrainHealth()
        assert tasks._read_resume_meta(d, health=th) == {"step": 7,
                                                         "epoch": 1}
        assert th.resume_meta_corrupt == 0

    def test_missing_sidecar_is_clean(self, tmp_path):
        th = guard_lib.TrainHealth()
        assert tasks._read_resume_meta(str(tmp_path), health=th) is None
        assert th.resume_meta_corrupt == 0


# -- task-level integration ------------------------------------------------

@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    d = tmp_path_factory.mktemp("preempt")
    libsvm.generate_synthetic_ctr(
        str(d / "data"), num_files=2, examples_per_file=48,
        feature_size=FEATURE_SIZE, field_size=FIELD_SIZE, prefix="tr",
        seed=5)
    return d


def _cfg(workdir, model_dir, **kw):
    base = dict(
        task_type="train", data_dir=str(workdir / "data"),
        model_dir=model_dir, feature_size=FEATURE_SIZE,
        field_size=FIELD_SIZE, embedding_size=4, deep_layers="8",
        dropout="1.0", batch_size=16, num_epochs=2,
        compute_dtype="float32", mesh_data=1, log_steps=0,
        scale_lr_by_world=False, seed=17, verify_crc=True,
        io_retry_backoff_secs=0.0)
    base.update(kw)
    return Config(**base)


def _final_params(cfg):
    trainer = Trainer(cfg)
    with ckpt_lib.CheckpointManager(cfg.model_dir) as mgr:
        state = mgr.restore(trainer.init_state())
    return jax.tree.map(np.asarray, state.params), int(state.step)


def _assert_params_equal(a, b, what):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


@pytest.fixture(scope="module")
def baseline(workdir):
    """Uninterrupted 2-epoch run: the parity oracle for preempt-resume and
    rollback-replay (checkpoint cadence never changes the trajectory)."""
    cfg = _cfg(workdir, str(workdir / "ckpt_base"))
    res = tasks.run(cfg)
    params, step = _final_params(cfg)
    assert step == 2 * BATCHES_PER_EPOCH
    return params, step, res


@pytest.fixture(autouse=True)
def _clean_listener():
    """The process-wide listener flag must never leak between tests."""
    yield
    preempt_lib.get_listener().clear()


class TestPreemptResume:
    def test_injected_preemption_then_resume_is_bit_identical(
            self, workdir, baseline, monkeypatch):
        params_base, step_base, _ = baseline
        ckpt = str(workdir / "ckpt_preempted")
        cfg = _cfg(workdir, ckpt)

        # Phase 1: the injectable trigger fires mid-epoch; the task
        # force-saves and raises Preempted (the launcher maps it to 42).
        monkeypatch.setenv("DEEPFM_TPU_PREEMPT_AFTER_STEPS", "3")
        with pytest.raises(preempt_lib.Preempted) as ei:
            tasks.run(cfg)
        assert ei.value.step == 3
        _, step_saved = _final_params(cfg)
        assert step_saved == 3  # the forced preemption save landed
        meta = tasks._read_resume_meta(ckpt)
        assert meta["step"] == 3 and not meta["completed"]

        # Phase 2: restart (fresh listener state), resume to completion.
        monkeypatch.delenv("DEEPFM_TPU_PREEMPT_AFTER_STEPS")
        preempt_lib.get_listener().clear()
        res = tasks.run(cfg)
        assert res["preemptions"] == 0.0
        params, step = _final_params(cfg)
        assert step == step_base
        _assert_params_equal(params_base, params,
                             "preempt-resume vs uninterrupted baseline")

    def test_flag_set_before_training_preempts_at_first_dispatch(
            self, workdir):
        ckpt = str(workdir / "ckpt_early")
        listener = preempt_lib.get_listener()
        listener.trigger("notice during startup")
        with pytest.raises(preempt_lib.Preempted) as ei:
            tasks.run(_cfg(workdir, ckpt))
        assert ei.value.step == 1  # first dispatch finished, then exit


class _TBRecorder:
    calls = []

    def __init__(self, logdir):
        pass

    def scalars(self, step, **values):
        _TBRecorder.calls.append((step, values))

    def close(self):
        pass


class TestGuardPoliciesTaskLevel:
    def test_skip_counts_in_result_and_tensorboard(self, workdir,
                                                   monkeypatch):
        _TBRecorder.calls = []
        monkeypatch.setattr(tasks, "_TensorBoardWriter", _TBRecorder)
        faults.set_nan_plan([2])
        cfg = _cfg(workdir, str(workdir / "ckpt_skip"),
                   on_nonfinite="skip")
        res = tasks.run(cfg)
        assert res["nonfinite_skips"] == 1.0
        assert res["rollbacks"] == 0.0
        # the poisoned dispatch was consumed but not trained
        assert res["steps"] == 2 * BATCHES_PER_EPOCH - 1
        health_calls = [v for _, v in _TBRecorder.calls
                        if "health/nonfinite_skips" in v]
        assert health_calls and \
            health_calls[-1]["health/nonfinite_skips"] == 1.0

    def test_rollback_replays_from_checkpoint_bit_identically(
            self, workdir, baseline):
        params_base, step_base, _ = baseline
        # Checkpoints at steps 2 and 4; batch index 4 (dispatch 5) poisons.
        # Rollback restores step 4 and replays from the recorded offset —
        # with the plan consumed, the replayed batch is clean, so the final
        # params must match the uninterrupted baseline exactly.
        faults.set_nan_plan([4])
        cfg = _cfg(workdir, str(workdir / "ckpt_rollback"),
                   on_nonfinite="rollback", save_checkpoints_steps=2)
        res = tasks.run(cfg)
        assert res["rollbacks"] == 1.0
        assert res["steps"] == step_base
        params, step = _final_params(cfg)
        assert step == step_base
        _assert_params_equal(params_base, params,
                             "rollback-replay vs uninterrupted baseline")

    def test_rollback_without_checkpoint_aborts(self, workdir):
        faults.set_nan_plan([1])
        cfg = _cfg(workdir, "", on_nonfinite="rollback")
        with pytest.raises(guard_lib.NonFiniteError,
                           match="no checkpoint exists"):
            tasks.run(cfg)

    def test_abort_raises_with_step_number(self, workdir):
        faults.set_nan_plan([1])
        cfg = _cfg(workdir, str(workdir / "ckpt_abort"),
                   on_nonfinite="abort", log_steps=1)
        with pytest.raises(guard_lib.NonFiniteError, match="at step 2"):
            tasks.run(cfg)


class TestCorruptSidecarResume:
    def test_task_degrades_to_checkpoint_step_resume(self, workdir):
        ckpt = str(workdir / "ckpt_torn")
        cfg = _cfg(workdir, ckpt, num_epochs=1)
        tasks.run(cfg)
        with open(os.path.join(ckpt, tasks._RESUME_META), "w") as f:
            f.write('{"step": 6, "ep')  # torn write mid-preemption
        res = tasks.run(cfg)  # must not raise: sidecar-free resume
        assert res["resume_meta_corrupt"] >= 1.0
        # checkpoint-step-only fallback: the epoch replays (reference
        # behavior), training continues past the restored step
        assert res["steps"] == 2 * BATCHES_PER_EPOCH


@pytest.mark.slow
def test_preempt_drill_end_to_end(tmp_path):
    """The full acceptance drill (SIGTERM a live subprocess mid-epoch,
    supervised restart loop, bit-identity with the uninterrupted baseline,
    staged + device-resident paths). Slow: spawns several real launcher
    subprocesses; excluded from tier-1, run via scripts/preempt_drill.py."""
    import preempt_drill
    preempt_drill.run_drill(str(tmp_path), verbose=False)


class TestLaunchExitCode:
    def test_preempted_maps_to_exit_42(self, workdir, monkeypatch, capsys):
        from deepfm_tpu import launch

        def fake_run(cfg):
            raise preempt_lib.Preempted(7, "test")

        monkeypatch.setattr(tasks, "run", fake_run)
        rc = launch.main(["--task_type", "train",
                          "--data_dir", str(workdir / "data")])
        assert rc == preempt_lib.EXIT_PREEMPTED
        out = capsys.readouterr().out
        assert '"preempted": true' in out and '"step": 7' in out
