"""Embedding-plane Pallas kernels vs NumPy oracles (interpret mode), plus
the kernel-selection gates and the trainer's kill-switch parity contract.

The compiled kernels run only on TPU; the ``pallas``-marked tests exercise
the identical kernel bodies through the Pallas interpreter on CPU against
``ops.pallas_embedding.reference_plan_numpy`` / hand-rolled NumPy scatter
oracles. The parity tests pin the ``--embedding_kernels`` contract:

* ``auto`` vs ``xla``: bit-identical (same fused formulation, A/B legs
  are element-identical).
* hashed layout, ``off`` vs ``auto``: bit-identical (plan-path swap only
  — counting and sort builds emit identical plans, the select-writeback
  companions are stripped by the trainer).
* monolithic, ``off`` vs ``auto``: the fused vocab-space formulation.
  Gradients are bit-identical; lazy Adam's bias-correction tail rounds
  1-2 ULP apart between the row-space and table-sweep programs (XLA:CPU
  fuses the [U]- and [rows]-shaped chains differently), so the
  trajectory is pinned within a tight tolerance and the per-step losses
  are pinned bit-equal.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfm_tpu.config import Config
from deepfm_tpu.ops import embedding as emb_ops
from deepfm_tpu.ops import pallas_embedding as pemb
from deepfm_tpu.train import Trainer

pytestmark = []


def _ids(shape, rows, seed=0, oob=False):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, rows, shape).astype(np.int32)
    if oob:
        ids.reshape(-1)[:: 7] = rows  # the OOB fill id (masked positions)
    return ids


# ---------------------------------------------------------------------------
# Kernel 1: device-side plan build
# ---------------------------------------------------------------------------


@pytest.mark.pallas
@pytest.mark.parametrize("shape,rows,seed", [
    ((8, 3), 32, 0), ((16, 5), 64, 1), ((4, 4), 16, 2),
])
def test_plan_kernel_matches_numpy_oracle(shape, rows, seed):
    ids = _ids(shape, rows, seed)
    got = pemb.plan_build_pallas(jnp.asarray(ids), rows, interpret=True)
    uids, inv, touched, rank = pemb.reference_plan_numpy(ids, rows)
    np.testing.assert_array_equal(np.asarray(got.uids), uids)
    np.testing.assert_array_equal(np.asarray(got.inv), inv)
    np.testing.assert_array_equal(np.asarray(got.touched), touched)
    # rank is only defined under touched (oracle zeros elsewhere).
    np.testing.assert_array_equal(
        np.asarray(got.rank)[touched], rank[touched])


@pytest.mark.pallas
def test_plan_kernel_matches_xla_legs():
    """All three plan legs must emit bit-identical uids/inv (the plan is
    part of the numerics contract: rows order decides scatter order)."""
    ids = jnp.asarray(_ids((12, 4), 40, seed=3))
    k = pemb.plan_build_pallas(ids, 40, interpret=True)
    c = emb_ops.make_plan_counting(ids, 40)
    s = emb_ops.make_plan(ids, 40)
    for a, b in ((k, c), (k, s)):
        np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
        np.testing.assert_array_equal(np.asarray(a.inv), np.asarray(b.inv))


def test_plan_build_gates():
    """Leg selection: off => sort-based seed; oversized tables keep the
    sort build even under auto/xla (the counting pass scales with rows);
    CPU auto/xla => counting (no compiled pallas off-TPU)."""
    ids = jnp.asarray(_ids((4, 2), 8))
    assert pemb.plan_build(ids, 8, mode="off").touched is None
    assert pemb.plan_build(ids, 8, mode="auto").touched is not None
    assert pemb.resolve("auto", "plan", num_rows=8, n_ids=8) == "opt"
    big = pemb.PLAN_COUNT_MAX_ROWS + 1
    assert pemb.resolve("auto", "plan", num_rows=big, n_ids=8) == "ref"
    assert pemb.resolve("off", "plan", num_rows=8, n_ids=8) == "ref"
    with pytest.raises(ValueError, match="embedding_kernels"):
        pemb.resolve("bogus", "plan", num_rows=8, n_ids=8)
    assert not pemb.supported("plan", num_rows=8, n_ids=8)  # CPU backend


# ---------------------------------------------------------------------------
# Kernel 2: fused gather forward + segment-sum backward
# ---------------------------------------------------------------------------


@pytest.mark.pallas
@pytest.mark.parametrize("u,n,d,seed", [(6, 24, 4, 0), (17, 40, 8, 1)])
def test_take_kernel_forward_and_vjp_match_oracle(u, n, d, seed):
    rng = np.random.default_rng(seed)
    rows = rng.standard_normal((u, d)).astype(np.float32)
    inv = rng.integers(0, u, (n,)).astype(np.int32)
    g = rng.standard_normal((n, d)).astype(np.float32)

    out, vjp = jax.vjp(
        lambda r: pemb.take_rows_pallas(r, jnp.asarray(inv), interpret=True),
        jnp.asarray(rows))
    np.testing.assert_array_equal(np.asarray(out), rows[inv])
    (d_rows,) = vjp(jnp.asarray(g))
    oracle = np.zeros_like(rows)
    for p in range(n):  # same accumulation order as the kernel's fori_loop
        oracle[inv[p]] += g[p]
    np.testing.assert_allclose(np.asarray(d_rows), oracle, rtol=1e-6,
                               atol=1e-6)


def test_take_rows_xla_leg_is_jnp_take():
    rows = jnp.asarray(np.random.default_rng(0)
                       .standard_normal((5, 3)).astype(np.float32))
    inv = jnp.asarray(np.array([0, 4, 2, 2], np.int32))
    np.testing.assert_array_equal(
        np.asarray(pemb.take_rows(rows, inv, mode="auto")),
        np.asarray(jnp.take(rows, inv, axis=0)))


# ---------------------------------------------------------------------------
# Kernel 3: fused install/evict scatter
# ---------------------------------------------------------------------------


@pytest.mark.pallas
def test_install_kernel_matches_numpy_oracle():
    rng = np.random.default_rng(4)
    H, D, n, p = 12, 4, 5, 8
    w = rng.standard_normal((H, D)).astype(np.float32)
    m = rng.standard_normal((H, D)).astype(np.float32)
    v = rng.standard_normal((H, D)).astype(np.float32)
    tau = rng.integers(0, 9, (H,)).astype(np.int32)
    slots = np.full((p,), H, np.int32)           # pow2 pad: OOB dropped
    slots[:n] = rng.choice(H, n, replace=False)
    wv = np.zeros((p, D), np.float32)
    wv[:n] = rng.standard_normal((n, D))
    mv = np.zeros((p, D), np.float32)
    mv[:n] = rng.standard_normal((n, D))
    vv = np.zeros((p, D), np.float32)
    vv[:n] = rng.standard_normal((n, D))
    tv = np.zeros((p,), np.int32)
    tv[:n] = 11
    got = pemb.install_pallas(
        jnp.asarray(w), jnp.asarray(m), jnp.asarray(v), jnp.asarray(tau),
        jnp.asarray(slots), jnp.asarray(wv), jnp.asarray(mv),
        jnp.asarray(vv), jnp.asarray(tv), interpret=True)
    ew, em, ev, et = w.copy(), m.copy(), v.copy(), tau.copy()
    ew[slots[:n]] = wv[:n]
    em[slots[:n]] = mv[:n]
    ev[slots[:n]] = vv[:n]
    et[slots[:n]] = tv[:n]
    for a, b in zip(got, (ew, em, ev, et)):
        np.testing.assert_array_equal(np.asarray(a), b)


@pytest.mark.pallas
def test_install_xla_leg_matches_pallas_leg():
    rng = np.random.default_rng(5)
    H, D, p = 8, 3, 4
    args = (rng.standard_normal((H, D)).astype(np.float32),
            rng.standard_normal((H, D)).astype(np.float32),
            rng.standard_normal((H, D)).astype(np.float32),
            rng.integers(0, 5, (H,)).astype(np.int32))
    slots = np.array([1, 5, H, H], np.int32)
    vals = (rng.standard_normal((p, D)).astype(np.float32),
            rng.standard_normal((p, D)).astype(np.float32),
            rng.standard_normal((p, D)).astype(np.float32),
            rng.integers(0, 5, (p,)).astype(np.int32))
    jargs = tuple(jnp.asarray(a) for a in args)
    jvals = tuple(jnp.asarray(a) for a in vals)
    a = pemb.install_pallas(*jargs, jnp.asarray(slots), *jvals,
                            interpret=True)
    b = pemb._install_fused_xla(*jargs, jnp.asarray(slots), *jvals)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_install_rows_ref_leg_returns_none():
    z = jnp.zeros((4, 2), jnp.float32)
    t = jnp.zeros((4,), jnp.int32)
    s = jnp.zeros((2,), jnp.int32)
    zv = jnp.zeros((2, 2), jnp.float32)
    tv = jnp.zeros((2,), jnp.int32)
    assert pemb.install_rows(z, z, z, t, s, zv, zv, zv, tv,
                             mode="off") is None
    assert pemb.install_rows(z, z, z, t, s, zv, zv, zv, tv,
                             mode="xla") is not None


# ---------------------------------------------------------------------------
# Writeback legs: select-over-ids vs scatter must be element-identical
# ---------------------------------------------------------------------------


def test_select_writeback_matches_scatter_writeback():
    """The counting plan's touched/rank companions enable a select-based
    writeback; it must place exactly the same rows as the ids scatter.
    (The trainer still strips it — the vocab-shaped where perturbs the
    backward's fusion at ~1 ULP — but the leg itself is element-exact,
    recorded as a parity loss in EMBED_r02.json.)"""
    rng = np.random.default_rng(6)
    rows_n, d = 20, 3
    ids = jnp.asarray(_ids((6, 3), rows_n, seed=6))
    plan = emb_ops.make_plan_counting(ids, rows_n)
    assert plan.touched is not None and plan.rank is not None
    table = jnp.asarray(rng.standard_normal((rows_n, d)).astype(np.float32))
    new_rows = jnp.asarray(
        rng.standard_normal((int(plan.uids.shape[0]), d)).astype(np.float32))
    got_select = emb_ops.scatter_rows(table, plan, new_rows)
    stripped = plan._replace(touched=None, rank=None)
    got_scatter = emb_ops.scatter_rows(table, stripped, new_rows)
    np.testing.assert_array_equal(np.asarray(got_select),
                                  np.asarray(got_scatter))
    cnt = jnp.asarray(9, jnp.int32)
    tau = jnp.zeros((rows_n,), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(emb_ops.set_rows_scalar(tau, plan, cnt)),
        np.asarray(emb_ops.set_rows_scalar(tau, stripped, cnt)))


# ---------------------------------------------------------------------------
# Trainer kill-switch parity (the --embedding_kernels contract)
# ---------------------------------------------------------------------------


def _pcfg(**kw):
    base = dict(
        feature_size=120, field_size=7, embedding_size=4,
        deep_layers="8,4", dropout="1.0,1.0", batch_size=16,
        compute_dtype="float32", l2_reg=0.0, learning_rate=1e-3,
        log_steps=0, seed=0, scale_lr_by_world=False,
        mesh_data=1, mesh_model=1, steps_per_loop=1,
        embedding_update="sparse")
    base.update(kw)
    return Config(**base)


def _train(kernels, steps=4, l2=0.0, buckets=""):
    cfg = _pcfg(l2_reg=l2, embedding_kernels=kernels,
                embedding_buckets=buckets)
    tr = Trainer(cfg)
    state = tr.init_state()
    step = tr._make_train_step()
    rng = np.random.RandomState(11)
    losses = []
    for _ in range(steps):
        batch = {
            "feat_ids": rng.randint(0, 120, (16, 7)).astype(np.int32),
            "feat_vals": rng.rand(16, 7).astype(np.float32),
            "label": (rng.rand(16, 1) > 0.5).astype(np.float32),
        }
        state, m = step(state, tr.put_batch(batch))
        losses.append(np.asarray(m["loss"]))
    return state, losses


def _leaves(state):
    return ([np.asarray(x) for x in jax.tree.leaves(state.params)]
            + [np.asarray(x) for x in jax.tree.leaves(
                state.opt_state["embed"])])


@pytest.mark.embedding
def test_auto_vs_xla_bitexact():
    sa, la = _train("auto")
    sx, lx = _train("xla")
    for a, b in zip(_leaves(sa), _leaves(sx)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(la, lx):
        np.testing.assert_array_equal(a, b)


@pytest.mark.embedding
def test_hashed_off_vs_auto_bitexact():
    so, _ = _train("off", buckets="48,32")
    sa, _ = _train("auto", buckets="48,32")
    for a, b in zip(_leaves(so), _leaves(sa)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.embedding
def test_fused_vs_seed_trajectory_pinned():
    """Monolithic off-vs-auto: losses bit-equal every step, params within
    the pinned ULP band (the Adam-tail rounding — see module docstring)."""
    so, lo = _train("off", l2=1e-4)
    sa, la = _train("auto", l2=1e-4)
    for a, b in zip(lo, la):
        np.testing.assert_array_equal(a, b)  # losses: bit-equal
    for a, b in zip(_leaves(so), _leaves(sa)):
        if a.dtype == np.int32:  # tau touch stamps: exact
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)


@pytest.mark.embedding
def test_fused_grad_bitexact_vs_seed_plan_grad():
    """The fused formulation's per-table gradient (one vocab-space
    scatter-add over all names) must be BIT-identical to the seed plan
    path's segment-sums scattered to vocab space."""
    cfg = _pcfg(embedding_kernels="off")
    tr = Trainer(cfg)
    state = tr.init_state()
    emb = tr.model.emb
    rng = np.random.RandomState(12)
    batch = jax.device_put({
        "feat_ids": rng.randint(0, 120, (16, 7)).astype(np.int32),
        "feat_vals": rng.rand(16, 7).astype(np.float32),
        "label": (rng.rand(16, 1) > 0.5).astype(np.float32),
    })
    rngk = jax.random.fold_in(state.rng, state.step)
    tabs = {n: state.params[n] for n in tr._embed_names}
    rest0 = {k: v for k, v in state.params.items()
             if k not in tr._embed_names}

    @jax.jit
    def seed_grads(state, batch):
        plan = emb.sparse_plan(batch["feat_ids"])
        rows0 = {n: emb.gather_rows(state.params[n], plan)
                 for n in tr._embed_names}

        def loss_fn(rows):
            params = {**rest0, **tabs}
            logits, _ = tr.model.apply(
                params, state.model_state, batch["feat_ids"],
                batch["feat_vals"], train=True, rng=rngk, shard_axis=None,
                data_axis=None, emb_rows=rows, emb_plan=plan)
            return jnp.mean(tr._per_example_loss(
                logits, tr._batch_labels(batch)))

        g_rows = jax.grad(loss_fn)(rows0)
        out = {}
        for n in tr._embed_names:
            e = plan[emb.MONO]
            g = g_rows[n][emb.MONO]
            w = (jnp.arange(e.uids.shape[0]) < e.num_rows)
            w = w.reshape((-1,) + (1,) * (g.ndim - 1))
            out[n] = jnp.zeros_like(
                tabs[n], jnp.float32).at[e.uids].add(jnp.where(w, g, 0))
        return out

    @jax.jit
    def fused_grads(state, batch):
        ids = batch["feat_ids"]
        views0 = {n: jnp.take(tabs[n], ids, axis=0)
                  for n in tr._embed_names}

        def loss_fn(views):
            params = {**rest0, **tabs}
            logits, _ = tr.model.apply(
                params, state.model_state, batch["feat_ids"],
                batch["feat_vals"], train=True, rng=rngk, shard_axis=None,
                data_axis=None,
                emb_rows={n: {emb.MONO: views[n]} for n in tr._embed_names},
                emb_plan=None)
            return jnp.mean(tr._per_example_loss(
                logits, tr._batch_labels(batch)))

        g_views = jax.grad(loss_fn)(views0)
        gext = tr._fused_grad_ext(tabs, ids, g_views)
        out, o = {}, 1
        for n in tr._embed_names:
            d = 1 if tabs[n].ndim == 1 else tabs[n].shape[-1]
            out[n] = gext[:, o:o + d].reshape(tabs[n].shape)
            o += d
        return out

    gs = seed_grads(state, batch)
    gf = fused_grads(state, batch)
    for n in tr._embed_names:
        np.testing.assert_array_equal(np.asarray(gs[n]), np.asarray(gf[n]))


@pytest.mark.embedding
def test_fused_gates_off_for_hashed_and_oversized():
    cfg = _pcfg(embedding_kernels="auto", embedding_buckets="48,32")
    tr = Trainer(cfg)
    assert not tr._use_fused_backward()  # hashed: plan path
    cfg2 = _pcfg(embedding_kernels="off")
    tr2 = Trainer(cfg2)
    assert not tr2._use_fused_backward()  # kill switch
    tr3 = Trainer(cfg2.replace(embedding_kernels="auto"))
    assert tr3._use_fused_backward()
    big = jnp.zeros((pemb.PLAN_COUNT_MAX_ROWS + 64, 2), jnp.float32)
    assert not tr3._fused_tables_ok({"fm_v": big})
    assert tr3._fused_tables_ok(
        {n: tr3.init_state().params[n] for n in tr3._embed_names})
