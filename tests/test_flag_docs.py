"""Config flags and docs/MIGRATION.md must agree in BOTH directions — a new
flag without its migration row, or a migration row still advertising a
deleted flag, fails tier-1, not code review."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import check_flag_docs  # noqa: E402


def test_all_config_flags_documented():
    missing = check_flag_docs.missing_flags()
    assert missing == [], (
        f"Config fields missing from docs/MIGRATION.md: {missing} — "
        "add a row/paragraph for each (see scripts/check_flag_docs.py)")


def test_checker_detects_missing_flag():
    # The checker itself must not silently pass on an empty doc.
    missing = check_flag_docs.missing_flags(doc_text="nothing documented")
    assert "batch_size" in missing and "online_mode" in missing


def test_no_stale_flags_in_migration_doc():
    stale = check_flag_docs.stale_flags()
    assert stale == [], (
        f"docs/MIGRATION.md references deleted flags: {stale} — fix or drop "
        "the row (see scripts/check_flag_docs.py)")


def test_checker_detects_stale_flag():
    # A row advertising a flag Config no longer has must be caught.
    doc = "use `--batch_size` and `--definitely_deleted_flag` together"
    assert check_flag_docs.stale_flags(doc_text=doc) == [
        "definitely_deleted_flag"]


def test_stale_check_ignores_reference_names_and_tool_flags():
    # Old reference-repo names are backticked WITHOUT dashes — not stale —
    # and the converter tool's own CLI is allowlisted.
    doc = ("`training_data_dir` maps to `--data_dir`; "
           "converter: `--input a --output b --shards 4`; "
           "syntax is `--flag value`")
    assert check_flag_docs.stale_flags(doc_text=doc) == []
