"""Every ``Config`` field must be documented in docs/MIGRATION.md — a new
flag without its migration row fails tier-1, not code review."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import check_flag_docs  # noqa: E402


def test_all_config_flags_documented():
    missing = check_flag_docs.missing_flags()
    assert missing == [], (
        f"Config fields missing from docs/MIGRATION.md: {missing} — "
        "add a row/paragraph for each (see scripts/check_flag_docs.py)")


def test_checker_detects_missing_flag():
    # The checker itself must not silently pass on an empty doc.
    missing = check_flag_docs.missing_flags(doc_text="nothing documented")
    assert "batch_size" in missing and "online_mode" in missing
