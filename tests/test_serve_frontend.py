"""Multi-process serving frontend tests.

The ring protocol, demux, backpressure, crash-safe shutdown, and wedge
detection all run in-process over ``THREAD_CTX`` rings (deterministic,
sleep-free where possible); one slow-marked test spawns REAL client
processes against a real server thread — the zero→aha path of the
multi-process frontend.
"""

import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from deepfm_tpu.data.shm_ring import THREAD_CTX
from deepfm_tpu.serve import (FrontendServer, ServerOverloaded,
                              ServingClient, ServingEngine)
from deepfm_tpu.serve.frontend import client_main

pytestmark = pytest.mark.serving

FIELD_SIZE = 3


def _rows(n, base=0):
    ids = np.full((n, FIELD_SIZE), base, np.int32)
    vals = np.ones((n, FIELD_SIZE), np.float32)
    return ids, vals


def base_predict(feat_ids, feat_vals):
    return feat_ids[:, 0].astype(np.float32) + 0.5 * feat_vals[:, 0]


@pytest.fixture
def engine():
    eng = ServingEngine(base_predict, max_batch=8, max_delay_ms=2)
    yield eng
    eng.close(timeout=5)


def _serve_bg(srv):
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    return t


class TestFrontendInProcess:
    def test_two_clients_end_to_end(self, engine):
        srv = FrontendServer(engine, 2, field_size=FIELD_SIZE,
                             ctx=THREAD_CTX)
        t = _serve_bg(srv)
        try:
            with ServingClient(srv.handle(0)) as c0, \
                    ServingClient(srv.handle(1)) as c1:
                p0 = c0.predict(*_rows(4, base=10), timeout=10)
                p1 = c1.predict(*_rows(2, base=20), timeout=10)
                np.testing.assert_array_equal(p0, np.full(4, 10.5, np.float32))
                np.testing.assert_array_equal(p1, np.full(2, 20.5, np.float32))
            t.join(timeout=10)          # both byes -> server exits
            assert not t.is_alive()
            assert srv.responses_sent == 2 and srv.errors_sent == 0
        finally:
            srv.stop()
            srv.close()

    def test_pipelined_requests_demux_by_req_id(self, engine):
        srv = FrontendServer(engine, 1, field_size=FIELD_SIZE,
                             ctx=THREAD_CTX)
        t = _serve_bg(srv)
        try:
            with ServingClient(srv.handle(0)) as c:
                r1 = c.submit(*_rows(1, base=1), timeout=5)
                r2 = c.submit(*_rows(2, base=2), timeout=5)
                r3 = c.submit(*_rows(3, base=3), timeout=5)
                # Collect out of submission order: demux must hold r2/r3
                # aside while r1's probs come back, and vice versa.
                np.testing.assert_array_equal(
                    c.recv(r3, timeout=10), np.full(3, 3.5, np.float32))
                np.testing.assert_array_equal(
                    c.recv(r1, timeout=10), np.full(1, 1.5, np.float32))
                np.testing.assert_array_equal(
                    c.recv(r2, timeout=10), np.full(2, 2.5, np.float32))
        finally:
            srv.stop()
            t.join(timeout=10)
            srv.close()

    def test_engine_overload_comes_back_typed(self):
        # start=False engine: nothing drains, so the queue bound trips and
        # the server must forward the typed rejection over the ring.
        eng = ServingEngine(base_predict, max_batch=2, queue_rows=2,
                            start=False)
        srv = FrontendServer(eng, 1, field_size=FIELD_SIZE, ctx=THREAD_CTX)
        t = _serve_bg(srv)
        try:
            with ServingClient(srv.handle(0)) as c:
                r1 = c.submit(*_rows(2), timeout=5)   # fills the queue
                r2 = c.submit(*_rows(1), timeout=5)   # over the bound
                with pytest.raises(ServerOverloaded, match="queue full"):
                    c.recv(r2, timeout=10)
                assert srv.errors_sent == 1
                eng.start()                           # drain r1 normally
                assert c.recv(r1, timeout=10).shape == (2,)
        finally:
            srv.stop()
            t.join(timeout=10)
            srv.close()
            eng.close(timeout=5)

    def test_request_ring_full_is_typed(self, engine):
        srv = FrontendServer(engine, 1, field_size=FIELD_SIZE,
                             ctx=THREAD_CTX, capacity=2)
        # Server NOT running: the ring's 2 slots fill, then acquire times
        # out and submit must raise the typed error, not hang.
        c = ServingClient(srv.handle(0))
        try:
            c.submit(*_rows(1), timeout=0)
            c.submit(*_rows(1), timeout=0)
            with pytest.raises(ServerOverloaded, match="request ring full"):
                c.submit(*_rows(1), timeout=0)
        finally:
            c.close()
            srv.close()

    def test_client_validates_shapes(self, engine):
        srv = FrontendServer(engine, 1, field_size=FIELD_SIZE,
                             ctx=THREAD_CTX)
        c = ServingClient(srv.handle(0))
        try:
            with pytest.raises(ValueError, match="feat_ids/feat_vals"):
                c.submit(np.zeros((2, 9), np.int32),
                         np.zeros((2, 9), np.float32))
            with pytest.raises(ValueError, match="outside 1"):
                c.submit(*_rows(srv.max_rows + 1))
        finally:
            c.close()
            srv.close()

    def test_dead_client_without_farewell_is_retired(self, engine):
        """A client that dies mid-conversation (no ``bye``) must not wedge
        the server: once its response ring backs up and the liveness probe
        says gone, its responses are dropped and the loop moves on."""
        alive = {"flag": True}
        srv = FrontendServer(
            engine, 1, field_size=FIELD_SIZE, ctx=THREAD_CTX, capacity=2,
            client_alive=lambda cid: alive["flag"])
        t = _serve_bg(srv)
        try:
            c = ServingClient(srv.handle(0))
            # Three pipelined requests, never read: responses 1+2 fill the
            # ring, response 3 blocks -> probe -> retire.
            for _ in range(3):
                c.submit(*_rows(1), timeout=5)
            deadline = time.monotonic() + 10
            while srv.responses_sent < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            alive["flag"] = False          # the "process" dies
            t.join(timeout=10)             # server retires it and exits
            assert not t.is_alive()
            assert srv.dropped_dead_client >= 1
        finally:
            srv.stop()
            srv.close()

    def test_wedged_predict_trips_watchdog(self):
        """A predict that never returns stops the beat stream; the watchdog
        aborts with the exit-43 contract (injected abort here)."""
        release = threading.Event()

        def wedged(ids, vals):
            release.wait(30)
            return base_predict(ids, vals)

        eng = ServingEngine(wedged, max_batch=4, max_delay_ms=1)
        fired = []
        srv = FrontendServer(
            eng, 1, field_size=FIELD_SIZE, ctx=THREAD_CTX, timeout_s=0.3,
            abort=lambda dump: (fired.append(dump), srv.stop()))
        t = _serve_bg(srv)
        try:
            c = ServingClient(srv.handle(0))
            c.submit(*_rows(1), timeout=5)
            t.join(timeout=15)
            assert not t.is_alive(), "watchdog never fired"
            assert fired and "serving-frontend" in fired[0]
        finally:
            release.set()
            srv.stop()
            srv.close()
            eng.close(timeout=5)

    def test_idle_server_does_not_false_trip(self, engine):
        """No traffic is not a wedge: the loop beats while idle, so a quiet
        server survives many timeout windows."""
        fired = []
        srv = FrontendServer(
            engine, 1, field_size=FIELD_SIZE, ctx=THREAD_CTX, timeout_s=0.2,
            abort=lambda dump: fired.append(dump))
        t = _serve_bg(srv)
        try:
            time.sleep(0.7)                # several timeout windows of idle
            assert not fired
            with ServingClient(srv.handle(0)) as c:
                assert c.predict(*_rows(2), timeout=10).shape == (2,)
        finally:
            srv.stop()
            t.join(timeout=10)
            srv.close()


@pytest.mark.slow
class TestRealProcesses:
    def test_spawned_clients_round_trip(self):
        """The production shape: spawn-context client PROCESSES against the
        device-owning server, zero failures."""
        ctx = mp.get_context("spawn")
        eng = ServingEngine(base_predict, max_batch=16, max_delay_ms=3)
        srv = FrontendServer(eng, 2, field_size=FIELD_SIZE, ctx=ctx,
                             slab_records=8)
        t = _serve_bg(srv)
        procs = [
            ctx.Process(target=client_main,
                        args=(srv.handle(i), 20, 8, 100, 1000 + i))
            for i in range(2)
        ]
        try:
            for p in procs:
                p.start()
            for p in procs:
                p.join(timeout=120)
                assert p.exitcode == 0, f"client failed: {p.exitcode}"
            t.join(timeout=30)
            assert not t.is_alive()
            assert srv.responses_sent == 40 and srv.errors_sent == 0
            assert eng.stats.requests_failed == 0
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            srv.stop()
            srv.close()
            eng.close(timeout=5)
