"""Multi-task graph subsystem tests.

Covers the PR-9 acceptance criteria end to end:

* graph-path DeepFM / Wide&Deep / DCN-v2 are BIT-identical to the legacy
  classes (which are now thin renames of the graph classes) — forward and
  a pinned 5-step training trajectory at identical seeds;
* an MMoE CTR+CVR run trains end-to-end, publishes a servable and serves
  named per-task probabilities through ServingEngine;
* the two-label input contract (codec byte-identity, native/Python decode
  parity, pipeline label2 column);
* tiering-aware checkpointing restores bit-exact across tiered/untiered
  and differently-sized-hot-cache configs (both directions);
* every registered model (and every --multitask mode) survives a 2-step
  CPU smoke.
"""

import json
import os

import jax
import numpy as np
import pytest

import deepfm_tpu.models as models_pkg
from deepfm_tpu.config import Config
from deepfm_tpu.data import example_codec, libsvm, pipeline, tfrecord
from deepfm_tpu.models import graph, registered_models
from deepfm_tpu.native import loader
from deepfm_tpu.serve import ServingEngine
from deepfm_tpu.train import Trainer, tasks
from deepfm_tpu.utils import checkpoint as ckpt_lib
from deepfm_tpu.utils import export as export_lib

V, F, B = 200, 5, 32


def _cfg(**kw):
    base = dict(
        feature_size=V, field_size=F, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=B,
        compute_dtype="float32", l2_reg=1e-4, learning_rate=0.01,
        log_steps=0, seed=11, scale_lr_by_world=False,
        mesh_data=1, mesh_model=1,
    )
    base.update(kw)
    return Config(**base)


def _batches(nb, seed=3, two_label=False, v=V, b=B):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(nb):
        label = rng.integers(0, 2, size=(b, 1)).astype(np.float32)
        batch = dict(
            feat_ids=rng.integers(0, v, size=(b, F)).astype(np.int32),
            feat_vals=rng.normal(size=(b, F)).astype(np.float32),
            label=label)
        if two_label:
            # click-gated conversions, like the synthetic generator
            batch["label2"] = (label *
                               rng.integers(0, 2, size=(b, 1))).astype(
                                   np.float32)
        out.append(batch)
    return out


_GRAPH = {"deepfm": graph.GraphDeepFM,
          "widedeep": graph.GraphWideDeep,
          "dcnv2": graph.GraphDCNv2}


class TestGraphLegacyParity:
    """The legacy model classes are literal renames of the graph classes:
    same init key derivation, same op order — everything below must be
    bit-identical, not approximately equal."""

    @pytest.mark.parametrize("name", sorted(_GRAPH))
    def test_wrapper_is_pure_rename(self, name):
        legacy = models_pkg._REGISTRY[name]
        base = _GRAPH[name]
        assert issubclass(legacy, base)
        # no overridden math: the wrapper may only restate the public name
        assert legacy.init is base.init
        assert legacy.apply is base.apply
        assert legacy.l2_loss is base.l2_loss

    @pytest.mark.parametrize("name", sorted(_GRAPH))
    def test_forward_bit_identical(self, name):
        cfg = _cfg(model=name)
        legacy = models_pkg._REGISTRY[name](cfg)
        base = _GRAPH[name](cfg)
        p_l, s_l = legacy.init(jax.random.PRNGKey(0))
        p_g, s_g = base.init(jax.random.PRNGKey(0))
        for a, b in zip(jax.tree.leaves(p_l), jax.tree.leaves(p_g)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        [batch] = _batches(1)
        l_l, _ = legacy.apply(p_l, s_l, batch["feat_ids"],
                              batch["feat_vals"], train=False)
        l_g, _ = base.apply(p_g, s_g, batch["feat_ids"],
                            batch["feat_vals"], train=False)
        np.testing.assert_array_equal(np.asarray(l_l), np.asarray(l_g))

    @pytest.mark.parametrize("name", sorted(_GRAPH))
    def test_five_step_trajectory_bit_identical(self, name, monkeypatch):
        cfg = _cfg(model=name)
        losses_legacy, losses_graph = [], []

        def _run(losses):
            tr = Trainer(cfg)
            state, _ = tr.fit(
                tr.init_state(), _batches(5),
                hooks=[lambda s, m: losses.append(float(m["loss"]))])
            return tr, state

        tr_l, s_l = _run(losses_legacy)
        assert type(tr_l.model) is models_pkg._REGISTRY[name]
        monkeypatch.setitem(models_pkg._REGISTRY, name, _GRAPH[name])
        tr_g, s_g = _run(losses_graph)
        assert type(tr_g.model) is _GRAPH[name]
        assert losses_legacy == losses_graph  # floats, exact
        for a, b in zip(jax.tree.leaves(s_l.params),
                        jax.tree.leaves(s_g.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestZooTwoStepSmoke:
    """Fast tier-1 smoke: every registered model and every --multitask mode
    must build and take 2 optimizer steps on CPU."""

    @pytest.mark.parametrize(
        "name", registered_models() + ["mmoe", "shared_bottom", "esmm"])
    def test_two_steps(self, name):
        if name in ("mmoe", "shared_bottom", "esmm"):
            cfg = _cfg(model="deepfm", tasks="ctr,cvr", multitask=name,
                       mmoe_experts=2)
        else:
            cfg = _cfg(model=name)
        tr = Trainer(cfg)
        losses = []
        state, summary = tr.fit(
            tr.init_state(), _batches(2, two_label=cfg.num_tasks > 1),
            hooks=[lambda s, m: losses.append(float(m["loss"]))])
        assert summary["steps"] == 2
        assert all(np.isfinite(l) for l in losses)


@pytest.fixture(scope="module")
def mt_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mt")
    data = str(d / "data")
    libsvm.generate_synthetic_ctr(
        data, num_files=3, examples_per_file=256, feature_size=300,
        field_size=5, prefix="tr", seed=7, num_labels=2)
    libsvm.generate_synthetic_ctr(
        data, num_files=1, examples_per_file=256, feature_size=300,
        field_size=5, prefix="va", seed=8, num_labels=2)
    libsvm.generate_synthetic_ctr(
        data, num_files=1, examples_per_file=128, feature_size=300,
        field_size=5, prefix="te", seed=9, num_labels=2)
    return d


def _mt_cfg(mt_dir, **kw):
    base = dict(
        feature_size=300, field_size=5, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=64,
        compute_dtype="float32", learning_rate=0.05, num_epochs=2,
        data_dir=str(mt_dir / "data"), val_data_dir=str(mt_dir / "data"),
        model_dir=str(mt_dir / "ckpt"), log_steps=0,
        save_checkpoints_steps=5, mesh_data=1, mesh_model=1,
        scale_lr_by_world=False, seed=3,
        tasks="ctr,cvr", multitask="mmoe", mmoe_experts=2,
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def mt_trained(mt_dir):
    """One MMoE CTR+CVR train → publish run shared by the e2e tests."""
    cfg = _mt_cfg(mt_dir, servable_model_dir=str(mt_dir / "servable"))
    result = tasks.run(cfg)
    [sub] = os.listdir(str(mt_dir / "servable"))
    return result, str(mt_dir / "servable" / sub)


class TestMultiTaskEndToEnd:
    def test_train_reports_per_task_auc(self, mt_trained):
        result, _ = mt_trained
        assert "auc_ctr" in result and "auc_cvr" in result, result
        assert 0.0 <= result["auc_ctr"] <= 1.0
        assert 0.0 <= result["auc_cvr"] <= 1.0
        # CTR is learnable on the synthetic data; the headline auc is task 0
        assert result["auc"] == result["auc_ctr"]
        assert result["auc_ctr"] > 0.55, result

    def test_artifact_declares_named_outputs(self, mt_trained):
        _, artifact = mt_trained
        meta = json.load(open(os.path.join(artifact, "model_config.json")))
        assert set(meta["signature"]["outputs"]) == {"ctr", "cvr"}

    def test_load_serving_returns_named_probs(self, mt_trained):
        _, artifact = mt_trained
        serve = export_lib.load_serving(artifact)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 300, (16, 5)).astype(np.int32)
        vals = rng.normal(size=(16, 5)).astype(np.float32)
        out = serve(ids, vals)
        assert set(out) == {"ctr", "cvr"}
        for arr in out.values():
            arr = np.asarray(arr)
            assert arr.shape == (16,)
            assert ((arr >= 0) & (arr <= 1)).all()

    def test_serving_engine_demuxes_named_outputs(self, mt_trained):
        _, artifact = mt_trained
        serve = export_lib.load_serving(artifact)
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 300, (5, 5)).astype(np.int32)
        vals = rng.normal(size=(5, 5)).astype(np.float32)
        with ServingEngine(serve, max_batch=8, max_delay_ms=5) as eng:
            got = eng.predict(ids, vals, timeout=60)
        assert set(got) == {"ctr", "cvr"}
        want = export_lib.padded_predict(serve, ids, vals, (8,))
        for k in ("ctr", "cvr"):
            np.testing.assert_array_equal(np.asarray(got[k]),
                                          np.asarray(want[k]))

    def test_infer_writes_two_columns(self, mt_dir, mt_trained):
        out = tasks.run(_mt_cfg(mt_dir, task_type="infer"))
        assert out["num_predictions"] == 128
        lines = open(os.path.join(str(mt_dir / "data"),
                                  "pred.txt")).read().splitlines()
        assert len(lines) == 128
        rows = np.array([[float(v) for v in ln.split()] for ln in lines])
        assert rows.shape == (128, 2)
        assert ((rows >= 0) & (rows <= 1)).all()


class TestEngineWireShapes:
    """ServingEngine demux is shape-agnostic; the single-output wire shape
    is a compatibility contract and must not change."""

    def test_single_output_keeps_old_wire_shape(self):
        def pred(ids, vals):
            return vals[:, 0]

        with ServingEngine(pred, max_batch=8, max_delay_ms=5) as eng:
            ids = np.zeros((3, F), np.int32)
            vals = np.arange(3 * F, dtype=np.float32).reshape(3, F)
            got = eng.predict(ids, vals, timeout=60)
        assert isinstance(got, np.ndarray)  # NOT a dict
        assert got.shape == (3,)
        np.testing.assert_array_equal(got, vals[:, 0])

    def test_dict_outputs_demuxed_row_for_row(self):
        def pred(ids, vals):
            return {"a": vals[:, 0], "b": 2.0 * vals[:, 0]}

        with ServingEngine(pred, max_batch=16, max_delay_ms=20,
                           buckets=(16,)) as eng:
            futs = [eng.submit(np.zeros((n, F), np.int32),
                               np.full((n, F), float(i), np.float32))
                    for i, n in enumerate((2, 3, 1))]
            outs = [f.result(timeout=60) for f in futs]
        for i, (out, n) in enumerate(zip(outs, (2, 3, 1))):
            assert set(out) == {"a", "b"}
            np.testing.assert_array_equal(out["a"], np.full(n, float(i)))
            np.testing.assert_array_equal(out["b"], np.full(n, 2.0 * i))


def _tier_cfg(**kw):
    base = dict(
        feature_size=400, field_size=F, embedding_size=8,
        deep_layers="16,8", dropout="1.0,1.0", batch_size=B,
        compute_dtype="float32", l2_reg=1e-4, learning_rate=1e-3,
        log_steps=0, seed=11, scale_lr_by_world=False,
        mesh_data=1, mesh_model=1, embedding_update="sparse",
    )
    base.update(kw)
    return Config(**base)


class TestTieringCheckpoint:
    """Hot/cold runs checkpoint the DENSIFIED table: restores must be
    bit-exact into untiered configs and into differently sized hot caches,
    in both directions."""

    def _eval_batches(self):
        return _batches(4, seed=17, v=400)

    def test_tiered_checkpoint_restores_untiered_and_resized(self, tmp_path):
        cfg = _tier_cfg(embedding_tiering="hot_cold",
                        embedding_hot_rows=256)
        tr = Trainer(cfg)
        state, _ = tr.fit(tr.init_state(), _batches(6, v=400))
        ev = tr.evaluate(state, self._eval_batches())
        d = str(tmp_path / "tiered")
        with ckpt_lib.CheckpointManager(d) as mgr:
            mgr.save(6, tr._tier.checkpoint_state(state))

        # direction A: restore into an untiered (dense-table) config
        tr_dense = Trainer(_tier_cfg())
        with ckpt_lib.CheckpointManager(d) as mgr:
            restored = mgr.restore(tr_dense.init_state())
        ev_dense = tr_dense.evaluate(restored, self._eval_batches())
        assert ev_dense["auc"] == ev["auc"]
        assert ev_dense["loss"] == ev["loss"]

        # direction A': restore into a DIFFERENTLY sized hot cache
        cfg2 = _tier_cfg(embedding_tiering="hot_cold",
                         embedding_hot_rows=320)
        tr2 = Trainer(cfg2)
        with ckpt_lib.CheckpointManager(d) as mgr:
            template = tr2.init_state(tiered=False)
            restored2 = tr2._tier.adopt(mgr.restore(template))
        ev2 = tr2.evaluate(restored2, self._eval_batches())
        assert ev2["auc"] == ev["auc"]
        assert ev2["loss"] == ev["loss"]

    def test_dense_checkpoint_restores_into_tiered(self, tmp_path):
        cfg = _tier_cfg()
        tr = Trainer(cfg)
        state, _ = tr.fit(tr.init_state(), _batches(6, v=400))
        ev = tr.evaluate(state, self._eval_batches())
        d = str(tmp_path / "dense")
        with ckpt_lib.CheckpointManager(d) as mgr:
            mgr.save(6, state)

        cfg_t = _tier_cfg(embedding_tiering="hot_cold",
                          embedding_hot_rows=256)
        tr_t = Trainer(cfg_t)
        with ckpt_lib.CheckpointManager(d) as mgr:
            template = tr_t.init_state(tiered=False)
            restored = tr_t._tier.adopt(mgr.restore(template))
        ev_t = tr_t.evaluate(restored, self._eval_batches())
        assert ev_t["auc"] == ev["auc"]
        assert ev_t["loss"] == ev["loss"]


class TestLabel2Codec:
    """Two-label input contract: byte-identity for single-label encodes,
    round-trip, defaulting, native/Python mirror parity, pipeline column."""

    def _example(self, seed=0):
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, 1000, F).astype(np.int64)
        vals = rng.normal(size=F).astype(np.float32)
        return ids, vals

    def test_single_label_encode_byte_identical(self):
        ids, vals = self._example()
        assert (example_codec.encode_ctr_example(1.0, ids, vals) ==
                example_codec.encode_ctr_example(1.0, ids, vals,
                                                 label2=None))

    def test_round_trip_and_default(self):
        ids, vals = self._example(1)
        buf = example_codec.encode_ctr_example(1.0, ids, vals, label2=1.0)
        lab, lab2, rid, rval = example_codec.decode_ctr_example2(buf, F)
        assert (lab, lab2) == (1.0, 1.0)
        np.testing.assert_array_equal(rid, ids)
        np.testing.assert_array_equal(rval, vals)
        # one-label decode still reads two-label bytes (ignores label2)
        lab_1, _, _ = example_codec.decode_ctr_example(buf, F)
        assert lab_1 == 1.0
        # two-label decode defaults label2=0.0 on single-label bytes
        buf1 = example_codec.encode_ctr_example(1.0, ids, vals)
        _, lab2_default, _, _ = example_codec.decode_ctr_example2(buf1, F)
        assert lab2_default == 0.0

    @pytest.mark.skipif(
        not (loader.available() and loader.has_labels2()),
        reason="native two-label decoder unavailable")
    def test_native_decode_matches_python_mirror(self, tmp_path):
        [path] = libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=1, examples_per_file=200,
            feature_size=500, field_size=F, seed=5, num_labels=2)
        records = tfrecord.read_all_records(path)
        l_n, l2_n, ids_n, vals_n = loader.decode_batch2(records, F)
        for i, rec in enumerate(records):
            lab, lab2, rid, rval = example_codec.decode_ctr_example2(rec, F)
            assert l_n[i] == np.float32(lab)
            assert l2_n[i] == np.float32(lab2)
            np.testing.assert_array_equal(ids_n[i], rid.astype(np.int32))
            np.testing.assert_array_equal(vals_n[i], rval)

    def test_pipeline_emits_label2_column(self, tmp_path):
        files = libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=1, examples_per_file=128,
            feature_size=100, field_size=F, seed=1, num_labels=2)
        p = pipeline.CtrPipeline(
            files, field_size=F, batch_size=32, num_epochs=1,
            shuffle=False, prefetch_batches=0, num_labels=2)
        batches = list(p)
        assert sum(b["label"].shape[0] for b in batches) == 128
        lab = np.concatenate([b["label"][:, 0] for b in batches])
        lab2 = np.concatenate([b["label2"][:, 0] for b in batches])
        assert all(b["label2"].shape == (b["label"].shape[0], 1)
                   for b in batches)
        # conversions are click-gated in the generator
        assert (lab2 <= lab).all()
        assert lab2.sum() > 0

    def test_single_label_files_read_as_all_negative_task2(self, tmp_path):
        files = libsvm.generate_synthetic_ctr(
            str(tmp_path), num_files=1, examples_per_file=64,
            feature_size=100, field_size=F, seed=2)
        p = pipeline.CtrPipeline(
            files, field_size=F, batch_size=32, num_epochs=1,
            shuffle=False, prefetch_batches=0, num_labels=2)
        for b in p:
            assert (b["label2"] == 0.0).all()
