"""Closed-loop production-day tests: the ``deepfm_tpu.loop`` feedback layer
(impression logging, delayed-label joining, skew audit, traffic plan), the
unified ``ChaosSchedule``, the hardened ``LatestWatcher`` poll loop, and the
in-process drill smoke (``scripts/production_drill.py``). The full
multi-process drill (subprocess trainer + SIGTERM preemption) rides behind
``slow``. CPU-only; all join/chaos decisions are logical-time, so the edge
tests are sleep-free."""

import json
import os
import sys
import time

import numpy as np
import pytest

from deepfm_tpu.data import tfrecord
from deepfm_tpu.loop import (DelayedLabelJoiner, DiurnalTrafficPlan,
                             LoopHealth, SeededLabelFeed, SkewChecker,
                             iter_impressions, staleness_summary,
                             windowed_auc)
from deepfm_tpu.loop.impressions import ImpressionLogger, encode_impression
from deepfm_tpu.loop.metrics import exact_auc
from deepfm_tpu.serve.stats import ServingStats
from deepfm_tpu.utils import export as export_lib
from deepfm_tpu.utils import faults

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))
import production_drill  # noqa: E402

pytestmark = pytest.mark.production

FIELD = 3


def _row(iid):
    """Deterministic per-impression feature row."""
    ids = (np.arange(FIELD, dtype=np.int32) + iid) % 64
    vals = (np.arange(FIELD, dtype=np.float32) * 0.5 + iid)
    return ids, vals


def _imp_shard(imp_dir, index, iids, served_at=0.0, prefix="imp"):
    """Write one impression shard by hand (bypassing the logger) so tests
    control exactly which iids land in which shard index."""
    os.makedirs(imp_dir, exist_ok=True)
    path = os.path.join(imp_dir, f"{prefix}-{index:05d}.tfrecords")
    with tfrecord.TFRecordWriter(path) as w:
        for iid in iids:
            ids, vals = _row(iid)
            w.write(encode_impression(iid, served_at, ids, vals))
    return path


def _pinned_feed(delay_s, seed=0):
    """Every impression gets exactly ``delay_s`` of label delay."""
    return SeededLabelFeed(seed, delay_min_s=delay_s, delay_max_s=delay_s)


class TestSeededLabelFeed:
    def test_delay_is_pure_function_of_seed_and_id(self):
        a, b = SeededLabelFeed(3, delay_min_s=1, delay_max_s=9), \
            SeededLabelFeed(3, delay_min_s=1, delay_max_s=9)
        assert [a.delay_for(i) for i in range(50)] \
            == [b.delay_for(i) for i in range(50)]
        c = SeededLabelFeed(4, delay_min_s=1, delay_max_s=9)
        assert [a.delay_for(i) for i in range(50)] \
            != [c.delay_for(i) for i in range(50)]

    def test_poll_delivers_in_arrival_order(self):
        feed = SeededLabelFeed(1, delay_min_s=0.5, delay_max_s=5.0)
        for iid in range(10):
            feed.push(iid, float(iid % 2), served_at_s=0.0)
        arrivals = [a for _, _, a in feed.poll(100.0)]
        assert arrivals == sorted(arrivals)
        assert feed.pending == 0

    def test_rejects_inverted_range(self):
        with pytest.raises(ValueError):
            SeededLabelFeed(0, delay_min_s=2.0, delay_max_s=1.0)


class TestJoinerEdgeCases:
    def _joiner(self, tmp_path, feed, window):
        imp = str(tmp_path / "imp")
        out = str(tmp_path / "out")
        os.makedirs(imp, exist_ok=True)
        health = LoopHealth()
        return imp, out, health, DelayedLabelJoiner(
            imp, out, feed, join_window_s=window, health=health)

    def test_duplicate_impression_id_dropped(self, tmp_path):
        feed = _pinned_feed(10.0)
        imp, out, health, j = self._joiner(tmp_path, feed, window=2.0)
        _imp_shard(imp, 0, [0, 1, 2])
        _imp_shard(imp, 1, [2, 3, 4])     # iid 2 again: later copy drops
        j.pump(100.0)                      # everything expires (delay 10>2)
        c = health.snapshot()
        assert c["duplicate_impressions"] == 1
        assert c["records_emitted"] == 5   # 3 + 2 (dup dropped)
        with open(os.path.join(out, ".tr-00001.manifest.json")) as f:
            assert json.load(f)["impressions"] == [3, 4]

    def test_label_past_window_never_applied(self, tmp_path):
        # Delay 5 > window 2: the ground-truth positive must NOT appear in
        # the emitted shard — the record closes with the no-label default,
        # and the late truth is counted, not retro-applied.
        feed = _pinned_feed(5.0)
        imp, out, health, j = self._joiner(tmp_path, feed, window=2.0)
        _imp_shard(imp, 0, [0])
        j.pump(0.0)
        feed.push(0, 1.0, served_at_s=0.0)       # arrival at t=5
        paths = j.pump(10.0)                     # label seen, then expiry
        c = health.snapshot()
        assert c["labels_past_window"] == 1
        assert c["impressions_expired"] == 1
        assert c["labels_joined"] == 0
        with open(os.path.join(out, ".tr-00000.manifest.json")) as f:
            assert json.load(f)["labels"] == [0.0]
        assert paths == [os.path.join(out, "tr-00000.tfrecords")]

    def test_pump_cadence_does_not_change_classification(self, tmp_path):
        # Same scenario, but the pump only runs long after both the window
        # closed and the label arrived: one coarse pump must produce the
        # identical counters as the fine-grained pumping above — that's
        # what makes a drill audit replayable regardless of loop timing.
        for pumps in ([10.0], [1.0, 3.0, 6.0, 10.0]):
            feed = _pinned_feed(5.0)
            tdir = tmp_path / f"cadence{len(pumps)}"
            imp, out, health, j = self._joiner(tdir, feed, window=2.0)
            _imp_shard(imp, 0, [0])
            feed.push(0, 1.0, served_at_s=0.0)
            for now in pumps:
                j.pump(now)
            c = health.snapshot()
            assert (c["labels_joined"], c["labels_past_window"],
                    c["impressions_expired"]) == (0, 1, 1), pumps

    def test_orphan_label_counts_late(self, tmp_path):
        feed = _pinned_feed(1.0)
        imp, out, health, j = self._joiner(tmp_path, feed, window=2.0)
        _imp_shard(imp, 0, [0])
        feed.push(0, 1.0, served_at_s=0.0)
        feed.push(999, 1.0, served_at_s=0.0)   # never logged anywhere
        j.pump(5.0)
        c = health.snapshot()
        assert c["labels_joined"] == 1
        assert c["labels_late"] == 1

    def test_torn_impression_shard_heals_mid_join(self, tmp_path):
        # Shard 1 loses its tail (torn write / injected fault): the intact
        # prefix joins normally, the torn tail is counted, and in-order
        # emission still proceeds past the damaged shard.
        feed = _pinned_feed(1.0)
        imp, out, health, j = self._joiner(tmp_path, feed, window=2.0)
        _imp_shard(imp, 0, [0, 1, 2])
        torn = _imp_shard(imp, 1, [3, 4, 5])
        with open(torn, "r+b") as f:
            f.truncate(os.path.getsize(torn) - 7)   # tear the last record
        for iid in (0, 1, 2, 3, 4):                 # 5 never materialized
            feed.push(iid, 1.0, served_at_s=0.0)
        j.pump(1.5)
        c = health.snapshot()
        assert c["torn_impression_shards"] == 1
        assert c["labels_joined"] == 5
        assert c["records_emitted"] == 5            # 3 + 2 intact
        assert sorted(os.path.basename(p) for p in j.emitted_shards) \
            == ["tr-00000.tfrecords", "tr-00001.tfrecords"]

    def test_exactly_once_emission_across_restart(self, tmp_path):
        feed = _pinned_feed(1.0)
        imp, out, health, j = self._joiner(tmp_path, feed, window=2.0)
        _imp_shard(imp, 0, [0, 1])
        feed.push(0, 1.0, served_at_s=0.0)
        feed.push(1, 0.0, served_at_s=0.0)
        (emitted,) = j.pump(1.5)
        with open(emitted, "rb") as f:
            before = f.read()
        mtime = os.path.getmtime(emitted)

        # "Restart": a fresh joiner over the same directories must treat
        # the existing output shard as durable state — no re-emission, no
        # double-join — and continue in order with the next shard.
        feed2 = _pinned_feed(1.0)
        h2 = LoopHealth()
        j2 = DelayedLabelJoiner(imp, out, feed2, join_window_s=2.0,
                                health=h2)
        j2.pump(1.5)
        with open(emitted, "rb") as f:
            assert f.read() == before
        assert os.path.getmtime(emitted) == mtime
        assert h2.snapshot()["records_emitted"] == 0
        assert j2.manifests[emitted] == [0, 1]      # manifest reloaded

        _imp_shard(imp, 1, [2])
        feed2.push(2, 1.0, served_at_s=0.0)
        paths = j2.pump(3.0)
        assert [os.path.basename(p) for p in paths] == ["tr-00001.tfrecords"]
        assert h2.snapshot()["records_emitted"] == 1


class TestImpressionLoggerRoundtrip:
    def test_log_join_skew_roundtrip_is_bit_identical(self, tmp_path):
        imp_dir = str(tmp_path / "imp")
        logger = ImpressionLogger(imp_dir, shard_records=2)
        served = {}
        for iid in range(5):
            ids, vals = _row(iid)
            logger.log(iid, ids, vals, served_at_s=float(iid))
            served[iid] = (ids, vals)
        # Two shards sealed, one row still buffered in a dot-file: readers
        # must only ever see sealed shards.
        assert len(logger.shards) == 2
        visible = [n for n in os.listdir(imp_dir) if not n.startswith(".")]
        assert sorted(visible) == ["imp-00000.tfrecords",
                                   "imp-00001.tfrecords"]
        logger.close()
        assert len(logger.shards) == 3

        got = []
        for shard in logger.shards:
            got += list(iter_impressions(shard))
        assert [iid for iid, _, _, _ in got] == list(range(5))
        for iid, served_at, ids, vals in got:
            assert served_at == float(iid)
            assert np.array_equal(ids, np.asarray(served[iid][0], np.int64))
            assert vals.tobytes() == served[iid][1].tobytes()

    def test_resumes_after_existing_shards(self, tmp_path):
        imp_dir = str(tmp_path / "imp")
        _imp_shard(imp_dir, 0, [0])
        logger = ImpressionLogger(imp_dir, shard_records=1)
        logger.log(1, *_row(1), served_at_s=0.0)
        logger.close()
        assert os.path.basename(logger.shards[0]) == "imp-00001.tfrecords"


class TestSkewChecker:
    def _emit_one(self, tmp_path, served):
        imp = str(tmp_path / "imp")
        out = str(tmp_path / "out")
        feed = _pinned_feed(1.0)
        j = DelayedLabelJoiner(imp, out, feed, join_window_s=2.0)
        _imp_shard(imp, 0, sorted(served))
        for iid in served:
            feed.push(iid, 1.0, served_at_s=0.0)
        (path,) = j.pump(1.5)
        return path

    def test_clean_roundtrip_passes(self, tmp_path):
        served = {iid: _row(iid) for iid in range(4)}
        path = self._emit_one(tmp_path, served)
        ck = SkewChecker(served)
        assert ck.audit_shard(path) == 4
        assert ck.ok and ck.mismatches == []

    def test_detects_single_ulp_drift(self, tmp_path):
        served = {iid: _row(iid) for iid in range(4)}
        path = self._emit_one(tmp_path, served)
        ids, vals = served[2]
        drifted = vals.copy()
        drifted[0] = np.nextafter(drifted[0], np.float32(np.inf))
        served[2] = (ids, drifted)
        ck = SkewChecker(served)
        ck.audit_shard(path)
        assert not ck.ok
        assert any("vals drifted" in m for m in ck.mismatches)


class TestChaosSchedule:
    def _sched(self, seed=5):
        return faults.ChaosSchedule.generate(
            seed, horizon_s=30.0, read_fault_every=9, publish_crashes=1,
            preemptions=1, cold_fetch_fails=2, nan_batches=2)

    def test_generate_is_deterministic(self):
        a, b = self._sched(), self._sched()
        assert a.to_json() == b.to_json()
        assert a.fingerprint() == b.fingerprint()
        assert self._sched(seed=6).fingerprint() != a.fingerprint()

    def test_json_roundtrip_is_canonical(self):
        a = self._sched()
        b = faults.ChaosSchedule.from_json(a.to_json())
        assert b.to_json() == a.to_json()
        assert b.fingerprint() == a.fingerprint()

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown chaos kind"):
            faults.ChaosSchedule(
                [faults.ChaosEvent.make(0.0, "meteor_strike")])

    def test_from_env_inline_and_at_file(self, tmp_path):
        a = self._sched()
        assert faults.ChaosSchedule.from_env(
            {faults.CHAOS_ENV: a.to_json()}).fingerprint() == a.fingerprint()
        p = tmp_path / "sched.json"
        p.write_text(a.to_json())
        assert faults.ChaosSchedule.from_env(
            {faults.CHAOS_ENV: "@" + str(p)}).fingerprint() \
            == a.fingerprint()
        assert faults.ChaosSchedule.from_env({}) is None

    def test_legacy_read_fault_env_still_works(self):
        # The old single-knob var alone becomes a read_faults event...
        s = faults.ChaosSchedule.from_env({faults.READ_FAULT_ENV: "7"})
        (ev,) = s.events_of("read_faults")
        assert ev.get("every") == 7
        # ...and when a schedule already specifies read faults, the
        # schedule wins (no double-arming, no knob fight).
        merged = faults.ChaosSchedule.from_env(
            {faults.CHAOS_ENV: self._sched().to_json(),
             faults.READ_FAULT_ENV: "7"})
        (ev,) = merged.events_of("read_faults")
        assert ev.get("every") == 9

    def test_due_fires_driver_events_once(self):
        s = self._sched()
        (preempt,) = s.events_of("preempt")
        fired = set()
        assert s.due(preempt.at_s - 0.001, fired) == []
        assert s.due(preempt.at_s + 0.001, fired) == [preempt]
        assert s.due(preempt.at_s + 100, fired) == []   # once only
        # process-local kinds never come through the driver pump
        assert all(ev.kind == "preempt"
                   for ev in s.due(1e9, set()))

    def test_install_oneshots_guarded_by_state_file(self, tmp_path):
        state = str(tmp_path / "chaos_state.json")
        s = faults.ChaosSchedule.generate(
            1, horizon_s=10.0, publish_crashes=1,
            publish_crash_stage="before_rename")
        try:
            s.install(state_path=state)
            with pytest.raises(faults.InjectedFault):
                faults.check_publish_crash("before_rename")   # armed, fires
            # A supervised restart re-installs the same schedule: the state
            # file must keep the already-fired crash from re-arming.
            s.install(state_path=state)
            faults.check_publish_crash("before_rename")       # no raise
        finally:
            faults.set_publish_crash("")

    def test_install_rearms_continuous_kinds(self, tmp_path):
        from deepfm_tpu.data import fileio
        s = faults.ChaosSchedule.generate(
            2, horizon_s=10.0, read_fault_every=4)
        try:
            fs = s.install(state_path=str(tmp_path / "st.json"))
            assert isinstance(fs, faults.FlakyFS)
            fs2 = s.install(state_path=str(tmp_path / "st.json"))
            assert isinstance(fs2, faults.FlakyFS)   # restarts: same weather
        finally:
            fileio.set_fault_injector(None)


class TestWatcherHardening:
    def _publish(self, publish_dir, version):
        d = os.path.join(publish_dir, version)
        os.makedirs(d, exist_ok=True)
        export_lib.write_latest(publish_dir, version)
        return d

    def test_poll_loop_survives_loader_exceptions(self, tmp_path):
        # A loader bug (NOT one of the anticipated ArtifactIncomplete/
        # OSError/ValueError classes) must never kill the poll thread: the
        # current model keeps serving, the failure is COUNTED as
        # watcher_errors (distinct from swap_failures), and on_error fires.
        publish_dir = str(tmp_path / "publish")
        self._publish(publish_dir, "1")
        calls, errors = [], []

        def loader(path):
            calls.append(path)
            if len(calls) > 1:
                raise RuntimeError("loader bug")
            return lambda ids, vals: np.zeros((len(ids), 1), np.float32)

        w = export_lib.LatestWatcher(
            publish_dir, poll_secs=0.01, loader=loader,
            on_error=errors.append, prewarm=False)
        try:
            assert os.path.basename(w.current_path) == "1"
            self._publish(publish_dir, "2")        # every reload now fails
            deadline = time.monotonic() + 5.0
            while w.watcher_errors < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert w.watcher_errors >= 3, "poll loop died or never failed"
            assert w._thread.is_alive()
            assert os.path.basename(w.current_path) == "1"  # still serving
            assert w.swap_count == 1 and w.swap_failures == 0
            assert len(errors) == w.watcher_errors
            assert all(isinstance(e, RuntimeError) for e in errors)
        finally:
            w.close()

    def test_anticipated_failures_still_count_as_swap_failures(self, tmp_path):
        # The pre-existing contract is untouched: a torn artifact is a
        # swap_failure, not a watcher_error.
        publish_dir = str(tmp_path / "publish")
        self._publish(publish_dir, "1")

        def loader(path):
            if path.endswith("2"):
                raise export_lib.ArtifactIncomplete(path)
            return lambda ids, vals: np.zeros((len(ids), 1), np.float32)

        w = export_lib.LatestWatcher(
            publish_dir, poll_secs=0.01, loader=loader, prewarm=False,
            start=False)
        try:
            self._publish(publish_dir, "2")
            assert w.check_once() is False
            assert w.swap_failures == 1 and w.watcher_errors == 0
        finally:
            w.close()

    def test_serving_stats_surfaces_watcher_errors(self):
        stats = ServingStats()
        assert stats.summary()["serving_watcher_errors"] == 0
        stats.record_watcher_error()
        stats.record_watcher_error()
        assert stats.summary()["serving_watcher_errors"] == 2


class TestLoopMetrics:
    def test_exact_auc_known_values(self):
        assert exact_auc([0.1, 0.4, 0.35, 0.8], [0, 0, 1, 1]) \
            == pytest.approx(0.75)
        assert exact_auc([0.5, 0.5], [0, 1]) == pytest.approx(0.5)  # midrank
        assert np.isnan(exact_auc([0.1, 0.2], [1, 1]))   # one-class

    def test_exact_auc_matches_rank_shuffle(self):
        rng = np.random.default_rng(0)
        y = rng.integers(0, 2, 200)
        p = rng.random(200)
        perm = rng.permutation(200)
        assert exact_auc(p, y) == pytest.approx(exact_auc(p[perm], y[perm]))

    def test_windowed_auc_splits_logical_time(self):
        samples = [(t, float(t >= 5), 0.9 if t >= 5 else 0.1, 0.5)
                   for t in np.linspace(0, 9.99, 40)]
        wins = windowed_auc(samples, 2, 10.0)
        assert [w["window"] for w in wins] == [0, 1]
        assert wins[0]["n"] + wins[1]["n"] == 40
        assert wins[0]["auc_online"] is None      # window 0: all negatives
        assert wins[1]["auc_online"] is None      # window 1: all positives

    def test_staleness_summary(self):
        s = staleness_summary([1.0, 2.0, 3.0, 4.0])
        assert s["n"] == 4
        assert s["staleness_max_s"] == 4.0
        assert staleness_summary([])["n"] == 0


class TestTrafficPlan:
    def _plan(self, seed=3):
        return DiurnalTrafficPlan(
            seed, duration_s=6.0, base_qps=4.0, peak_qps=12.0,
            feature_size=32, field_size=FIELD, max_rows=3)

    def test_same_seed_bit_identical(self):
        a, b = self._plan(), self._plan()
        assert a.fingerprint_data() == b.fingerprint_data()
        assert a.fingerprint_data() != self._plan(seed=4).fingerprint_data()

    def test_plan_shape_invariants(self):
        p = self._plan()
        assert p.total_rows == sum(r.ids.shape[0] for r in p.requests)
        times = [r.t_s for r in p.requests]
        assert times == sorted(times)
        assert all(0 <= t < 6.0 for t in times)
        next_id = 0
        for r in p.requests:
            assert r.first_id == next_id          # ids are gap-free
            next_id += r.ids.shape[0]
            assert set(np.unique(r.labels)) <= {0.0, 1.0}


class TestDrillAuditDeterminism:
    def test_audit_fingerprint_is_seed_pure(self):
        # The full acceptance property — same seed + schedule reproduces
        # the identical drill audit — reduced to its pure core: every
        # audited quantity is a function of the seeds alone.
        def fingerprint():
            sched = faults.ChaosSchedule.generate(
                7, horizon_s=8.0, publish_crashes=1)
            plan = DiurnalTrafficPlan(
                7, duration_s=8.0, base_qps=5.0, peak_qps=9.0,
                feature_size=32, field_size=4, max_rows=3)
            feed = SeededLabelFeed(8, delay_min_s=0.3, delay_max_s=4.5)
            counters, labels = production_drill._expected_join(
                plan, feed, 3.0)
            return production_drill._audit_fingerprint(
                sched, plan, counters, labels)

        assert fingerprint() == fingerprint()


def _assert_drill_gates(r):
    assert r["ok"]
    assert r["request_loss"]["failed"] == 0
    assert r["request_loss"]["overloads"] == 0
    assert r["request_loss"]["swap_failures"] == 0
    assert r["request_loss"]["watcher_errors"] == 0
    assert r["request_loss"]["hot_swaps"] >= 3
    assert r["determinism"]["counters_match_simulation"]
    assert r["determinism"]["labels_match_simulation"]
    assert r["skew"]["mismatches"] == 0
    assert r["skew"]["records_audited"] == r["traffic"]["rows"]
    assert r["chaos"]["publish_crash_fired"]
    assert r["publish"]["staging_leaks"] >= 1
    assert r["publish"]["crashed_version"] not in r["publish"]["versions"]
    assert r["publish"]["final_params_finite"]
    assert r["loop_health"]["labels_late"] == 0
    assert r["loop_health"]["duplicate_impressions"] == 0


def test_production_smoke_closed_loop(tmp_path):
    """Tier-1 drill: the whole serve->log->join->train->publish loop in one
    process (mini-trainer thread), with the scheduled publish crash live —
    run under --trace ring, which must change nothing about the gates and
    must leave a merged, correlated Chrome trace."""
    r = production_drill.run_smoke(str(tmp_path), verbose=False,
                                   trace="ring")
    assert r["mode"] == "smoke"
    _assert_drill_gates(r)
    # The online trainer actually trained: versions beyond bootstrap exist
    # and staleness was measured for covered rows.
    assert max(r["publish"]["versions"]) >= 3 * 4
    assert r["staleness"]["covered_rows"] > 0
    # Telemetry plane: one merged Perfetto-loadable trace whose timeline
    # shows a request served by version N while version M > N staged.
    tr = r["trace"]
    assert tr["mode"] == "ring"
    assert os.path.exists(tr["merged_path"])
    with open(tr["merged_path"]) as f:
        merged = json.load(f)
    assert len(merged["traceEvents"]) == tr["events"] > 0
    corr = tr["correlated_serve_publish_overlap"]
    assert corr["publish_version"] > corr["serve_model_step"]
    assert corr["sample_trace_ids"], "no trace_ids reached the flush"
    # trace_report digests the merged file: the hot serving/publish spans
    # appear with counts and self-time.
    import trace_report
    rows, _, _ = trace_report.summarize(merged["traceEvents"])
    names = {row["name"] for row in rows}
    assert "serve.flush" in names and "publish.stage" in names
    # The drill reset the global tracer on the way out (no env leak).
    from deepfm_tpu.obs import trace as trace_lib
    assert not trace_lib.enabled()
    assert trace_lib.ENV_MODE not in os.environ


@pytest.mark.slow
def test_production_drill_end_to_end(tmp_path):
    """The full drill: subprocess online trainer under the supervisor, read
    faults + publish crash + SIGTERM preemption from one chaos schedule."""
    r = production_drill.run_drill(str(tmp_path), report_path="",
                                   verbose=False)
    assert r["mode"] == "full"
    _assert_drill_gates(r)
    assert r["chaos"]["supervised_restarts"] >= 1
    assert r["chaos"]["preemptions_sent_at_logical_s"]
    assert r["staleness"]["staleness_p95_s"] is not None
    assert r["staleness"]["staleness_p95_s"] <= r["staleness"]["bound_s"]
